"""Join execs: TPU equi-join (sorted-build + searchsorted probe) and CPU oracle.

Reference: GpuShuffledHashJoinExec + GpuHashJoin trait (execution/GpuHashJoin.scala:994,
gather-map iterators :259-985), GpuBroadcastNestedLoopJoinExec, GpuSortMergeJoinMeta
(SMJ replaced by hash join on the accelerator — same policy here).

TPU algorithm (XLA-static-shape friendly — cuDF's dynamic hash table does not
map to TPU):
  1. composite 64-bit mix of the equi-key columns on both sides (null keys never
     match: rows with any null key are excluded from candidates)
  2. sort the build side by hash; probe via two searchsorted calls → per-row
     candidate ranges (hash collisions included)
  3. expand ranges into candidate pairs (one host sync for the pair count →
     bucketed output capacity, like the reference's gather-map sizing)
  4. verify true key equality per pair (collision + null filtering)
  5. join-type specific assembly: inner gathers both sides; left/right/full add
     null-extended unmatched rows; semi/anti reduce to per-row match flags.
Residual (non-equi) conditions evaluate over the joined batch and recompute
match bookkeeping, mirroring the reference's conditional-join iterators.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..columnar.batch import TpuColumnarBatch, compact, concat_batches, gather
from ..columnar.vector import TpuColumnVector, bucket_capacity, row_mask
from ..expressions.base import (AttributeReference, Expression, to_column)
from ..types import StringType
from .aggregates import _sortable_bits
from .base import (CpuExec, PhysicalPlan, TaskContext, TpuExec, bind_all,
                   bind_references)

def _mix64(h, v):
    """Width-adaptive mix chain (splitmix-style, 64-bit where the backend is
    natively 64-bit, 32-bit on demoting TPU backends); the verified-equality
    pass makes collisions harmless."""
    from ..utils.hw import hash_plane
    _, mix_const, _, _ = hash_plane()
    h = (h ^ v) * mix_const
    h = h ^ (h >> (29 if h.dtype == jnp.uint64 else 15))
    return h


def encode_fixed_key_pair(lb, rb, l_validity, r_validity, native: bool,
                          l_enc: list, r_enc: list) -> None:
    """Append one fixed-width key pair's cross-side-comparable codes to the
    per-side encode lists. The 64-bit limb split is a per-PAIR decision, and
    the eager path and the opjit traced encode both call exactly this code
    (they must agree bit-for-bit).

    On demoting backends a 64-bit key splits into two i32 limbs so the
    verified-equality pass stays EXACT (a single truncated i32 would
    silently join keys equal mod 2^32); floats were already narrowed to the
    backend's compute width upstream."""
    if native:
        l_enc.append((lb.astype(jnp.int64), l_validity))
        r_enc.append((rb.astype(jnp.int64), r_validity))
    elif lb.dtype.itemsize == 8 or rb.dtype.itemsize == 8:
        for b, out, v in ((lb, l_enc, l_validity), (rb, r_enc, r_validity)):
            b64 = b.astype(jnp.int64)
            out.append(((b64 >> 32).astype(jnp.int32), v))
            out.append((b64.astype(jnp.int32), v))
    else:
        l_enc.append((lb.astype(jnp.int32), l_validity))
        r_enc.append((rb.astype(jnp.int32), r_validity))


def _encode_sides(left_cols: List[TpuColumnVector], right_cols: List[TpuColumnVector],
                  l_rows: int, r_rows: int, l_cap: int, r_cap: int):
    """Comparable per-key codes for both sides; string keys dictionary-encode
    over the UNION of both sides so codes are cross-side comparable."""
    l_enc, r_enc = [], []
    for lc, rc in zip(left_cols, right_cols):
        if isinstance(lc.dtype, StringType):
            import pyarrow as pa
            import pyarrow.compute as pc
            la, ra = lc.to_arrow(), rc.to_arrow()
            combined = pa.concat_arrays([la.cast(pa.string()), ra.cast(pa.string())])
            enc = pc.dictionary_encode(combined)
            if isinstance(enc, pa.ChunkedArray):
                enc = enc.combine_chunks()
            codes = np.asarray(enc.indices.fill_null(-1).to_numpy(zero_copy_only=False))
            lbuf = np.zeros(l_cap, np.int64)
            lbuf[:l_rows] = codes[:l_rows]
            rbuf = np.zeros(r_cap, np.int64)
            rbuf[:r_rows] = codes[l_rows:l_rows + r_rows]
            l_enc.append((jnp.asarray(lbuf), lc.validity))
            r_enc.append((jnp.asarray(rbuf), rc.validity))
        else:
            from ..utils.hw import x64_native
            encode_fixed_key_pair(_sortable_bits(lc), _sortable_bits(rc),
                                  lc.validity, rc.validity, x64_native(),
                                  l_enc, r_enc)
    return l_enc, r_enc


import functools as _functools

import jax as _jax


@_jax.jit
def _join_probe_ranges(b_vals, b_valids, p_vals, p_valids, b_rows, p_rows):
    """Stage A of the matcher as ONE compiled program: composite hashes,
    build-side sort, range probe. On the tunneled TPU every eager op costs a
    ~100 ms dispatch round trip, so the join core MUST be whole-stage
    compiled (two programs split at the single candidate-count host sync) —
    measured: warm q3 ran 768 XLA compiles / ~3600 op dispatches eagerly."""
    from ..utils.hw import hash_plane
    uint_t, _, init, sentinel = hash_plane()
    b_cap = b_vals[0].shape[0]
    p_cap = p_vals[0].shape[0]

    def chash(vals, valids, rows, cap):
        h = jnp.full((cap,), init, uint_t)
        ok = jnp.arange(cap) < rows
        for v, vd in zip(vals, valids):
            if v.dtype.itemsize == jnp.dtype(uint_t).itemsize:
                vv = v.view(uint_t)
            else:  # cross-width: wrap cast (equality-preserving mod 2^w)
                vv = v.astype(uint_t)
            h = _mix64(h, vv)
            ok = ok & vd
        return h, ok

    bh, b_ok = chash(b_vals, b_valids, b_rows, b_cap)
    ph, p_ok = chash(p_vals, p_valids, p_rows, p_cap)
    # exclude invalid build rows: sort them to the end under a max sentinel
    sort_key = jnp.where(b_ok, bh, sentinel)
    order = jnp.argsort(sort_key)
    bh_sorted = jnp.take(sort_key, order)
    ph_safe = jnp.where(p_ok, ph, jnp.zeros((), bh.dtype))
    lo = jnp.searchsorted(bh_sorted, ph_safe, side="left")
    hi = jnp.searchsorted(bh_sorted, ph_safe, side="right")
    counts = jnp.where(p_ok, hi - lo, 0)
    return counts, lo, order, b_ok, p_ok, jnp.sum(counts)


@_functools.partial(_jax.jit, static_argnames=("out_cap",))
def _join_emit_pairs(counts, lo, order, b_ok, p_ok, b_vals, p_vals, total,
                     out_cap: int):
    """Stage B: expand candidate ranges into verified pairs (one program;
    out_cap is the bucketed static output shape). Also returns the verified
    pair count as a DEVICE scalar so it never needs its own blocking read —
    it either rides the joined batch's boundary device_get (deferred
    compaction) or fuses into the single eager sync below."""
    p_cap = counts.shape[0]
    b_cap = order.shape[0]
    ends = jnp.cumsum(counts)
    starts = ends - counts
    j = jnp.arange(out_cap)
    pi = jnp.clip(jnp.searchsorted(ends, j, side="right"),
                  0, p_cap - 1).astype(jnp.int32)
    off = j - jnp.take(starts, pi)
    bi_sorted = jnp.take(lo, pi) + off
    bi = jnp.take(order, jnp.clip(bi_sorted, 0, b_cap - 1)).astype(jnp.int32)
    ok = (j < total) & jnp.take(b_ok, bi) & jnp.take(p_ok, pi)
    for bv, pv in zip(b_vals, p_vals):
        ok = ok & (jnp.take(bv, bi) == jnp.take(pv, pi))
    return pi, bi, ok, jnp.sum(ok)


def _device_equi_join(build_enc, build_rows: int, probe_enc, probe_rows: int):
    """Core matcher. Returns (pair_probe_idx, pair_build_idx, verified_mask,
    total_candidates, out_capacity). Index arrays have out_capacity entries."""
    b_cap = build_enc[0][0].shape[0]
    p_cap = probe_enc[0][0].shape[0]

    def split(enc, cap):
        vals = [v for v, _ in enc]
        valids = [vd if vd is not None else jnp.ones((cap,), jnp.bool_)
                  for _, vd in enc]
        return vals, valids

    b_vals, b_valids = split(build_enc, b_cap)
    p_vals, p_valids = split(probe_enc, p_cap)
    counts, lo, order, b_ok, p_ok, total_dev = _join_probe_ranges(
        b_vals, b_valids, p_vals, p_valids,
        jnp.int32(build_rows), jnp.int32(probe_rows))
    from ..columnar.vector import audited_sync_int
    # host sync: candidate-pair count (it sizes the static output shape, so
    # it cannot defer); the VERIFIED count below stays a device scalar
    total = audited_sync_int(total_dev, "pairs")
    out_cap = bucket_capacity(max(total, 1))
    pi, bi, ok, n_ok = _join_emit_pairs(counts, lo, order, b_ok, p_ok,
                                        b_vals, p_vals, jnp.int32(total),
                                        out_cap=out_cap)
    return pi, bi, ok, n_ok, total, out_cap


@_jax.jit
def _compact_pairs_device(pi, bi, ok, n):
    out_cap = pi.shape[0]
    pos = jnp.cumsum(ok) - 1
    idx = jnp.full((out_cap,), out_cap, jnp.int32)
    idx = idx.at[jnp.where(ok, pos, out_cap)].set(
        jnp.arange(out_cap, dtype=jnp.int32), mode="drop")
    take = jnp.clip(idx, 0, out_cap - 1)
    slot_ok = jnp.arange(out_cap) < n
    return jnp.take(pi, take), jnp.take(bi, take), slot_ok


def _compact_pairs(pi, bi, ok, n_ok, deferred: bool):
    """Stable-compact verified pairs (one compiled program). The kept count
    `n_ok` arrives as a device scalar from the emit program: deferred mode
    keeps it on device (the joined batch carries it to the boundary);
    otherwise it syncs here — fused with the candidate-count read into the
    join's single per-batch scalar accounting, instead of the historical
    second `int(jnp.sum(ok))` round trip."""
    n = n_ok if deferred else _audited_pairs_int(n_ok)
    a, b, slot_ok = _compact_pairs_device(pi, bi, ok, jnp.int32(n))
    return a, b, slot_ok, n


def _audited_pairs_int(n_dev) -> int:
    from ..columnar.vector import audited_sync_int
    return audited_sync_int(n_dev, "pairs")


def _all_null_cols(attrs_or_cols, num_rows: int, capacity: int):
    out = []
    for c in attrs_or_cols:
        dt = c.dtype
        out.append(TpuColumnVector.from_scalar(None, dt, num_rows, capacity))
    return out


class TpuShuffledHashJoinExec(TpuExec):
    """Equi-join with optional residual condition (reference
    GpuShuffledHashJoinExec; build side = right, Spark's BuildRight default)."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, join_type: str,
                 left_keys: Sequence[Expression], right_keys: Sequence[Expression],
                 condition: Optional[Expression],
                 output: List[AttributeReference], per_partition: bool = False):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = bind_all(list(left_keys), left.output)
        self.right_keys = bind_all(list(right_keys), right.output)
        self.condition = (bind_references(condition, left.output + right.output)
                          if condition is not None else None)
        self._output = output
        # per_partition: both sides are co-partitioned by the join keys (hash
        # exchanges below us) so each partition joins independently
        self.per_partition = per_partition

    @property
    def output(self):
        return self._output

    def num_partitions(self) -> int:
        return self.children[0].num_partitions() if self.per_partition else 1

    def node_desc(self) -> str:
        return f"TpuShuffledHashJoin[{self.join_type}]"

    def additional_metrics(self):
        return {"buildTime": "MODERATE", "joinTime": "MODERATE",
                "numPairs": "DEBUG"}

    def _collect_side(self, child: PhysicalPlan, ctx, idx: int) -> Optional[TpuColumnarBatch]:
        batches = []
        if self.per_partition:
            batches.extend(child.execute_partition(idx, ctx))
        else:
            for p in range(child.num_partitions()):
                batches.extend(child.execute_partition(p, ctx))
        return concat_batches(batches) if batches else None

    def _collect_sides(self, ctx, idx: int):
        """Collect both join inputs. The two sides are independent subtrees,
        so with shuffle pipelining enabled the build side materializes on a
        worker thread while the probe side materializes here — its shuffle
        reads, uploads and device dispatches overlap instead of running
        back-to-back (device concurrency stays bounded by the semaphore)."""
        from ..config import SHUFFLE_PIPELINE_ENABLED
        if ctx.conf.get(SHUFFLE_PIPELINE_ENABLED):
            import threading
            from ..obs import tracer as _obs
            res: dict = {}
            # per-query tracing routes by thread: the side-collector thread
            # inherits this query's tracer via the captured handoff token,
            # so its shuffle reads/uploads/dispatches stay in THIS query's
            # record (no-op when untraced)
            obs_parent = _obs.current_span()
            # the query lifecycle binding rides the same handoff: a
            # cancel/deadline trips the build-side collection too
            from ..serving import query_context as _qlc
            qctx = _qlc.current()

            def collect_right():
                try:
                    with _obs.inherit(obs_parent), _qlc.bind(qctx):
                        res["right"] = self._collect_side(self.children[1],
                                                          ctx, idx)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    res["err"] = e

            t = threading.Thread(target=collect_right, name="join-side")
            t.start()
            try:
                left = self._collect_side(self.children[0], ctx, idx)
            finally:
                t.join()
            if "err" in res:
                raise res["err"]
            return left, res["right"]
        return (self._collect_side(self.children[0], ctx, idx),
                self._collect_side(self.children[1], ctx, idx))

    def internal_do_execute_columnar(self, idx: int, ctx: TaskContext) -> Iterator:
        left, right = self._collect_sides(ctx, idx)
        jt = self.join_type
        names = [a.name for a in self._output]
        l_empty = left is None or left.num_rows == 0
        r_empty = right is None or right.num_rows == 0
        if l_empty or r_empty:
            out = self._join_pair(left if not l_empty else None,
                                  right if not r_empty else None, names, ctx)
            if out is not None and out.num_rows:
                yield out
            return
        from ..config import BATCH_SIZE_ROWS
        max_rows = ctx.conf.get(BATCH_SIZE_ROWS)
        if self.left_keys and max(left.num_rows, right.num_rows) > max_rows:
            # sub-partitioning: both sides split by the same key hash, each
            # pair joined independently — keys land in exactly one pair so
            # outer/semi/anti semantics compose (reference
            # GpuSubPartitionHashJoin.scala)
            from ..shuffle.partitioner import hash_split_parts
            k = max(2, -(-max(left.num_rows, right.num_rows) // max_rows))
            # seed 100 (not the exchange's 42): upstream co-partitioning fixes
            # h42 % N, so re-bucketing with the same seed would collapse into
            # few sub-partitions (GpuSubPartitionHashJoin.scala hashSeed=100).
            # Each side's encode+split pair runs as one cached executable
            # when the keys trace (opjit.partition_split_plan).
            l_parts = hash_split_parts(left, self.left_keys, k, ctx, seed=100,
                                       metrics=self.metrics)
            r_parts = hash_split_parts(right, self.right_keys, k, ctx,
                                       seed=100, metrics=self.metrics)
            with self.metrics["joinTime"].timed():
                for lp, rp in zip(l_parts, r_parts):
                    out = self._join_pair(lp, rp, names, ctx)
                    if out is not None and out.num_rows:
                        yield out
            return
        with self.metrics["joinTime"].timed():
            yield self._join(left, right, ctx)

    def _join_pair(self, lp, rp, names, ctx):
        """One sub-partition pair with the empty-side fast paths preserved."""
        jt = self.join_type
        l_empty = lp is None or lp.num_rows == 0
        r_empty = rp is None or rp.num_rows == 0
        if l_empty and r_empty:
            return None
        if l_empty:
            if jt in ("rightouter", "right", "fullouter", "outer", "full"):
                nulls_l = _all_null_cols(self.children[0].output,
                                         rp.num_rows, rp.capacity)
                return TpuColumnarBatch(nulls_l + rp.columns, rp.num_rows,
                                        names)
            return None
        if r_empty:
            if jt in ("leftanti", "anti"):
                return lp.rename(names)
            if jt in ("leftouter", "left", "fullouter", "outer", "full"):
                # only left/full outer pad unmatched left rows; a right outer
                # join emits nothing for a partition with no right rows
                nulls_r = _all_null_cols(self.children[1].output,
                                         lp.num_rows, lp.capacity)
                return TpuColumnarBatch(lp.columns + nulls_r, lp.num_rows,
                                        names)
            return None
        return self._join(lp, rp, ctx)

    def _join(self, left: TpuColumnarBatch, right: TpuColumnarBatch,
              ctx: TaskContext) -> TpuColumnarBatch:
        jt = self.join_type
        names = [a.name for a in self._output]
        l_cap, r_cap = left.capacity, right.capacity
        # key eval + sortable-bit encode for BOTH sides as one cached
        # executable (execs/opjit.py); string/host keys keep the eager path
        from . import opjit
        enc = opjit.encode_join_sides(self.left_keys, self.right_keys,
                                      left, right, ctx.eval_ctx,
                                      self.metrics)
        if enc is not None:
            l_enc, r_enc = enc
        else:
            lk = [to_column(k.eval_tpu(left, ctx.eval_ctx), left, k.dtype)
                  for k in self.left_keys]
            rk = [to_column(k.eval_tpu(right, ctx.eval_ctx), right, k.dtype)
                  for k in self.right_keys]
            l_enc, r_enc = _encode_sides(lk, rk, left.num_rows,
                                         right.num_rows, l_cap, r_cap)
        # probe = left, build = right
        pi, bi, ok, n_ok, total, out_cap = _device_equi_join(
            r_enc, right.num_rows, l_enc, left.num_rows)
        self.metrics["numPairs"].add(total)
        from ..config import DEFERRED_COMPACTION
        deferred = bool(ctx.conf.get(DEFERRED_COMPACTION))
        cpi, cbi, slot_ok, n_pairs = _compact_pairs(pi, bi, ok, n_ok,
                                                    deferred)

        lg = gather(left, jnp.where(slot_ok, cpi, -1), n_pairs, out_cap)
        rg = gather(right, jnp.where(slot_ok, cbi, -1), n_pairs, out_cap)
        joined = TpuColumnarBatch(lg.columns + rg.columns, n_pairs)

        pair_keep = slot_ok
        if self.condition is not None:
            cond = to_column(self.condition.eval_tpu(joined, ctx.eval_ctx), joined)
            keep = cond.data.astype(jnp.bool_)
            if cond.validity is not None:
                keep = keep & cond.validity
            pair_keep = pair_keep & keep
            joined = compact(joined, keep, deferred=deferred)

        if jt in ("inner", "cross"):
            # deferred: the verified-pair count rides the joined batch as a
            # device scalar to the exchange/collect boundary
            return joined.rename(names)

        # bookkeeping over VERIFIED+residual-surviving pairs
        match_cnt = jnp.zeros((l_cap + 1,), jnp.int32).at[
            jnp.where(pair_keep, cpi, l_cap)].add(1, mode="drop")[:l_cap]
        build_matched = jnp.zeros((r_cap + 1,), jnp.bool_).at[
            jnp.where(pair_keep, cbi, r_cap)].max(True, mode="drop")[:r_cap]

        lmask = row_mask(left.num_rows, l_cap)
        if jt in ("leftsemi", "semi"):
            return compact(left, (match_cnt > 0) & lmask).rename(names)
        if jt in ("leftanti", "anti"):
            return compact(left, (match_cnt == 0) & lmask).rename(names)

        parts = [joined] if joined.num_rows else []
        if jt in ("leftouter", "left", "fullouter", "outer", "full"):
            unmatched_l = compact(left, (match_cnt == 0) & lmask)
            if unmatched_l.num_rows:
                nulls_r = _all_null_cols(right.columns, unmatched_l.num_rows,
                                         unmatched_l.capacity)
                parts.append(TpuColumnarBatch(unmatched_l.columns + nulls_r,
                                              unmatched_l.num_rows))
        if jt in ("rightouter", "right", "fullouter", "outer", "full"):
            rmask = row_mask(right.num_rows, r_cap)
            unmatched_r = compact(right, (~build_matched) & rmask)
            if unmatched_r.num_rows:
                nulls_l = _all_null_cols(left.columns, unmatched_r.num_rows,
                                         unmatched_r.capacity)
                parts.append(TpuColumnarBatch(nulls_l + unmatched_r.columns,
                                              unmatched_r.num_rows))
        if not parts:
            parts = [joined]
        return concat_batches(parts).rename(names)


class TpuBroadcastNestedLoopJoinExec(TpuExec):
    """Cross join / conditional non-equi join (reference
    GpuBroadcastNestedLoopJoinExec). Blockwise cartesian expansion + filter."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, join_type: str,
                 condition: Optional[Expression],
                 output: List[AttributeReference]):
        super().__init__([left, right])
        self.join_type = join_type
        self.condition = (bind_references(condition, left.output + right.output)
                          if condition is not None else None)
        self._output = output

    @property
    def output(self):
        return self._output

    def num_partitions(self) -> int:
        return 1

    def node_desc(self) -> str:
        return f"TpuBroadcastNestedLoopJoin[{self.join_type}]"

    def internal_do_execute_columnar(self, idx: int, ctx: TaskContext) -> Iterator:
        def side(child):
            batches = []
            for p in range(child.num_partitions()):
                batches.extend(child.execute_partition(p, ctx))
            return concat_batches(batches) if batches else None

        left, right = side(self.children[0]), side(self.children[1])
        jt = self.join_type
        names = [a.name for a in self._output]
        l_empty = left is None or not left.num_rows
        r_empty = right is None or not right.num_rows
        if l_empty or r_empty:
            # empty-side semantics (reference GpuBroadcastNestedLoopJoinExec
            # computeBuildRowCount special cases)
            if not l_empty:
                if jt in ("leftsemi", "semi"):
                    return
                if jt in ("leftanti", "anti"):
                    yield left.rename(names)
                    return
                if jt in ("leftouter", "left", "fullouter", "outer", "full"):
                    nulls_r = _all_null_cols(self.children[1].output,
                                             left.num_rows, left.capacity)
                    yield TpuColumnarBatch(left.columns + nulls_r,
                                           left.num_rows, names)
                    return
            if not r_empty and jt in ("rightouter", "right", "fullouter",
                                      "outer", "full"):
                nulls_l = _all_null_cols(self.children[0].output,
                                         right.num_rows, right.capacity)
                yield TpuColumnarBatch(nulls_l + right.columns,
                                       right.num_rows, names)
            return
        n_l, n_r = left.num_rows, right.num_rows
        total = n_l * n_r
        out_cap = bucket_capacity(max(total, 1))
        j = jnp.arange(out_cap)
        li = jnp.where(j < total, j // n_r, -1).astype(jnp.int32)
        ri = jnp.where(j < total, j % n_r, -1).astype(jnp.int32)
        lg = gather(left, li, total, out_cap)
        rg = gather(right, ri, total, out_cap)
        joined = TpuColumnarBatch(lg.columns + rg.columns, total)
        keep = j < total
        if self.condition is not None:
            cond = to_column(self.condition.eval_tpu(joined, ctx.eval_ctx), joined)
            keep = keep & cond.data.astype(jnp.bool_)
            if cond.validity is not None:
                keep = keep & cond.validity
        if jt in ("inner", "cross"):
            if self.condition is None:
                yield joined.rename(names)  # keep == row mask: no copy needed
            else:
                yield compact(joined, keep).rename(names)
            return
        # per-side match flags (scatter-max over pair keep mask; padding pairs
        # route to the dropped slot n)
        safe_li = jnp.where(j < total, li, n_l)
        safe_ri = jnp.where(j < total, ri, n_r)
        l_matched = jnp.zeros((n_l,), jnp.bool_).at[safe_li].max(keep, mode="drop")
        r_matched = jnp.zeros((n_r,), jnp.bool_).at[safe_ri].max(keep, mode="drop")
        l_pad = jnp.zeros((left.capacity,), jnp.bool_).at[
            jnp.arange(n_l)].set(l_matched)
        r_pad = jnp.zeros((right.capacity,), jnp.bool_).at[
            jnp.arange(n_r)].set(r_matched)
        if jt in ("leftsemi", "semi"):
            yield compact(left, l_pad).rename(names)
            return
        if jt in ("leftanti", "anti"):
            mask = (~l_pad) & row_mask(left.num_rows, left.capacity)
            yield compact(left, mask).rename(names)
            return
        parts = [compact(joined, keep)]
        if jt in ("leftouter", "left", "fullouter", "outer", "full"):
            lo_mask = (~l_pad) & row_mask(left.num_rows, left.capacity)
            lo = compact(left, lo_mask)
            if lo.num_rows:
                nulls_r = _all_null_cols(self.children[1].output,
                                         lo.num_rows, lo.capacity)
                parts.append(TpuColumnarBatch(lo.columns + nulls_r, lo.num_rows))
        if jt in ("rightouter", "right", "fullouter", "outer", "full"):
            ro_mask = (~r_pad) & row_mask(right.num_rows, right.capacity)
            ro = compact(right, ro_mask)
            if ro.num_rows:
                nulls_l = _all_null_cols(self.children[0].output,
                                         ro.num_rows, ro.capacity)
                parts.append(TpuColumnarBatch(nulls_l + ro.columns, ro.num_rows))
        yield concat_batches(parts).rename(names)


# ---------------------------------------------------------------------------
# CPU oracle
# ---------------------------------------------------------------------------

_ARROW_JOIN_TYPE = {"inner": "inner", "leftouter": "left outer", "left": "left outer",
                    "rightouter": "right outer", "right": "right outer",
                    "fullouter": "full outer", "outer": "full outer",
                    "full": "full outer", "leftsemi": "left semi",
                    "semi": "left semi", "leftanti": "left anti",
                    "anti": "left anti"}


class CpuShuffledHashJoinExec(CpuExec):
    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, join_type: str,
                 left_keys: Sequence[Expression], right_keys: Sequence[Expression],
                 condition: Optional[Expression],
                 output: List[AttributeReference], per_partition: bool = False):
        super().__init__([left, right])
        self.join_type = join_type
        self.left_keys = bind_all(list(left_keys), left.output)
        self.right_keys = bind_all(list(right_keys), right.output)
        self.condition = (bind_references(condition, left.output + right.output)
                          if condition is not None else None)
        self._output = output
        self.per_partition = per_partition

    @property
    def output(self):
        return self._output

    def num_partitions(self) -> int:
        return self.children[0].num_partitions() if self.per_partition else 1

    def node_desc(self) -> str:
        return f"CpuShuffledHashJoin[{self.join_type}]"

    def _side_table(self, child, ctx, prefix: str, idx: int = 0):
        """Collect one side with positionally-unique column names (both sides may
        share user-visible names; expressions bind by ordinal, not name)."""
        import pyarrow as pa
        from ..types import to_arrow
        tables = []
        if self.per_partition:
            tables.extend(child.execute_partition(idx, ctx))
        else:
            for p in range(child.num_partitions()):
                tables.extend(child.execute_partition(p, ctx))
        names = [f"{prefix}{i}" for i in range(len(child.output))]
        if tables:
            return pa.concat_tables(
                [t.rename_columns(names) for t in tables])
        return pa.schema([(n, to_arrow(a.dtype))
                          for n, a in zip(names, child.output)]).empty_table()

    def execute_partition(self, idx: int, ctx: TaskContext) -> Iterator:
        import pyarrow as pa
        import pyarrow.compute as pc
        lt = self._side_table(self.children[0], ctx, "l", idx)
        rt = self._side_table(self.children[1], ctx, "r", idx)
        jt = self.join_type
        n_l = len(self.children[0].output)
        n_r = len(self.children[1].output)
        lkeys, rkeys = [], []
        for i, (lk, rk) in enumerate(zip(self.left_keys, self.right_keys)):
            la = _norm_key(_as_arr(lk.eval_cpu(lt, ctx.eval_ctx)))
            ra = _norm_key(_as_arr(rk.eval_cpu(rt, ctx.eval_ctx)))
            la, ra = _align_key_pair(la, ra)
            lt = lt.append_column(f"__lk_{i}", la)
            lkeys.append(f"__lk_{i}")
            rt = rt.append_column(f"__rk_{i}", ra)
            rkeys.append(f"__rk_{i}")
        l_out = [f"l{i}" for i in range(n_l)]
        r_out = [f"r{i}" for i in range(n_r)]
        if jt in ("leftsemi", "semi", "leftanti", "anti"):
            sel = l_out
        else:
            sel = l_out + r_out
        out_names = [a.name for a in self._output]
        if self.condition is not None:
            yield self._conditional(lt, rt, lkeys, rkeys, l_out, r_out,
                                    sel, out_names, ctx)
            return
        res = lt.join(rt, keys=lkeys, right_keys=rkeys,
                      join_type=_ARROW_JOIN_TYPE[jt], coalesce_keys=False)
        yield res.select(sel).rename_columns(out_names)

    def _conditional(self, lt, rt, lkeys, rkeys, l_out, r_out, sel, out_names, ctx):
        """Residual condition joins: inner pairs + filter, then reconstruct
        unmatched rows via row ids."""
        import pyarrow as pa
        import pyarrow.compute as pc
        from ..types import to_arrow
        jt = self.join_type
        lt = lt.append_column("__lrow", pa.array(np.arange(lt.num_rows)))
        rt = rt.append_column("__rrow", pa.array(np.arange(rt.num_rows)))
        inner = lt.join(rt, keys=lkeys, right_keys=rkeys, join_type="inner",
                        coalesce_keys=False)
        mask = pc.fill_null(self.condition.eval_cpu(
            inner.select(l_out + r_out), ctx.eval_ctx), False)
        kept = inner.filter(mask)
        if jt in ("inner", "cross"):
            return kept.select(sel).rename_columns(out_names)

        # vectorized match flags: pc.is_in of the full row-id range against
        # the surviving pairs' row ids. The previous set(to_pylist()) +
        # per-row `i in set` python loop dominated parity-test time on wide
        # inputs (O(rows) python-level membership tests per side).
        def matched(table, row_col):
            ids = pa.array(np.arange(table.num_rows, dtype=np.int64))
            vals = kept.column(row_col).combine_chunks()
            return pc.is_in(ids, value_set=vals)

        if jt in ("leftsemi", "semi"):
            return lt.filter(matched(lt, "__lrow")).select(sel) \
                .rename_columns(out_names)
        if jt in ("leftanti", "anti"):
            keep = pc.invert(matched(lt, "__lrow"))
            return lt.filter(keep).select(sel).rename_columns(out_names)
        parts = [kept.select(sel)]
        r_attrs = self.children[1].output
        l_attrs = self.children[0].output
        if jt in ("leftouter", "left", "fullouter", "outer", "full"):
            lu = lt.filter(pc.invert(matched(lt, "__lrow"))).select(l_out)
            for name, a in zip(r_out, r_attrs):
                lu = lu.append_column(name, pa.nulls(lu.num_rows, to_arrow(a.dtype)))
            parts.append(lu.select(sel))
        if jt in ("rightouter", "right", "fullouter", "outer", "full"):
            ru = rt.filter(pc.invert(matched(rt, "__rrow"))).select(r_out)
            for name, a in reversed(list(zip(l_out, l_attrs))):
                ru = ru.add_column(0, name, pa.nulls(ru.num_rows, to_arrow(a.dtype)))
            parts.append(ru.select(sel))
        return pa.concat_tables(parts).rename_columns(out_names)


def _as_arr(x):
    import pyarrow as pa
    return x.combine_chunks() if isinstance(x, pa.ChunkedArray) else x


def _align_key_pair(la, ra):
    """Promote mismatched join-key types to a common comparable type
    (date32 vs int as day numbers — shared rule with the comparison
    predicates; int widths to the wider) — the device plane compares via
    width-normalized sortable bits, so the CPU oracle must accept the same
    pairs."""
    import pyarrow as pa
    from ..expressions.predicates import _align_date_int

    both_arr = all(isinstance(x, (pa.Array, pa.ChunkedArray))
                   for x in (la, ra))
    if both_arr and la.type != ra.type:
        la, ra = _align_date_int(pa, la, ra)
        if pa.types.is_date32(la.type) or pa.types.is_date32(ra.type):
            # date vs non-int (e.g. date32 vs int64-backed date): day numbers
            la = la.cast(pa.int32()) if pa.types.is_date32(la.type) else la
            ra = ra.cast(pa.int32()) if pa.types.is_date32(ra.type) else ra
        if pa.types.is_integer(la.type) and pa.types.is_integer(ra.type) \
                and la.type != ra.type:
            target = (la.type if la.type.bit_width >= ra.type.bit_width
                      else ra.type)
            la, ra = la.cast(target), ra.cast(target)
    return la, ra


def _norm_key(arr):
    """NaN/-0.0 normalization for join keys (Spark: NaN==NaN in joins)."""
    import pyarrow as pa
    import pyarrow.compute as pc
    if isinstance(arr, (pa.Array, pa.ChunkedArray)) and pa.types.is_floating(arr.type):
        zero = pa.scalar(0.0, arr.type)
        arr = pc.if_else(pc.equal(arr, zero), zero, arr)
    return arr


class CpuBroadcastNestedLoopJoinExec(CpuExec):
    def __init__(self, left: PhysicalPlan, right: PhysicalPlan, join_type: str,
                 condition: Optional[Expression],
                 output: List[AttributeReference]):
        super().__init__([left, right])
        self.join_type = join_type
        self.condition = (bind_references(condition, left.output + right.output)
                          if condition is not None else None)
        self._output = output

    @property
    def output(self):
        return self._output

    def num_partitions(self) -> int:
        return 1

    def node_desc(self) -> str:
        return f"CpuBroadcastNestedLoopJoin[{self.join_type}]"

    def execute_partition(self, idx: int, ctx: TaskContext) -> Iterator:
        import pyarrow as pa
        import pyarrow.compute as pc

        def side(child, prefix):
            tables = []
            for p in range(child.num_partitions()):
                tables.extend(child.execute_partition(p, ctx))
            names = [f"{prefix}{i}" for i in range(len(child.output))]
            if tables:
                return pa.concat_tables([t.rename_columns(names) for t in tables])
            from ..types import to_arrow
            return pa.schema([(n, to_arrow(a.dtype))
                              for n, a in zip(names, child.output)]).empty_table()

        lt, rt = side(self.children[0], "l"), side(self.children[1], "r")
        n_l, n_r = lt.num_rows, rt.num_rows
        jt = self.join_type
        names = [a.name for a in self._output]

        def with_nulls(keep_t, null_src, left_side: bool):
            kept = [keep_t.column(i) for i in range(keep_t.num_columns)]
            nulls = [pa.nulls(keep_t.num_rows, null_src.column(i).type)
                     for i in range(null_src.num_columns)]
            cols = kept + nulls if left_side else nulls + kept
            # from_arrays, not pa.table(dict(...)): output names may repeat
            return pa.Table.from_arrays(
                [c.combine_chunks() if isinstance(c, pa.ChunkedArray) else c
                 for c in cols], names=names)

        if n_l == 0 or n_r == 0:
            if n_l:
                if jt in ("leftanti", "anti"):
                    yield lt.rename_columns(names)
                elif jt in ("leftouter", "left", "fullouter", "outer", "full"):
                    yield with_nulls(lt, rt, True)
            elif n_r and jt in ("rightouter", "right", "fullouter", "outer",
                                "full"):
                yield with_nulls(rt, lt, False)
            return
        li = np.repeat(np.arange(n_l), n_r)
        ri = np.tile(np.arange(n_r), n_l)
        joined = lt.take(pa.array(li))
        for i, name in enumerate(rt.column_names):
            joined = joined.append_column(name, rt.column(i).take(pa.array(ri)))
        if self.condition is not None:
            mask = self.condition.eval_cpu(joined, ctx.eval_ctx)
            mask_np = np.asarray(pc.fill_null(
                pa.array(mask) if not isinstance(mask, (pa.Array, pa.ChunkedArray))
                else mask, False))
        else:
            mask_np = np.ones(n_l * n_r, bool)
        if jt in ("inner", "cross"):
            yield joined.filter(pa.array(mask_np)).rename_columns(names)
            return
        l_matched = np.zeros(n_l, bool)
        l_matched[li[mask_np]] = True
        r_matched = np.zeros(n_r, bool)
        r_matched[ri[mask_np]] = True
        if jt in ("leftsemi", "semi"):
            yield lt.filter(pa.array(l_matched)).rename_columns(names)
            return
        if jt in ("leftanti", "anti"):
            yield lt.filter(pa.array(~l_matched)).rename_columns(names)
            return
        parts = [joined.filter(pa.array(mask_np)).rename_columns(names)]
        if jt in ("leftouter", "left", "fullouter", "outer", "full"):
            lo = lt.filter(pa.array(~l_matched))
            if lo.num_rows:
                parts.append(with_nulls(lo, rt, True))
        if jt in ("rightouter", "right", "fullouter", "outer", "full"):
            ro = rt.filter(pa.array(~r_matched))
            if ro.num_rows:
                parts.append(with_nulls(ro, lt, False))
        yield pa.concat_tables(parts)





# ---------------------------------------------------------------------------
# symmetric shuffled hash join (reference GpuShuffledSymmetricHashJoinExec,
# 1225 LoC: the join that picks its build side by the size actually
# materialized per partition rather than trusting the planner's estimate)
# ---------------------------------------------------------------------------

_MIRROR_JOIN = {"inner": "inner", "cross": "cross",
                "leftouter": "rightouter", "left": "rightouter",
                "rightouter": "leftouter", "right": "leftouter",
                "fullouter": "fullouter", "outer": "fullouter",
                "full": "fullouter"}


class TpuShuffledSymmetricHashJoinExec(TpuShuffledHashJoinExec):
    """Size-adaptive build side: each partition builds on whichever side
    materialized smaller, flipping the join orientation (and mirroring the
    join type) when the left is the better build side. Semi/anti joins are
    direction-bound and keep the fixed orientation."""

    def __init__(self, left, right, join_type, left_keys, right_keys,
                 condition, output, per_partition: bool = False):
        super().__init__(left, right, join_type, left_keys, right_keys,
                         condition, output, per_partition)
        self._can_flip = join_type in _MIRROR_JOIN
        if self._can_flip:
            self._twin = TpuShuffledHashJoinExec(
                right, left, _MIRROR_JOIN[join_type], right_keys, left_keys,
                condition, list(right.output) + list(left.output),
                per_partition)
            self._n_left_cols = len(left.output)

    def node_desc(self) -> str:
        return f"TpuShuffledSymmetricHashJoin[{self.join_type}]"

    def additional_metrics(self):
        m = dict(super().additional_metrics())
        m["buildSideFlips"] = "DEBUG"
        return m

    def _join(self, left: TpuColumnarBatch, right: TpuColumnarBatch,
              ctx: TaskContext) -> TpuColumnarBatch:
        # the base implementation builds on the RIGHT; flip when the left is
        # smaller so the hash table always comes from the smaller side
        if self._can_flip and left.num_rows < right.num_rows:
            self.metrics["buildSideFlips"].add(1)
            self._twin.metrics = self.metrics  # shared sink
            out = self._twin._join(right, left, ctx)
            nl = self._n_left_cols
            cols = out.columns[len(out.columns) - nl:] + \
                out.columns[: len(out.columns) - nl]
            names = [a.name for a in self._output]
            return TpuColumnarBatch(cols, out.num_rows, names)
        return super()._join(left, right, ctx)


# ---------------------------------------------------------------------------
# cartesian product (reference org/apache/spark/sql/rapids/
# GpuCartesianProductExec.scala: dedicated pairwise-partition product for
# large×large inner joins where neither side broadcasts)
# ---------------------------------------------------------------------------

class CpuCartesianProductExec(CpuExec):
    """Host cartesian product: output partition k = left part (k // nr) ×
    right part (k % nr)."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 condition: Optional[Expression],
                 output: List[AttributeReference]):
        super().__init__([left, right])
        self.condition = (bind_references(condition, left.output + right.output)
                          if condition is not None else None)
        self._output = output

    @property
    def output(self):
        return self._output

    def num_partitions(self) -> int:
        return self.children[0].num_partitions() * \
            self.children[1].num_partitions()

    def node_desc(self) -> str:
        return "CpuCartesianProduct"

    def _pair_tables(self, idx: int, ctx: TaskContext):
        import pyarrow as pa
        from ..types import to_arrow
        nr = self.children[1].num_partitions()
        li, ri = idx // nr, idx % nr

        def side(child, p, prefix):
            tables = list(child.execute_partition(p, ctx))
            names = [f"{prefix}{i}" for i in range(len(child.output))]
            if tables:
                return pa.concat_tables([t.rename_columns(names)
                                         for t in tables])
            return pa.schema([(n, to_arrow(a.dtype))
                              for n, a in zip(names, child.output)]).empty_table()

        return side(self.children[0], li, "l"), side(self.children[1], ri, "r")

    def execute_partition(self, idx: int, ctx: TaskContext) -> Iterator:
        import numpy as np
        import pyarrow as pa
        lt, rt = self._pair_tables(idx, ctx)
        nl, nr_rows = lt.num_rows, rt.num_rows
        if nl == 0 or nr_rows == 0:
            return
        li = np.repeat(np.arange(nl), nr_rows)
        ri = np.tile(np.arange(nr_rows), nl)
        joined = pa.Table.from_arrays(
            [lt.column(i).take(pa.array(li)) for i in range(lt.num_columns)]
            + [rt.column(i).take(pa.array(ri)) for i in range(rt.num_columns)],
            names=list(lt.column_names) + list(rt.column_names))
        if self.condition is not None:
            import pyarrow.compute as pc
            keep = self.condition.eval_cpu(joined, ctx.eval_ctx)
            joined = joined.filter(pc.fill_null(keep, False))
        yield joined.rename_columns([a.name for a in self._output])


class TpuCartesianProductExec(TpuExec):
    """Device cartesian product: the repeat/tile expansion is two gathers over
    an index grid — the same kernel BNLJ uses, but scoped to one
    (left-partition, right-partition) pair per output partition so the
    expansion never exceeds a partition pair's footprint."""

    def __init__(self, left: PhysicalPlan, right: PhysicalPlan,
                 condition: Optional[Expression],
                 output: List[AttributeReference]):
        super().__init__([left, right])
        self.condition = (bind_references(condition, left.output + right.output)
                          if condition is not None else None)
        self._output = output

    @property
    def output(self):
        return self._output

    def num_partitions(self) -> int:
        return self.children[0].num_partitions() * \
            self.children[1].num_partitions()

    def node_desc(self) -> str:
        return "TpuCartesianProduct"

    def additional_metrics(self):
        return {"joinTime": "MODERATE", "numPairs": "DEBUG"}

    def internal_do_execute_columnar(self, idx: int, ctx: TaskContext) -> Iterator:
        nr = self.children[1].num_partitions()
        li, ri = idx // nr, idx % nr

        def side(child, p):
            batches = list(child.execute_partition(p, ctx))
            return concat_batches(batches) if batches else None

        left, right = side(self.children[0], li), side(self.children[1], ri)
        if left is None or right is None or not left.num_rows \
                or not right.num_rows:
            return
        names = [a.name for a in self._output]
        n_l, n_r = left.num_rows, right.num_rows
        total = n_l * n_r
        self.metrics["numPairs"].add(total)
        with self.metrics["joinTime"].timed():
            out_cap = bucket_capacity(max(total, 1))
            j = jnp.arange(out_cap)
            gl = gather(left, jnp.where(j < total, j // n_r, -1).astype(jnp.int32),
                        total, out_cap)
            gr = gather(right, jnp.where(j < total, j % n_r, -1).astype(jnp.int32),
                        total, out_cap)
            joined = TpuColumnarBatch(gl.columns + gr.columns, total)
            if self.condition is not None:
                cond = to_column(self.condition.eval_tpu(joined, ctx.eval_ctx),
                                 joined)
                keep = (j < total) & cond.data.astype(jnp.bool_)
                if cond.validity is not None:
                    keep = keep & cond.validity
                joined = compact(joined, keep)
            if joined.num_rows:
                yield joined.rename(names)
