"""Join execs — land in the joins milestone (next)."""


def plan_cpu_join(plan, conf):
    raise NotImplementedError("joins land in the next milestone")
