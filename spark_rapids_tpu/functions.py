"""pyspark.sql.functions-compatible surface."""

from __future__ import annotations

from typing import Any, List, Optional, Union

from .expressions import arithmetic as _A
from .expressions import aggregates as _G
from .expressions import conditional as _C
from .expressions import hashexprs as _H
from .expressions import mathexprs as _M
from .expressions import nullexprs as _N
from .expressions import predicates as _P
from .expressions import strings as _S
from .expressions.base import Alias, Expression, Literal, UnresolvedAttribute
from .expressions.cast import Cast
from .session import Column, _expr


def col(name: str) -> Column:
    return Column(UnresolvedAttribute(name))


column = col


def lit(value: Any) -> Column:
    return Column(Literal(value))


def expr_col(e: Expression) -> Column:
    return Column(e)


def alias(c, name: str) -> Column:
    return Column(Alias(_expr(c), name))


# --- conditional -----------------------------------------------------------

class WhenBuilder(Column):
    def __init__(self, branches):
        self._branches = branches
        super().__init__(_C.CaseWhen(branches))

    def when(self, condition, value) -> "WhenBuilder":
        return WhenBuilder(self._branches + [(_expr(condition), _expr(value))])

    def otherwise(self, value) -> Column:
        return Column(_C.CaseWhen(self._branches, _expr(value)))


def when(condition, value) -> WhenBuilder:
    return WhenBuilder([(_expr(condition), _expr(value))])


def coalesce(*cols) -> Column:
    return Column(_N.Coalesce(*[_expr(c) for c in cols]))


def isnull(c) -> Column:
    return Column(_N.IsNull(_expr(c)))


def isnan(c) -> Column:
    return Column(_N.IsNaN(_expr(c)))


def nanvl(a, b) -> Column:
    return Column(_N.NaNvl(_expr(a), _expr(b)))


def greatest(*cols) -> Column:
    return Column(_C.Greatest(*[_expr(c) for c in cols]))


def least(*cols) -> Column:
    return Column(_C.Least(*[_expr(c) for c in cols]))


# --- math ------------------------------------------------------------------

def _unary(cls):
    def fn(c) -> Column:
        e = UnresolvedAttribute(c) if isinstance(c, str) else _expr(c)
        return Column(cls(e))
    return fn


sqrt = _unary(_M.Sqrt)
cbrt = _unary(_M.Cbrt)
exp = _unary(_M.Exp)
expm1 = _unary(_M.Expm1)
log = _unary(_M.Log)
log10 = _unary(_M.Log10)
log2 = _unary(_M.Log2)
log1p = _unary(_M.Log1p)
sin = _unary(_M.Sin)
cos = _unary(_M.Cos)
tan = _unary(_M.Tan)
asin = _unary(_M.Asin)
acos = _unary(_M.Acos)
atan = _unary(_M.Atan)
sinh = _unary(_M.Sinh)
cosh = _unary(_M.Cosh)
tanh = _unary(_M.Tanh)
signum = _unary(_M.Signum)
floor = _unary(_M.Floor)
ceil = _unary(_M.Ceil)
ceiling = ceil
abs = _unary(_A.Abs)  # noqa: A001 - pyspark exports `abs` too


def pow(l, r) -> Column:  # noqa: A001
    return Column(_M.Pow(_expr_or_col(l), _expr_or_col(r)))


def atan2(l, r) -> Column:
    return Column(_M.Atan2(_expr_or_col(l), _expr_or_col(r)))


def round(c, scale: int = 0) -> Column:  # noqa: A001
    return Column(_M.Round(_expr_or_col(c), Literal(scale)))


def pmod(l, r) -> Column:
    return Column(_A.Pmod(_expr_or_col(l), _expr_or_col(r)))


def negative(c) -> Column:
    return Column(_A.UnaryMinus(_expr_or_col(c)))


def _expr_or_col(c) -> Expression:
    if isinstance(c, str):
        return UnresolvedAttribute(c)
    return _expr(c)


# --- strings ---------------------------------------------------------------

def length(c) -> Column:
    return Column(_S.Length(_expr_or_col(c)))


def upper(c) -> Column:
    return Column(_S.Upper(_expr_or_col(c)))


def lower(c) -> Column:
    return Column(_S.Lower(_expr_or_col(c)))


def substring(c, pos: int, length_: int) -> Column:
    return Column(_S.Substring(_expr_or_col(c), Literal(pos), Literal(length_)))


def concat(*cols) -> Column:
    return Column(_S.ConcatStr(*[_expr_or_col(c) for c in cols]))


trim = _unary(_S.Trim)
ltrim = _unary(_S.LTrim)
rtrim = _unary(_S.RTrim)
reverse = _unary(_S.Reverse)
initcap = _unary(_S.InitCap)


def repeat(c, n: int) -> Column:
    return Column(_S.StringRepeat(_expr_or_col(c), Literal(n)))


def regexp_replace(c, pattern: str, replacement: str) -> Column:
    from .expressions.regex import RegexpReplace
    return Column(RegexpReplace(_expr_or_col(c), pattern, replacement))


def regexp_extract(c, pattern: str, idx: int = 1) -> Column:
    from .expressions.regex import RegexpExtract
    return Column(RegexpExtract(_expr_or_col(c), pattern, idx))


def rlike(c, pattern: str) -> Column:
    from .expressions.regex import RLike
    return Column(RLike(_expr_or_col(c), pattern))


def like(c, pattern: str) -> Column:
    from .expressions.regex import Like
    return Column(Like(_expr_or_col(c), pattern))


def locate(substr: str, c, pos: int = 1) -> Column:
    return Column(_S.StringLocate(Literal(substr), _expr_or_col(c), Literal(pos)))


def instr(c, substr: str) -> Column:
    return Column(_S.StringLocate(Literal(substr), _expr_or_col(c)))


def lpad(c, length_: int, pad: str = " ") -> Column:
    return Column(_S.LPad(_expr_or_col(c), Literal(length_), Literal(pad)))


def rpad(c, length_: int, pad: str = " ") -> Column:
    return Column(_S.RPad(_expr_or_col(c), Literal(length_), Literal(pad)))


def translate(c, from_str: str, to_str: str) -> Column:
    return Column(_S.StringTranslate(_expr_or_col(c), Literal(from_str),
                                     Literal(to_str)))


def replace(c, search: str, replacement: str = "") -> Column:
    return Column(_S.StringReplace(_expr_or_col(c), Literal(search),
                                   Literal(replacement)))


# --- hash ------------------------------------------------------------------

def hash(*cols) -> Column:  # noqa: A001
    return Column(_H.Murmur3Hash(*[_expr_or_col(c) for c in cols]))


# --- aggregates ------------------------------------------------------------

def sum(c) -> Column:  # noqa: A001
    return Column(_G.Sum(_expr_or_col(c)))


def count(c) -> Column:
    return Column(_G.Count(_expr_or_col(c) if not isinstance(c, str) or c != "*"
                           else Literal(1)))


def avg(c) -> Column:
    return Column(_G.Average(_expr_or_col(c)))


mean = avg


def min(c) -> Column:  # noqa: A001
    return Column(_G.Min(_expr_or_col(c)))


def max(c) -> Column:  # noqa: A001
    return Column(_G.Max(_expr_or_col(c)))


def first(c, ignorenulls: bool = False) -> Column:
    return Column(_G.First(_expr_or_col(c), ignorenulls))


def last(c, ignorenulls: bool = False) -> Column:
    return Column(_G.Last(_expr_or_col(c), ignorenulls))


def stddev(c) -> Column:
    return Column(_G.StddevSamp(_expr_or_col(c)))


stddev_samp = stddev


def stddev_pop(c) -> Column:
    return Column(_G.StddevPop(_expr_or_col(c)))


def variance(c) -> Column:
    return Column(_G.VarianceSamp(_expr_or_col(c)))


var_samp = variance


def var_pop(c) -> Column:
    return Column(_G.VariancePop(_expr_or_col(c)))


def count_star() -> Column:
    return Column(_G.Count(Literal(1)))


# --- datetime --------------------------------------------------------------

from .expressions import datetime as _D


year = _unary(_D.Year)
month = _unary(_D.Month)
dayofmonth = _unary(_D.DayOfMonth)
quarter = _unary(_D.Quarter)
dayofweek = _unary(_D.DayOfWeek)
weekday = _unary(_D.WeekDay)
dayofyear = _unary(_D.DayOfYear)
weekofyear = _unary(_D.WeekOfYear)
hour = _unary(_D.Hour)
minute = _unary(_D.Minute)
second = _unary(_D.Second)
last_day = _unary(_D.LastDay)


def date_add(date, days) -> Column:
    return Column(_D.DateAdd(_expr_or_col(date), _expr_or_col(days)))


def date_sub(date, days) -> Column:
    return Column(_D.DateAdd(_expr_or_col(date), _expr_or_col(days), negate=True))


def datediff(end, start) -> Column:
    return Column(_D.DateDiff(_expr_or_col(end), _expr_or_col(start)))


def add_months(date, months) -> Column:
    return Column(_D.AddMonths(_expr_or_col(date), _expr_or_col(months)))


def unix_timestamp(ts, fmt: str = None) -> Column:
    """unix_timestamp(ts) for timestamp columns; string columns parse with
    fmt (default yyyy-MM-dd HH:mm:ss, host-assisted, UTC)."""
    from .types import StringType
    e = _expr_or_col(ts)
    if fmt is not None:
        return Column(_D.UnixTimestamp(e, Literal(fmt)))
    try:
        is_string = isinstance(e.dtype, StringType)
    except Exception:  # unresolved attribute: dtype unknown until binding
        is_string = False
    if is_string:
        return Column(_D.UnixTimestamp(e, Literal("yyyy-MM-dd HH:mm:ss")))
    return Column(_D.UnixTimestampFromTs(e))


# --- window functions ------------------------------------------------------

def row_number() -> Column:
    from .window import RowNumber
    return Column(RowNumber())


def rank() -> Column:
    from .window import Rank
    return Column(Rank())


def dense_rank() -> Column:
    from .window import DenseRank
    return Column(DenseRank())


def ntile(n: int) -> Column:
    from .expressions.base import Literal
    from .window import NTile
    return Column(NTile(Literal(int(n))))


def percent_rank() -> Column:
    from .window import PercentRank
    return Column(PercentRank())


def cume_dist() -> Column:
    from .window import CumeDist
    return Column(CumeDist())


def lead(c, offset: int = 1, default=None) -> Column:
    from .window import Lead
    d = Literal(default) if default is not None else None
    return Column(Lead(_expr_or_col(c), offset, d))


def lag(c, offset: int = 1, default=None) -> Column:
    from .window import Lag
    d = Literal(default) if default is not None else None
    return Column(Lag(_expr_or_col(c), offset, d))


# --- collection functions --------------------------------------------------
# (reference: collectionOperations.scala / higherOrderFunctions.scala rules in
#  GpuOverrides.commonExpressions)

from .expressions import collections as _CL


def _make_lambda(f, n_args: int):
    """Python callable → LambdaFunction over fresh NamedLambdaVariables.
    Variable types are filled in by the HOF's _sync_vars at resolution."""
    from .types import NullT
    names = ("x", "y", "z")
    vars_ = [_CL.NamedLambdaVariable(names[i], NullT) for i in range(n_args)]
    body = _expr(f(*[Column(v) for v in vars_]))
    return _CL.LambdaFunction(body, vars_)


def _lambda_arity(f) -> int:
    import inspect
    return len(inspect.signature(f).parameters)


def array(*cols) -> Column:
    return Column(_CL.CreateArray([_expr_or_col(c) for c in cols]))


def size(c) -> Column:
    return Column(_CL.Size(_expr_or_col(c)))


def array_contains(c, value) -> Column:
    return Column(_CL.ArrayContains(_expr_or_col(c), _expr(value)))


def element_at(c, extraction) -> Column:
    return Column(_CL.ElementAt(_expr_or_col(c), _expr(extraction)))


def get(c, index) -> Column:
    return Column(_CL.GetArrayItem(_expr_or_col(c), _expr(index)))


def array_position(c, value) -> Column:
    return Column(_CL.ArrayPosition(_expr_or_col(c), _expr(value)))


def array_min(c) -> Column:
    return Column(_CL.ArrayMin(_expr_or_col(c)))


def array_max(c) -> Column:
    return Column(_CL.ArrayMax(_expr_or_col(c)))


def sort_array(c, asc: bool = True) -> Column:
    return Column(_CL.SortArray(_expr_or_col(c), Literal(asc)))


def array_distinct(c) -> Column:
    return Column(_CL.ArrayDistinct(_expr_or_col(c)))


def array_union(a, b) -> Column:
    return Column(_CL.ArrayUnion(_expr_or_col(a), _expr_or_col(b)))


def array_intersect(a, b) -> Column:
    return Column(_CL.ArrayIntersect(_expr_or_col(a), _expr_or_col(b)))


def array_except(a, b) -> Column:
    return Column(_CL.ArrayExcept(_expr_or_col(a), _expr_or_col(b)))


def arrays_overlap(a, b) -> Column:
    return Column(_CL.ArraysOverlap(_expr_or_col(a), _expr_or_col(b)))


def array_repeat(c, count) -> Column:
    return Column(_CL.ArrayRepeat(_expr(c), _expr(count)))


def slice(c, start, length) -> Column:  # noqa: A001 - pyspark name
    return Column(_CL.Slice(_expr_or_col(c), _expr(start), _expr(length)))


def concat_arrays(*cols) -> Column:
    return Column(_CL.ConcatArrays([_expr_or_col(c) for c in cols]))


def flatten(c) -> Column:
    return Column(_CL.Flatten(_expr_or_col(c)))


def array_join(c, delimiter: str, null_replacement=None) -> Column:
    rep = Literal(null_replacement) if null_replacement is not None else None
    return Column(_CL.ArrayJoin(_expr_or_col(c), Literal(delimiter), rep))


def sequence(start, stop, step=None) -> Column:
    s = _expr_or_col(step) if step is not None else None
    return Column(_CL.Sequence(_expr_or_col(start), _expr_or_col(stop), s))


def array_reverse(c) -> Column:
    return Column(_CL.ArrayReverse(_expr_or_col(c)))


def arrays_zip(*cols) -> Column:
    return Column(_CL.ArraysZip([_expr_or_col(c) for c in cols]))


def create_map(*cols) -> Column:
    return Column(_CL.CreateMap([_expr_or_col(c) for c in cols]))


def map_keys(c) -> Column:
    return Column(_CL.MapKeys(_expr_or_col(c)))


def map_values(c) -> Column:
    return Column(_CL.MapValues(_expr_or_col(c)))


def map_concat(*cols) -> Column:
    return Column(_CL.MapConcat([_expr_or_col(c) for c in cols]))


def map_from_arrays(keys, values) -> Column:
    return Column(_CL.MapFromArrays(_expr_or_col(keys), _expr_or_col(values)))


def transform(c, f) -> Column:
    return Column(_CL.ArrayTransform(_expr_or_col(c), _make_lambda(f, _lambda_arity(f))))


def exists(c, f) -> Column:
    return Column(_CL.ArrayExists(_expr_or_col(c), _make_lambda(f, 1)))


def forall(c, f) -> Column:
    return Column(_CL.ArrayForAll(_expr_or_col(c), _make_lambda(f, 1)))


def filter(c, f) -> Column:  # noqa: A001 - pyspark name
    return Column(_CL.ArrayFilter(_expr_or_col(c), _make_lambda(f, _lambda_arity(f))))


def aggregate(c, zero, merge, finish=None) -> Column:
    m = _make_lambda(merge, 2)
    fin = _make_lambda(finish, 1) if finish is not None else None
    return Column(_CL.ArrayAggregate(_expr_or_col(c), _expr(zero), m, fin))


def zip_with(a, b, f) -> Column:
    return Column(_CL.ZipWith(_expr_or_col(a), _expr_or_col(b), _make_lambda(f, 2)))


# --- generators (reference GpuExplode/GpuPosExplode/GpuStack, GpuGenerateExec.scala)

def explode(c) -> Column:
    from .expressions.generators import Explode
    return Column(Explode(_expr_or_col(c)))


def explode_outer(c) -> Column:
    from .expressions.generators import Explode
    return Column(Explode(_expr_or_col(c), outer=True))


def posexplode(c) -> Column:
    from .expressions.generators import Explode
    return Column(Explode(_expr_or_col(c), with_position=True))


def posexplode_outer(c) -> Column:
    from .expressions.generators import Explode
    return Column(Explode(_expr_or_col(c), outer=True, with_position=True))


def stack(n, *cols) -> Column:
    from .expressions.generators import Stack
    if isinstance(n, Column):
        from .expressions.base import Literal as _Lit
        assert isinstance(n._expr, _Lit), "stack row count must be a literal"
        n = n._expr.value
    return Column(Stack(int(n), [_expr_or_col(c) for c in cols]))


def grouping_id() -> Column:
    from .expressions.generators import GroupingID
    return Column(GroupingID())


def grouping(c) -> Column:
    from .expressions.generators import GroupingExpr
    return Column(GroupingExpr(_expr_or_col(c)))


# --- JSON (reference GpuGetJsonObject/GpuJsonToStructs/GpuStructsToJson/GpuJsonTuple)

def get_json_object(c, path: str) -> Column:
    from .expressions.json import GetJsonObject
    return Column(GetJsonObject(_expr_or_col(c), Literal(path)))


def from_json(c, schema) -> Column:
    from .expressions.json import JsonToStructs
    from .types import StructType, parse_ddl
    if isinstance(schema, str):
        schema = parse_ddl(schema)
    return Column(JsonToStructs(_expr_or_col(c), schema))


def to_json(c) -> Column:
    from .expressions.json import StructsToJson
    return Column(StructsToJson(_expr_or_col(c)))


def json_tuple(c, *fields: str) -> Column:
    from .expressions.json import JsonTuple
    return Column(JsonTuple(_expr_or_col(c), list(fields)))


def schema_of_json(sample: str):
    """Infer a StructType from one JSON document (host-side helper)."""
    import json as _j
    from .types import (ArrayType, BooleanT, DoubleT, LongT, NullT, StringT,
                        StructField, StructType)

    def infer(v):
        if isinstance(v, bool):
            return BooleanT
        if isinstance(v, int):
            return LongT
        if isinstance(v, float):
            return DoubleT
        if isinstance(v, str):
            return StringT
        if isinstance(v, list):
            return ArrayType(infer(v[0]) if v else StringT)
        if isinstance(v, dict):
            return StructType(tuple(StructField(k, infer(x), True)
                                    for k, x in v.items()))
        return StringT

    return infer(_j.loads(sample))


# --- collection / statistical aggregates (reference aggregateFunctions.scala,
#     GpuPercentile.scala, GpuApproximatePercentile.scala)

def collect_list(c) -> Column:
    return Column(_G.CollectList(_expr_or_col(c)))


def collect_set(c) -> Column:
    return Column(_G.CollectSet(_expr_or_col(c)))


def percentile(c, percentage) -> Column:
    return Column(_G.Percentile(_expr_or_col(c), percentage))


def percentile_approx(c, percentage, accuracy: int = 10000) -> Column:
    return Column(_G.ApproximatePercentile(_expr_or_col(c), percentage, accuracy))


approx_percentile = percentile_approx


def covar_samp(x, y) -> Column:
    return Column(_G.CovSample(_expr_or_col(x), _expr_or_col(y)))


def covar_pop(x, y) -> Column:
    return Column(_G.CovPopulation(_expr_or_col(x), _expr_or_col(y)))


def corr(x, y) -> Column:
    return Column(_G.Corr(_expr_or_col(x), _expr_or_col(y)))


def bloom_filter_agg(c, estimated_items: int = 1_000_000,
                     num_bits: int = 8_388_608) -> Column:
    from .expressions.bloom import BloomFilterAggregate
    return Column(BloomFilterAggregate(_expr_or_col(c), estimated_items, num_bits))


def might_contain(bloom, value) -> Column:
    from .expressions.bloom import BloomFilterMightContain
    return Column(BloomFilterMightContain(_expr(bloom), _expr_or_col(value)))


# --- string breadth 2 + hashes + url (reference stringFunctions.scala,
#     HashFunctions.scala, GpuParseUrl.scala, bitwise.scala)

def concat_ws(sep: str, *cols) -> Column:
    from .expressions.strings import ConcatWs
    return Column(ConcatWs(Literal(sep) if isinstance(sep, str) else _expr(sep),
                           *[_expr_or_col(c) for c in cols]))


def split(c, pattern: str, limit: int = -1) -> Column:
    from .expressions.strings import StringSplit
    return Column(StringSplit(_expr_or_col(c), Literal(pattern), Literal(limit)))


def substring_index(c, delim: str, count: int) -> Column:
    from .expressions.strings import SubstringIndex
    return Column(SubstringIndex(_expr_or_col(c), Literal(delim), Literal(count)))


def octet_length(c) -> Column:
    from .expressions.strings import OctetLength
    return Column(OctetLength(_expr_or_col(c)))


def bit_length(c) -> Column:
    from .expressions.strings import BitLength
    return Column(BitLength(_expr_or_col(c)))


def format_number(c, d: int) -> Column:
    from .expressions.strings import FormatNumber
    return Column(FormatNumber(_expr_or_col(c), Literal(d)))


def conv(c, from_base: int, to_base: int) -> Column:
    from .expressions.strings import Conv
    return Column(Conv(_expr_or_col(c), Literal(from_base), Literal(to_base)))


def str_to_map(c, pair_delim: str = ",", kv_delim: str = ":") -> Column:
    from .expressions.strings import StringToMap
    return Column(StringToMap(_expr_or_col(c), Literal(pair_delim),
                              Literal(kv_delim)))


def regexp_extract_all(c, pattern: str, idx: int = 1) -> Column:
    from .expressions.regex import RegexpExtractAll
    return Column(RegexpExtractAll(_expr_or_col(c), pattern, idx))


def xxhash64(*cols) -> Column:
    from .expressions.hashexprs import XxHash64
    return Column(XxHash64(*[_expr_or_col(c) for c in cols]))


def hive_hash(*cols) -> Column:
    from .expressions.hashexprs import HiveHash
    return Column(HiveHash(*[_expr_or_col(c) for c in cols]))


def parse_url(c, part: str, key: str = None) -> Column:
    from .expressions.urlexprs import ParseUrl
    return Column(ParseUrl(_expr_or_col(c), Literal(part),
                           Literal(key) if key is not None else None))


def bitwise_not(c) -> Column:
    from .expressions.bitwise import BitwiseNot
    return Column(BitwiseNot(_expr_or_col(c)))


def bit_count(c) -> Column:
    from .expressions.bitwise import BitwiseCount
    return Column(BitwiseCount(_expr_or_col(c)))


def shiftleft(c, n: int) -> Column:
    from .expressions.bitwise import ShiftLeft
    return Column(ShiftLeft(_expr_or_col(c), Literal(n)))


def shiftright(c, n: int) -> Column:
    from .expressions.bitwise import ShiftRight
    return Column(ShiftRight(_expr_or_col(c), Literal(n)))


def shiftrightunsigned(c, n: int) -> Column:
    from .expressions.bitwise import ShiftRightUnsigned
    return Column(ShiftRightUnsigned(_expr_or_col(c), Literal(n)))


def interleave_bits(*cols) -> Column:
    """Z-order clustering key: bit-interleave of integral columns (reference
    zorder/GpuInterleaveBits.scala)."""
    from .expressions.zorder import InterleaveBits
    return Column(InterleaveBits([_expr_or_col(c) for c in cols]))


def hilbert_index(num_bits: int, *cols) -> Column:
    """Hilbert-curve clustering key (reference zorder/GpuHilbertLongIndex.scala)."""
    from .expressions.zorder import HilbertLongIndex
    return Column(HilbertLongIndex(num_bits, [_expr_or_col(c) for c in cols]))


# ---------------------------------------------------------------------------
# breadth 2: math / null / misc / datetime / map-struct functions
# ---------------------------------------------------------------------------

def asinh(c) -> Column:
    from .expressions.mathexprs import Asinh
    return Column(Asinh(_expr_or_col(c)))


def acosh(c) -> Column:
    from .expressions.mathexprs import Acosh
    return Column(Acosh(_expr_or_col(c)))


def atanh(c) -> Column:
    from .expressions.mathexprs import Atanh
    return Column(Atanh(_expr_or_col(c)))


def cot(c) -> Column:
    from .expressions.mathexprs import Cot
    return Column(Cot(_expr_or_col(c)))


def degrees(c) -> Column:
    from .expressions.mathexprs import ToDegrees
    return Column(ToDegrees(_expr_or_col(c)))


def radians(c) -> Column:
    from .expressions.mathexprs import ToRadians
    return Column(ToRadians(_expr_or_col(c)))


def rint(c) -> Column:
    from .expressions.mathexprs import Rint
    return Column(Rint(_expr_or_col(c)))


def hypot(a, b) -> Column:
    from .expressions.mathexprs import Hypot
    return Column(Hypot(_expr_or_col(a), _expr_or_col(b)))


def log(base, c=None) -> Column:
    """log(x) natural log, or log(base, x)."""
    from .expressions.mathexprs import Log, Logarithm
    if c is None:
        return Column(Log(_expr_or_col(base)))
    return Column(Logarithm(_expr_or_col(base), _expr_or_col(c)))


def bround(c, scale: int = 0) -> Column:
    from .expressions.mathexprs import BRound
    return Column(BRound(_expr_or_col(c), Literal(scale)))


def ascii(c) -> Column:
    from .expressions.strings import Ascii
    return Column(Ascii(_expr_or_col(c)))


def md5(c) -> Column:
    from .expressions.hashexprs import Md5
    return Column(Md5(_expr_or_col(c)))


def spark_partition_id() -> Column:
    from .expressions.misc import SparkPartitionID
    return Column(SparkPartitionID())


def monotonically_increasing_id() -> Column:
    from .expressions.misc import MonotonicallyIncreasingID
    return Column(MonotonicallyIncreasingID())


def rand(seed: int = 0) -> Column:
    from .expressions.misc import Rand
    return Column(Rand(Literal(seed)))


def input_file_name() -> Column:
    from .expressions.misc import InputFileName
    return Column(InputFileName())


def input_file_block_start() -> Column:
    from .expressions.misc import InputFileBlockStart
    return Column(InputFileBlockStart())


def input_file_block_length() -> Column:
    from .expressions.misc import InputFileBlockLength
    return Column(InputFileBlockLength())


def timestamp_seconds(c) -> Column:
    from .expressions.datetime import SecondsToTimestamp
    return Column(SecondsToTimestamp(_expr_or_col(c)))


def timestamp_millis(c) -> Column:
    from .expressions.datetime import MillisToTimestamp
    return Column(MillisToTimestamp(_expr_or_col(c)))


def timestamp_micros(c) -> Column:
    from .expressions.datetime import MicrosToTimestamp
    return Column(MicrosToTimestamp(_expr_or_col(c)))


def from_unixtime(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    from .expressions.datetime import FromUnixTime
    return Column(FromUnixTime(_expr_or_col(c), Literal(fmt)))


def date_format(c, fmt: str) -> Column:
    from .expressions.datetime import DateFormatClass
    return Column(DateFormatClass(_expr_or_col(c), Literal(fmt)))


def to_unix_timestamp(c, fmt: str = "yyyy-MM-dd HH:mm:ss") -> Column:
    from .expressions.datetime import ToUnixTimestamp
    return Column(ToUnixTimestamp(_expr_or_col(c), Literal(fmt)))


def array_remove(c, elem) -> Column:
    from .expressions.collections import ArrayRemove
    e = elem if isinstance(elem, Column) else lit(elem)
    return Column(ArrayRemove(_expr_or_col(c), _expr_or_col(e)))


def map_entries(c) -> Column:
    from .expressions.collections import MapEntries
    return Column(MapEntries(_expr_or_col(c)))


def map_filter(c, fn) -> Column:
    from .expressions.collections import MapFilter
    return Column(MapFilter(_expr_or_col(c), _lambda2(fn)))


def transform_keys(c, fn) -> Column:
    from .expressions.collections import TransformKeys
    return Column(TransformKeys(_expr_or_col(c), _lambda2(fn)))


def transform_values(c, fn) -> Column:
    from .expressions.collections import TransformValues
    return Column(TransformValues(_expr_or_col(c), _lambda2(fn)))


def named_struct(*name_value_pairs) -> Column:
    """named_struct(name1, col1, name2, col2, ...)."""
    from .expressions.collections import CreateNamedStruct
    names = [name_value_pairs[i] for i in range(0, len(name_value_pairs), 2)]
    vals = [_expr_or_col(name_value_pairs[i])
            for i in range(1, len(name_value_pairs), 2)]
    return Column(CreateNamedStruct(names, vals))


def _lambda2(fn):
    """Python (k, v) -> Column lambda → LambdaFunction over two vars."""
    from .expressions.collections import LambdaFunction, NamedLambdaVariable
    from .types import StringT
    k = NamedLambdaVariable("k", StringT)
    v = NamedLambdaVariable("v", StringT)
    body = fn(Column(k), Column(v))
    return LambdaFunction(_expr_or_col(body), [k, v])
