"""Profiling, tracing, and task-level metrics.

Reference (SURVEY.md §5 tracing/profiling):
  (a) NVTX ranges around every operator (NvtxWithMetrics.scala) → here
      `trace_scope` emits jax.profiler TraceAnnotations, visible in
      xprof/TensorBoard timelines;
  (b) the built-in sampled profiler (profiler.scala:37, JNI CUPTI Profiler,
      `spark.rapids.profile.*` configs) → `TpuProfiler` drives
      jax.profiler.start_trace/stop_trace writing to
      `spark.rapids.profile.pathPrefix`;
  (c) per-task accumulators GpuTaskMetrics (semaphore-wait, retry count/time,
      spill-to-host/disk bytes, GpuTaskMetrics.scala:82-101) →
      `TaskMetricsRegistry`;
  (d) per-operator SQLMetrics at ESSENTIAL/MODERATE/DEBUG (GpuExec.scala:41)
      → TpuMetric on every exec, surfaced via `collect_plan_metrics`;
  (e) DumpUtils.scala (dump problem batches to parquet for offline repro) →
      `dump_batch`.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, Optional

# ---------------------------------------------------------------------------
# (c) task metrics


class TaskMetricsRegistry:
    """Process-wide accumulators mirroring GpuTaskMetrics: semaphore wait,
    retry counts/time, spill bytes, read-spill time."""

    _instance: Optional["TaskMetricsRegistry"] = None
    _lock = threading.Lock()

    KNOWN = ("semaphoreWaitNs", "retryCount", "splitAndRetryCount",
             "retryBlockTimeNs", "spillToHostBytes", "spillToDiskBytes",
             "readSpillTimeNs", "deviceRetryCount", "deviceRetryBlockTimeNs")

    def __init__(self):
        self._vals: Dict[str, int] = {k: 0 for k in self.KNOWN}
        self._mu = threading.Lock()

    @classmethod
    def get(cls) -> "TaskMetricsRegistry":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "TaskMetricsRegistry":
        with cls._lock:
            cls._instance = cls()
            return cls._instance

    def add(self, name: str, value: int) -> None:
        with self._mu:
            self._vals[name] = self._vals.get(name, 0) + int(value)

    def snapshot(self) -> Dict[str, int]:
        with self._mu:
            return dict(self._vals)


# ---------------------------------------------------------------------------
# (c2) the sync ledger: every BLOCKING device→host transfer, attributed to
# the operator that caused it. On the tunneled TPU each blocking sync is a
# full ~100ms round trip, so the *count* of syncs per partition — not their
# payload size — dominates general-path wall time. All engine syncs route
# through columnar/vector.py's audited_sync helpers (tracelint TL011 flags
# strays), which record here; execs/base.py maintains the active-operator
# scope around every batch pull.


class SyncLedger:
    """Process-wide {operator: {kind: count}} of blocking D→H transfers."""

    _instance: Optional["SyncLedger"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._mu = threading.Lock()
        self._by_op: Dict[str, Dict[str, int]] = {}
        self._total = 0

    @classmethod
    def get(cls) -> "SyncLedger":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "SyncLedger":
        with cls._lock:
            cls._instance = cls()
            return cls._instance

    def record(self, kind: str, op: Optional[str] = None) -> None:
        if op is None:
            op = current_sync_scope()
        with self._mu:
            ops = self._by_op.setdefault(op, {})
            ops[kind] = ops.get(kind, 0) + 1
            self._total += 1
        # piggyback the query tracer (obs): one instant event per blocking
        # sync PLUS the bound tracer's per-query sync counter, attributed
        # with the SAME operator scope the ledger used — the diagnostics
        # bundle reconciles against its own query's deltas even when other
        # queries run concurrently (the process-wide ledger cross-bleeds)
        from .obs import tracer as _obs
        if _obs._ACTIVE:
            _obs.sync_event(op, kind)

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._mu:
            return {op: dict(kinds) for op, kinds in self._by_op.items()}

    def total(self) -> int:
        with self._mu:
            return self._total

    def totals_by_op(self) -> Dict[str, int]:
        with self._mu:
            return {op: sum(kinds.values())
                    for op, kinds in self._by_op.items()}


class _SyncScope(threading.local):
    """Stack of operator names; the top attributes recorded syncs. Thread-
    local: pipelined map tasks and prefetch workers each carry their own."""
    stack = ()


_sync_scope_tls = _SyncScope()


def current_sync_scope() -> str:
    st = _sync_scope_tls.stack
    return st[-1] if st else "<unattributed>"


@contextlib.contextmanager
def sync_scope(name: str):
    """Attribute blocking syncs inside the scope to `name` (set by
    TpuExec.execute_partition around each batch pull, so nested pulls
    re-attribute to the producing operator)."""
    _sync_scope_tls.stack = _sync_scope_tls.stack + (name,)
    try:
        yield
    finally:
        _sync_scope_tls.stack = _sync_scope_tls.stack[:-1]


def record_sync(kind: str, op: Optional[str] = None) -> None:
    """Record one blocking device→host transfer (called by the audited sync
    helpers in columnar/vector.py)."""
    SyncLedger.get().record(kind, op)


# ---------------------------------------------------------------------------
# (a) operator trace scopes (NVTX analogue)

_PROFILING_ACTIVE = False


@contextlib.contextmanager
def trace_scope(name: str):
    """NVTX-range analogue: a named scope in the xprof timeline. Free when no
    trace is being captured."""
    if not _PROFILING_ACTIVE:
        yield
        return
    import jax.profiler
    with jax.profiler.TraceAnnotation(name):
        yield


# ---------------------------------------------------------------------------
# (b) the profiler driver


class TpuProfiler:
    """Capture an xprof trace of a query region (reference ProfilerOnExecutor:
    scoped by configs, written under spark.rapids.profile.pathPrefix)."""

    def __init__(self, path_prefix: str):
        self.path = os.path.join(path_prefix,
                                 f"rapids-tpu-profile-{int(time.time())}")
        self._active = False

    def start(self) -> None:
        global _PROFILING_ACTIVE
        import jax.profiler
        os.makedirs(self.path, exist_ok=True)
        jax.profiler.start_trace(self.path)
        self._active = True
        _PROFILING_ACTIVE = True

    def stop(self) -> None:
        global _PROFILING_ACTIVE
        if not self._active:
            return
        import jax.profiler
        jax.profiler.stop_trace()
        self._active = False
        _PROFILING_ACTIVE = False

    def __enter__(self) -> "TpuProfiler":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


# ---------------------------------------------------------------------------
# (d) plan metric collection


_LEVEL_ORDER = {"ESSENTIAL": 0, "MODERATE": 1, "DEBUG": 2}


def collect_plan_metrics(plan, level: str = "MODERATE") -> Dict[str, Dict[str, int]]:
    """Per-operator metric values at or above the requested level
    (ESSENTIAL ⊂ MODERATE ⊂ DEBUG, reference GpuMetric levels)."""
    want = _LEVEL_ORDER.get(str(level).upper(), 1)
    out: Dict[str, Dict[str, int]] = {}
    for i, node in enumerate(plan.collect_nodes()):
        vals = {m.name: m.value for m in node.metrics.values()
                if _LEVEL_ORDER.get(m.level, 1) <= want and m.value}
        if vals:
            out[f"{i}:{node.node_name()}"] = vals
    return out


def snapshot_plan_metrics(plan) -> Dict[str, Dict[str, tuple]]:
    """All non-zero metrics with their levels, as plain data — lets the
    session drop the plan reference after the query (no device buffers
    pinned) while still supporting level filtering later."""
    out: Dict[str, Dict[str, tuple]] = {}
    for i, node in enumerate(plan.collect_nodes()):
        vals = {m.name: (m.value, m.level) for m in node.metrics.values()
                if m.value}
        if vals:
            out[f"{i}:{node.node_name()}"] = vals
    return out


def metric_level_filter(snapshot: Dict[str, Dict[str, tuple]],
                        level: str) -> Dict[str, Dict[str, int]]:
    want = _LEVEL_ORDER.get(str(level).upper(), 1)
    out: Dict[str, Dict[str, int]] = {}
    for op, vals in snapshot.items():
        kept = {n: v for n, (v, lvl) in vals.items()
                if _LEVEL_ORDER.get(lvl, 1) <= want}
        if kept:
            out[op] = kept
    return out


# ---------------------------------------------------------------------------
# (e) batch dump for offline repro


def dump_batch(batch, path_prefix: str, op_name: str) -> str:
    """Write a problem batch to parquet for offline repro (reference
    DumpUtils.scala). Returns the written path."""
    import pyarrow.parquet as pq
    os.makedirs(path_prefix, exist_ok=True)
    p = os.path.join(path_prefix,
                     f"dump-{op_name}-{int(time.time() * 1000)}.parquet")
    table = batch if hasattr(batch, "num_columns") and not hasattr(
        batch, "to_arrow") else batch.to_arrow()
    pq.write_table(table, p)
    return p
