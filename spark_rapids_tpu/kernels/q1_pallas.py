"""Pallas TPU kernel for the Q1 partial aggregation — single-pass fusion.

Why: the XLA path (kernels/q1.py) is correct and MXU-friendly, but it
materializes the [n, 16] one-hot operand and the [n, 6] measure stack in HBM
(~1.4 GB of extra traffic at n=16.7M). This kernel streams each row tile
through VMEM once — measures and the one-hot tile live only in registers /
VMEM, and the [16, 6] group table accumulates across sequential grid steps —
so total HBM traffic collapses to the 8 input columns (~0.5 GB), the
bandwidth floor for this query.

Reference analogue: one fused cuDF kernel chain of GpuAggFirstPassIterator;
here it is literally one kernel.

The caller (`q1_partial_best`) compiles this lazily and falls back to the
XLA path if the backend rejects it (CPU tests run it under interpret=True).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .q1 import N_GROUPS, N_STATUS, Q1Inputs, Q1State

_LANES = 128
_TILE_ROWS = 256  # rows of 128 lanes → 32768 elements per grid step


def _q1_kernel(cutoff_ref, rf_ref, ls_ref, qty_ref, price_ref, disc_ref,
               tax_ref, ship_ref, valid_ref, out_ref):
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    keep = valid_ref[:, :] & (ship_ref[:, :] <= cutoff_ref[0, 0])
    w = keep.astype(jnp.float32)
    price_raw = price_ref[:, :]
    disc_raw = disc_ref[:, :]
    qty = qty_ref[:, :] * w
    price = price_raw * w
    disc_price = price_raw * (1.0 - disc_raw) * w
    charge = disc_price * (1.0 + tax_ref[:, :])
    disc = disc_raw * w

    group = rf_ref[:, :] * N_STATUS + ls_ref[:, :]          # [R, 128] int32
    # masked VPU reductions over the row axis only (Mosaic rejects scalar
    # VMEM stores and the transposed MXU contraction): one [16,R,128] mask
    # broadcast, six reductions, a single [16, 6*128] accumulate; the caller
    # finishes the tiny lane sum
    gidx = jax.lax.broadcasted_iota(jnp.int32, (N_GROUPS, 1, 1), 0)
    masks = (group[None, :, :] == gidx).astype(jnp.float32)  # [16, R, 128]
    measures = (qty, price, disc_price, charge, disc, w)
    per = [jnp.sum(masks * col[None, :, :], axis=1)          # [16, 128] each
           for col in measures]
    out_ref[:, :] += jnp.concatenate(per, axis=1)            # [16, 6*128]


def q1_partial_pallas(batch: Q1Inputs, cutoff_days,
                      interpret: bool = False) -> Q1State:
    """Pallas single-pass partial aggregation (shapes padded to tile size)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = batch.quantity.shape[0]
    per_tile = _TILE_ROWS * _LANES
    padded = -(-n // per_tile) * per_tile

    def shape2d(a, fill):
        if padded != n:
            a = jnp.pad(a, (0, padded - n), constant_values=fill)
        return a.reshape(-1, _LANES)

    rf = shape2d(batch.returnflag, 0)
    ls = shape2d(batch.linestatus, 0)
    qty = shape2d(batch.quantity, 0)
    price = shape2d(batch.extendedprice, 0)
    disc = shape2d(batch.discount, 0)
    tax = shape2d(batch.tax, 0)
    ship = shape2d(batch.shipdate, 0)
    valid = shape2d(batch.valid, False)

    grid = padded // per_tile
    col_spec = pl.BlockSpec((_TILE_ROWS, _LANES), lambda i: (i, 0))
    # Mosaic rejects the program under jax_enable_x64 (64-bit index types leak
    # into the lowering); every dtype in this kernel is explicitly 32-bit, so
    # tracing the call in a disable-x64 scope is semantics-preserving
    with jax.enable_x64(False):
        out = pl.pallas_call(
            _q1_kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),  # cutoff scalar
                col_spec, col_spec, col_spec, col_spec, col_spec, col_spec,
                col_spec, col_spec,
            ],
            out_specs=pl.BlockSpec((N_GROUPS, 6 * _LANES), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((N_GROUPS, 6 * _LANES),
                                           jnp.float32),
            interpret=interpret,
        )(jnp.asarray([[cutoff_days]], jnp.int32), rf, ls, qty, price, disc,
          tax, ship, valid)

    sums = out.reshape(N_GROUPS, 6, _LANES).sum(axis=2)  # finish lane sum
    return Q1State(
        sum_qty=sums[:, 0], sum_base_price=sums[:, 1],
        sum_disc_price=sums[:, 2], sum_charge=sums[:, 3],
        sum_disc=sums[:, 4],
        count=sums[:, 5].astype(jnp.int32),
    )


def _q1_kernel_mxu(cutoff_ref, rf_ref, ls_ref, qty_ref, price_ref, disc_ref,
                   tax_ref, ship_ref, valid_ref, out_ref):
    """MXU formulation: the [16, E] one-hot contraction runs as ONE matmul
    per tile instead of 16×6 masked VPU reductions.

    Roofline: the VPU variant does 16 groups × 6 measures × 2 ops per input
    element = 192 flops/element; at the measured 9.6 Grows/s that is
    ~1.8 Tflop/s — the VPU's peak, which is why it plateaus at ~36% of HBM
    bandwidth (it is COMPUTE-bound, not memory-bound). The same contraction
    as `onehot[16, E] @ measures[E, 8]` rides the MXU's systolic array,
    taking the per-element VPU work down to building the one-hot and the
    measure stack (~20 flops/element) — the kernel becomes memory-bound,
    which is the roofline cuDF's agg kernels sit on (SURVEY §2.4)."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    keep = valid_ref[:, :] & (ship_ref[:, :] <= cutoff_ref[0, 0])
    w = keep.astype(jnp.float32)
    price_raw = price_ref[:, :]
    disc_raw = disc_ref[:, :]
    qty = qty_ref[:, :] * w
    price = price_raw * w
    disc_price = price_raw * (1.0 - disc_raw) * w
    charge = disc_price * (1.0 + tax_ref[:, :])
    disc = disc_raw * w

    group = rf_ref[:, :] * N_STATUS + ls_ref[:, :]           # [R, 128] int32
    flat = group.reshape(1, -1)                              # [1, E]
    gidx = jax.lax.broadcasted_iota(jnp.int32, (N_GROUPS, 1), 0)
    onehot = (flat == gidx).astype(jnp.float32)              # [16, E]
    meas = jnp.concatenate(
        [m.reshape(-1, 1) for m in
         (qty, price, disc_price, charge, disc, w,
          w, w)], axis=1)                                    # [E, 8]
    out_ref[:, :] += jax.lax.dot_general(
        onehot, meas, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [16, 8]


def q1_partial_pallas_mxu(batch: Q1Inputs, cutoff_days,
                          interpret: bool = False) -> Q1State:
    """MXU-contraction variant of the single-pass partial aggregation."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = batch.quantity.shape[0]
    per_tile = _TILE_ROWS * _LANES
    padded = -(-n // per_tile) * per_tile

    def shape2d(a, fill):
        if padded != n:
            a = jnp.pad(a, (0, padded - n), constant_values=fill)
        return a.reshape(-1, _LANES)

    rf = shape2d(batch.returnflag, 0)
    ls = shape2d(batch.linestatus, 0)
    qty = shape2d(batch.quantity, 0)
    price = shape2d(batch.extendedprice, 0)
    disc = shape2d(batch.discount, 0)
    tax = shape2d(batch.tax, 0)
    ship = shape2d(batch.shipdate, 0)
    valid = shape2d(batch.valid, False)

    grid = padded // per_tile
    col_spec = pl.BlockSpec((_TILE_ROWS, _LANES), lambda i: (i, 0))
    with jax.enable_x64(False):
        out = pl.pallas_call(
            _q1_kernel_mxu,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((1, 1), lambda i: (0, 0),
                             memory_space=pltpu.SMEM),
                col_spec, col_spec, col_spec, col_spec, col_spec, col_spec,
                col_spec, col_spec,
            ],
            out_specs=pl.BlockSpec((N_GROUPS, 8), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((N_GROUPS, 8), jnp.float32),
            interpret=interpret,
        )(jnp.asarray([[cutoff_days]], jnp.int32), rf, ls, qty, price, disc,
          tax, ship, valid)

    return Q1State(
        sum_qty=out[:, 0], sum_base_price=out[:, 1],
        sum_disc_price=out[:, 2], sum_charge=out[:, 3],
        sum_disc=out[:, 4],
        count=out[:, 5].astype(jnp.int32),
    )


_BEST = {}


def q1_step_best(interpret: bool = False):
    """Jitted full Q1 step using the pallas partial when the backend accepts
    it, the XLA einsum path otherwise (compile-or-fallback, cached)."""
    from .q1 import make_example_batch, q1_final, q1_step

    key = (jax.default_backend(), interpret)
    if key in _BEST:
        return _BEST[key]

    @jax.jit
    def pallas_step(batch, cutoff):
        return q1_final(q1_partial_pallas(batch, cutoff,
                                          interpret=interpret))

    try:
        probe, cutoff = make_example_batch(1 << 15)
        jax.block_until_ready(pallas_step(probe, jnp.int32(cutoff)))
        _BEST[key] = pallas_step
    except Exception:  # noqa: BLE001 — backend rejected the kernel
        _BEST[key] = q1_step
    return _BEST[key]
