"""Fully-jittable TPC-H Q1 pipeline kernel — the framework's flagship compiled
query step (BASELINE milestone config #2).

This is the shape the exec layer lowers hot aggregations to when key
cardinality is small and known (dictionary-encoded keys): filter + projection
fused with a fixed-capacity scatter-add group table, no host synchronization
anywhere — one XLA executable per batch shape. The general exec path
(execs/aggregates.py) handles arbitrary cardinality with a sort-based plan.

Reference analogue: the fused scan→project→partial-agg iterator chain of
GpuAggFirstPassIterator (GpuAggregateExec.scala:549) — but compiled as ONE
program instead of a kernel launch per expression.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Q1 groups by (returnflag, linestatus): tiny key domain → direct-indexed table
N_FLAGS = 4
N_STATUS = 4
N_GROUPS = N_FLAGS * N_STATUS


class Q1Inputs(NamedTuple):
    """One columnar batch of lineitem (dictionary-encoded keys)."""
    returnflag: jax.Array   # int32 codes [0, N_FLAGS)
    linestatus: jax.Array   # int32 codes [0, N_STATUS)
    quantity: jax.Array     # float32
    extendedprice: jax.Array  # float32
    discount: jax.Array     # float32
    tax: jax.Array          # float32
    shipdate: jax.Array     # int32 days since epoch
    valid: jax.Array        # bool row mask (padding/validity)


class Q1State(NamedTuple):
    """Per-group partial aggregate state (the shuffle payload in multi-chip)."""
    sum_qty: jax.Array
    sum_base_price: jax.Array
    sum_disc_price: jax.Array
    sum_charge: jax.Array
    sum_disc: jax.Array
    count: jax.Array


def q1_partial(batch: Q1Inputs, cutoff_days: jnp.int32) -> Q1State:
    """Filter (shipdate <= cutoff) + project + grouped partial aggregation.

    Segment-sum strategy: with a small known group count, the reduction is a
    one-hot matmul — [n, 6 measures]ᵀ gathered through onehot[n, 16] on the MXU.
    Scatter-add (`.at[].add`) serializes under index collisions on TPU; the
    matmul form keeps the whole pipeline bandwidth-bound (this is the central
    "design for the MXU" decision of the aggregation layer)."""
    keep = batch.valid & (batch.shipdate <= cutoff_days)
    group = (batch.returnflag * N_STATUS + batch.linestatus).astype(jnp.int32)
    w = keep.astype(jnp.float32)

    qty = batch.quantity * w
    price = batch.extendedprice * w
    disc_price = batch.extendedprice * (1.0 - batch.discount) * w
    charge = disc_price * (1.0 + batch.tax)
    disc = batch.discount * w

    measures = jnp.stack([qty, price, disc_price, charge, disc, w], axis=1)
    onehot = jax.nn.one_hot(group, N_GROUPS, dtype=jnp.float32)
    sums = jnp.einsum("ng,nm->gm", onehot, measures,
                      preferred_element_type=jnp.float32)

    return Q1State(
        sum_qty=sums[:, 0],
        sum_base_price=sums[:, 1],
        sum_disc_price=sums[:, 2],
        sum_charge=sums[:, 3],
        sum_disc=sums[:, 4],
        count=sums[:, 5].astype(jnp.int32),
    )


def q1_final(state: Q1State):
    """Final projection: averages from sums/counts (reference
    GpuAggFinalPassIterator result projection)."""
    n = jnp.maximum(state.count, 1).astype(jnp.float32)
    return {
        "sum_qty": state.sum_qty,
        "sum_base_price": state.sum_base_price,
        "sum_disc_price": state.sum_disc_price,
        "sum_charge": state.sum_charge,
        "avg_qty": state.sum_qty / n,
        "avg_price": state.sum_base_price / n,
        "avg_disc": state.sum_disc / n,
        "count_order": state.count,
    }


@jax.jit
def q1_step(batch: Q1Inputs, cutoff_days: jnp.int32):
    """Single-chip forward step: one compiled program for the whole query."""
    return q1_final(q1_partial(batch, cutoff_days))


def make_example_batch(n: int = 1 << 16, seed: int = 0) -> Tuple[Q1Inputs, np.int32]:
    rng = np.random.default_rng(seed)
    batch = Q1Inputs(
        returnflag=jnp.asarray(rng.integers(0, 3, n, dtype=np.int32)),
        linestatus=jnp.asarray(rng.integers(0, 2, n, dtype=np.int32)),
        quantity=jnp.asarray(rng.integers(1, 51, n).astype(np.float32)),
        extendedprice=jnp.asarray((rng.random(n) * 1e5).astype(np.float32)),
        discount=jnp.asarray((rng.random(n) * 0.1).astype(np.float32)),
        tax=jnp.asarray((rng.random(n) * 0.08).astype(np.float32)),
        shipdate=jnp.asarray(rng.integers(8000, 11000, n, dtype=np.int32)),
        valid=jnp.ones((n,), jnp.bool_),
    )
    return batch, np.int32(10471)  # 1998-09-02 in days-since-epoch


def q1_reference_numpy(batch: Q1Inputs, cutoff: int) -> Dict[str, np.ndarray]:
    """Pure-numpy oracle for correctness checks."""
    b = {k: np.asarray(v) for k, v in batch._asdict().items()}
    keep = b["valid"] & (b["shipdate"] <= cutoff)
    group = b["returnflag"] * N_STATUS + b["linestatus"]
    out = {}
    disc_price = b["extendedprice"] * (1 - b["discount"])
    charge = disc_price * (1 + b["tax"])
    sums = {"sum_qty": b["quantity"], "sum_base_price": b["extendedprice"],
            "sum_disc_price": disc_price, "sum_charge": charge}
    for name, col in sums.items():
        out[name] = np.bincount(group[keep], weights=col[keep],
                                minlength=N_GROUPS).astype(np.float64)
    out["count_order"] = np.bincount(group[keep], minlength=N_GROUPS)
    return out
