"""Device regex v1: compile a Java-regex subset to a byte DFA executed on
TPU over the Arrow offsets+bytes string layout.

Reference analogue: RegexParser.scala's CudfRegexTranspiler (:687) —
transpile-or-reject to a *device* regex engine. cuDF ships a CUDA NFA
engine; XLA has nothing, so the TPU formulation compiles the pattern
host-side all the way to a DFA table and executes it as a fixed-shape
table-walk: state[row] advances one byte per step of a fori_loop whose trip
count is the longest row's byte length. All rows advance in lock-step on
the VPU (one gather from the byte buffer + one 2D table lookup per step);
work is O(rows * max_len) with full lane parallelism, which beats any
host round-trip for the batch sizes the exec layer feeds us.

Supported subset (reject -> host fallback, same policy as the reference):
literals, escaped metas, \\d \\D \\w \\W \\s \\S, char classes with ranges
and negation (ASCII), '.', alternation, groups, greedy/lazy quantifiers
* + ? {m} {m,} {m,n} (bounded expansion), leading ^ / trailing $.
Rejected: backrefs, lookaround, unicode properties, possessive
quantifiers, mid-pattern anchors, word boundaries, non-ASCII pattern
bytes, or a DFA exceeding the state cap.

Find-vs-anchored semantics are folded into the automaton: without ^ the
start state self-loops on every byte, without $ accepting states absorb —
so "some substring matches" is exactly "state after the LAST byte accepts",
and one uniform execution handles rlike/^/$ forms.
"""

from __future__ import annotations

import functools
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

import numpy as np

MAX_DFA_STATES = 128
MAX_EXPANSION = 512  # AST atom budget after {m,n} duplication

_ALL = frozenset(range(256))
_LINE_TERMS = frozenset((0x0A, 0x0D))
_DOT = _ALL - _LINE_TERMS
_DIGIT = frozenset(range(0x30, 0x3A))
_WORD = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
    + list(range(0x61, 0x7B)) + [0x5F])
_SPACE = frozenset((0x20, 0x09, 0x0A, 0x0B, 0x0C, 0x0D))


class RegexReject(Exception):
    """Pattern is outside the device subset."""


# --- AST -------------------------------------------------------------------

class _Node:
    pass


class _Lit(_Node):
    def __init__(self, bytes_: FrozenSet[int]):
        self.bytes = bytes_

    def count(self):
        return 1


class _Concat(_Node):
    def __init__(self, parts: List[_Node]):
        self.parts = parts

    def count(self):
        return sum(p.count() for p in self.parts)


class _Alt(_Node):
    def __init__(self, parts: List[_Node]):
        self.parts = parts

    def count(self):
        return sum(p.count() for p in self.parts)


class _Star(_Node):
    def __init__(self, inner: _Node):
        self.inner = inner

    def count(self):
        return self.inner.count()


class _Empty(_Node):
    def count(self):
        return 0


# --- parser ----------------------------------------------------------------

class _Parser:
    def __init__(self, pattern: str):
        try:
            self.p = pattern.encode("ascii")
        except UnicodeEncodeError:
            raise RegexReject("non-ASCII pattern")
        self.i = 0
        self.anchored_start = False
        self.anchored_end = False
        self.top_level_alt = False
        self.has_alternation = False  # any '|' at any depth
        self.has_lazy = False         # any lazy quantifier marker

    def parse(self) -> _Node:
        if self.p.startswith(b"^"):
            self.anchored_start = True
            self.i = 1
        node = self._alt(top=True)
        if self.i != len(self.p):
            raise RegexReject(f"unparsed tail at {self.i}")
        if self.top_level_alt and (self.anchored_start or self.anchored_end):
            # Java scopes a leading ^ / trailing $ to only the first / last
            # alternative ('^a|b' is '(^a)|(b)'), while this parser would
            # anchor the whole alternation.  Per-branch anchor modeling is not
            # implemented, so such patterns must go to the host engine.
            # '^(a|b)$' is unaffected: its '|' is consumed inside a group.
            raise RegexReject("anchor over top-level alternation")
        return node

    def _peek(self) -> int:
        return self.p[self.i] if self.i < len(self.p) else -1

    def _alt(self, top: bool = False) -> _Node:
        parts = [self._concat(top)]
        while self._peek() == 0x7C:  # '|'
            self.i += 1
            self.has_alternation = True
            if top:
                self.top_level_alt = True
            parts.append(self._concat(top))
        return parts[0] if len(parts) == 1 else _Alt(parts)

    def _concat(self, top: bool) -> _Node:
        parts: List[_Node] = []
        while True:
            c = self._peek()
            if c in (-1, 0x7C) or c == 0x29:  # end, '|', ')'
                break
            if c == 0x24:  # '$'
                # only valid as the very last pattern byte at top level
                if top and self.i == len(self.p) - 1:
                    self.anchored_end = True
                    self.i += 1
                    break
                raise RegexReject("mid-pattern $")
            if c == 0x5E:  # '^'
                raise RegexReject("mid-pattern ^")
            parts.append(self._repeat(top))
        if not parts:
            return _Empty()
        return parts[0] if len(parts) == 1 else _Concat(parts)

    def _repeat(self, top: bool) -> _Node:
        node = self._atom(top)
        while True:
            c = self._peek()
            if c == 0x2A:  # '*'
                self.i += 1
                node = _Star(node)
            elif c == 0x2B:  # '+'
                self.i += 1
                node = _Concat([node, _Star(node)])
            elif c == 0x3F:  # '?'
                self.i += 1
                node = _Alt([node, _Empty()])
            elif c == 0x7B:  # '{'
                node = self._bounded(node)
            else:
                break
            # lazy marker: greedy==lazy for boolean acceptance (span-based
            # consumers must check has_lazy and reject)
            if self._peek() == 0x3F:
                self.i += 1
                self.has_lazy = True
            if self._peek() == 0x2B:  # possessive
                raise RegexReject("possessive quantifier")
        return node

    def _bounded(self, node: _Node) -> _Node:
        close = self.p.find(b"}", self.i)
        if close < 0:
            raise RegexReject("unclosed {")
        body = self.p[self.i + 1:close].decode()
        self.i = close + 1
        try:
            if "," in body:
                lo_s, hi_s = body.split(",", 1)
                lo = int(lo_s)
                hi = int(hi_s) if hi_s else None
            else:
                lo = hi = int(body)
        except ValueError:
            raise RegexReject(f"bad bound {{{body}}}")
        if hi is not None and hi < lo:
            raise RegexReject("bad bound order")
        parts: List[_Node] = [node] * lo
        if hi is None:
            parts.append(_Star(node))
        else:
            parts.extend([_Alt([node, _Empty()])] * (hi - lo))
        out = _Concat(parts) if parts else _Empty()
        if out.count() > MAX_EXPANSION:
            raise RegexReject("bound expansion too large")
        return out

    def _atom(self, top: bool) -> _Node:
        c = self._peek()
        if c == 0x28:  # '('
            self.i += 1
            if self.p[self.i:self.i + 2] == b"?:":
                self.i += 2
            elif self._peek() == 0x3F:
                raise RegexReject("special group")
            inner = self._alt()
            if self._peek() != 0x29:
                raise RegexReject("unclosed group")
            self.i += 1
            return inner
        if c == 0x5B:  # '['
            return _Lit(self._char_class())
        if c == 0x2E:  # '.'
            self.i += 1
            return _Lit(_DOT)
        if c == 0x5C:  # '\'
            return _Lit(self._escape())
        if c in (0x2A, 0x2B, 0x3F, 0x7B):
            raise RegexReject("dangling quantifier")
        self.i += 1
        return _Lit(frozenset((c,)))

    def _escape(self) -> FrozenSet[int]:
        self.i += 1
        c = self._peek()
        if c == -1:
            raise RegexReject("trailing backslash")
        self.i += 1
        simple = {0x64: _DIGIT, 0x44: _ALL - _DIGIT, 0x77: _WORD,
                  0x57: _ALL - _WORD, 0x73: _SPACE, 0x53: _ALL - _SPACE}
        if c in simple:
            return simple[c]
        ctrl = {0x6E: 0x0A, 0x74: 0x09, 0x72: 0x0D, 0x66: 0x0C,
                0x61: 0x07, 0x65: 0x1B}
        if c in ctrl:
            return frozenset((ctrl[c],))
        if c == 0x30:  # Java \0n[n[n]] octal escape — digits are REQUIRED
            digits = b""
            while len(digits) < 3 and 0x30 <= self._peek() <= 0x37:
                digits += bytes((self._peek(),))
                self.i += 1
            if not digits:
                raise RegexReject("bare \\0 (illegal octal escape in java)")
            v = int(digits.decode(), 8)
            if v > 0x7F:
                raise RegexReject("non-ASCII octal escape")
            return frozenset((v,))
        if c == 0x78:  # \xhh
            hx = self.p[self.i:self.i + 2]
            try:
                v = int(hx.decode(), 16)
            except ValueError:
                raise RegexReject("bad \\x escape")
            self.i += 2
            if v > 0x7F:
                raise RegexReject("non-ASCII escape")
            return frozenset((v,))
        if chr(c).isalnum():
            raise RegexReject(f"unsupported escape \\{chr(c)}")
        return frozenset((c,))  # escaped punctuation/meta

    def _char_class(self) -> FrozenSet[int]:
        self.i += 1  # '['
        negate = False
        if self._peek() == 0x5E:
            negate = True
            self.i += 1
        out: Set[int] = set()
        first = True
        while True:
            c = self._peek()
            if c == -1:
                raise RegexReject("unclosed class")
            if c == 0x5D and not first:  # ']'
                self.i += 1
                break
            first = False
            if c == 0x5B and self.p[self.i:self.i + 2] == b"[:":
                raise RegexReject("posix class")
            if c == 0x5C:
                s = self._escape()
                if len(s) != 1:
                    out |= s
                    continue
                # a single-byte escape can START a range: [\x41-\x45]
                c = next(iter(s))
            else:
                self.i += 1
            # range?
            if (self._peek() == 0x2D and self.i + 1 < len(self.p)
                    and self.p[self.i + 1] != 0x5D):
                self.i += 1
                hi = self._peek()
                if hi == 0x5C:
                    s = self._escape()
                    if len(s) != 1:
                        raise RegexReject("class range to multi-escape")
                    hi = next(iter(s))
                else:
                    self.i += 1
                if hi < c:
                    raise RegexReject("reversed class range")
                out |= set(range(c, hi + 1))
            else:
                out.add(c)
        if any(b > 0x7F for b in out):
            raise RegexReject("non-ASCII in class")
        return frozenset(_ALL - out) if negate else frozenset(out)


# --- NFA (Thompson) --------------------------------------------------------

class _NFA:
    def __init__(self):
        self.eps: List[List[int]] = []
        self.trans: List[List[Tuple[FrozenSet[int], int]]] = []

    def new_state(self) -> int:
        self.eps.append([])
        self.trans.append([])
        return len(self.eps) - 1

    def add(self, node: _Node, src: int, dst: int) -> None:
        if isinstance(node, _Empty):
            self.eps[src].append(dst)
        elif isinstance(node, _Lit):
            self.trans[src].append((node.bytes, dst))
        elif isinstance(node, _Concat):
            cur = src
            for part in node.parts[:-1]:
                nxt = self.new_state()
                self.add(part, cur, nxt)
                cur = nxt
            self.add(node.parts[-1] if node.parts else _Empty(), cur, dst)
        elif isinstance(node, _Alt):
            for part in node.parts:
                self.add(part, src, dst)
        elif isinstance(node, _Star):
            mid = self.new_state()
            self.eps[src].append(mid)
            self.add(node.inner, mid, mid)
            self.eps[mid].append(dst)
        else:  # pragma: no cover
            raise RegexReject(f"unknown node {node}")

    def eclose(self, states: FrozenSet[int]) -> FrozenSet[int]:
        seen = set(states)
        stack = list(states)
        while stack:
            s = stack.pop()
            for t in self.eps[s]:
                if t not in seen:
                    seen.add(t)
                    stack.append(t)
        return frozenset(seen)


class DFA:
    """table[s, cls] transition over byte classes; start/accept metadata."""

    def __init__(self, table: np.ndarray, byte_class: np.ndarray,
                 accepting: np.ndarray, start: int, pattern: str,
                 ascii_atoms: bool):
        self.table = table            # [S, n_classes] int32
        self.byte_class = byte_class  # [256] int32
        self.accepting = accepting    # [S] bool
        self.start = start
        self.pattern = pattern
        # every atom set is ASCII-only => byte-level run is exact for ANY
        # UTF-8 input (multi-byte chars can never match an ASCII atom, and
        # the find-loops consume them exactly like a char-level engine);
        # patterns with '.', negated classes or \D \W \S need ASCII data
        self.ascii_atoms = ascii_atoms

    @property
    def n_states(self) -> int:
        return self.table.shape[0]


def _byte_classes(sets: Sequence[FrozenSet[int]]) -> np.ndarray:
    """Partition 0..255 into equivalence classes under all transition sets
    (bytes with identical membership across every set share a class)."""
    sigs: Dict[Tuple[bool, ...], int] = {}
    out = np.zeros(256, np.int32)
    masks = []
    for s in sets:
        m = np.zeros(256, bool)
        m[list(s)] = True
        masks.append(m)
    for b in range(256):
        key = tuple(m[b] for m in masks)
        out[b] = sigs.setdefault(key, len(sigs))
    return out


@functools.lru_cache(maxsize=256)
def compile_dfa(pattern: str,
                max_states: int = None) -> Optional[DFA]:
    """Compile to a DFA for whole-row acceptance with find semantics folded
    in, or None when the pattern is outside the subset (host fallback)."""
    try:
        parser = _Parser(pattern)
        ast = parser.parse()
        if ast.count() > MAX_EXPANSION:
            raise RegexReject("pattern too large")
        nfa = _NFA()
        start = nfa.new_state()
        accept = nfa.new_state()
        nfa.add(ast, start, accept)
        ascii_atoms = all(max(s, default=0) < 0x80
                          for row in nfa.trans for (s, _) in row)
        if not parser.anchored_start:
            nfa.trans[start].append((_ALL, start))
        if parser.anchored_end:
            # Java (non-MULTILINE) '$' also matches just before a FINAL
            # line terminator: accept --\n-->F, --\r-->F, --\r\n-->F.
            # Unicode terminators (U+0085/U+2028/U+2029) can't be modeled
            # byte-wise, so $-anchored patterns require ASCII data.
            final = nfa.new_state()
            nfa.eps[accept].append(final)
            cr_mid = nfa.new_state()
            nfa.trans[accept].append((frozenset((0x0D,)), cr_mid))
            nfa.trans[cr_mid].append((frozenset((0x0A,)), final))
            nfa.eps[cr_mid].append(final)
            nfa.trans[accept].append((frozenset((0x0A,)), final))
            accept = final
            ascii_atoms = False
        else:
            nfa.trans[accept].append((_ALL, accept))

        all_sets = [s for row in nfa.trans for (s, _) in row] or [_ALL]
        byte_class = _byte_classes(all_sets)
        n_classes = int(byte_class.max()) + 1
        # representative byte per class
        reps = [int(np.argmax(byte_class == c)) for c in range(n_classes)]

        d0 = nfa.eclose(frozenset((start,)))
        states: List[FrozenSet[int]] = [d0]
        ids: Dict[FrozenSet[int], int] = {d0: 0}
        rows: List[List[int]] = []
        i = 0
        while i < len(states):
            cur = states[i]
            row = []
            for rep in reps:
                nxt = set()
                for s in cur:
                    for bs, t in nfa.trans[s]:
                        if rep in bs:
                            nxt.add(t)
                closed = nfa.eclose(frozenset(nxt))
                if closed not in ids:
                    if len(states) >= (max_states or MAX_DFA_STATES):
                        raise RegexReject("DFA too large")
                    ids[closed] = len(states)
                    states.append(closed)
                row.append(ids[closed])
            rows.append(row)
            i += 1
        table = np.asarray(rows, np.int32)
        accepting = np.asarray([accept in st for st in states], bool)
        return DFA(table, byte_class, accepting, 0, pattern, ascii_atoms)
    except RegexReject:
        return None


MAX_DEVICE_ROW_BYTES = 4096  # longer rows go to the host engine


def rlike_device(data, offsets, num_rows: int, dfa: DFA, max_len: int):
    """Run the DFA over every row in lock-step. data: uint8[nbytes] HBM
    buffer; offsets: int32[n+1]. Returns bool[num_rows_capacity] matches.

    Each of the `max_len` steps advances every row's state by one byte:
    a gather from the byte buffer and a [S, C] table lookup — no host
    round-trips, no dynamic shapes."""
    import jax
    import jax.numpy as jnp

    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    n = starts.shape[0]
    nbytes = int(data.shape[0])
    table = jnp.asarray(dfa.table)          # [S, C]
    cls = jnp.asarray(dfa.byte_class)       # [256]
    accepting = jnp.asarray(dfa.accepting)  # [S]

    state0 = jnp.full((n,), dfa.start, jnp.int32)
    if nbytes == 0 or max_len == 0:
        return accepting[state0]

    def body(j, state):
        pos = jnp.clip(starts + j, 0, nbytes - 1)
        byte = data[pos].astype(jnp.int32)
        nxt = table[state, cls[byte]]
        return jnp.where(j < lens, nxt, state)

    final = jax.lax.fori_loop(0, max_len, body, state0)
    return accepting[final]


# --- span matching (regexp_replace / regexp_extract) ------------------------

MAX_DEVICE_SPAN_ROW_BYTES = 512  # span walk is O(nbytes · max_row_len)


class ExactDFA(DFA):
    """DFA for exact-at-position matching: no find loops, plus a dead state
    and the shortest accepted length (for output-capacity bounds)."""

    def __init__(self, base: DFA, dead: int, min_len: int):
        super().__init__(base.table, base.byte_class, base.accepting,
                         base.start, base.pattern, base.ascii_atoms)
        self.dead = dead
        self.min_len = min_len


def _flatten_atoms(node: _Node) -> List[_Node]:
    """Top-level atom sequence: Concats inlined, quantified nodes atomic."""
    if isinstance(node, _Concat):
        out: List[_Node] = []
        for p in node.parts:
            out.extend(_flatten_atoms(p))
        return out
    return [node]


def _contains_var(node: _Node) -> bool:
    if isinstance(node, (_Star, _Alt)):
        return True
    if isinstance(node, _Concat):
        return any(_contains_var(p) for p in node.parts)
    return False


def _byteset(node: _Node) -> FrozenSet[int]:
    if isinstance(node, _Lit):
        return node.bytes
    if isinstance(node, _Star):
        return _byteset(node.inner)
    if isinstance(node, (_Concat, _Alt)):
        out: Set[int] = set()
        for p in node.parts:
            out |= _byteset(p)
        return frozenset(out)
    return frozenset()


def _nullable(node: _Node) -> bool:
    if isinstance(node, (_Star, _Empty)):
        return True
    if isinstance(node, _Alt):
        return any(_nullable(p) for p in node.parts)
    if isinstance(node, _Concat):
        return all(_nullable(p) for p in node.parts)
    return False


def _first_set(node: _Node) -> FrozenSet[int]:
    if isinstance(node, _Lit):
        return node.bytes
    if isinstance(node, _Star):
        return _first_set(node.inner)
    if isinstance(node, _Alt):
        out: Set[int] = set()
        for p in node.parts:
            out |= _first_set(p)
        return frozenset(out)
    if isinstance(node, _Concat):
        out = set()
        for p in node.parts:
            out |= _first_set(p)
            if not _nullable(p):
                break
        return frozenset(out)
    return frozenset()


def _var_atom(seg: _Node) -> Optional[_Node]:
    """The single-repetition atom of a variable-length segment, or None if
    the segment is fixed. After the global alternation rejection, any _Alt
    is a desugared '?' / '{m,n}' optional: [node, _Empty]."""
    if isinstance(seg, _Star):
        return seg.inner
    if isinstance(seg, _Alt):
        real = [p for p in seg.parts if not isinstance(p, _Empty)]
        return real[0] if len(real) == 1 else seg
    return None


def _reject_ambiguous_span(ast: _Node) -> None:
    """Greedy backtracking (Java) == leftmost-longest (this DFA) only for
    unambiguous-match-length patterns (ADVICE r4 high). Divergence needs a
    variable-length segment V followed — across only nullable segments — by
    another variable segment W whose first-set overlaps V's bytes, where at
    least one of the two repeats a MULTI-byte atom: for `a+(ab)?` on "aab"
    Java matches "aa" (greedy a+ never gives bytes back to lengthen the
    total) while the DFA takes "aab". Single-byte-atom chains (a{0,2}x?,
    [ab]*c*) are safe: one byte per repetition means surrendering a byte to
    a later single-byte quantifier never extends the overall end. Nested
    variable quantifiers ((a*b)+) are rejected outright — their inner
    backtracking order is beyond this static check."""
    segs = _flatten_atoms(ast)
    atoms = [_var_atom(s) for s in segs]
    for i, ai in enumerate(atoms):
        if ai is None:
            continue
        if _contains_var(ai):
            raise RegexReject("nested variable quantifier span")
        multi_i = ai.count() >= 2
        bytes_i = _byteset(segs[i])
        for j in range(i + 1, len(segs)):
            aj = atoms[j]
            if aj is not None:
                if (bytes_i & _first_set(segs[j])
                        and (multi_i or aj.count() >= 2)):
                    raise RegexReject("ambiguous greedy span: variable "
                                      "segments with overlapping byte sets")
            # a required segment ends the competition window ONLY if V
            # could never have consumed it: a required atom overlapping V's
            # bytes may sit inside V's territory ((ab)*a(bab)? — the 'a'
            # does not fence off the later (bab)?), so keep scanning
            if not _nullable(segs[j]) and not (_byteset(segs[j]) & bytes_i):
                break


@functools.lru_cache(maxsize=256)
def compile_exact_dfa(pattern: str,
                      max_states: int = None) -> Optional["ExactDFA"]:
    """Compile for SPAN matching (longest match starting at a position), or
    None when outside the subset. Rejections beyond compile_dfa's:
      * '|' anywhere and lazy quantifiers: Java's backtracking engine picks
        the first-alternative / shortest span, not the longest the DFA
        computes (greedy-only concat/class/quantifier patterns ARE
        leftmost-longest, which is what Java picks for them);
      * anchors: find-with-spans over '^'/'$' is a different machine;
      * nullable patterns: Java's empty-match advance rules
        (replaceAll("a*",..) emitting between every char) are out of scope.
    """
    try:
        parser = _Parser(pattern)
        ast = parser.parse()
        if parser.anchored_start or parser.anchored_end:
            raise RegexReject("anchored pattern for span matching")
        if parser.has_alternation:
            raise RegexReject("alternation: greedy-first != longest")
        if parser.has_lazy:
            raise RegexReject("lazy quantifier span")
        if ast.count() > MAX_EXPANSION:
            raise RegexReject("pattern too large")
        _reject_ambiguous_span(ast)
        nfa = _NFA()
        start = nfa.new_state()
        accept = nfa.new_state()
        nfa.add(ast, start, accept)
        ascii_atoms = all(max(s, default=0) < 0x80
                          for row in nfa.trans for (s, _) in row)

        all_sets = [s for row in nfa.trans for (s, _) in row] or [_ALL]
        byte_class = _byte_classes(all_sets)
        n_classes = int(byte_class.max()) + 1
        reps = [int(np.argmax(byte_class == c)) for c in range(n_classes)]

        d0 = nfa.eclose(frozenset((start,)))
        if accept in d0:
            raise RegexReject("nullable pattern (matches empty)")
        states: List[FrozenSet[int]] = [d0]
        ids: Dict[FrozenSet[int], int] = {d0: 0}
        rows: List[List[int]] = []
        i = 0
        while i < len(states):
            cur = states[i]
            row = []
            for rep in reps:
                nxt = set()
                for s in cur:
                    for bs, t in nfa.trans[s]:
                        if rep in bs:
                            nxt.add(t)
                closed = nfa.eclose(frozenset(nxt))
                if closed not in ids:
                    if len(states) >= (max_states or MAX_DFA_STATES):
                        raise RegexReject("DFA too large")
                    ids[closed] = len(states)
                    states.append(closed)
                row.append(ids[closed])
            rows.append(row)
            i += 1
        table = np.asarray(rows, np.int32)
        accepting = np.asarray([accept in st for st in states], bool)
        dead = ids.get(frozenset())
        if dead is None:  # make an explicit dead state
            dead = len(states)
            table = np.vstack([table, np.full((1, n_classes), dead,
                                              np.int32)])
            accepting = np.append(accepting, False)
        # shortest accepted length: BFS over the DFA
        from collections import deque
        dist = {0: 0}
        dq = deque([0])
        min_len = None
        while dq:
            s = dq.popleft()
            if accepting[s]:
                min_len = dist[s]
                break
            for t in table[s]:
                t = int(t)
                if t not in dist:
                    dist[t] = dist[s] + 1
                    dq.append(t)
        if not min_len:  # unreachable accept or nullable: host
            raise RegexReject("no reachable non-empty match")
        base = DFA(table, byte_class, accepting, 0, pattern, ascii_atoms)
        return ExactDFA(base, dead, min_len)
    except RegexReject:
        return None


def match_lengths_device(data, offsets, dfa: "ExactDFA", max_len: int):
    """int32[nbytes]: longest match length starting at each byte position
    (0 = no match there). Diagonal DFA walk: every byte position is a lane;
    step t feeds lane p the byte at p+t, masked at its row end."""
    import jax
    import jax.numpy as jnp

    from .strings import byte_rows
    nbytes = int(data.shape[0])
    if nbytes == 0:
        return jnp.zeros((0,), jnp.int32)
    rows = byte_rows(offsets, nbytes)
    rowend = jnp.take(offsets, rows + 1).astype(jnp.int32)
    table = jnp.asarray(dfa.table)
    cls = jnp.asarray(dfa.byte_class)
    accepting = jnp.asarray(dfa.accepting)
    dead = jnp.int32(dfa.dead)
    pos = jnp.arange(nbytes, dtype=jnp.int32)

    def body(t, carry):
        state, mlen = carry
        idx = pos + t
        ok = idx < rowend
        b = data[jnp.clip(idx, 0, nbytes - 1)].astype(jnp.int32)
        nxt = table[state, cls[b]]
        state = jnp.where(ok, nxt, dead)
        mlen = jnp.where(accepting[state], t + 1, mlen)
        return state, mlen

    _, mlen = jax.lax.fori_loop(
        0, max_len, body,
        (jnp.full((nbytes,), dfa.start, jnp.int32),
         jnp.zeros((nbytes,), jnp.int32)))
    return mlen


def select_leftmost_nonoverlapping(mlen, offsets, max_row_len: int):
    """bool[nbytes]: Java replaceAll's match selection — scan each row left
    to right, take a match when its start is past the previous taken match's
    end. The scan runs over the row-offset axis (≤ max_row_len steps) with a
    per-ROW carry, so rows are processed in parallel."""
    import jax
    import jax.numpy as jnp

    nbytes = int(mlen.shape[0])
    n = int(offsets.shape[0]) - 1
    if nbytes == 0 or n == 0:
        return jnp.zeros((nbytes,), bool)
    starts = offsets[:-1].astype(jnp.int32)
    ends = offsets[1:].astype(jnp.int32)

    def step(allowed, o):
        j = starts + o
        ok = j < ends
        m = mlen[jnp.clip(j, 0, nbytes - 1)]
        take = ok & (m > 0) & (j >= allowed)
        allowed = jnp.where(take, j + m, allowed)
        return allowed, take

    _, takes = jax.lax.scan(step, starts,
                            jnp.arange(max_row_len, dtype=jnp.int32))
    # takes: [max_row_len, n] → flat bool[nbytes]
    grid = starts[None, :] + jnp.arange(max_row_len,
                                        dtype=jnp.int32)[:, None]
    ok = grid < ends[None, :]
    out = jnp.zeros((nbytes + 1,), bool)
    out = out.at[jnp.where(ok, grid, nbytes)].set(takes, mode="drop")
    return out[:nbytes]
