"""Device-side Parquet page-decode kernels (XLA, jit-composable).

Reference: the plugin decodes parquet bytes ON DEVICE after host staging —
`GpuParquetScan.scala:1983,2506` acquires the semaphore and hands the raw
(decompressed) column-chunk bytes to cuDF's page decoders. The TPU analogue
lives here: every O(rows) transform of the Parquet physical encodings is a
pure jnp function over device uint8 buffers, composed per row group into ONE
cached program by io/device_decode.py. The host touches only O(pages) +
O(runs) metadata (footer, page headers, RLE run headers) and the
decompression pass; the unpack/expand/gather/scatter work below runs on
device.

Encodings covered (the flat fixed-width column classes):

* **bit-unpacking** (`unpack_bits`) — 1..32-bit packed little-endian values
  at arbitrary per-element bit offsets (PLAIN booleans, bit-packed literal
  runs, dictionary indices of any per-page bit width);
* **RLE / bit-packed hybrid run expansion** (`expand_runs`) — dictionary
  indices and definition levels. The host walks the varint run headers into
  a run table (one row per run: output start, absolute bit offset, repeated
  value, literal flag, bit width); the kernel positions every output element
  in its run with one `searchsorted` and either bit-unpacks (literal run) or
  broadcasts the run value (RLE run);
* **dictionary gather** (`dictionary_gather`) — expanded indices into the
  PLAIN-decoded dictionary values;
* **definition levels → validity** (`validity_from_defs`) and **null
  compaction** (`expand_dense`) — Parquet stores only non-null values
  densely; the scatter re-expands them into the padded-batch layout
  `columnar/batch.py` uses (rows in [num_rows, capacity) stay zero/invalid);
* **PLAIN fixed-width reinterpret** (`plain_fixed_width`) — raw
  little-endian value bytes to int8/16/32/64, float32/64 carriers via byte
  math + bitcast (no host round trip);
* **BYTE_ARRAY strings** (`string_offsets`, `gather_string_bytes`) — the
  variable-width classes decode into the engine's own Arrow-style
  offsets+bytes layout (`columnar/vector.py`): per-row byte lengths (from
  the 4-byte PLAIN length prefixes, or gathered from the dictionary's
  entry lengths) cumsum into the int32 offsets vector, and one
  searchsorted byte gather materializes the char buffer — the same ragged
  shape `kernels/strings.py` computes over, so a decoded string column is
  immediately a first-class device string column.

All functions are shape-polymorphic jnp (no data-dependent host syncs), so
tracelint's kernel scan classifies them device-clean and io/device_decode.py
can fuse any per-row-group combination into a single dispatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: run-table column indices (int64 [n_runs, 5] built by io/device_decode.py;
#: padding runs carry start = RUN_PAD_START so searchsorted never lands on
#: them)
RUN_START, RUN_BITOFF, RUN_VALUE, RUN_LITERAL, RUN_WIDTH = range(5)
RUN_COLS = 5
RUN_PAD_START = 1 << 62


def unpack_bits(data_u8, bit_offsets, widths):
    """Unpack little-endian bit-packed values of per-element `widths` (1..32
    bits) starting at absolute `bit_offsets` into uint64 values.

    `data_u8` must carry >= 8 bytes of zero padding past the last addressed
    bit (io/device_decode.py pads every staged buffer); out-of-range offsets
    clip into the padding and decode to garbage the caller masks off.
    """
    pos = bit_offsets.astype(jnp.int64)
    byte = pos >> 3
    shift = (pos & 7).astype(jnp.uint64)
    word = jnp.zeros(pos.shape, jnp.uint64)
    for k in range(5):  # 5 bytes cover any 32-bit value at any bit shift
        word = word | (jnp.take(data_u8, byte + k, mode="clip")
                       .astype(jnp.uint64) << jnp.uint64(8 * k))
    mask = (jnp.uint64(1) << widths.astype(jnp.uint64)) - jnp.uint64(1)
    return (word >> shift) & mask


def expand_runs(run_table, data_u8, out_len: int):
    """Expand an RLE / bit-packed hybrid run table into `out_len` int64
    values (dictionary indices or definition levels).

    Each output element finds its run by binary search over the run starts,
    then either broadcasts the run's repeated value (RLE run) or bit-unpacks
    its element from the staged page bytes (bit-packed literal run).
    Elements past the last real run read padding and are masked downstream.
    """
    idx = jnp.arange(out_len, dtype=jnp.int64)
    starts = run_table[:, RUN_START]
    r = jnp.searchsorted(starts, idx, side="right") - 1
    r = jnp.clip(r, 0, run_table.shape[0] - 1)
    local = idx - jnp.take(starts, r, mode="clip")
    width = jnp.take(run_table[:, RUN_WIDTH], r, mode="clip")
    bitoff = jnp.take(run_table[:, RUN_BITOFF], r, mode="clip") \
        + local * width
    unpacked = unpack_bits(data_u8, bitoff, width).astype(jnp.int64)
    literal = jnp.take(run_table[:, RUN_LITERAL], r, mode="clip") != 0
    value = jnp.take(run_table[:, RUN_VALUE], r, mode="clip")
    return jnp.where(literal, unpacked, value)


def validity_from_defs(def_levels, max_def, num_rows):
    """Definition levels → dense validity mask over the padded capacity.
    Rows in [num_rows, capacity) are padding and always invalid."""
    n = def_levels.shape[0]
    in_range = jnp.arange(n, dtype=jnp.int64) < num_rows
    return (def_levels == max_def) & in_range


def expand_dense(dense, validity):
    """Null compaction inverse: scatter the densely-stored non-null values
    into their row slots (Parquet data pages store only rows whose
    definition level is max_def). Null/padding rows read zero."""
    pos = jnp.cumsum(validity.astype(jnp.int64)) - 1
    safe = jnp.clip(pos, 0, dense.shape[0] - 1)
    g = jnp.take(dense, safe, axis=0, mode="clip")
    return jnp.where(validity, g, jnp.zeros((), dense.dtype))


def dictionary_gather(dict_values, indices):
    """Gather decoded dictionary values by expanded indices (clipped: padding
    indices land on dictionary slot 0 and are masked by validity)."""
    return jnp.take(dict_values, indices.astype(jnp.int32), axis=0,
                    mode="clip")


def plain_fixed_width(data_u8, itemsize: int, kind: str):
    """PLAIN fixed-width reinterpret: little-endian value bytes → carrier
    values, entirely on device (byte combine + bitcast).

    kind: "i" signed int, "u" unsigned int, "f" float; itemsize 1/2/4/8.
    """
    b = data_u8.reshape(-1, itemsize).astype(jnp.uint64)
    word = jnp.zeros((b.shape[0],), jnp.uint64)
    for k in range(itemsize):
        word = word | (b[:, k] << jnp.uint64(8 * k))
    if kind == "f":
        if itemsize == 4:
            return jax.lax.bitcast_convert_type(
                word.astype(jnp.uint32), jnp.float32)
        return jax.lax.bitcast_convert_type(word, jnp.float64)
    target = {1: jnp.int8, 2: jnp.int16, 4: jnp.int32, 8: jnp.int64}[itemsize]
    if kind == "u":
        utarget = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
                   8: jnp.uint64}[itemsize]
        return word.astype(utarget)
    narrow = {1: jnp.uint8, 2: jnp.uint16, 4: jnp.uint32,
              8: jnp.uint64}[itemsize]
    return jax.lax.bitcast_convert_type(word.astype(narrow), target)


def merge_plain_segments(seg_table, plain_values, base, out_len: int):
    """Mid-chunk dictionary fallback: once a writer's dictionary overflows,
    later data pages store PLAIN values while earlier pages stay
    dictionary-indexed (parquet's standard fallback; cuDF decodes such
    chunks natively). `seg_table` marks each data page's dense range
    ([dense_start, plain_src_start, 0, is_plain, 0] rows): elements inside
    a PLAIN page's range read `plain_values[src_start + (i - dense_start)]`,
    everything else keeps `base` (the dictionary-gathered stream)."""
    idx = jnp.arange(out_len, dtype=jnp.int64)
    starts = seg_table[:, RUN_START]
    r = jnp.searchsorted(starts, idx, side="right") - 1
    r = jnp.clip(r, 0, seg_table.shape[0] - 1)
    src = jnp.take(seg_table[:, RUN_BITOFF], r, mode="clip") \
        + idx - jnp.take(starts, r, mode="clip")
    is_plain = jnp.take(seg_table[:, RUN_LITERAL], r, mode="clip") != 0
    vals = jnp.take(plain_values,
                    jnp.clip(src, 0, plain_values.shape[0] - 1), axis=0)
    return jnp.where(is_plain, vals, base)


def string_offsets(row_lengths):
    """Per-row byte lengths → the Arrow-style int32 offsets vector
    (length capacity+1, offsets[0] == 0). Null and padding rows carry
    length 0, so their offsets repeat the running total — exactly the
    layout `TpuColumnVector.from_strings` builds host-side."""
    return jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(row_lengths.astype(jnp.int32), dtype=jnp.int32)])


def gather_string_bytes(data_u8, row_starts, offsets, out_len: int):
    """Materialize the output char buffer: output byte j belongs to row
    r = searchsorted(offsets, j) and reads
    `data_u8[row_starts[r] + (j - offsets[r])]` (the dictionary bytes or
    the staged PLAIN value region). Bytes past the total length
    (offsets[-1]) are zero padding."""
    j = jnp.arange(out_len, dtype=jnp.int32)
    r = jnp.searchsorted(offsets[1:], j, side="right").astype(jnp.int32)
    r = jnp.clip(r, 0, row_starts.shape[0] - 1)
    src = jnp.take(row_starts, r).astype(jnp.int64) \
        + (j - jnp.take(offsets, r)).astype(jnp.int64)
    in_range = j < offsets[offsets.shape[0] - 1]
    got = jnp.take(data_u8, jnp.clip(src, 0, data_u8.shape[0] - 1),
                   mode="clip")
    return jnp.where(in_range, got, jnp.uint8(0))


def decode_bool_runs(run_table, data_u8, out_len: int):
    """Boolean values from the run machinery: PLAIN bit-packed pages stage
    as one literal run each (width 1), RLE-encoded pages (data page v2) as
    ordinary runs — either way the expansion is `expand_runs` != 0."""
    return expand_runs(run_table, data_u8, out_len) != 0
