"""Device string kernels over the Arrow offsets+bytes layout.

The reference implements its string surface as hand-written CUDA over cuDF's
string columns (stringFunctions.scala, 2433 LoC; cudf strings/ kernels). The
TPU-native formulation (SURVEY.md §7 "Variable-width strings in XLA") keeps the
same physical layout — int32 offsets + a flat uint8 byte buffer, both resident
in HBM — and expresses every op as a composition of three XLA-friendly pieces:

  1. a byte→row map (`searchsorted` over the offsets),
  2. segment reductions over that map (first/last/any/count per row),
  3. one ragged gather that materializes the output byte buffer from
     per-row (start, length) ranges — with a *static* output capacity bound
     computed host-side, so XLA never sees a dynamic shape.

Everything here is pure jax on fixed shapes: no host hop, no per-row Python.
Ops with character (not byte) semantics take the ASCII fast path on device and
leave non-ASCII to the caller's host fallback — the same pricing the reference
applies via incompat tags for locale-sensitive ops.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_BIG = np.int32(2**31 - 1)


# ---------------------------------------------------------------------------
# layout helpers
# ---------------------------------------------------------------------------

def starts_lengths(offsets: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row (start, byte-length) from an offsets vector."""
    starts = offsets[:-1]
    return starts, offsets[1:] - starts


def byte_rows(offsets: jax.Array, nbytes: int) -> jax.Array:
    """Row index of every byte position in [0, nbytes). Bytes past the last
    offset map to the last row (callers mask with `in-range` tests)."""
    return jnp.searchsorted(offsets[1:], jnp.arange(nbytes, dtype=jnp.int32),
                            side="right").astype(jnp.int32)


def is_ascii(data: jax.Array) -> bool:
    """Host-synced scalar: True when every byte is ASCII. One scalar D→H
    transfer gates the device fast path (chars == bytes)."""
    if int(data.shape[0]) == 0:
        return True
    return bool(jnp.all(data < 0x80))


def segment_min(values: jax.Array, rows: jax.Array, n: int,
                init=_BIG) -> jax.Array:
    return jnp.full((n,), init, values.dtype).at[rows].min(values, mode="drop")


def segment_max(values: jax.Array, rows: jax.Array, n: int,
                init=np.int32(-1)) -> jax.Array:
    return jnp.full((n,), init, values.dtype).at[rows].max(values, mode="drop")


def segment_sum(values: jax.Array, rows: jax.Array, n: int) -> jax.Array:
    return jnp.zeros((n,), values.dtype).at[rows].add(values, mode="drop")


# ---------------------------------------------------------------------------
# the ragged output builder
# ---------------------------------------------------------------------------

def gather_plan(starts: jax.Array, lengths: jax.Array, out_cap: int,
                stride: Optional[jax.Array] = None
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The shared ragged-gather index computation (strings AND list columns):
    output slot j of row i reads source index starts[i] + k*stride[i] where k
    is j's position within the row. Returns (src_idx[out_cap],
    in_range[out_cap], new_offsets[n+1]); callers gather data/validity with
    the same plan."""
    n = int(starts.shape[0])
    lengths = jnp.maximum(lengths, 0).astype(jnp.int32)
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(lengths, dtype=jnp.int32)])
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, max(n - 1, 0))
    pos = j - new_offs[row_c]
    step = stride[row_c] if stride is not None else 1
    src = starts[row_c] + pos * step
    return src, j < new_offs[n], new_offs


def build_ranges(data: jax.Array, starts: jax.Array, lengths: jax.Array,
                 out_cap: int, stride: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """Materialize a new string column whose row i is the byte range
    data[starts[i] : starts[i] + lengths[i]] (negative lengths clamp to 0).

    `out_cap` is the static output byte capacity — callers bound it host-side
    (e.g. substring output never exceeds input capacity). `stride`, when given,
    replaces the unit step: output byte k of row i reads
    data[starts[i] + k*stride[i]] (stride -1 + start at row end = reverse).

    Returns (out_bytes[out_cap], new_offsets[n+1]).
    """
    nbytes = int(data.shape[0])
    src, in_range, new_offs = gather_plan(starts, lengths, out_cap,
                                          stride=stride)
    if nbytes == 0:
        return jnp.zeros((out_cap,), jnp.uint8), new_offs
    out = jnp.where(in_range, data[jnp.clip(src, 0, nbytes - 1)],
                    jnp.uint8(0))
    return out, new_offs


def build_from_contributions(data: jax.Array, keep: jax.Array,
                             offsets: jax.Array, out_cap: int,
                             replace_at: Optional[jax.Array] = None,
                             replacement: Optional[np.ndarray] = None,
                             mapped: Optional[jax.Array] = None
                             ) -> Tuple[jax.Array, jax.Array]:
    """Per-input-byte output construction: input byte j emits
      * `replacement` (len r static) when replace_at[j] (a taken match start),
      * nothing when not keep[j],
      * else the single byte mapped[j] (defaults to data[j]).

    This is the translate/replace/delete builder: output position of byte j is
    the exclusive cumsum of per-byte emit counts; scatter resolves the rest.
    Returns (out_bytes[out_cap], new_offsets[n+1]).
    """
    n = int(offsets.shape[0]) - 1
    nbytes = int(data.shape[0])
    rlen = 0 if replacement is None else int(replacement.shape[0])
    contrib = keep.astype(jnp.int32)
    if replace_at is not None:
        contrib = jnp.where(replace_at, jnp.int32(rlen), contrib)
    cum = jnp.cumsum(contrib, dtype=jnp.int32)
    out_pos = cum - contrib  # exclusive
    rows = byte_rows(offsets, nbytes)
    new_lens = segment_sum(contrib, rows, n)
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(new_lens, dtype=jnp.int32)])
    src = data if mapped is None else mapped
    out = jnp.zeros((out_cap,), jnp.uint8)
    plain = keep & ((replace_at == False) if replace_at is not None  # noqa: E712
                    else jnp.ones_like(keep))
    idx = jnp.where(plain, out_pos, out_cap)  # out-of-range drops
    out = out.at[idx].set(src.astype(jnp.uint8), mode="drop")
    if replace_at is not None and rlen:
        for k in range(rlen):
            idx_k = jnp.where(replace_at, out_pos + k, out_cap)
            out = out.at[idx_k].set(jnp.uint8(replacement[k]), mode="drop")
    return out, new_offs


def concat_columns(parts: Sequence[Tuple[jax.Array, jax.Array, jax.Array]],
                   out_cap: int,
                   part_emit: Optional[Sequence[jax.Array]] = None,
                   seps: Optional[Sequence[Tuple[np.ndarray, jax.Array]]] = None
                   ) -> Tuple[jax.Array, jax.Array]:
    """Row-wise concatenation of K string columns.

    parts: per column (data, starts, lengths). part_emit: per column a bool[n]
    — rows where the column contributes nothing (concat_ws null-skip). seps:
    optional per-column (sep_bytes, emit_sep bool[n]) PREPENDED before that
    column's bytes when emit_sep (concat_ws separators between non-null parts).
    Returns (out_bytes[out_cap], new_offsets[n+1]).
    """
    n = int(parts[0][1].shape[0])
    k = len(parts)
    eff_lens = []
    for i, (_, _, ln) in enumerate(parts):
        ln = jnp.maximum(ln, 0)
        if part_emit is not None:
            ln = jnp.where(part_emit[i], ln, 0)
        if seps is not None and seps[i] is not None:
            sep_b, emit = seps[i]
            ln = ln + jnp.where(emit, np.int32(len(sep_b)), 0)
        eff_lens.append(ln.astype(jnp.int32))
    total = sum(eff_lens)
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(total, dtype=jnp.int32)])
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, max(n - 1, 0))
    pos = j - new_offs[row_c]
    out = jnp.zeros((out_cap,), jnp.uint8)
    cum = jnp.zeros((n,), jnp.int32)
    for i, (data, st, ln) in enumerate(parts):
        ln = jnp.maximum(ln, 0)
        if part_emit is not None:
            ln = jnp.where(part_emit[i], ln, 0)
        if seps is not None and seps[i] is not None:
            sep_b, emit = seps[i]
            slen = jnp.where(emit, np.int32(len(sep_b)), 0)
            sel = (pos >= cum[row_c]) & (pos < cum[row_c] + slen[row_c])
            sp = jnp.clip(pos - cum[row_c], 0, max(len(sep_b) - 1, 0))
            sep_arr = jnp.asarray(sep_b, jnp.uint8) if len(sep_b) else \
                jnp.zeros((1,), jnp.uint8)
            out = jnp.where(sel & (j < new_offs[n]), sep_arr[sp], out)
            cum = cum + slen
        nb = int(data.shape[0])
        sel = (pos >= cum[row_c]) & (pos < cum[row_c] + ln[row_c])
        src = st[row_c] + pos - cum[row_c]
        if nb:
            out = jnp.where(sel & (j < new_offs[n]),
                            data[jnp.clip(src, 0, nb - 1)], out)
        cum = cum + ln
    return out, new_offs


# ---------------------------------------------------------------------------
# pattern search
# ---------------------------------------------------------------------------

def match_windows(data: jax.Array, offsets: jax.Array,
                  pattern: np.ndarray,
                  wildcard: Optional[np.ndarray] = None) -> jax.Array:
    """bool[nbytes]: position j starts a full in-row match of `pattern`
    (static bytes). `wildcard` marks pattern bytes that match any byte
    (LIKE `_`). Empty patterns match everywhere."""
    nbytes = int(data.shape[0])
    plen = int(pattern.shape[0])
    if nbytes == 0:
        return jnp.zeros((0,), jnp.bool_)
    if plen == 0:
        return jnp.ones((nbytes,), jnp.bool_)
    j = jnp.arange(nbytes, dtype=jnp.int32)
    idx = j[:, None] + jnp.arange(plen, dtype=jnp.int32)[None, :]
    window = data[jnp.clip(idx, 0, nbytes - 1)]
    eq = window == jnp.asarray(pattern, jnp.uint8)[None, :]
    if wildcard is not None and wildcard.any():
        eq = eq | jnp.asarray(wildcard, jnp.bool_)[None, :]
    hit = jnp.all(eq, axis=1)
    # window must stay inside the row: byte j and j+plen-1 share a row
    rows = byte_rows(offsets, nbytes)
    row_end = offsets[rows + 1]
    return hit & (j + plen <= row_end)


def first_match(data: jax.Array, offsets: jax.Array, pattern: np.ndarray,
                from_pos: Optional[jax.Array] = None,
                wildcard: Optional[np.ndarray] = None) -> jax.Array:
    """int32[n]: byte position *within the row* of the first match of
    `pattern`, or -1. `from_pos` (int32[n]) restricts to positions >= it."""
    n = int(offsets.shape[0]) - 1
    nbytes = int(data.shape[0])
    if nbytes == 0 or n == 0:
        return jnp.full((n,), -1, jnp.int32)
    hit = match_windows(data, offsets, pattern, wildcard)
    rows = byte_rows(offsets, nbytes)
    pos_in_row = jnp.arange(nbytes, dtype=jnp.int32) - offsets[rows]
    ok = hit
    if from_pos is not None:
        ok = ok & (pos_in_row >= from_pos[rows])
    cand = jnp.where(ok, pos_in_row, _BIG)
    first = segment_min(cand, rows, n)
    return jnp.where(first == _BIG, -1, first)


def nth_match(data: jax.Array, offsets: jax.Array, pattern: np.ndarray,
              nth: int) -> jax.Array:
    """int32[n]: in-row byte position of the nth (1-based) *non-overlapping
    left-to-right* match (split() semantics), or -1. Negative nth counts from
    the end (-1 = last match)."""
    n = int(offsets.shape[0]) - 1
    nbytes = int(data.shape[0])
    if nbytes == 0 or n == 0:
        return jnp.full((n,), -1, jnp.int32)
    hit = greedy_matches(data, offsets, pattern)
    rows = byte_rows(offsets, nbytes)
    pos_in_row = jnp.arange(nbytes, dtype=jnp.int32) - offsets[rows]
    hits_i = hit.astype(jnp.int32)
    # rank of each hit within its row (1-based): global cumsum minus the
    # cumsum just before the row start
    gcum = jnp.cumsum(hits_i, dtype=jnp.int32)
    row_base = jnp.concatenate([jnp.zeros((1,), jnp.int32), gcum])[offsets[:-1]]
    rank = gcum - row_base[rows]
    if nth >= 0:
        want = jnp.full((n,), nth, jnp.int32)
    else:
        total = segment_sum(hits_i, rows, n)
        want = total + (nth + 1)
    sel = hit & (rank == want[rows])
    cand = jnp.where(sel, pos_in_row, _BIG)
    first = segment_min(cand, rows, n)
    return jnp.where(first == _BIG, -1, first)


def greedy_matches(data: jax.Array, offsets: jax.Array,
                   pattern: np.ndarray) -> jax.Array:
    """bool[nbytes]: left-to-right non-overlapping ("greedy") match starts —
    the semantics of replace(). When the pattern cannot overlap itself (no
    proper border, the common case) every window match is taken and this is
    pure vector code; self-overlapping patterns resolve the overlap chains
    with an O(nbytes) `lax.scan` that stays on device."""
    plen = int(pattern.shape[0])
    hit = match_windows(data, offsets, pattern)
    if plen <= 1:
        return hit
    # self-overlap check (host, on the static pattern): proper border exists?
    p = pattern.tobytes()
    self_overlaps = any(p[:k] == p[-k:] for k in range(1, plen))
    if not self_overlaps:
        return hit
    nbytes = int(data.shape[0])
    if nbytes == 0:
        return hit
    rows = byte_rows(offsets, nbytes)
    row_start = offsets[rows]

    def step(carry, x):
        allowed, cur_row = carry
        h, j, r, rs = x
        allowed = jnp.where(r != cur_row, rs, allowed)
        take = h & (j >= allowed)
        allowed = jnp.where(take, j + plen, allowed)
        return (allowed, r), take

    xs = (hit, jnp.arange(nbytes, dtype=jnp.int32), rows, row_start)
    (_, _), taken = jax.lax.scan(step, (jnp.int32(0), jnp.int32(-1)), xs)
    return taken


def build_repeat(data: jax.Array, starts: jax.Array, lengths: jax.Array,
                 times: int, out_cap: int) -> Tuple[jax.Array, jax.Array]:
    """repeat(str, times): row i becomes its bytes tiled `times` times.
    Byte-level tiling is UTF-8 safe. Returns (out_bytes, new_offsets)."""
    n = int(starts.shape[0])
    nbytes = int(data.shape[0])
    lengths = jnp.maximum(lengths, 0).astype(jnp.int32)
    times = max(int(times), 0)
    new_lens = lengths * times
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(new_lens, dtype=jnp.int32)])
    if nbytes == 0 or times == 0:
        return jnp.zeros((out_cap,), jnp.uint8), new_offs
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, max(n - 1, 0))
    pos = j - new_offs[row_c]
    src = starts[row_c] + pos % jnp.maximum(lengths[row_c], 1)
    out = jnp.where(j < new_offs[n], data[jnp.clip(src, 0, nbytes - 1)],
                    jnp.uint8(0))
    return out, new_offs


def build_pad(data: jax.Array, starts: jax.Array, lengths: jax.Array,
              target: int, pad: np.ndarray, left: bool, out_cap: int,
              active: Optional[jax.Array] = None) -> Tuple[jax.Array, jax.Array]:
    """lpad/rpad to `target` chars with literal `pad` (ASCII caller-gated:
    chars == bytes). Spark semantics: longer inputs truncate to target; empty
    pad leaves short inputs unchanged. `active` (bool[n]) limits padding to
    logical rows so batch-capacity padding rows stay empty.
    Returns (out_bytes, new_offsets)."""
    n = int(starts.shape[0])
    nbytes = int(data.shape[0])
    plen = int(pad.shape[0])
    target = max(int(target), 0)
    lengths = jnp.maximum(lengths, 0).astype(jnp.int32)
    if plen == 0:
        new_lens = jnp.minimum(lengths, target)
        return build_ranges(data, starts, new_lens, out_cap)
    new_lens = jnp.full((n,), target, jnp.int32)
    if active is not None:
        new_lens = jnp.where(active, new_lens, 0)
    new_offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                jnp.cumsum(new_lens, dtype=jnp.int32)])
    j = jnp.arange(out_cap, dtype=jnp.int32)
    row = jnp.searchsorted(new_offs[1:], j, side="right").astype(jnp.int32)
    row_c = jnp.clip(row, 0, max(n - 1, 0))
    pos = j - new_offs[row_c]
    ln = lengths[row_c]
    fill = jnp.maximum(target - ln, 0)
    pad_arr = jnp.asarray(pad, jnp.uint8)
    if left:
        from_pad = pos < fill
        src = starts[row_c] + pos - fill
        pad_pos = pos % plen
    else:
        from_pad = pos >= jnp.minimum(ln, target)
        src = starts[row_c] + pos
        pad_pos = jnp.maximum(pos - ln, 0) % plen
    byte = pad_arr[pad_pos]
    if nbytes:
        byte = jnp.where(from_pad, byte, data[jnp.clip(src, 0, nbytes - 1)])
    out = jnp.where(j < new_offs[n], byte, jnp.uint8(0))
    return out, new_offs
