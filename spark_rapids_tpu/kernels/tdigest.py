"""t-digest sketches for approx_percentile.

Reference: GpuApproximatePercentile.scala — the reference builds mergeable
t-digest sketches on device (cuDF tdigest kernels) with partial/final merge
through the shuffle, because map-side pre-aggregation of percentiles needs a
bounded-size mergeable state.

TPU design: the k1 scale function admits a DIRECT assignment of sorted ranks
to clusters — cluster(r) = floor(C · (asin(2(r+½)/n − 1)/π + ½)) — so digest
construction over segment-sorted values is pure vector math + one segment
reduction per group ("device-side bucketing", no sequential centroid walk).
The same formula runs in numpy for the CPU oracle, so both engines produce
IDENTICAL digests for identical input order: oracle parity is exact, not
just within error bounds.

Merging (partial/final through an exchange) concatenates centroid lists,
sorts by mean, and re-clusters by cumulative weight with the same scale
function — bounded size in, bounded size out.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

#: Spark's approx_percentile accuracy default; compression scales with it
DEFAULT_ACCURACY = 10000


def compression_for(accuracy: int) -> int:
    """Map Spark's accuracy knob to a t-digest compression (centroid
    budget). cuDF uses delta=max(accuracy/100, 1000)-ish; 100..1000 keeps
    digests small with error well inside 1/accuracy for realistic data."""
    return int(min(max(accuracy // 10, 100), 2000))


def cluster_ids_for_ranks(n, compression: int, xp=np):
    """k1-scale cluster index for each rank 0..n-1 of a sorted run (vector
    formula — the heart of the device bucketing)."""
    r = (xp.arange(n) + 0.5) / xp.maximum(n, 1)
    q = xp.clip(2.0 * r - 1.0, -1.0, 1.0)
    k = compression * (xp.arcsin(q) / xp.pi + 0.5)
    return xp.clip(k.astype(xp.int32), 0, compression - 1)


def build_digest_np(sorted_vals: np.ndarray,
                    compression: int) -> Tuple[np.ndarray, np.ndarray]:
    """(means, weights) for one group's sorted values (host path)."""
    n = len(sorted_vals)
    if n == 0:
        return np.zeros(0), np.zeros(0)
    cid = cluster_ids_for_ranks(n, compression)
    sums = np.zeros(compression)
    cnts = np.zeros(compression)
    np.add.at(sums, cid, sorted_vals.astype(np.float64))
    np.add.at(cnts, cid, 1.0)
    occ = cnts > 0
    return sums[occ] / cnts[occ], cnts[occ]


def merge_digests(parts: List[Tuple[np.ndarray, np.ndarray]],
                  compression: int) -> Tuple[np.ndarray, np.ndarray]:
    """Partial/final merge: concatenate centroids, sort by mean, re-cluster
    by cumulative weight under the same scale function."""
    means = np.concatenate([p[0] for p in parts]) if parts else np.zeros(0)
    weights = np.concatenate([p[1] for p in parts]) if parts else np.zeros(0)
    if len(means) == 0:
        return means, weights
    order = np.argsort(means, kind="stable")
    means, weights = means[order], weights[order]
    total = weights.sum()
    # cumulative-weight midpoint of each centroid → k1 cluster index
    cum = np.cumsum(weights)
    mid = (cum - weights / 2.0) / total
    q = np.clip(2.0 * mid - 1.0, -1.0, 1.0)
    cid = np.clip((compression * (np.arcsin(q) / np.pi + 0.5)).astype(
        np.int64), 0, compression - 1)
    sums = np.zeros(compression)
    cnts = np.zeros(compression)
    np.add.at(sums, cid, means * weights)
    np.add.at(cnts, cid, weights)
    occ = cnts > 0
    return sums[occ] / cnts[occ], cnts[occ]


def quantile(means: np.ndarray, weights: np.ndarray, p: float) -> float:
    """t-digest quantile: linear interpolation between centroid means at
    cumulative-weight midpoints (the standard estimator)."""
    if len(means) == 0:
        return float("nan")
    if len(means) == 1:
        return float(means[0])
    total = weights.sum()
    target = p * total
    cum = np.cumsum(weights)
    mid = cum - weights / 2.0
    if target <= mid[0]:
        return float(means[0])
    if target >= mid[-1]:
        return float(means[-1])
    i = int(np.searchsorted(mid, target, side="right")) - 1
    lo, hi = mid[i], mid[i + 1]
    f = 0.0 if hi == lo else (target - lo) / (hi - lo)
    return float(means[i] + (means[i + 1] - means[i]) * f)


def grouped_digest_quantiles_device(vals_sorted, seg2, valid2, starts, n_g,
                                    g_cap: int, percentages,
                                    compression: int):
    """Device path: per-group digests + quantiles over segment-sorted data.

    vals_sorted: float64[cap] values in (segment, value) sort order;
    seg2: int32[cap] segment id per position (g_cap = invalid);
    starts/n_g: per-group run start / valid count. Returns
    {k: float64[g_cap]} per requested percentage.

    Clustering: global position p with rank r = p - starts[seg] maps to
    cluster cid(seg) = seg * C + k1(r / n_seg) — one segment-sum into a
    [g_cap · C] table builds EVERY group's digest in one shot, matching
    build_digest_np exactly (same formula, same float64 math)."""
    import jax
    import jax.numpy as jnp

    C = compression
    cap = int(vals_sorted.shape[0])
    pos = jnp.arange(cap, dtype=jnp.int32)
    seg_c = jnp.clip(seg2, 0, g_cap - 1)
    rank = (pos - jnp.take(starts, seg_c)).astype(jnp.float64)
    n_of = jnp.take(n_g, seg_c).astype(jnp.float64)
    r = (rank + 0.5) / jnp.maximum(n_of, 1.0)
    qq = jnp.clip(2.0 * r - 1.0, -1.0, 1.0)
    k = (C * (jnp.arcsin(qq) / jnp.pi + 0.5)).astype(jnp.int32)
    k = jnp.clip(k, 0, C - 1)
    flat = jnp.where(valid2, seg_c * C + k, g_cap * C)
    sums = jax.ops.segment_sum(
        jnp.where(valid2, vals_sorted.astype(jnp.float64), 0.0), flat,
        num_segments=g_cap * C + 1)[:-1].reshape(g_cap, C)
    cnts = jax.ops.segment_sum(
        valid2.astype(jnp.float64), flat,
        num_segments=g_cap * C + 1)[:-1].reshape(g_cap, C)
    means = jnp.where(cnts > 0, sums / jnp.maximum(cnts, 1.0), 0.0)

    # quantile per group: interpolate on cumulative-weight midpoints over
    # the C-slot digest (empty slots carry zero weight and never select)
    cum = jnp.cumsum(cnts, axis=1)
    total = cum[:, -1:]
    mid = cum - cnts / 2.0
    big = jnp.where(cnts > 0, mid, jnp.inf)  # empty slots never match
    out = {}
    for kk, p in enumerate(percentages):
        target = p * total[:, 0]
        # rightmost occupied slot with mid <= target
        le = (big <= target[:, None]) & (cnts > 0)
        has_lo = le.any(axis=1)
        i_lo = jnp.where(has_lo, (jnp.where(le, jnp.arange(C), -1)
                                  ).max(axis=1), 0)
        gt = (big > target[:, None]) & (cnts > 0)
        has_hi = gt.any(axis=1)
        i_hi = jnp.where(has_hi,
                         jnp.where(gt, jnp.arange(C), C).min(axis=1), 0)
        m_lo = jnp.take_along_axis(means, i_lo[:, None], axis=1)[:, 0]
        m_hi = jnp.take_along_axis(means, i_hi[:, None], axis=1)[:, 0]
        d_lo = jnp.take_along_axis(mid, i_lo[:, None], axis=1)[:, 0]
        d_hi = jnp.take_along_axis(mid, i_hi[:, None], axis=1)[:, 0]
        frac = jnp.where(d_hi > d_lo, (target - d_lo)
                         / jnp.maximum(d_hi - d_lo, 1e-300), 0.0)
        interp = m_lo + (m_hi - m_lo) * jnp.clip(frac, 0.0, 1.0)
        v = jnp.where(has_lo & has_hi, interp,
                      jnp.where(has_lo, m_lo, m_hi))
        out[kk] = v
    return out
