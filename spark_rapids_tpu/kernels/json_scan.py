"""Device JSON scanning: single-key path extraction over HBM byte buffers.

Reference: GpuGetJsonObject.scala / JNI JSONUtils — the reference runs JSON
path extraction on device with a custom kernel. TPU re-design: one lockstep
byte scan (`lax.fori_loop`, byte t of every row per step) carrying a
validating micro-parser per row:

  * depth counter + a 1-bit-per-depth container-kind stack (int32 bitmask,
    the simdjson trick) — a real pushdown for JSON's nesting with O(1) state;
  * a structural automaton (expect-key / after-key / expect-value /
    after-value) driven by the container kind on pop;
  * a token DFA validating every primitive's spelling (numbers per RFC 8259,
    true/false/null) — the host engine strict-parses, so the device must
    reject what the host rejects;
  * target-key progress + value-span capture at object depth 1.

Rows the scan cannot certify (backslash escapes, non-canonical numbers,
depth > 31, structural errors the automaton can't attribute, duplicate key
hits) report confident=False and are re-run on the host engine — a per-ROW
hybrid, so one weird row no longer drags the whole batch to the host.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

# --- byte classes -----------------------------------------------------------

_CLS = np.zeros(256, np.int32)


def _set(chars: str, v: int) -> None:
    for ch in chars:
        _CLS[ord(ch)] = v


C_OTHER = 0
C_LBRACE, C_RBRACE, C_LBRACK, C_RBRACK = 1, 2, 3, 4
C_COMMA, C_COLON, C_QUOTE, C_WS, C_BSLASH = 5, 6, 7, 8, 9
C_TOKEN = 10  # primitive token chars: digits, letters, + - .

_set("{", C_LBRACE)
_set("}", C_RBRACE)
_set("[", C_LBRACK)
_set("]", C_RBRACK)
_set(",", C_COMMA)
_set(":", C_COLON)
_set('"', C_QUOTE)
_set(" \t\n\r", C_WS)
_set("\\", C_BSLASH)
_set("0123456789+-.eE", C_TOKEN)
_set("abcdfghijklmnopqrstuvwxyz", C_TOKEN)  # letters for true/false/null
_set("ABCDFGHIJKLMNOPQRSTUVWXYZ", C_TOKEN)

# --- primitive-token DFA ----------------------------------------------------
# States validate numbers (RFC 8259) and the three literals; DEAD rejects.
# 0 START, 1 MINUS, 2 ZERO, 3 INT, 4 DOT, 5 FRAC, 6 E, 7 ESIGN, 8 EXP,
# literals: 9.. tr ue / fa lse / nu ll tries, DEAD = 31
_T_DEAD = 31
_T_ACCEPT = frozenset({2, 3, 5, 8, 12, 17, 21, 22})  # zero int frac exp literals -0


def _build_token_dfa() -> np.ndarray:
    t = np.full((32, 256), _T_DEAD, np.int32)

    def arc(s, chars, d):
        for ch in chars:
            t[s, ord(ch)] = d

    digits = "0123456789"
    arc(0, "-", 1)
    arc(0, "0", 2)
    arc(0, "123456789", 3)
    arc(1, "0", 22)  # "-0": valid JSON but renders as "0" -> host
    arc(1, "123456789", 3)
    arc(3, digits, 3)
    for s in (2, 3):
        arc(s, ".", 4)
        arc(s, "eE", 6)
    arc(4, digits, 5)
    arc(5, digits, 5)
    arc(5, "eE", 6)
    arc(6, "+-", 7)
    arc(6, digits, 8)
    arc(7, digits, 8)
    arc(8, digits, 8)
    arc(22, ".", 4)   # -0.5 continues like ZERO
    arc(22, "eE", 6)
    # true: 9 10 11 12 ; false: 13 14 15 16 17 ; null: 18 19 20 21
    arc(0, "t", 9)
    arc(9, "r", 10)
    arc(10, "u", 11)
    arc(11, "e", 12)
    arc(0, "f", 13)
    arc(13, "a", 14)
    arc(14, "l", 15)
    arc(15, "s", 16)
    arc(16, "e", 17)
    arc(0, "n", 18)
    arc(18, "u", 19)
    arc(19, "l", 20)
    arc(20, "l", 21)
    return t


_TOKEN_DFA = _build_token_dfa()
_TOKEN_ACCEPT = np.zeros(32, bool)
for _s in _T_ACCEPT:
    _TOKEN_ACCEPT[_s] = True

# --- structural automaton states -------------------------------------------
S_START = 0          # before the top-level value
S_OBJ_KEY = 1        # inside object, expecting a key (or '}': empty object)
S_OBJ_COLON = 2      # key seen, expecting ':'
S_OBJ_VALUE = 3      # ':' seen, expecting a value
S_OBJ_AFTER = 4      # value done, expecting ',' or '}'
S_ARR_VALUE = 5      # inside array, expecting a value (or ']': empty array)
S_ARR_AFTER = 6      # value done, expecting ',' or ']'
S_DONE = 7           # top-level value complete (only ws allowed)
S_OBJ_KEY2 = 9       # after ',': a key is REQUIRED ('}' here = trailing comma)
S_ARR_VALUE2 = 10    # after ',': a value is REQUIRED

# value kinds for the captured span
K_NONE, K_STRING, K_PRIMITIVE, K_OBJECT, K_ARRAY = 0, 1, 2, 3, 4

MAX_DEPTH = 31


class JsonSpans(NamedTuple):
    start: "object"      # int32[n] byte offset of the value (quote excluded)
    length: "object"     # int32[n] byte length (0 valid for "")
    kind: "object"       # int32[n] K_*
    tok: "object"        # int32[n] final token-DFA state of a primitive
    found: "object"      # bool[n] key present with a captured value
    valid_doc: "object"  # bool[n] document parses
    confident: "object"  # bool[n] device result is authoritative


def scan_key_spans(data, offsets, key: bytes, max_len: int) -> JsonSpans:
    """For each row (a JSON document), find the FIRST value of `key` in the
    top-level object and validate the whole document structurally."""
    import jax
    import jax.numpy as jnp

    nbytes = int(data.shape[0])
    n = int(offsets.shape[0]) - 1
    starts = offsets[:-1].astype(jnp.int32)
    lens = (offsets[1:] - offsets[:-1]).astype(jnp.int32)
    cls_lut = jnp.asarray(_CLS)
    tok_dfa = jnp.asarray(_TOKEN_DFA)
    tok_acc = jnp.asarray(_TOKEN_ACCEPT)
    kb = np.frombuffer(key, np.uint8)
    klen = int(kb.shape[0])
    key_arr = jnp.asarray(np.pad(kb, (0, 1)))  # +1 pad for safe gather

    z32 = jnp.zeros((n,), jnp.int32)
    zb = jnp.zeros((n,), bool)

    class C(NamedTuple):
        state: "object"; depth: "object"; arrmask: "object"
        in_str: "object"; str_is_key: "object"
        kprog: "object"; armed: "object"
        tok_state: "object"; in_tok: "object"
        cap_start: "object"; cap_len: "object"; cap_kind: "object"
        cap_tok: "object"
        captured: "object"; cap_depth: "object"; capturing: "object"
        dup: "object"; bad: "object"; unconf: "object"

    init = C(jnp.full((n,), S_START, jnp.int32), z32, z32,
             zb, zb, z32, zb, z32, zb,
             z32, z32, z32, z32, zb, z32, zb, zb, zb, zb)

    def body(t, c):
        pos = jnp.clip(starts + t, 0, max(nbytes - 1, 0))
        live = t < lens
        b = data[pos].astype(jnp.int32) if nbytes else jnp.zeros((n,), jnp.int32)
        k = cls_lut[b]

        state, depth, arrmask = c.state, c.depth, c.arrmask
        in_str, str_is_key = c.in_str, c.str_is_key
        kprog, armed = c.kprog, c.armed
        tok_state, in_tok = c.tok_state, c.in_tok
        cap_start, cap_len, cap_kind = c.cap_start, c.cap_len, c.cap_kind
        cap_tok = c.cap_tok
        captured, cap_depth, capturing = c.captured, c.cap_depth, c.capturing
        dup, bad, unconf = c.dup, c.bad, c.unconf

        # ---- inside a string -----------------------------------------
        bslash = in_str & (k == C_BSLASH)
        unconf = unconf | (live & bslash)  # escapes: host semantics
        str_end = in_str & (k == C_QUOTE)
        # key progress while inside a key string
        in_key_body = in_str & str_is_key & ~str_end
        kexp = key_arr[jnp.clip(kprog, 0, klen)].astype(jnp.int32)
        kmatch = in_key_body & (kprog >= 0) & (kprog < klen) & (b == kexp)
        kprog = jnp.where(in_key_body,
                          jnp.where(kmatch, kprog + 1, jnp.int32(-1)),
                          kprog)
        # a key string that ends with full progress arms the capture
        key_hit = (str_end & str_is_key & (depth == 1) & (kprog == klen)
                   & ~captured & ~capturing)
        dup = dup | (live & str_end & str_is_key & (depth == 1)
                     & (kprog == klen) & captured)
        armed = jnp.where(live & str_end, key_hit, armed)
        # string VALUE end while capturing a string value at depth cap_depth
        str_val_end = str_end & capturing & (cap_kind == K_STRING) \
            & (depth == cap_depth)
        cap_len = jnp.where(live & str_val_end, pos - cap_start, cap_len)
        captured = captured | (live & str_val_end)
        capturing = capturing & ~(live & str_val_end)
        # structural: leaving a string
        state = jnp.where(
            live & str_end,
            jnp.where(str_is_key, jnp.int32(S_OBJ_COLON),
                      _after_value_state(depth, arrmask, jnp)),
            state)
        in_str = in_str & ~(live & str_end)

        # ---- outside strings -----------------------------------------
        out = live & ~c.in_str  # state BEFORE this byte
        ws = out & (k == C_WS)

        # token continuation / termination
        tok_char = out & (k == C_TOKEN)
        tok_cont = tok_char & in_tok
        tok_begin = tok_char & ~in_tok
        # beginning a token only legal when expecting a value
        expects_value = ((state == S_START) | (state == S_OBJ_VALUE)
                         | (state == S_ARR_VALUE)
                         | (state == S_ARR_VALUE2))
        bad = bad | (tok_begin & ~expects_value)
        tok_state = jnp.where(tok_begin, tok_dfa[0, b],
                              jnp.where(tok_cont, tok_dfa[tok_state, b],
                                        tok_state))
        # primitive value capture start
        prim_cap = tok_begin & armed & (state == S_OBJ_VALUE)
        cap_start = jnp.where(prim_cap, pos, cap_start)
        cap_kind = jnp.where(prim_cap, jnp.int32(K_PRIMITIVE), cap_kind)
        cap_depth = jnp.where(prim_cap, depth, cap_depth)
        capturing = capturing | prim_cap
        armed = armed & ~tok_begin
        in_tok = jnp.where(out, tok_char, in_tok)
        state = jnp.where(tok_begin, jnp.int32(S_DONE * 0 + 99), state)
        # 99 = IN_TOKEN sentinel: resolved at the delimiter below

        # token end: a non-token byte while in a 99 state
        tok_end = out & (state == 99) & ~tok_char
        unconf = unconf | (tok_end & ~tok_acc[jnp.clip(tok_state, 0, 31)])
        prim_val_end = tok_end & capturing & (cap_kind == K_PRIMITIVE)
        cap_len = jnp.where(prim_val_end, pos - cap_start, cap_len)
        cap_tok = jnp.where(prim_val_end, tok_state, cap_tok)
        captured = captured | prim_val_end
        capturing = capturing & ~prim_val_end
        state = jnp.where(tok_end,
                          _after_value_state(depth, arrmask, jnp), state)

        # now handle the structural byte itself (unless ws / in token)
        struct = out & ~ws & ~(state == 99)

        def when(cond, new_state):
            return cond & struct, new_state

        # '"' opening a string
        q = struct & (k == C_QUOTE)
        opening_key = q & ((state == S_OBJ_KEY) | (state == S_OBJ_KEY2))
        opening_val = q & expects_value
        bad = bad | (q & ~(opening_key | opening_val))
        str_is_key = jnp.where(q, opening_key, str_is_key)
        kprog = jnp.where(opening_key, jnp.int32(0), kprog)
        in_str = in_str | q
        # string value capture start (content begins after the quote)
        s_cap = opening_val & armed & (state == S_OBJ_VALUE)
        cap_start = jnp.where(s_cap, pos + 1, cap_start)
        cap_kind = jnp.where(s_cap, jnp.int32(K_STRING), cap_kind)
        cap_depth = jnp.where(s_cap, depth, cap_depth)
        capturing = capturing | s_cap
        armed = armed & ~opening_val

        # '{' / '['
        open_obj = struct & (k == C_LBRACE)
        open_arr = struct & (k == C_LBRACK)
        opener = open_obj | open_arr
        bad = bad | (opener & ~expects_value)
        # container value capture start
        c_cap = opener & armed & (state == S_OBJ_VALUE)
        cap_start = jnp.where(c_cap, pos, cap_start)
        cap_kind = jnp.where(c_cap, jnp.where(open_obj,
                                              jnp.int32(K_OBJECT),
                                              jnp.int32(K_ARRAY)), cap_kind)
        cap_depth = jnp.where(c_cap, depth, cap_depth)
        capturing = capturing | c_cap
        armed = armed & ~opener
        # a top-level ARRAY: Spark's name step maps over its elements —
        # host semantics, out of the device subset
        unconf = unconf | (open_arr & (c.state == S_START))
        depth = jnp.where(opener, depth + 1, depth)
        unconf = unconf | (opener & (depth > MAX_DEPTH))
        sel = jnp.int32(1) << jnp.clip(depth, 0, 31)
        arrmask = jnp.where(open_arr, arrmask | sel,
                            jnp.where(open_obj, arrmask & ~sel, arrmask))
        state = jnp.where(open_obj, jnp.int32(S_OBJ_KEY),
                          jnp.where(open_arr, jnp.int32(S_ARR_VALUE), state))

        # '}' / ']'
        close_obj = struct & (k == C_RBRACE)
        close_arr = struct & (k == C_RBRACK)
        closer = close_obj | close_arr
        in_arr = (arrmask >> jnp.clip(depth, 0, 31)) & 1
        ok_close_obj = close_obj & (in_arr == 0) & (depth > 0) \
            & ((state == S_OBJ_AFTER) | (state == S_OBJ_KEY))
        ok_close_arr = close_arr & (in_arr == 1) & (depth > 0) \
            & ((state == S_ARR_AFTER) | (state == S_ARR_VALUE))
        # S_OBJ_KEY2 / S_ARR_VALUE2 (after a comma) do NOT admit a closer:
        # that's the trailing-comma malformation
        bad = bad | (closer & ~(ok_close_obj | ok_close_arr))
        # a closing bracket ending the captured container value
        cont_end = closer & capturing & (depth == cap_depth + 1) \
            & ((cap_kind == K_OBJECT) | (cap_kind == K_ARRAY))
        cap_len = jnp.where(cont_end, pos + 1 - cap_start, cap_len)
        captured = captured | cont_end
        capturing = capturing & ~cont_end
        depth = jnp.where(closer, jnp.maximum(depth - 1, 0), depth)
        state = jnp.where(closer,
                          _after_value_state(depth, arrmask, jnp), state)

        # ',' and ':'
        comma = struct & (k == C_COMMA)
        in_arr2 = (arrmask >> jnp.clip(depth, 0, 31)) & 1
        ok_comma = comma & (((state == S_OBJ_AFTER) & (in_arr2 == 0))
                            | ((state == S_ARR_AFTER) & (in_arr2 == 1)))
        bad = bad | (comma & ~ok_comma)
        state = jnp.where(comma & (in_arr2 == 0), jnp.int32(S_OBJ_KEY2),
                          jnp.where(comma, jnp.int32(S_ARR_VALUE2), state))
        colon = struct & (k == C_COLON)
        bad = bad | (colon & ~(state == S_OBJ_COLON))
        state = jnp.where(colon, jnp.int32(S_OBJ_VALUE), state)

        # any other byte outside strings/tokens is structural garbage
        bad = bad | (struct & (k == C_OTHER))
        bad = bad | (struct & (k == C_BSLASH))
        # ws after DONE is fine; anything else after DONE is garbage
        bad = bad | (out & (c.state == S_DONE) & ~ws)

        return C(state, depth, arrmask, in_str, str_is_key, kprog, armed,
                 tok_state, in_tok, cap_start, cap_len, cap_kind, cap_tok,
                 captured, cap_depth, capturing, dup, bad, unconf)

    final = jax.lax.fori_loop(0, max_len, body, init) if nbytes else init

    # end-of-row resolution: a trailing primitive token ends the document
    tok_tail = (final.state == 99)
    tail_ok = tok_tail & tok_acc[jnp.clip(final.tok_state, 0, 31)] \
        & (final.depth == 0)
    bad = final.bad
    unconf_extra = tok_tail & ~tail_ok
    ends = offsets[1:].astype(jnp.int32)
    tail_prim = tail_ok & final.capturing & (final.cap_kind == K_PRIMITIVE)
    cap_len = jnp.where(tail_prim, ends - final.cap_start, final.cap_len)
    cap_tok = jnp.where(tail_prim, final.tok_state, final.cap_tok)
    captured = final.captured | tail_prim
    nonempty = lens > 0
    done = ((final.state == S_DONE) & (final.depth == 0)) | tail_ok
    valid_doc = (nonempty & done & ~bad & ~final.in_str
                 & ~(final.capturing & ~tail_prim))
    confident = ~final.unconf & ~final.dup & ~unconf_extra
    return JsonSpans(final.cap_start, cap_len, final.cap_kind, cap_tok,
                     captured, valid_doc, confident)


def _after_value_state(depth, arrmask, jnp):
    """State to resume after a value completes at `depth`."""
    in_arr = (arrmask >> jnp.clip(depth, 0, 31)) & 1
    return jnp.where(depth == 0, jnp.int32(S_DONE),
                     jnp.where(in_arr == 1, jnp.int32(S_ARR_AFTER),
                               jnp.int32(S_OBJ_AFTER)))
