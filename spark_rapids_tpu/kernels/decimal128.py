"""128-bit decimal arithmetic as two int64 limbs.

Reference: spark-rapids-jni DecimalUtils (CUDA __int128 kernels). TPUs have
no native 128-bit integers either, so a decimal(>18) value v is carried as
  hi = v >> 64   (signed int64)
  lo = v & mask  (low 64 bits, stored as the int64 BIT PATTERN)
and every op is built from int64 adds/multiplies with explicit carries —
pure elementwise VPU code. Unsigned comparison of bit patterns uses the
sign-flip trick (u(x) < u(y) ⟺ (x^MIN) < (y^MIN) signed).

Scale handling lives in the expression layer (Spark's type coercion aligns
scales before the kernel, exactly as with the scaled-int64 ≤18 carrier);
these kernels are pure 128-bit integer math plus precision-overflow checks.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

_MIN = np.int64(np.iinfo(np.int64).min)
_MASK64 = (1 << 64) - 1


# ---------------------------------------------------------------------------
# host-side conversion
# ---------------------------------------------------------------------------

_DEC_CTX = None


def _ctx():
    global _DEC_CTX
    if _DEC_CTX is None:
        import decimal
        _DEC_CTX = decimal.Context(prec=60)  # default prec=28 would ROUND
    return _DEC_CTX


def unscaled_int(value, scale: int) -> int:
    """Decimal/str/int → exact unscaled int at `scale` (no context rounding)."""
    import decimal
    d = value if isinstance(value, decimal.Decimal) else decimal.Decimal(value)
    return int(d.scaleb(scale, context=_ctx()))


def scaled_decimal(unscaled: int, scale: int):
    """Exact unscaled int → Decimal at `scale` (no context rounding)."""
    import decimal
    return decimal.Decimal(unscaled).scaleb(-scale, context=_ctx())


def int_to_limbs(v: int) -> Tuple[int, int]:
    """python int → (hi, lo) with lo as a signed-int64 bit pattern."""
    lo = v & _MASK64
    if lo >= 1 << 63:
        lo -= 1 << 64
    return (v >> 64, lo)


def limbs_to_int(hi: int, lo: int) -> int:
    return (int(hi) << 64) | (int(lo) & _MASK64)


def pack(values) -> np.ndarray:
    """iterable of python ints → (n, 2) int64 [hi, lo] array."""
    out = np.zeros((len(values), 2), np.int64)
    for i, v in enumerate(values):
        h, l = int_to_limbs(int(v))
        out[i, 0] = h
        out[i, 1] = l
    return out


def unpack(arr: np.ndarray):
    return [limbs_to_int(h, l) for h, l in np.asarray(arr)]


# ---------------------------------------------------------------------------
# limb primitives (jax)
# ---------------------------------------------------------------------------

def _ult(x, y):
    """unsigned x < y on int64 bit patterns."""
    return (x ^ _MIN) < (y ^ _MIN)


def add128(ah, al, bh, bl):
    """(hi, lo) + (hi, lo) with wraparound; returns (hi, lo, signed_overflow)."""
    lo = al + bl  # two's-complement wrap == mod 2^64
    carry = _ult(lo, al).astype(jnp.int64)
    hi = ah + bh + carry
    # signed 128 overflow: same-sign operands, different-sign result
    ovf = ((ah >= 0) == (bh >= 0)) & ((hi >= 0) != (ah >= 0))
    return hi, lo, ovf


def neg128(h, l):
    lo = -l
    hi = ~h + (l == 0).astype(jnp.int64)
    return hi, lo


def sub128(ah, al, bh, bl):
    nh, nl = neg128(bh, bl)
    return add128(ah, al, nh, nl)


def _umul64(a, b):
    """unsigned 64x64 → (hi64, lo64) via 32-bit halves (int64 bit patterns)."""
    mask32 = jnp.int64(0xFFFFFFFF)
    a_lo = a & mask32
    a_hi = (a >> 32) & mask32
    b_lo = b & mask32
    b_hi = (b >> 32) & mask32
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    mid = (ll >> 32 & mask32) + (lh & mask32) + (hl & mask32)
    lo = (ll & mask32) | (mid << 32)
    hi = hh + ((lh >> 32) & mask32) + ((hl >> 32) & mask32) + \
        ((mid >> 32) & mask32)
    return hi, lo


def mul128(ah, al, bh, bl):
    """128x128 → 128 with overflow detection. Sign-magnitude: negate to
    magnitudes, multiply unsigned, re-apply the sign."""
    a_neg = ah < 0
    b_neg = bh < 0
    mah, mal = neg128(ah, al)
    mah = jnp.where(a_neg, mah, ah)
    mal = jnp.where(a_neg, mal, al)
    mbh, mbl = neg128(bh, bl)
    mbh = jnp.where(b_neg, mbh, bh)
    mbl = jnp.where(b_neg, mbl, bl)
    # |a| = mah*2^64 + u(mal); |b| = mbh*2^64 + u(mbl); magnitudes < 2^127 so
    # mah/mbh are non-negative
    p_hi, p_lo = _umul64(mal, mbl)
    c1_hi, c1_lo = _umul64(mal, mbh)
    c2_hi, c2_lo = _umul64(mah, mbl)
    hi = p_hi + c1_lo
    ovf = _ult(hi, p_hi)  # carry out of bit 127 of the magnitude
    hi2 = hi + c2_lo
    ovf = ovf | _ult(hi2, hi)
    ovf = ovf | ((mah != 0) & (mbh != 0)) | (c1_hi != 0) | (c2_hi != 0)
    # magnitude must fit 127 bits (sign bit clear)
    ovf = ovf | (hi2 < 0)
    out_neg = a_neg != b_neg
    nh, nl = neg128(hi2, p_lo)
    rh = jnp.where(out_neg, nh, hi2)
    rl = jnp.where(out_neg, nl, p_lo)
    return rh, rl, ovf


def cmp128(ah, al, bh, bl):
    """-1 / 0 / +1 like a signed 128-bit compare."""
    lt = (ah < bh) | ((ah == bh) & _ult(al, bl))
    gt = (ah > bh) | ((ah == bh) & _ult(bl, al))
    return jnp.where(lt, -1, jnp.where(gt, 1, 0)).astype(jnp.int32)


def abs_exceeds(h, l, bound: int):
    """|value| > bound (python int bound < 2^127), elementwise."""
    bh, bl = int_to_limbs(bound)
    neg = h < 0
    mh, ml = neg128(h, l)
    mh = jnp.where(neg, mh, h)
    ml = jnp.where(neg, ml, l)
    return (mh > bh) | ((mh == bh) & _ult(jnp.asarray(bl, jnp.int64), ml))


def from_int64(v):
    """int64 vector → limb pair (sign-extended)."""
    v = v.astype(jnp.int64)
    return jnp.where(v < 0, jnp.int64(-1), jnp.int64(0)), v


def precision_overflow(h, l, precision: int):
    """Spark decimal overflow: |v| >= 10^precision (unscaled)."""
    return abs_exceeds(h, l, 10 ** precision - 1)
