"""HBM budget accounting — the RMM-pool analogue for TPU.

Reference: RMM via GpuDeviceManager.initializeRmm (GpuDeviceManager.scala:275)
+ DeviceMemoryEventHandler.onAllocFailure (drain spill store, retry alloc,
DeviceMemoryEventHandler.scala:36,108). XLA owns the physical HBM allocator
(SURVEY §2.4 mapping note), so this layer tracks *logical* bytes of live
columnar data against a budget; exceeding it triggers the same synchronous
spill→retry→OOM escalation the reference drives from RMM callbacks, raising
TpuRetryOOM/TpuSplitAndRetryOOM for the retry framework to absorb.

Test hooks mirror RmmSpark.forceRetryOOM / forceSplitAndRetryOOM
(spark-rapids-jni; used by the reference's retry suites, SURVEY §4).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..config import OOM_RETRY_MAX, RapidsConf, default_conf
from .device import TpuDeviceManager


class TpuOOM(MemoryError):
    """Unrecoverable device OOM (reference GpuOOM)."""


class TpuRetryOOM(TpuOOM):
    """Retryable: caller should release, spill, and re-execute
    (reference GpuRetryOOM)."""


class TpuSplitAndRetryOOM(TpuOOM):
    """Retryable with input splitting (reference GpuSplitAndRetryOOM)."""


class HbmBudget:
    """Logical HBM accounting with synchronous spill-on-pressure."""

    _instance: Optional["HbmBudget"] = None
    _lock = threading.Lock()

    def __init__(self, budget_bytes: int, oom_max_retries: int = 3):
        self.budget = budget_bytes
        self.used = 0
        self.oom_max_retries = oom_max_retries
        self._alloc_lock = threading.RLock()
        self._spill_callback: Optional[Callable[[int], int]] = None
        self.peak_used = 0
        self.alloc_count = 0

    @classmethod
    def get(cls, conf: Optional[RapidsConf] = None) -> "HbmBudget":
        with cls._lock:
            if cls._instance is None:
                conf = conf or default_conf()
                cls._instance = HbmBudget(TpuDeviceManager.hbm_budget_bytes(),
                                          conf.get(OOM_RETRY_MAX))
            return cls._instance

    @classmethod
    def reset_for_tests(cls, budget_bytes: Optional[int] = None) -> "HbmBudget":
        from ..chaos import FaultInjector
        # forced-OOM counters are part of the budget's test-hook state: a
        # partially-consumed force must not leak into the next test
        FaultInjector.get().clear_forced("hbm.alloc")
        with cls._lock:
            cls._instance = HbmBudget(budget_bytes
                                      or TpuDeviceManager.hbm_budget_bytes())
            return cls._instance

    def set_spill_callback(self, cb: Callable[[int], int]) -> None:
        """cb(bytes_needed) -> bytes_freed; called under allocation pressure
        (reference RmmEventHandler.onAllocFailure wiring)."""
        self._spill_callback = cb

    # --- test injection (reference RmmSpark.forceRetryOOM) -----------------
    # routed through the chaos fault injector's forced counters so manual
    # one-shot OOMs and the randomized chaos harness share one site/trace
    def force_retry_oom(self, n: int = 1) -> None:
        from ..chaos import FaultInjector
        FaultInjector.get().force("hbm.alloc", "retry_oom", n)

    def force_split_and_retry_oom(self, n: int = 1) -> None:
        from ..chaos import FaultInjector
        FaultInjector.get().force("hbm.alloc", "split_oom", n)

    # --- allocation --------------------------------------------------------
    def allocate(self, nbytes: int) -> None:
        from ..chaos import inject
        from ..obs import metrics as _metrics
        from ..obs import tracer as _obs
        with self._alloc_lock:
            self.alloc_count += 1
            inject("hbm.alloc", detail=f"{nbytes}B")
            if _obs._ACTIVE:
                _obs.event("hbm.alloc", cat="memory", bytes=nbytes,
                           used=self.used)
            retries = 0
            while self.used + nbytes > self.budget:
                freed = 0
                if self._spill_callback is not None:
                    freed = self._spill_callback(
                        self.used + nbytes - self.budget)
                # allocation under pressure: the spill-or-synchronize
                # loop is where HBM waits hide — counted in the always-on
                # registry (pressure is rare by construction)
                _metrics.counter_inc("hbm.pressure_events")
                if _obs._ACTIVE:
                    _obs.event("hbm.pressure", cat="memory", bytes=nbytes,
                               used=self.used, freed=freed)
                if freed <= 0:
                    retries += 1
                    if retries > self.oom_max_retries:
                        from ..obs import flight as _flight
                        _metrics.counter_inc("hbm.oom_events")
                        exc = TpuRetryOOM(
                            f"HBM budget exhausted: used={self.used} "
                            f"request={nbytes} budget={self.budget}")
                        # marks this as a REAL budget exhaustion (vs the
                        # chaos-injected healable TpuRetryOOM). No
                        # postmortem HERE: the retry framework above may
                        # still heal this by spilling/splitting — the dump
                        # happens in failure.handle_task_failure, reached
                        # only when the OOM actually kills the query
                        exc.budget_exhausted = True
                        _flight.note("hbm.oom", used=self.used,
                                     request=nbytes, budget=self.budget)
                        raise exc
                    TpuDeviceManager.synchronize()
            self.used += nbytes
            self.peak_used = max(self.peak_used, self.used)
            _metrics.gauge_max("hbm.high_water_bytes", self.peak_used)
        # per-tenant attribution (docs/serving.md): one thread-local read
        # + a GIL add on the bound QueryContext — outside the alloc lock
        # (no lock is taken; plain counter discipline)
        from ..serving.query_context import charge_hbm
        charge_hbm(nbytes)

    def free(self, nbytes: int) -> None:
        with self._alloc_lock:
            self.used = max(0, self.used - nbytes)
        from ..serving.query_context import release_hbm
        release_hbm(nbytes)
