"""Spill framework: tiered buffer catalog DEVICE → HOST → DISK.

Reference: RapidsBufferCatalog.scala (1018; handle-based), RapidsBufferStore /
RapidsDeviceMemoryStore / RapidsHostMemoryStore / RapidsDiskStore,
SpillPriorities.scala, SpillableColumnarBatch.scala:29,90. Device batches
register for a handle; under HBM pressure the catalog spills lowest-priority
buffers to host Arrow tables, then to Arrow IPC files on disk; `get_batch`
unspills on demand. jax.Arrays are immutable so "spill" = materialize to host
and drop the device reference (XLA frees it), accounting via HbmBudget.
"""

from __future__ import annotations

import os
import tempfile
import threading
from typing import Dict, List, Optional

from ..columnar.batch import TpuColumnarBatch
from ..config import HOST_SPILL_STORAGE_SIZE, RapidsConf, default_conf
from .hbm import HbmBudget

TIER_DEVICE = "DEVICE"
TIER_HOST = "HOST"
TIER_DISK = "DISK"


class SpillCorruptionError(IOError):
    """A disk-spilled buffer failed its integrity check on unspill (bit rot,
    truncation, or chaos-injected corruption). For ICI shuffle blocks the
    catalog converts this into FetchFailedError so lineage recompute heals
    it; anywhere else it surfaces as the storage fault it is."""

# Spill priorities (reference SpillPriorities.scala): lower value spills first
ACTIVE_ON_DECK_PRIORITY = -100
ACTIVE_BATCHING_PRIORITY = 0
OUTPUT_FOR_SHUFFLE_PRIORITY = 100


class _Entry:
    __slots__ = ("handle", "tier", "priority", "batch", "host_table",
                 "disk_path", "disk_checksum", "nbytes", "names")

    def __init__(self, handle: int, batch: TpuColumnarBatch, priority: int):
        self.handle = handle
        self.tier = TIER_DEVICE
        self.priority = priority
        self.batch = batch
        self.host_table = None
        self.disk_path: Optional[str] = None
        self.disk_checksum: Optional[int] = None
        self.nbytes = batch.device_memory_size()
        self.names = batch.names


class TpuBufferCatalog:
    """Handle-based spillable-buffer registry (reference RapidsBufferCatalog)."""

    _instance: Optional["TpuBufferCatalog"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[RapidsConf] = None):
        conf = conf or default_conf()
        self._entries: Dict[int, _Entry] = {}
        self._next_handle = 0
        self._reg_lock = threading.RLock()
        self._disk_dir = tempfile.mkdtemp(prefix="tpu_spill_")
        self.host_limit = conf.get(HOST_SPILL_STORAGE_SIZE)
        self.host_used = 0
        self.spilled_to_host = 0
        self.spilled_to_disk = 0
        HbmBudget.get(conf).set_spill_callback(self.synchronous_spill)

    @classmethod
    def get(cls, conf: Optional[RapidsConf] = None) -> "TpuBufferCatalog":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TpuBufferCatalog(conf)
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "TpuBufferCatalog":
        with cls._lock:
            cls._instance = TpuBufferCatalog()
            return cls._instance

    # --- registration ------------------------------------------------------
    def add_batch(self, batch: TpuColumnarBatch,
                  priority: int = ACTIVE_BATCHING_PRIORITY) -> int:
        with self._reg_lock:
            self._next_handle += 1
            h = self._next_handle
            e = _Entry(h, batch, priority)
            self._entries[h] = e
            HbmBudget.get().allocate(e.nbytes)
            return h

    def remove(self, handle: int) -> None:
        with self._reg_lock:
            e = self._entries.pop(handle, None)
            if e is None:
                return
            if e.tier == TIER_DEVICE:
                HbmBudget.get().free(e.nbytes)
            elif e.tier == TIER_HOST:
                self.host_used -= e.nbytes
            elif e.disk_path and os.path.exists(e.disk_path):
                os.unlink(e.disk_path)

    # --- access ------------------------------------------------------------
    def get_batch(self, handle: int) -> TpuColumnarBatch:
        with self._reg_lock:
            e = self._entries[handle]
            if e.tier == TIER_DEVICE:
                return e.batch
            self._unspill(e)
            return e.batch

    def _unspill(self, e: _Entry) -> None:
        import pyarrow as pa
        import time as _time
        from ..obs import tracer as _obs
        from ..profiling import TaskMetricsRegistry
        t0 = _time.perf_counter_ns()
        self._unspill_inner(e, pa)
        dt = _time.perf_counter_ns() - t0
        TaskMetricsRegistry.get().add("readSpillTimeNs", dt)
        from ..obs import metrics as _metrics
        _metrics.counter_inc("spill.read_bytes", e.nbytes)
        if _obs._ACTIVE:
            _obs.event("spill.read", cat="memory", bytes=e.nbytes,
                       wait_ns=dt)

    def _unspill_inner(self, e: _Entry, pa) -> None:
        if e.tier == TIER_DISK:
            import io
            from ..shuffle.serializer import xxhash64_bytes
            with open(e.disk_path, "rb") as f:
                data = f.read()
            if e.disk_checksum is not None \
                    and xxhash64_bytes(data) != e.disk_checksum:
                raise SpillCorruptionError(
                    f"spill file {e.disk_path} failed its xxhash64 "
                    f"integrity check on unspill ({len(data)} bytes)")
            with pa.ipc.open_file(io.BytesIO(data)) as r:
                e.host_table = r.read_all()
            os.unlink(e.disk_path)
            e.disk_path = None
            e.disk_checksum = None
            e.tier = TIER_HOST
            self.host_used += e.nbytes
        if e.tier == TIER_HOST:
            HbmBudget.get().allocate(e.nbytes)
            batch = TpuColumnarBatch.from_arrow(e.host_table)
            if e.names:
                batch = batch.rename(e.names)
            e.batch = batch
            e.host_table = None
            self.host_used -= e.nbytes
            e.tier = TIER_DEVICE

    # --- spilling ----------------------------------------------------------
    def synchronous_spill(self, bytes_needed: int) -> int:
        """Spill lowest-priority device buffers until bytes_needed freed
        (reference: RMM alloc-failure drains the device store)."""
        freed = 0
        with self._reg_lock:
            device_entries = sorted(
                (e for e in self._entries.values() if e.tier == TIER_DEVICE),
                key=lambda e: e.priority)
            for e in device_entries:
                if freed >= bytes_needed:
                    break
                freed += self._spill_entry_to_host(e)
        return freed

    def _spill_entry_to_host(self, e: _Entry) -> int:
        from ..chaos import inject
        from ..obs import tracer as _obs
        inject("spill.to_host")  # before any state mutation: a raised fault
        # must leave the entry intact on its current tier
        from ..obs import metrics as _metrics
        _metrics.counter_inc("spill.to_host_bytes", e.nbytes)
        if _obs._ACTIVE:
            _obs.event("spill.to_host", cat="memory", bytes=e.nbytes)
        e.host_table = e.batch.to_arrow()
        e.batch = None
        e.tier = TIER_HOST
        HbmBudget.get().free(e.nbytes)
        self.host_used += e.nbytes
        self.spilled_to_host += e.nbytes
        from ..profiling import TaskMetricsRegistry
        TaskMetricsRegistry.get().add("spillToHostBytes", e.nbytes)
        if self.host_used > self.host_limit:
            self._spill_host_to_disk()
        return e.nbytes

    def _spill_host_to_disk(self) -> None:
        import pyarrow as pa
        with self._reg_lock:
            host_entries = sorted(
                (e for e in self._entries.values() if e.tier == TIER_HOST),
                key=lambda e: e.priority)
            for e in host_entries:
                if self.host_used <= self.host_limit:
                    break
                import io
                from ..chaos import corrupt_bytes, inject
                from ..shuffle.serializer import xxhash64_bytes
                inject("spill.to_disk")  # pre-mutation, like spill.to_host
                from ..obs import flight as _flight
                from ..obs import metrics as _metrics
                from ..obs import tracer as _obs
                _metrics.counter_inc("spill.to_disk_bytes", e.nbytes)
                # disk spill is rare and a pressure signal: flight-note it
                _flight.note("spill.to_disk", bytes=e.nbytes)
                if _obs._ACTIVE:
                    _obs.event("spill.to_disk", cat="memory",
                               bytes=e.nbytes)
                path = os.path.join(self._disk_dir, f"buf_{e.handle}.arrow")
                buf = io.BytesIO()
                with pa.ipc.new_file(buf, e.host_table.schema) as w:
                    w.write_table(e.host_table)
                data = buf.getvalue()
                # checksum BEFORE the chaos mangle: injected corruption must
                # be detectable on unspill, exactly like real bit rot
                e.disk_checksum = xxhash64_bytes(data)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(corrupt_bytes("spill.to_disk", data))
                os.replace(tmp, path)  # atomic: no truncated spill files
                e.host_table = None
                e.disk_path = path
                e.tier = TIER_DISK
                self.host_used -= e.nbytes
                self.spilled_to_disk += e.nbytes
                from ..profiling import TaskMetricsRegistry
                TaskMetricsRegistry.get().add("spillToDiskBytes", e.nbytes)


class SpillableColumnarBatch:
    """RAII wrapper: batch registered in the catalog, retrievable, closable
    (reference SpillableColumnarBatch.scala)."""

    def __init__(self, batch: TpuColumnarBatch,
                 priority: int = ACTIVE_BATCHING_PRIORITY):
        from .cleaner import MemoryCleaner
        self._catalog = TpuBufferCatalog.get()
        self._handle: Optional[int] = self._catalog.add_batch(batch, priority)
        # a deferred-compaction batch's row count stays a device scalar here:
        # wrapping a batch must not force the sync its producer deferred
        self._rows_lazy = batch.rows_lazy
        self.size_bytes = batch.device_memory_size()
        rows_label = self._rows_lazy if isinstance(self._rows_lazy, int) \
            else "?"
        # pin the cleaner INSTANCE: close() must unregister from the same
        # book we registered in, or a reset_for_tests between creation and
        # close (long-lived caches, shutdown hooks) strands the token in the
        # old instance — a phantom "leak" its atexit report shows while the
        # CI gate, checking the current instance, passes (VERDICT r4 weak #2)
        self._cleaner = MemoryCleaner.get()
        self._cleaner_token = self._cleaner.register(
            f"SpillableColumnarBatch[{rows_label}r "
            f"{self.size_bytes}B]")

    @property
    def num_rows(self) -> int:
        if not isinstance(self._rows_lazy, int):
            from ..columnar.vector import audited_sync_int
            self._rows_lazy = audited_sync_int(self._rows_lazy, "rows")
        return self._rows_lazy

    @property
    def rows_lazy(self):
        """Row count WITHOUT forcing: host int when known, device scalar
        otherwise (see materialize_spillable_counts for the batched force)."""
        return self._rows_lazy

    def get_batch(self) -> TpuColumnarBatch:
        if self._handle is None:
            raise ValueError("spillable batch already closed")
        return self._catalog.get_batch(self._handle)

    def close(self) -> None:
        if self._handle is not None:
            self._catalog.remove(self._handle)
            self._handle = None
        # second unregister of the same token IS the double-close signal
        # (raises in the cleaner's debug mode, counted otherwise)
        self._cleaner.unregister(self._cleaner_token)

    def __enter__(self) -> "SpillableColumnarBatch":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def materialize_spillable_counts(spillables: List[SpillableColumnarBatch]) -> int:
    """Force every pending deferred row count in the list with ONE batched
    transfer and return the exact total. A coalesce window deciding whether
    its row target really tripped pays one sync for the whole window, not
    one per batch."""
    import numpy as np
    dev_ix = [i for i, sp in enumerate(spillables)
              if not isinstance(sp._rows_lazy, (int, np.integer))]
    if dev_ix:
        from ..columnar.vector import audited_device_get
        got = audited_device_get([spillables[i]._rows_lazy for i in dev_ix],
                                 "rows")
        for i, n in zip(dev_ix, got):
            spillables[i]._rows_lazy = int(n)
    return sum(int(sp._rows_lazy) for sp in spillables)
