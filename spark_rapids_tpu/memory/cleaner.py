"""Shutdown leak tracking + double-close detection for device resources.

Reference: cuDF's MemoryCleaner (leak logging at shutdown, re-registered
hook Plugin.scala:581-596) and the refcount double-close/leak logging in
GpuColumnVector / RapidsBuffer. jax.Arrays are garbage-collected, so a leak
here never corrupts memory — but an unclosed SpillableColumnarBatch keeps
HBM pinned in the catalog past its useful life, which is exactly the class
of bug the reference's tracker exists to surface.

Always-on cheap tracking (a dict of live tokens); with
spark.rapids.memory.debug.leakTracking=true each registration also captures
its creation stack so the shutdown report says WHERE the leak was made, and
double-closes raise instead of logging.
"""

from __future__ import annotations

import atexit
import sys
import threading
import traceback
from typing import Dict, List, Optional


class DoubleCloseError(RuntimeError):
    pass


class _Record:
    __slots__ = ("token", "kind", "stack", "closed")

    def __init__(self, token: int, kind: str, stack: Optional[str]):
        self.token = token
        self.kind = kind
        self.stack = stack
        self.closed = False


class MemoryCleaner:
    """Process-wide registry of closeable device resources."""

    _instance: Optional["MemoryCleaner"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._live: Dict[int, _Record] = {}
        self._next = 0
        self._mu = threading.Lock()
        self.debug = False
        self.double_closes = 0

    @classmethod
    def get(cls) -> "MemoryCleaner":
        with cls._lock:
            if cls._instance is None:
                cls._instance = MemoryCleaner()
                atexit.register(cls._instance._at_shutdown)
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "MemoryCleaner":
        with cls._lock:
            cls._instance = MemoryCleaner()
            return cls._instance

    def set_debug(self, on: bool) -> None:
        self.debug = on

    def register(self, kind: str) -> int:
        with self._mu:
            token = self._next
            self._next += 1
            stack = "".join(traceback.format_stack(limit=12)) \
                if self.debug else None
            self._live[token] = _Record(token, kind, stack)
            return token

    def unregister(self, token: int) -> None:
        """Mark closed; a second unregister of the same token is a
        double-close (raises in debug mode, counted otherwise)."""
        with self._mu:
            if self._live.pop(token, None) is not None:
                return
            self.double_closes += 1
            if self.debug:
                raise DoubleCloseError(
                    f"resource token {token} closed twice")

    def live_resources(self) -> List[_Record]:
        with self._mu:
            return list(self._live.values())

    def check_leaks(self, raise_on_leak: bool = False) -> List[str]:
        """Report (and optionally fail on) unclosed resources — the
        test-suite analogue of the shutdown hook."""
        leaks = [f"{r.kind} (token {r.token})"
                 + (f"\n{r.stack}" if r.stack else "")
                 for r in self.live_resources()]
        if leaks and raise_on_leak:
            raise AssertionError(
                f"{len(leaks)} leaked device resources:\n" + "\n".join(leaks))
        return leaks

    def _at_shutdown(self) -> None:
        # catalog-held shuffle blocks are OWNED state (released by the
        # catalog's own shutdown); free them first so the report below only
        # shows genuine leaks, regardless of atexit registration order
        try:
            from ..shuffle.ici import IciShuffleCatalog
            IciShuffleCatalog._shutdown_instance()
        except Exception:  # noqa: BLE001 — report must never fail shutdown
            pass
        try:
            from ..execs.compiled_join import clear_dim_cache
            clear_dim_cache()
        except Exception:  # noqa: BLE001
            pass
        leaks = self.check_leaks(raise_on_leak=False)
        if leaks:
            print(f"[spark-rapids-tpu] MemoryCleaner: {len(leaks)} leaked "
                  f"resources at shutdown:", file=sys.stderr)
            for item in leaks[:20]:
                print(f"  {item}", file=sys.stderr)
