"""TPU device manager: device discovery + HBM budget accounting bootstrap.

Reference: GpuDeviceManager.scala (initializeGpuAndMemory:150, initializeRmm:275).
On TPU the XLA runtime owns the physical HBM allocator, so the RMM-pool analogue
is byte *accounting* against a budget (allocFraction × HBM) plus the spill/retry
machinery in memory/ (SURVEY.md §2.4 TPU mapping note).
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..config import HBM_ALLOC_FRACTION, RapidsConf, default_conf

log = logging.getLogger("spark_rapids_tpu")

# v5e has 16 GiB HBM per chip; used when the runtime doesn't report memory stats
_DEFAULT_HBM_BYTES = 16 * 1024 ** 3


class TpuDeviceManager:
    _lock = threading.Lock()
    _initialized = False
    _device = None
    _hbm_budget_bytes: int = 0

    @classmethod
    def initialize(cls, conf: Optional[RapidsConf] = None) -> None:
        with cls._lock:
            if cls._initialized:
                return
            conf = conf or default_conf()
            import jax
            devices = jax.devices()
            cls._device = devices[0]
            total = _DEFAULT_HBM_BYTES
            try:
                stats = cls._device.memory_stats()
                if stats and "bytes_limit" in stats:
                    total = int(stats["bytes_limit"])
            except Exception:
                pass
            frac = conf.get(HBM_ALLOC_FRACTION)
            cls._hbm_budget_bytes = int(total * frac)
            cls._initialized = True
            log.info("TpuDeviceManager: device=%s hbm_budget=%d bytes",
                     cls._device, cls._hbm_budget_bytes)

    @classmethod
    def device(cls):
        cls.initialize()
        return cls._device

    @classmethod
    def hbm_budget_bytes(cls) -> int:
        cls.initialize()
        return cls._hbm_budget_bytes

    @classmethod
    def synchronize(cls) -> None:
        """Block until outstanding device work completes (reference Cuda.deviceSynchronize)."""
        import jax
        (jax.device_put(0) + 0).block_until_ready()

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._initialized = False
