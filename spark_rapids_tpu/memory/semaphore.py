"""TpuSemaphore: admission control limiting concurrent tasks holding HBM.

Reference: GpuSemaphore.scala (acquireIfNecessary/releaseIfNecessary; default
concurrency spark.rapids.tpu.concurrentTpuTasks=2, RapidsConf.scala:544-551).
A task acquires once before its first device allocation and releases at task
completion (guaranteed by the TaskContext completion listener); operators may
release around long host-IO waits to let other tasks use the device, exactly
the reference's pattern around shuffle/scan IO.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..config import CONCURRENT_TPU_TASKS, RapidsConf, default_conf


class TpuSemaphore:
    _instance: Optional["TpuSemaphore"] = None
    _lock = threading.Lock()

    def __init__(self, permits: int):
        self.permits = permits
        self._sem = threading.BoundedSemaphore(permits)
        self._holders: Dict[int, int] = {}  # task id -> acquire depth
        self._shared: set = set()  # task ids riding another task's permit
        self._state_lock = threading.Lock()
        self.total_waits_ns = 0

    @classmethod
    def get(cls, conf: Optional[RapidsConf] = None) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                conf = conf or default_conf()
                cls._instance = TpuSemaphore(conf.get(CONCURRENT_TPU_TASKS))
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._instance = None

    def acquire_if_necessary(self, ctx) -> None:
        """First call for a task blocks for a permit; later calls are no-ops.
        Registers release at task completion (reference: task-completion
        listener guarantees release, GpuSemaphore.scala). Safe when two
        threads share one task context (pipelined exchange map / join side
        collection): the loser of the first-acquire race hands its extra
        permit back — release runs once per task, so a double-acquire would
        otherwise leak a permit permanently."""
        import time
        tid = id(ctx)
        with self._state_lock:
            if tid in self._shared:
                return  # rides its group's permit (adopt)
            if tid in self._holders:
                self._holders[tid] += 1
                return
        t0 = time.perf_counter_ns()
        self._sem.acquire()
        waited = time.perf_counter_ns() - t0
        from ..obs import metrics as _metrics
        from ..obs import tracer as _obs
        from ..profiling import TaskMetricsRegistry
        TaskMetricsRegistry.get().add("semaphoreWaitNs", waited)
        _metrics.counter_inc("semaphore.waits")
        _metrics.counter_inc("semaphore.wait_ns", waited)
        if _obs._ACTIVE:
            _obs.event("semaphore.wait", cat="memory", wait_ns=waited)
        with self._state_lock:
            self.total_waits_ns += waited
            if tid in self._holders:  # lost the first-acquire race
                self._holders[tid] += 1
                self._sem.release()
                return
            self._holders[tid] = 1
        ctx.add_completion_listener(lambda: self.release_if_necessary(ctx))

    def adopt(self, parent_ctx, child_ctx) -> None:
        """Batched multi-partition dispatch (spark.rapids.tpu.dispatch.
        partitionBatch): a partition GROUP is one unit of device work gated
        by ONE permit, held by the group's context. Member task contexts are
        adopted so their own acquire_if_necessary calls (scans take a permit
        per task) become no-ops — G members each blocking for a permit from
        one pool thread would deadlock the pool against concurrentTpuTasks.
        The parent must already hold; members release nothing at completion
        (the parent's completion releases the one real permit)."""
        ptid, ctid = id(parent_ctx), id(child_ctx)
        with self._state_lock:
            if ptid not in self._holders and ptid not in self._shared:
                return  # parent holds nothing: child acquires normally
            if ctid in self._holders or ctid in self._shared:
                return
            self._shared.add(ctid)
        child_ctx.add_completion_listener(
            lambda: self.release_if_necessary(child_ctx))

    def release_if_necessary(self, ctx) -> None:
        tid = id(ctx)
        with self._state_lock:
            if tid in self._shared:
                self._shared.discard(tid)
                return  # shared rider: the real permit is the parent's
            if tid not in self._holders:
                return
            del self._holders[tid]
        self._sem.release()
