"""Retry framework: idempotent re-execution + input splitting under OOM.

Reference: RmmRapidsRetryIterator.scala:62-200 (withRetry / withRetryNoSplit /
RetryIterator; split on GpuSplitAndRetryOOM; inputs must already be spillable).
This is the key robustness mechanism of the whole design (SURVEY §7 point 3):
any batch-level work can be retried after a spill, or split in half when a
single batch cannot fit.
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, TypeVar

from ..columnar.batch import TpuColumnarBatch, slice_batch
from .hbm import HbmBudget, TpuRetryOOM, TpuSplitAndRetryOOM
from .spill import SpillableColumnarBatch, TpuBufferCatalog

T = TypeVar("T")


class RetryStats:
    def __init__(self) -> None:
        self.retries = 0
        self.split_retries = 0


def split_in_half(spillable: SpillableColumnarBatch) -> List[SpillableColumnarBatch]:
    """Default split policy (reference splitSpillableInHalfByRows)."""
    batch = spillable.get_batch()
    n = batch.num_rows
    if n < 2:
        raise TpuSplitAndRetryOOM("cannot split a batch of fewer than 2 rows")
    half = n // 2
    halves: List[SpillableColumnarBatch] = []
    try:
        halves.append(SpillableColumnarBatch(slice_batch(batch, 0, half)))
        halves.append(SpillableColumnarBatch(slice_batch(batch, half,
                                                         n - half)))
    except BaseException:
        # registering the second half can itself OOM mid-split (its
        # catalog add allocates): the first half must not leak (TL020)
        for s in halves:
            s.close()
        raise
    spillable.close()
    return halves


def with_retry(
    spillable: SpillableColumnarBatch,
    fn: Callable[[TpuColumnarBatch], T],
    split_policy: Optional[Callable[[SpillableColumnarBatch],
                                    List[SpillableColumnarBatch]]] = split_in_half,
    max_retries: int = 8,
    stats: Optional[RetryStats] = None,
) -> Iterator[T]:
    """Run fn over the spillable input, retrying on TpuRetryOOM (after letting
    the catalog spill) and splitting the input on TpuSplitAndRetryOOM. fn MUST
    be idempotent w.r.t. the input batch (reference withRetry contract).
    Yields one result per (sub-)batch."""
    from ..chaos import retry_scope
    pending: List[SpillableColumnarBatch] = [spillable]
    attempts = 0
    try:
        while pending:
            cur = pending[0]
            try:
                # chaos scope: injected OOMs are healable exactly here (the
                # except arms below absorb them), so the randomized injector
                # only fires its OOM kinds inside this window; splitting is
                # only survivable when the input has >= 2 rows and a policy
                with retry_scope(splittable=split_policy is not None
                                 and cur.num_rows >= 2):
                    batch = cur.get_batch()
                    result = fn(batch)
                pending.pop(0)
                cur.close()
                yield result
                attempts = 0
            except TpuSplitAndRetryOOM:
                if stats:
                    stats.split_retries += 1
                from ..profiling import TaskMetricsRegistry
                TaskMetricsRegistry.get().add("splitAndRetryCount", 1)
                if split_policy is None:
                    raise
                pending = split_policy(cur) + pending[1:]
            except TpuRetryOOM:
                if stats:
                    stats.retries += 1
                from ..profiling import TaskMetricsRegistry
                TaskMetricsRegistry.get().add("retryCount", 1)
                attempts += 1
                if attempts > max_retries:
                    raise
                # let pressure drain: spill everything spillable, then retry
                import time as _time
                t0 = _time.perf_counter_ns()
                TpuBufferCatalog.get().synchronous_spill(cur.size_bytes)
                TaskMetricsRegistry.get().add("retryBlockTimeNs",
                                              _time.perf_counter_ns() - t0)
    finally:
        # fn may raise (ANSI errors, ...) and a consumer may abandon the
        # generator: never leak the remaining spillables (close discipline —
        # the MemoryCleaner shutdown report caught exactly this on the ANSI
        # path)
        for s in pending:
            s.close()


def with_retry_no_split(spillable: SpillableColumnarBatch,
                        fn: Callable[[TpuColumnarBatch], T],
                        max_retries: int = 8,
                        stats: Optional[RetryStats] = None) -> T:
    """Retry without splitting (reference withRetryNoSplit)."""
    results = list(with_retry(spillable, fn, split_policy=None,
                              max_retries=max_retries, stats=stats))
    assert len(results) == 1
    return results[0]
