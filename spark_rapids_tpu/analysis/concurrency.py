"""Concurrency lint: module-level mutable state mutated outside a lock.

PR 2's pipelined shuffle turned the general path multi-threaded, and the
bugs it actually hit were exactly this shape: a module-level dict/OrderedDict
(`opjit._CACHE`, metric accumulators, the semaphore wait counters) mutated
from pool threads without the module's lock.  This pass finds the pattern
statically (rule **TL010**, error — baseline the deliberate ones with a
comment):

* a module-level name bound to a mutable container (dict/list/set literal,
  ``dict()``/``list()``/``set()``/``OrderedDict()``/``defaultdict()``/
  ``deque()``) in ``shuffle/``, ``memory/`` or ``execs/``;
* a function/method in the same module that mutates it — subscript store,
  ``del``, augmented assignment, or a mutating method call (``append``,
  ``update``, ``pop``, ``clear``, ...) — with no enclosing ``with`` on a
  lock (a module-level ``threading.Lock``/``RLock`` or any context-manager
  whose name looks lock-ish: contains "lock" or ends in ``_mu``).

Module top-level statements (import-time initialization, single-threaded by
construction) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set, Tuple

from .astwalk import ModuleIndex, lockish as _lockish
from .registry_check import Finding

#: packages the lint covers (relative to the spark_rapids_tpu package root).
#: chaos/ holds the fault injector's process-wide singleton + trace state,
#: reached from every pool thread via the woven injection sites; parallel/
#: holds the mesh-exchange program cache and collective-launch counters,
#: reached from concurrent query threads.
DEFAULT_SUBPACKAGES = ("shuffle", "memory", "execs", "chaos", "parallel")

#: top-level modules with shared state the lint also covers: failure.py's
#: device-retry path runs on exchange pool threads and prefetch workers.
DEFAULT_MODULES = ("failure.py", "profiling.py")

_MUTABLE_CTORS = frozenset((
    "dict", "list", "set", "OrderedDict", "defaultdict", "deque", "Counter",
))

_MUTATING_METHODS = frozenset((
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "popleft", "appendleft", "clear", "remove", "discard", "setdefault",
    "sort", "reverse", "move_to_end",
))


def _is_mutable_ctor(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        return name in _MUTABLE_CTORS
    return False


def _module_mutables(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if _is_mutable_ctor(value):
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


class _FnLint(ast.NodeVisitor):
    """Walk one function keeping a stack of held locks."""

    def __init__(self, mutables: Set[str], lock_names: Set[str],
                 mod: ModuleIndex, qualname: str,
                 findings: List[Finding], relpath: str):
        self.mutables = mutables
        self.lock_names = lock_names
        self.mod = mod
        self.qualname = qualname
        self.findings = findings
        self.relpath = relpath
        self.lock_depth = 0

    # -- lock scoping ----------------------------------------------------
    def visit_With(self, node: ast.With):
        locked = any(self._is_lock_expr(i.context_expr) for i in node.items)
        if locked:
            self.lock_depth += 1
        for st in node.body:
            self.visit(st)
        if locked:
            self.lock_depth -= 1

    def _is_lock_expr(self, expr: ast.AST) -> bool:
        if isinstance(expr, ast.Call):  # with lock.acquire_timeout(...) etc.
            expr = expr.func
        if isinstance(expr, ast.Name):
            return expr.id in self.lock_names or _lockish(expr.id)
        if isinstance(expr, ast.Attribute):
            return _lockish(expr.attr)
        return False

    # -- mutations -------------------------------------------------------
    def _flag(self, node: ast.AST, name: str, how: str) -> None:
        if self.lock_depth:
            return
        self.findings.append(Finding(
            "TL010", "error",
            f"{self.relpath}::{self.qualname}",
            f"module-level mutable `{name}` {how} outside a lock "
            f"(line {getattr(node, 'lineno', '?')}) — pool threads race on "
            f"it; guard with the module lock or baseline with a comment"))

    def visit_Assign(self, node: ast.Assign):
        for t in node.targets:
            self._check_store_target(node, t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        t = node.target
        if isinstance(t, ast.Subscript) and isinstance(t.value, ast.Name) \
                and t.value.id in self.mutables:
            self._flag(node, t.value.id, "augmented in place")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete):
        for t in node.targets:
            self._check_store_target(node, t)
        self.generic_visit(node)

    def _check_store_target(self, node: ast.AST, target: ast.AST) -> None:
        if isinstance(target, ast.Subscript) and \
                isinstance(target.value, ast.Name) and \
                target.value.id in self.mutables:
            self._flag(node, target.value.id, "written by subscript")

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS \
                and isinstance(f.value, ast.Name) \
                and f.value.id in self.mutables:
            self._flag(node, f.value.id, f"mutated via .{f.attr}()")
        self.generic_visit(node)

    def run_body(self, fn: ast.FunctionDef) -> None:
        """Lint the function's statements (not the def node itself, so the
        nested-def skip below doesn't swallow the whole body)."""
        for st in fn.body:
            self.visit(st)

    # don't descend into nested defs with the current lock state —
    # "closures run under the caller's lock" is NOT a safe assumption, so
    # they are linted as their own (unlocked) scope by the module walk
    def visit_FunctionDef(self, node: ast.FunctionDef):
        return

    visit_AsyncFunctionDef = visit_FunctionDef


def lint_module_source(source: str, relpath: str) -> List[Finding]:
    findings: List[Finding] = []
    try:
        mod = ModuleIndex(source, relpath)
    except SyntaxError:
        return findings
    mutables = _module_mutables(mod.tree)
    if not mutables:
        return findings
    lock_names = set(mod.lock_names)

    def walk_fns(body: Iterable[ast.stmt], prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.FunctionDef):
                qual = f"{prefix}{node.name}"
                _FnLint(mutables, lock_names, mod, qual, findings,
                        relpath).run_body(node)
                walk_fns(node.body, f"{qual}.")
            elif isinstance(node, ast.ClassDef):
                walk_fns(node.body, f"{prefix}{node.name}.")

    walk_fns(mod.tree.body, "")
    return findings


def lint_tree(root: Optional[str] = None,
              subpackages: Tuple[str, ...] = DEFAULT_SUBPACKAGES,
              modules: Tuple[str, ...] = DEFAULT_MODULES
              ) -> List[Finding]:
    """Lint the shipped tree (root defaults to the spark_rapids_tpu pkg)."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    for relpath, src in iter_module_sources(root, subpackages, modules):
        findings.extend(lint_module_source(src, relpath))
    return findings
