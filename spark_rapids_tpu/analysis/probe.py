"""Dynamic corroboration: probe registered expressions with jax.eval_shape.

The static detectors predict whether an `eval_tpu` can trace; this pass
*checks* the prediction the same way execs/opjit.py discovers it at runtime —
by tracing.  `jax.eval_shape` runs the function over abstract tracers without
compiling or executing, so any host-boundary op (`np.asarray` on a tracer,
``bool()``/``int()`` coercion, ``.item()``, pyarrow conversion) raises one of
jax's concretization errors exactly where a real opjit trace would fail.

For every trace-relevant registered expression we:

1. build an instance over synthetic fixed-width columns (constructor
   heuristics over common arities/dtypes; unconstructable classes are
   reported as *skipped*, never as agreement);
2. sanity-check it eagerly over a real 8-row batch (an expression that can't
   even run eagerly says nothing about traceability);
3. `jax.eval_shape` the same evaluation over abstract inputs.

Probe verdict **traceable**/**untraceable** is then compared with the static
verdict; a disagreement is finding **TL005** (error): either the detectors
miss a pattern or the implementation changed under the declaration.
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .astwalk import CONDITIONAL_HOST, DEVICE
from .registry_check import ExprReport, Finding

TRACEABLE = "traceable"
NOT_TRACEABLE = "untraceable"
SKIPPED = "skipped"


@dataclass
class ProbeResult:
    status: str   # traceable | untraceable | skipped
    detail: str = ""


def _trace_failure_types() -> Tuple[type, ...]:
    from ..execs.opjit import _TRACE_FAILURES
    return _TRACE_FAILURES


def _synthetic_batch():
    """8-row batch with two columns of every fixed-width family the probes
    draw children from (nulls included so validity paths trace too)."""
    import datetime as _dt

    import pyarrow as pa

    from ..columnar.batch import TpuColumnarBatch
    t = pa.table({
        "l1": pa.array([1, 2, None, 4, 5, 6, 7, 8], pa.int64()),
        "l2": pa.array([8, 7, 6, 5, None, 3, 2, 1], pa.int64()),
        "d1": pa.array([1.5, -2.0, None, 0.0, 3.25, -0.5, 2.0, 9.0]),
        "d2": pa.array([0.5, 2.0, 4.0, None, -1.0, 8.0, 0.25, 1.0]),
        "i1": pa.array([1, -2, 3, None, 5, -6, 7, 8], pa.int32()),
        "i2": pa.array([2, 2, None, 4, 4, 6, 6, 8], pa.int32()),
        "b1": pa.array([True, False, None, True, False, True, False, True]),
        "b2": pa.array([False, False, True, True, None, True, False, True]),
        "dt1": pa.array([_dt.date(2023, 1, 1 + i) for i in range(8)]),
        "ts1": pa.array([_dt.datetime(2023, 1, 1, 0, 0, i)
                         for i in range(8)], pa.timestamp("us")),
    })
    return TpuColumnarBatch.from_arrow(t)


#: child ordinal families over the synthetic batch, tried in order
_FAMILIES = (("long", (0, 1)), ("double", (2, 3)), ("int", (4, 5)),
             ("bool", (6, 7)), ("date", (8, 8)), ("timestamp", (9, 9)))


def _required_arity(cls: type) -> int:
    try:
        sig = inspect.signature(cls.__init__)
    except (TypeError, ValueError):
        return 1
    n = 0
    for name, p in list(sig.parameters.items())[1:]:  # drop self
        if p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
            continue
        if p.default is p.empty:
            n += 1
    return n


def _candidates(cls: type, batch):
    """Yield constructed instances to try, cheapest guess first."""
    from ..expressions.base import AttributeReference

    def ref(ordinal):
        c = batch.columns[ordinal]
        return AttributeReference(f"c{ordinal}", c.dtype, True,
                                  ordinal=ordinal)

    arity = _required_arity(cls)
    for n_kids in dict.fromkeys((arity, 0, 1, 2, 3)):
        if n_kids == 0:
            try:
                yield cls()
            except Exception:  # noqa: BLE001 — constructor guess failed
                pass
            continue
        if n_kids < 1 or n_kids > 3:
            continue
        for _, (o1, o2) in _FAMILIES:
            kids = [ref(o1), ref(o2), ref(o1)][:n_kids]
            try:
                yield cls(*kids)
            except Exception:  # noqa: BLE001 — constructor guess failed
                continue


def probe_class(cls: type, batch=None) -> ProbeResult:
    import jax

    from ..expressions.base import EvalContext, to_column
    if batch is None:
        batch = _synthetic_batch()
    ctx = EvalContext()
    failures = _trace_failure_types()
    last_err: Optional[str] = None
    for expr in _candidates(cls, batch):
        # eager sanity: dtype resolvable and evaluation succeeds at all
        try:
            expr.dtype
            to_column(expr.eval_tpu(batch, ctx), batch)
        except Exception as e:  # noqa: BLE001 — candidate doesn't apply
            last_err = f"eager: {type(e).__name__}: {e}"
            continue

        dtypes = [c.dtype for c in batch.columns]
        cap = batch.capacity
        n = batch.num_rows

        def fn(*flat, _expr=expr):
            from ..columnar.batch import TpuColumnarBatch
            from ..columnar.vector import TpuColumnVector
            cols = [TpuColumnVector(dt, flat[2 * i], flat[2 * i + 1], n)
                    for i, dt in enumerate(dtypes)]
            out = to_column(_expr.eval_tpu(TpuColumnarBatch(cols, n), ctx),
                            batch)
            leaves = [out.data]
            if out.validity is not None:
                leaves.append(out.validity)
            return tuple(leaves)

        flat = []
        abstract = []
        import jax.numpy as jnp
        ragged = False
        for c in batch.columns:
            if c.offsets is not None or c.host_data is not None:
                ragged = True
                break
            v = c.validity if c.validity is not None \
                else jnp.ones((cap,), jnp.bool_)
            flat.extend([c.data, v])
        if ragged:
            return ProbeResult(SKIPPED, "synthetic batch has ragged columns")
        abstract = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat]
        try:
            jax.eval_shape(fn, *abstract)
            return ProbeResult(TRACEABLE)
        except failures as e:
            return ProbeResult(NOT_TRACEABLE, f"{type(e).__name__}")
        except Exception as e:  # noqa: BLE001 — ambiguous: not a trace fact
            return ProbeResult(SKIPPED, f"trace: {type(e).__name__}: {e}")
    return ProbeResult(SKIPPED, last_err or "no constructible candidate")


def corroborate(reports: List[ExprReport]
                ) -> Tuple[Dict[str, ProbeResult], List[Finding]]:
    """Probe every trace-relevant report; return per-class results and the
    TL005 disagreement findings.  `conditional-host` verdicts are exempt: the
    guard may or may not concretize under trace, both outcomes are consistent
    with the declaration."""
    batch = _synthetic_batch()
    results: Dict[str, ProbeResult] = {}
    findings: List[Finding] = []
    for rep in reports:
        if not rep.trace_relevant:
            results[rep.cls.__name__] = ProbeResult(
                SKIPPED, "not trace-relevant (ragged/string or no "
                "fixed-width signature)")
            continue
        res = probe_class(rep.cls, batch)
        results[rep.cls.__name__] = res
        if res.status == SKIPPED or rep.verdict == CONDITIONAL_HOST:
            continue
        static_traceable = rep.verdict == DEVICE
        dynamic_traceable = res.status == TRACEABLE
        if static_traceable != dynamic_traceable:
            findings.append(Finding(
                "TL005", "error", rep.location,
                f"static verdict `{rep.verdict}` disagrees with the "
                f"jax.eval_shape probe (`{res.status}`"
                f"{': ' + res.detail if res.detail else ''}) — fix the "
                f"detector or the declaration"))
    return results, findings
