"""Host-boundary / trace-unsafety detectors over one function body.

Each detector has a stable id (used in findings, baselines and docs):

==========================  ==================================================
id                          fires on
==========================  ==================================================
``np-on-device``            ``np.*`` / ``numpy.*`` call consuming a device
                            value (``np.asarray(col.data)`` syncs to host)
``device-get``              ``jax.device_get(...)`` (explicit download)
``host-method``             ``.to_arrow()`` / ``.to_numpy()`` / ``.to_pylist()``
                            / ``.as_py()`` / ``.item()`` / ``.tolist()`` /
                            ``.block_until_ready()`` on a device value
``pyarrow-on-device``       ``pa.*`` / ``pc.*`` call consuming a device value
``py-coercion``             ``bool()/int()/float()`` of a device value (the
                            implicit ``TracerBoolConversionError`` sites)
``value-dependent-branch``  Python ``if``/``while`` whose test reads a raw
                            device value (data-dependent control flow)
``per-row-loop``            Python ``for``/comprehension iterating a device
                            array row by row (iterating a python list OF
                            columns is fine and does not fire)
``host-helper-call``        call of a module helper / same-module method that
                            itself crosses the host boundary
                            (e.g. ``_to_arrow_side``, ``self._host_from_vals``)
==========================  ==================================================

A hit is *conditional* when the statement only runs behind a branch, a
ternary arm, an except handler, or the implicit else of a guard that
returns.  Verdict impact (astwalk.FunctionReport.verdict): unconditional
host hit ⇒ ``host``; conditional-only hits ⇒ ``conditional-host``;
unconditional branch/loop unsafety ⇒ ``untraceable``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from .astwalk import (COERCION_CALLS, COL, DEVICE_KINDS, EXEMPT_CALLS,
                      HOST, HOST_METHODS, Detection, FunctionReport,
                      ModuleIndex, TaintState, _root_name,
                      isinstance_scalar_names, may_terminate, seed_params)

#: detector ids in documentation order
DETECTOR_IDS = (
    "np-on-device", "device-get", "host-method", "pyarrow-on-device",
    "py-coercion", "value-dependent-branch", "per-row-loop",
    "host-helper-call",
)

#: helper names marking the function as operating on ragged string/array
#: layouts (never admitted by the opjit gate), wherever they are defined
_STRING_LAYOUT_HELPERS = frozenset((
    "_dev_str", "_ascii_dev", "_sl", "_to_arrow_side",
    "_string_result_from_arrow", "_bool_result_from_arrow",
    "starts_lengths", "_expand_list", "_fixed_list", "_eval_list",
    "_compact_list", "_result_from_pylist",
))


class _Scanner:
    def __init__(self, fn: ast.FunctionDef, mod: ModuleIndex,
                 taint_seeds: Dict[str, str], qualname: str):
        self.fn = fn
        self.mod = mod
        self.taint = TaintState(dict(taint_seeds), mod)
        self.report = FunctionReport(qualname=qualname)

    # ------------------------------------------------------------------
    def run(self) -> FunctionReport:
        self._stmts(self.fn.body, cond=False)
        return self.report

    def _hit(self, detector: str, node: ast.AST, cond: bool, msg: str) -> None:
        self.report.detections.append(Detection(
            detector=detector, line=getattr(node, "lineno", 0),
            snippet=self.mod.snippet(node), conditional=cond, message=msg))

    # -- statements ----------------------------------------------------
    def _stmts(self, body: Sequence[ast.stmt], cond: bool) -> None:
        # `guarded` flips once a prior `if` MAY leave the function — the
        # rest of the body is then not on every path, i.e. conditional.
        # may_terminate (not terminates) so `if guard: try: return kernel()
        # except: pass` still makes the host tail the fallback it is.
        guarded = False
        for st in body:
            self._stmt(st, cond or guarded)
            if isinstance(st, ast.If) and may_terminate(st.body):
                guarded = True

    def _stmt(self, st: ast.stmt, cond: bool) -> None:
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = st.value
            if value is not None:
                self._expr(value, cond)
            if isinstance(st, ast.Assign):
                self.taint.assign(st.targets, value)
            elif isinstance(st, ast.AnnAssign) and value is not None:
                self.taint.assign([st.target], value)
            elif isinstance(st, ast.AugAssign):
                if self.taint.is_device(st.value):
                    self.taint._mark(st.target, self.taint.kind_of(st.value))
        elif isinstance(st, (ast.Expr, ast.Return)):
            if st.value is not None:
                self._expr(st.value, cond)
        elif isinstance(st, ast.If):
            self._branch_test(st.test, cond)
            scalar_names = isinstance_scalar_names(st.test)
            saved = dict(self.taint.kinds)
            # inside `isinstance(x, TpuScalar)` the value is a host scalar
            for n in scalar_names:
                self.taint.kinds.pop(n, None)
            self._stmts(st.body, cond=True)
            after_body = dict(self.taint.kinds)
            self.taint.kinds = dict(saved)
            self._stmts(st.orelse, cond=True)
            # conservative join: taint acquired in EITHER arm survives (a
            # name assigned a device value under `if` is device after it),
            # except the scalar-narrowed names, which only lose taint
            # inside their guard
            for k, v in after_body.items():
                if k not in scalar_names:
                    self.taint.kinds.setdefault(k, v)
        elif isinstance(st, ast.While):
            self._branch_test(st.test, cond)
            self._stmts(st.body, cond=True)
        elif isinstance(st, ast.For):
            self._expr(st.iter, cond)
            k = self.taint.kind_of(st.iter)
            if k in DEVICE_KINDS:
                self._hit("per-row-loop", st, cond,
                          "python loop iterates a device value row by row")
            self.taint._mark(st.target, COL if k else None)
            # a for-body inherits the loop's conditionality: eval loops run
            # over non-empty children/columns, so a host op inside is paid
            # per batch — treating it as conditional would let an
            # unconditional per-batch sync dodge TL001
            self._stmts(st.body, cond)
            self._stmts(st.orelse, cond=True)
        elif isinstance(st, ast.With):
            for item in st.items:
                self._expr(item.context_expr, cond)
                if item.optional_vars is not None:
                    self.taint._mark(item.optional_vars,
                                     self.taint.kind_of(item.context_expr))
            self._stmts(st.body, cond)
        elif isinstance(st, ast.Try):
            self._stmts(st.body, cond)
            for h in st.handlers:
                self._stmts(h.body, cond=True)
            self._stmts(st.orelse, cond=True)
            self._stmts(st.finalbody, cond)
        elif isinstance(st, ast.FunctionDef):
            # nested closure (e.g. a traced fn): may or may not run —
            # analyze conservatively as conditional, sharing the namespace
            self._stmts(st.body, cond=True)
        elif isinstance(st, ast.Assert):
            self._branch_test(st.test, cond)
        elif isinstance(st, ast.Raise):
            if st.exc is not None:
                self._expr(st.exc, cond)
        # Pass/Break/Continue/Import/Global/Delete: nothing to do

    # -- branch tests ---------------------------------------------------
    def _branch_test(self, test: ast.AST, cond: bool) -> None:
        self._expr(test, cond)
        if self._test_value_dependent(test):
            self._hit("value-dependent-branch", test, cond,
                      "branch condition depends on device data")

    def _test_value_dependent(self, test: ast.AST) -> bool:
        """A raw device value decides the branch.  Structural forms
        (isinstance, `is None`, metadata attrs) and explicit host coercions
        (flagged separately as py-coercion) are exempt."""
        if isinstance(test, ast.BoolOp):
            return any(self._test_value_dependent(v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self._test_value_dependent(test.operand)
        if isinstance(test, ast.Call):
            f = test.func
            name = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else None)
            if name in EXEMPT_CALLS or name in COERCION_CALLS:
                return False  # structural, or already a py-coercion finding
        return self.taint.kind_of(test) in DEVICE_KINDS

    # -- expressions ----------------------------------------------------
    def _expr(self, node: ast.AST, cond: bool) -> None:
        """Recursive expression walk that keeps ternary arms conditional."""
        if isinstance(node, ast.IfExp):
            self._branch_test(node.test, cond)
            self._expr(node.body, True)
            self._expr(node.orelse, True)
            return
        if isinstance(node, ast.Call):
            self._call(node, cond)
            self._expr(node.func, cond)
            for a in node.args:
                self._expr(a, cond)
            for k in node.keywords:
                if k.value is not None:
                    self._expr(k.value, cond)
            return
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            saved = dict(self.taint.kinds)
            for gen in node.generators:
                self._expr(gen.iter, cond)
                k = self.taint.kind_of(gen.iter)
                if k in DEVICE_KINDS:
                    self._hit("per-row-loop", node, cond,
                              "comprehension iterates a device value row "
                              "by row")
                self.taint._mark(gen.target, COL if k else None)
                for if_ in gen.ifs:
                    self._branch_test(if_, cond)
            if isinstance(node, ast.DictComp):
                self._expr(node.key, cond)
                self._expr(node.value, cond)
            else:
                self._expr(node.elt, cond)
            self.taint.kinds = saved
            return
        if isinstance(node, ast.Attribute):
            if node.attr == "offsets" \
                    and self.taint.kind_of(node.value) in DEVICE_KINDS:
                self.report.string_layout = True
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.keyword, ast.Slice)):
                self._expr(child, cond)

    def _call(self, node: ast.Call, cond: bool) -> None:
        f = node.func
        any_device_arg = any(self.taint.is_device(a) for a in node.args) \
            or any(k.value is not None and self.taint.is_device(k.value)
                   for k in node.keywords)
        for k in node.keywords:
            # constructing a column from a freshly computed device offsets
            # array => ragged/string output.  ARR only: a pass-through
            # `offsets=offsets` parameter inside generic constructors like
            # base.make_column must NOT mark every caller ragged.
            if k.arg == "offsets" and k.value is not None \
                    and self.taint.kind_of(k.value) == "arr":
                self.report.string_layout = True

        summary = None
        helper_label = None
        if isinstance(f, ast.Name):
            name = f.id
            if name in COERCION_CALLS and any_device_arg:
                self._hit("py-coercion", node, cond,
                          f"{name}() of a device value syncs to host")
                return
            if name in _STRING_LAYOUT_HELPERS:
                self.report.string_layout = True
            summary, helper_label = self.mod.helpers.get(name), name
        elif isinstance(f, ast.Attribute):
            attr = f.attr
            root = _root_name(f)
            origin = self.mod.root_module(root) if root else ""
            recv_kind = self.taint.kind_of(f.value)

            if attr in _STRING_LAYOUT_HELPERS:
                self.report.string_layout = True
            if attr in HOST_METHODS and recv_kind in DEVICE_KINDS:
                self._hit("host-method", node, cond,
                          f".{attr}() on a device value is a host hop")
                return
            if attr == "device_get" and (origin.startswith("jax")
                                         or root == "jax"):
                self._hit("device-get", node, cond,
                          "jax.device_get downloads to host")
                return
            any_seq_arg = any(self.taint.kind_of(a) == "seq"
                              for a in node.args)
            if origin.startswith("numpy") and (any_device_arg or any_seq_arg):
                self._hit("np-on-device", node, cond,
                          f"np.{attr}() consumes a device value (host sync)")
                return
            if origin.startswith("pyarrow") and (any_device_arg
                                                 or any_seq_arg):
                self._hit("pyarrow-on-device", node, cond,
                          f"pyarrow {root}.{attr}() consumes a device value")
                return
            if "kernels.strings" in origin:
                self.report.string_layout = True
            if isinstance(f.value, ast.Name) and f.value.id in ("self", "cls"):
                summary = self.mod.methods.get(attr)
                helper_label = f"self.{attr}"

        if summary is not None:
            if summary.string_layout:
                self.report.string_layout = True
            if summary.host_grade is not None:
                self._hit("host-helper-call", node,
                          cond or summary.host_grade != HOST,
                          f"helper {helper_label}() crosses the host "
                          f"boundary")


def scan_function(fn: ast.FunctionDef, mod: ModuleIndex,
                  taint_seeds: Optional[Dict[str, str]] = None,
                  qualname: str = "") -> FunctionReport:
    """Run every detector over one function body.

    `taint_seeds` maps parameter names to taint kinds on entry.  For an
    `eval_tpu(self, batch, ctx)` method the seed is `{"batch": COL}` (column
    access via `batch.column(...)` / child `eval_tpu` produces the taint);
    for module helpers use astwalk.seed_params (device-ish by default with
    scalar/sequence name heuristics)."""
    if taint_seeds is None:
        taint_seeds = {"batch": COL}
    return _Scanner(fn, mod, dict(taint_seeds),
                    qualname or fn.name).run()


def find_method(mod: ModuleIndex, class_name: str,
                method: str) -> Optional[ast.FunctionDef]:
    cls = mod.classes.get(class_name)
    if cls is None:
        return None
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == method:
            return node
    return None


def scan_source(source: str, path: str = "<string>"):
    """Classify every function/method in a source blob (test fixtures, kernel
    modules).  Returns {qualname: FunctionReport}."""
    mod = ModuleIndex(source, path)
    out = {}
    for name, fn in mod.functions.items():
        out[name] = scan_function(fn, mod, taint_seeds=seed_params(fn),
                                  qualname=name)
    for cname, cls in mod.classes.items():
        for node in cls.body:
            if isinstance(node, ast.FunctionDef):
                out[f"{cname}.{node.name}"] = scan_function(
                    node, mod, taint_seeds={"batch": COL},
                    qualname=f"{cname}.{node.name}")
    return out
