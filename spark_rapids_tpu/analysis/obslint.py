"""Observability lint (rule **TL012**): emission discipline for the whole
obs plane — tracer spans/events, metrics-registry increments, and flight-
recorder notes.

The obs layer (docs/observability.md) is only trustworthy if engine code
follows two rules, checked statically here over ``execs/``, ``shuffle/``,
``memory/`` and ``parallel/`` (the mesh.exchange spans):

1. **Route through the obs API.** Emission sites must use the public
   helpers (``obs.span`` / ``obs.event`` / ``obs.dispatch_event`` /
   ``obs.sync_event`` / ``obs.current_span``; ``metrics.counter_inc`` /
   ``gauge_set`` / ``gauge_max`` / ``histogram_observe``;
   ``flight.note``) — not the tracer internals (``QueryTracer``,
   ``_Span``, the ring-buffer ``_append``), not the registry internals
   (``MetricsRegistry`` cells), and not raw ``jax.profiler`` annotations
   (those belong in profiling.py's ``trace_scope``, which carries the
   off-fast-path). A bypass would skip the ``_ACTIVE``/enabled gates
   (overhead when off), the category filter, and the thread-local span
   stacks (corrupting the tree for every later span on that thread).

2. **Instrumentation must not introduce unaudited blocking syncs.** An
   emission ARGUMENT — a span/event arg, a registry label or value, a
   flight-note field — that forces a device value to host
   (``np.asarray(...)``, ``.item()``, ``jax.device_get(...)``, or
   ``int()``/``float()`` of a jnp expression) is a hidden ~100 ms round
   trip through the tunnel that fires exactly when the observability
   plane is on — the observer would perturb the observed, and the sync
   would bypass the audited ledger gate (TL011's contract). Emission args
   must be values the caller already has on host; the always-on registry
   makes this non-negotiable (the sync would fire on EVERY query, not
   just traced ones).

3. **The fused collective dataplane stays one dispatch.** The post-
   collective compact of ``parallel/mesh.py`` runs INSIDE the cached
   exchange program (scatter to ``bases[src] + pos`` under the host-known
   sizing counts — ISSUE 16's fused compact): a call to the host-compact
   idiom (``columnar.batch._compact_plan`` / ``gather``) in that module
   re-introduces the per-partition host round-trips the fusion removed,
   so it fails static analysis here rather than waiting for a bench round
   to notice the compact wall is back.

All are errors; the baseline stays EMPTY — our own instrumentation
complies, and new emission sites must too.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .registry_check import Finding

#: packages the lint covers (relative to the spark_rapids_tpu package root)
OBS_SUBPACKAGES: Tuple[str, ...] = ("execs", "shuffle", "memory", "parallel",
                                    "serving")

#: individual modules additionally covered: obs/mesh_profile.py is part of
#: the obs package but is itself an EMITTER (registry histograms, flight
#: notes, the watchdog) — its emission arguments obey the same
#: no-blocking-sync contract as engine code. io/device_decode.py emits
#: scan.page/scan.fallback events per staged page/demoted column (the
#: BYTE_ARRAY string staging added more of them) — same contract.
OBS_MODULES: Tuple[str, ...] = ("obs/mesh_profile.py", "io/device_decode.py")

#: names that count as obs emission entry points when bound from the obs
#: package (rule 2 scans their call arguments): tracer spans/events,
#: per-query counter events, metrics-registry increments, flight notes,
#: mesh-profiler records
_EMIT_NAMES = ("span", "event", "dispatch_event", "sync_event",
               "counter_inc", "gauge_set", "gauge_max",
               "histogram_observe", "note", "record_exchange",
               "record_fallback")

#: obs submodules whose attribute calls are emission sites when imported
#: (``from ..obs import tracer as obs`` / ``metrics`` / ``flight`` /
#: ``mesh_profile``)
_OBS_MODULE_NAMES = ("tracer", "metrics", "flight", "obs", "mesh_profile")

#: tracer/registry internals whose use outside obs/ is a rule-1 finding
_INTERNAL_NAMES = ("QueryTracer", "_Span", "_NullSpan", "MetricsRegistry")
_INTERNAL_ATTRS = ("_append", "_alloc_span", "_ring", "_cells",
                   "_counters", "_gauges", "_hists")

#: rule 3 — the fused one-dispatch surface: modules whose post-collective
#: consumption must stay inside the ONE cached exchange program; calling
#: the host-compact idiom there is the regression the fusion removed
_FUSED_DISPATCH_MODULES: Tuple[str, ...] = ("parallel/mesh.py",)
_HOST_COMPACT_CALLS: Tuple[str, ...] = ("_compact_plan", "gather")


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an expression ('jax.profiler.start_trace',
    'obs.event', ...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _is_blocking_call(call: ast.Call) -> Optional[str]:
    """The blocking-sync shapes of TL011, syntactically: raw transfer calls
    plus int()/float() coercion of a jnp/jax expression."""
    name = _dotted(call.func)
    if name.endswith(("np.asarray", "numpy.asarray", "np.array",
                      "numpy.array")):
        return name
    if name in ("jax.device_get", "device_get") \
            or name.endswith(".device_get"):
        return name
    if isinstance(call.func, ast.Attribute) and call.func.attr == "item" \
            and not call.args:
        return _dotted(call.func)
    if name in ("int", "float") and call.args:
        inner = _dotted(call.args[0].func) if isinstance(
            call.args[0], ast.Call) else _dotted(call.args[0])
        if inner.startswith(("jnp.", "jax.")):
            return f"{name}({inner})"
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, relpath: str):
        self.relpath = relpath
        self.stack: List[str] = []
        self.obs_modules: set = set()   # names bound to the obs pkg/tracer
        self.obs_helpers: set = set()   # emission helpers imported by name
        self.hits: List[Tuple[str, int, str]] = []  # (qual, line, msg)

    # --- import tracking ---------------------------------------------------
    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        # a module inside obs/ itself imports siblings relatively
        # (``from . import metrics``) — same binding rules apply
        in_obs_pkg = self.relpath.startswith("obs/") and not mod
        if in_obs_pkg or mod.endswith("obs") or ".obs." in f".{mod}." or \
                mod.endswith(("obs.tracer", "obs.metrics", "obs.flight",
                              "obs.mesh_profile")):
            for a in node.names:
                bound = a.asname or a.name
                if a.name in _EMIT_NAMES:
                    self.obs_helpers.add(bound)
                elif a.name in _OBS_MODULE_NAMES:
                    self.obs_modules.add(bound)
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            if a.name.endswith((".obs", ".obs.tracer", ".obs.metrics",
                                ".obs.flight")):
                self.obs_modules.add(a.asname or a.name.split(".")[-1])
        self.generic_visit(node)

    # --- qualname tracking --------------------------------------------------
    def _qual(self) -> str:
        return ".".join(self.stack) or "<module>"

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    # --- the rules -----------------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        name = _dotted(node)
        if name.startswith("jax.profiler."):
            self.hits.append((
                self._qual(), node.lineno,
                f"raw jax.profiler use ({name}) — emission sites route "
                f"through the obs API (obs.span/obs.event) or "
                f"profiling.trace_scope, which carry the tracing-off "
                f"fast path"))
        elif node.attr in _INTERNAL_ATTRS and self._is_obs_value(node.value):
            self.hits.append((
                self._qual(), node.lineno,
                f"tracer internal ({name}) — use the public obs helpers; "
                f"bypassing them skips the _ACTIVE gate and the "
                f"thread-local span stacks"))
        self.generic_visit(node)

    def _is_obs_value(self, node: ast.AST) -> bool:
        name = _dotted(node)
        head = name.split(".")[0]
        return head in self.obs_modules or "QueryTracer" in name

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in _INTERNAL_NAMES and isinstance(node.ctx, ast.Load):
            self.hits.append((
                self._qual(), node.lineno,
                f"tracer internal ({node.id}) — construct spans/events "
                f"through the public obs helpers only"))
        self.generic_visit(node)

    def _is_emit_call(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return f.id in self.obs_helpers
        if isinstance(f, ast.Attribute) and f.attr in _EMIT_NAMES:
            return self._is_obs_value(f.value)
        return False

    def visit_Call(self, node: ast.Call) -> None:
        if self.relpath in _FUSED_DISPATCH_MODULES:
            last = _dotted(node.func).split(".")[-1]
            if last in _HOST_COMPACT_CALLS:
                self.hits.append((
                    self._qual(), node.lineno,
                    f"host-side compact ({_dotted(node.func)}) in the "
                    f"fused collective dataplane — the post-collective "
                    f"compact is part of the ONE cached exchange dispatch "
                    f"(scatter to bases[src]+pos under the host-known "
                    f"sizing counts); a host _compact_plan/gather here "
                    f"regresses the compact wall the fusion removed"))
        if self._is_emit_call(node):
            for arg in list(node.args) + [k.value for k in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Call):
                        blocked = _is_blocking_call(sub)
                        if blocked:
                            self.hits.append((
                                self._qual(), sub.lineno,
                                f"blocking device→host sync ({blocked}) "
                                f"inside a span/event argument — "
                                f"instrumentation must not sync; pass a "
                                f"value the caller already holds on host"))
        self.generic_visit(node)


def lint_obs_module(source: str, relpath: str) -> List[Finding]:
    """TL012 findings for one module's source."""
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return []
    v = _Visitor(relpath)
    v.visit(tree)
    findings: List[Finding] = []
    seen = set()
    for qual, line, msg in v.hits:
        key = f"{relpath}::{qual}"
        if (key, msg) in seen:
            continue
        seen.add((key, msg))
        findings.append(Finding("TL012", "error", key,
                                f"{msg} (line {line})"))
    return findings


def lint_obs_tree(root: Optional[str] = None,
                  subpackages: Tuple[str, ...] = OBS_SUBPACKAGES,
                  modules: Tuple[str, ...] = OBS_MODULES
                  ) -> List[Finding]:
    """Lint the shipped tree (root defaults to the spark_rapids_tpu pkg)."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    for relpath, src in iter_module_sources(root, subpackages,
                                            modules=modules):
        findings.extend(lint_obs_module(src, relpath))
    return findings
