"""Blocking-sync lint: raw device→host transfers outside the audited gate.

PR 5's sync ledger (profiling.SyncLedger) only stays trustworthy if every
blocking device→host transfer actually routes through the audited helpers
in columnar/vector.py (``audited_sync`` / ``audited_sync_int`` /
``audited_device_get``) — a raw ``np.asarray(device_value)``, ``.item()``
or ``jax.device_get(...)`` is both an unledgered ~100ms round trip and the
exact per-batch-sync regression the ledger exists to catch. This pass finds
the pattern statically (rule **TL011**, error — baseline the deliberate
ones with a comment):

* a ``np.asarray(...)``/``np.array(...)`` call whose argument the taint
  walk grades as a device value, in ``execs/``, ``shuffle/`` or
  ``parallel/`` (the mesh data plane must not reintroduce unaudited
  syncs);
* ``.item()`` on a device value;
* ``jax.device_get(...)`` anywhere outside the audited helper module.

The detection layer is the shared astwalk/detectors taint machinery (the
same walk the registry cross-check uses), filtered down to the three
blocking-transfer shapes; ``int()``/``float()`` coercions are TL001's
territory (they are usually inside eval methods) and stay out of scope
here. Baselined survivors are sites where the sync is inherent and already
understood (e.g. host-assisted fallback paths that materialize whole
columns — those are counted by the ledger at the ``to_arrow`` boundary
instead).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from .detectors import scan_source
from .registry_check import Finding

#: packages the lint covers (relative to the spark_rapids_tpu package root)
SYNC_SUBPACKAGES: Tuple[str, ...] = ("execs", "shuffle", "parallel")


def _is_blocking_sync(d) -> bool:
    if d.detector == "device-get":
        return True
    if d.detector == "np-on-device":
        # only the pure-transfer calls: np.asarray/np.array of a device
        # value. Other np.* consumers (np.iinfo etc. on metadata) are not
        # transfers, and genuinely-compute np-on-device hits are TL001's
        # registry territory.
        snip = d.snippet or ""
        return "np.asarray(" in snip or "np.array(" in snip \
            or "numpy.asarray(" in snip
    if d.detector == "host-method":
        return ".item()" in (d.snippet or "")
    return False


def lint_sync_module(source: str, relpath: str) -> List[Finding]:
    """TL011 findings for one module's source."""
    findings: List[Finding] = []
    try:
        reports = scan_source(source, relpath)
    except SyntaxError:
        return findings
    for qual, rep in sorted(reports.items()):
        hits = [d for d in rep.detections if _is_blocking_sync(d)]
        if not hits:
            continue
        lines = sorted({d.line for d in hits})
        kinds = sorted({d.detector for d in hits})
        findings.append(Finding(
            "TL011", "error", f"{relpath}::{qual}",
            f"blocking device→host sync outside the audited gate "
            f"({'/'.join(kinds)} at line{'s' if len(lines) > 1 else ''} "
            f"{', '.join(map(str, lines))}) — route through "
            f"columnar/vector.py audited_sync*/audited_device_get so the "
            f"sync ledger sees it, or baseline with a comment"))
    return findings


def lint_sync_tree(root: Optional[str] = None,
                   subpackages: Tuple[str, ...] = SYNC_SUBPACKAGES
                   ) -> List[Finding]:
    """Lint the shipped tree (root defaults to the spark_rapids_tpu pkg)."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    for relpath, src in iter_module_sources(root, subpackages):
        findings.extend(lint_sync_module(src, relpath))
    return findings
