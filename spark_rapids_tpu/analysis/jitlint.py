"""jitlint: program-cache & dispatch-discipline analyzer (TL030–TL034).

The engine's performance contract — ONE cached program per operator
forest / exchange / row group, O(exchanges) collective launches, donated
staging — rests on four invariants that until now lived only in reviewers'
heads and after-the-fact counter assertions.  This pass proves them
statically over the cached-program surfaces (`execs/`, `kernels/`,
`parallel/`, `io/`, `shuffle/`):

**TL030 cache-key stability** — every cached-program key must be built
from hashable, bounded-cardinality, value-stable components.  Flagged
inside key expressions (a cache-dict ``.get``/``[k] =`` argument, or any
local conventionally named ``key``/``cache_key``):

* float literals (FP noise aliases or explodes entries);
* ``id(...)``/``hash(obj)`` (identity is per-process, per-object: a
  restarted worker or a rebuilt plan never hits);
* per-query values (names matching query/session/task/request ids,
  timestamps) — unbounded cardinality, the cache becomes a leak;
* inline conf reads (``conf.get(...)`` / ``.conf`` chains) — hoist a
  bounded fingerprint (the ``_conf_fp``/``conf_fp`` idiom) instead, so
  reviewers can see exactly which conf axes key the program;
* unhashable displays (list/dict/set literals).

Names carrying a sanctioned fingerprint (``*fp*``, ``*fingerprint*``,
``*sig*``) are trusted and not resolved further — that is the approved
way to put derived state into a key.

**TL031 static-shape bucketing** — a value fetched from the device
(``audited_device_get``/``audited_sync*``/``.item()``/``jax.device_get``)
is data-dependent; if it reaches an array-allocation shape or a program
cache key without passing through ``bucket_capacity`` (or another
``bucket*`` helper), every distinct batch recompiles — the per-shape
recompile the hit-rate counters only reveal after the fact.  Taint is
tracked per function and cleared by the bucketing helpers.

**TL032 trace purity** — a function body that gets traced (``jax.jit``
directly, through ``shard_map``, via a decorator, or as the inner def a
``build`` closure hands to ``opjit._cached_call``) must be pure w.r.t.
the host: no wall-clock, no host RNG, no blocking syncs, no mutable
module-global reads, no conf lookups, and no capture of a live session
context (``eval_ctx``/``ctx``) — a conf captured at trace time but keyed
out of the fingerprint is a WRONG-RESULTS bug (first trace wins for every
later conf), not just a perf bug.  The sanctioned idiom is
``opjit._trace_ctx(eval_ctx)``: a detached minimal context whose conf
axes are exactly the ``_conf_fp`` components in the cache key.
Complements TL011/TL012, which cover runtime emission sites, not trace
closures.

**TL033 donated-buffer safety** — a buffer passed at a ``donate_argnums``
position is dead after dispatch.  Donating programs are discovered from
``jax.jit(..., donate_argnums=...)`` (including positions built with the
``_donate((...))`` / ``_donate(range(a, b))`` gate) and propagated
TL020-style through same-module helper returns, program-cache dicts and
single-binding call parameters.  Flagged:

* a read of a donated name after the dispatch, on any path that does not
  rebind it first (rebinding at the dispatch itself —
  ``accs = comp(*accs)`` — is the sanctioned double-buffer pattern);
* a donated ref that lives in an outliving container (module-level pool
  or cache, ``self.`` attribute) — the container now holds a dead buffer;
* a donating dispatch reachable under ``with_device_retry`` whose donated
  buffers are captured free variables or parameters of the retried
  callable — after a failed launch their state is undefined, so the
  retry MUST re-stage from still-open spillables (buffers constructed
  INSIDE the retried callable, the shuffle/exchange.py discipline).

Donation tracking is deliberately conservative: a program whose donated
positions cannot be resolved statically is not tracked (opjit's generic
``_cached_call``/``_dispatch`` plumbing guards donated dispatches
dynamically and is modeled explicitly instead).

**TL034 plan-cache key surface** — the scheduler-owned PLAN cache
(``serving/plan_cache.py``) keys finished physical plans, so its
fingerprint builders get the same scrutiny as program-cache keys but
with a different sanction rule.  Inside every ``fingerprint``/``*_sig``
function under ``serving/``, flagged:

* ``id(...)``/``hash(...)`` of an object NOT pinned by the entry —
  identity is only stable while the object is alive, so the sanctioned
  idiom records the object (or its id) in a ``pins``/``rel_ids``
  container the entry keeps (``rel_ids.append(id(plan))``, the mesh
  token next to ``pins.append(mesh)``); unpinned identity is the TL030
  bug with a longer fuse;
* per-query values, wall-clock reads, per-call randomness — unbounded
  cardinality;
* live conf reads (``conf.get(...)``) inside a key builder — key off
  the pre-filtered ``plan_relevant_conf`` items so every fingerprinted
  axis is visible in one place (the TL032 bug class: a conf read at
  build time but absent from the key silently reuses stale plans);
* bare schema-ish objects (``output``/``attrs``/``schema``/``fields``)
  fed to key material (f-strings, token appends, hashing) without a
  ``_attrs_sig``/``_safe_repr`` wrapper — default reprs carry expr_ids
  and addresses, so the "signature" changes per plan object.

All five report one finding per (rule, function) with line numbers in the
message, keyed ``relpath::qualname`` — stable under reformatting, same
baseline machinery as every other tracelint pass.  TL030–TL033 cover the
JIT surfaces (``lint_jit_tree``); TL034 covers ``serving/``
(``lint_plan_key_tree``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from .registry_check import Finding

#: subpackages the lint covers — every cached-program / donation surface
JIT_SUBPACKAGES: Tuple[str, ...] = ("execs", "kernels", "parallel", "io",
                                    "shuffle")

# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name for Name/Attribute chains ('' otherwise)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if parts:
        return "." + ".".join(reversed(parts))
    return ""


def _call_name(call: ast.Call) -> str:
    return _dotted(call.func)


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _walk_no_defs(root: ast.AST):
    """ast.walk that does not descend into nested function defs/lambdas
    (their bodies belong to a different scope/analysis)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


#: per-function node-list memo — every pass re-traverses the same defs,
#: and ast.walk dominates the lint wall time without it (the --only
#: TL03x loop must stay sub-second); keyed by the node object itself
#: (keeps it alive — no id-reuse hazard) and cleared per module
_WALK_CACHE: Dict[ast.AST, List[ast.AST]] = {}


def _walk(node: ast.AST) -> List[ast.AST]:
    nodes = _WALK_CACHE.get(node)
    if nodes is None:
        nodes = list(ast.walk(node))
        _WALK_CACHE[node] = nodes
    return nodes


def _local_defs(fn: ast.AST) -> Dict[str, ast.FunctionDef]:
    """Directly nested function defs of `fn` (not recursing into them)."""
    out = {}
    for st in _walk(fn):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and st is not fn:
            out.setdefault(st.name, st)
    return out


def _assigned_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for elt in target.elts:
            out.extend(_assigned_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return _assigned_names(target.value)
    return []


def _fn_params(fn: ast.FunctionDef) -> List[str]:
    a = fn.args
    names = [p.arg for p in a.posonlyargs + a.args]
    if a.vararg:
        names.append(a.vararg.arg)
    names += [p.arg for p in a.kwonlyargs]
    if a.kwarg:
        names.append(a.kwarg.arg)
    return names


def _mutable_globals(tree: ast.Module) -> Set[str]:
    """Module-level names bound to mutable displays/constructors (the
    state TL010 guards with locks; a traced body must never read them)."""
    out: Set[str] = set()
    for st in tree.body:
        targets = []
        if isinstance(st, ast.Assign):
            targets = st.targets
            value = st.value
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            targets = [st.target]
            value = st.value
        else:
            continue
        mutable = isinstance(value, (ast.Dict, ast.List, ast.Set))
        if isinstance(value, ast.Call) and _last(_call_name(value)) in (
                "dict", "list", "set", "OrderedDict", "defaultdict",
                "deque"):
            mutable = True
        if mutable:
            for t in targets:
                out.update(_assigned_names(t))
    return out


def _module_cache_dicts(tree: ast.Module) -> Set[str]:
    """Module-level dict-valued names — program caches, pools, memo maps."""
    caches: Set[str] = set()
    for st in tree.body:
        targets = []
        if isinstance(st, ast.Assign):
            targets, value = st.targets, st.value
        elif isinstance(st, ast.AnnAssign) and st.value is not None:
            targets, value = [st.target], st.value
        else:
            continue
        is_dict = isinstance(value, ast.Dict) or (
            isinstance(value, ast.Call)
            and _last(_call_name(value)) in ("dict", "OrderedDict",
                                             "defaultdict"))
        if is_dict:
            for t in targets:
                caches.update(_assigned_names(t))
    return caches


# ---------------------------------------------------------------------------
# TL030 — cache-key stability
# ---------------------------------------------------------------------------

#: a name that IS a fingerprint/signature: trusted, never resolved deeper
_SANCTIONED_KEY_NAME = re.compile(r"fp|fingerprint|sig", re.I)
#: per-query / unbounded-cardinality value names
_PER_QUERY_NAME = re.compile(
    r"(?:^|_)(?:query|session|task|request|shuffle)_?id(?:$|_)"
    r"|timestamp|(?:^|_)now(?:$|_)", re.I)
#: helper calls whose first positional arg is a program-cache key
#: (opjit._cached_call and friends)
_CACHE_CALL = re.compile(r"cached?_call|_cached", re.I)
_CLOCK_PREFIXES = ("time.", "datetime.")
_CLOCK_CALLS = {"perf_counter", "perf_counter_ns", "monotonic",
                "monotonic_ns", "time_ns"}


def _key_component_issues(expr: ast.AST, local_assigns: Dict[str, ast.AST],
                          depth: int = 0) -> List[Tuple[int, str]]:
    """(line, issue) pairs for one cache-key expression.  Names with a
    local assignment are resolved one level (fingerprint-named locals are
    trusted as-is)."""
    issues: List[Tuple[int, str]] = []
    seen: Set[int] = set()

    def visit(node: ast.AST, d: int) -> None:
        if id(node) in seen:
            return
        seen.add(id(node))
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            issues.append((line, f"float literal {node.value!r}"))
        elif isinstance(node, (ast.List, ast.Dict, ast.Set)):
            issues.append((line, "unhashable "
                           f"{type(node).__name__.lower()} display"))
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            last = _last(name)
            if last in ("id", "hash") and node.args \
                    and not isinstance(node.args[0], ast.Constant):
                issues.append((line, f"identity hash {last}(...)"))
            elif name.startswith(_CLOCK_PREFIXES) or last in _CLOCK_CALLS:
                issues.append((line, f"wall-clock read {name}(...)"))
            elif name.startswith(("uuid.", "random.", "np.random.",
                                  "numpy.random.")):
                issues.append((line, f"per-call random value {name}(...)"))
            elif last == "get" and isinstance(node.func, ast.Attribute) \
                    and "conf" in _dotted(node.func.value).lower():
                issues.append((line, "inline conf read "
                               f"{_dotted(node.func.value)}.get(...) — "
                               "hoist a bounded _conf_fp-style fingerprint"))
            for sub in ast.iter_child_nodes(node):
                visit(sub, d)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if _PER_QUERY_NAME.search(node.id):
                issues.append((line, f"per-query value '{node.id}'"))
            elif not _SANCTIONED_KEY_NAME.search(node.id) and d < 2:
                resolved = local_assigns.get(node.id)
                if resolved is not None:
                    visit(resolved, d + 1)
        elif isinstance(node, ast.Attribute):
            if _PER_QUERY_NAME.search(node.attr):
                issues.append((line, f"per-query value '.{node.attr}'"))
            # do not resolve through attribute bases
        else:
            for sub in ast.iter_child_nodes(node):
                visit(sub, d)

    visit(expr, depth)
    return issues


_ASSIGN_MAP_CACHE: Dict[ast.AST, Dict[str, ast.AST]] = {}


def _function_assign_map(fn: ast.FunctionDef) -> Dict[str, ast.AST]:
    """name -> last assigned value expr (single-target assigns only)."""
    cached = _ASSIGN_MAP_CACHE.get(fn)
    if cached is not None:
        return cached
    out: Dict[str, ast.AST] = {}
    for st in _walk(fn):
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            out[st.targets[0].id] = st.value
    _ASSIGN_MAP_CACHE[fn] = out
    return out


def _cache_key_exprs(fn: ast.FunctionDef, caches: Set[str]
                     ) -> List[ast.AST]:
    """Key expressions this function feeds into a program cache: args of
    cache-dict get/setdefault/subscript, plus arg0 of cache helpers
    (opjit._cached_call).  Local dicts / per-query registries (shuffle
    block maps, sort-key accumulators) are deliberately out of scope —
    only module-level program caches have the ONE-program contract."""
    exprs: List[ast.AST] = []
    assigns = _function_assign_map(fn)
    for st in _walk(fn):
        if isinstance(st, ast.Call) and isinstance(st.func, ast.Attribute) \
                and st.func.attr in ("get", "setdefault", "pop") \
                and isinstance(st.func.value, ast.Name) \
                and st.func.value.id in caches and st.args:
            exprs.append(st.args[0])
        elif isinstance(st, ast.Subscript) \
                and isinstance(st.value, ast.Name) \
                and st.value.id in caches:
            exprs.append(st.slice)
        elif isinstance(st, ast.Call) \
                and _CACHE_CALL.search(_last(_call_name(st))) and st.args:
            exprs.append(st.args[0])
    # dedupe: a `key = ...` local used at two cache sites appears once
    uniq: List[ast.AST] = []
    seen: Set[int] = set()
    for e in exprs:
        resolved = e
        if isinstance(e, ast.Name) and e.id in assigns:
            resolved = assigns[e.id]
        if id(resolved) not in seen:
            seen.add(id(resolved))
            uniq.append(resolved)
    return uniq


def _lint_cache_keys(fn: ast.FunctionDef, caches: Set[str], relpath: str
                     ) -> List[Finding]:
    assigns = _function_assign_map(fn)
    issues: List[Tuple[int, str]] = []
    for expr in _cache_key_exprs(fn, caches):
        issues.extend(_key_component_issues(expr, assigns))
    if not issues:
        return []
    issues = sorted(set(issues))
    detail = "; ".join(f"line {ln}: {msg}" for ln, msg in issues)
    return [Finding(
        "TL030", "error", f"{relpath}::{fn.name}",
        f"unstable cached-program key component(s): {detail} — keys must "
        f"be hashable, bounded-cardinality and value-stable (structural "
        f"fingerprints + _conf_fp, never identity/floats/per-query "
        f"values/inline conf reads); see docs/analysis.md cache-key "
        f"design rules")]


# ---------------------------------------------------------------------------
# TL031 — static-shape bucketing
# ---------------------------------------------------------------------------

_SYNC_SUFFIXES = ("audited_device_get", "audited_sync", "audited_sync_int",
                  "device_get")
_ALLOC_CALLS = {"zeros", "ones", "full", "empty", "arange"}
_BUCKET_NAME = re.compile(r"bucket")


def _is_sync_call(node: ast.Call) -> bool:
    name = _call_name(node)
    if _last(name) in _SYNC_SUFFIXES or name == "jax.device_get":
        return True
    return isinstance(node.func, ast.Attribute) \
        and node.func.attr == "item" and not node.args


def _contains_sync_call(expr: ast.AST) -> bool:
    return any(isinstance(node, ast.Call) and _is_sync_call(node)
               for node in ast.walk(expr))


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _is_bucketed(expr: ast.AST) -> bool:
    """The whole value passes through a bucketing/slot-cap helper."""
    e = expr
    while isinstance(e, ast.Call):
        if _BUCKET_NAME.search(_last(_call_name(e)) or ""):
            return True
        return False
    return False


def _tainted_names(fn: ast.FunctionDef) -> Dict[str, int]:
    """name -> taint-source line, forward-propagated (two passes so loop
    carried assignments converge), cleared by bucket* helpers."""
    # taint can only originate at a sync call; almost no function has one,
    # so one memoized scan prunes the quadratic statement passes below
    if not any(isinstance(n, ast.Call) and _is_sync_call(n)
               for n in _walk(fn)):
        return {}
    tainted: Dict[str, int] = {}
    stmts = [st for st in _walk(fn)
             if isinstance(st, (ast.Assign, ast.AugAssign, ast.For))]
    # ast.walk is BFS; re-sort by source position for forward flow
    stmts.sort(key=lambda s: (s.lineno, s.col_offset))
    for _ in range(2):
        for st in stmts:
            if isinstance(st, ast.For):
                # `for x in zip(.., tainted, ..)` style unpack
                if _names_in(st.iter) & tainted.keys() \
                        or _contains_sync_call(st.iter):
                    line = st.lineno
                    for name in _assigned_names(st.target):
                        tainted.setdefault(name, line)
                continue
            value = st.value
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            names = []
            for t in targets:
                names.extend(_assigned_names(t))
            if not names:
                continue
            if _is_bucketed(value):
                for name in names:
                    tainted.pop(name, None)
                continue
            src = None
            if _contains_sync_call(value):
                src = value.lineno
            else:
                hit = _names_in(value) & tainted.keys()
                if hit:
                    src = min(tainted[h] for h in hit)
            if src is not None:
                for name in names:
                    tainted.setdefault(name, src)
            elif isinstance(st, ast.Assign):
                # clean reassignment kills earlier taint
                for name in names:
                    tainted.pop(name, None)
    return tainted


def _lint_bucketing(fn: ast.FunctionDef, caches: Set[str], relpath: str
                    ) -> List[Finding]:
    tainted = _tainted_names(fn)
    if not tainted:
        return []
    issues: List[Tuple[int, str]] = []
    for node in _walk(fn):
        # only DEVICE allocations (jnp/jax): a host numpy array with a
        # data-dependent shape never enters a jitted signature
        if isinstance(node, ast.Call) \
                and _last(_call_name(node)) in _ALLOC_CALLS \
                and _call_name(node).split(".")[0] in ("jnp", "jax"):
            shape_args = list(node.args[:1]) + [
                kw.value for kw in node.keywords if kw.arg == "shape"]
            for a in shape_args:
                for name in sorted(_names_in(a) & tainted.keys()):
                    issues.append(
                        (node.lineno,
                         f"device-derived '{name}' (synced at line "
                         f"{tainted[name]}) in allocation shape"))
    assigns = _function_assign_map(fn)
    for expr in _cache_key_exprs(fn, caches):
        for name in sorted(_names_in(expr) & tainted.keys()):
            if isinstance(assigns.get(name), ast.AST) \
                    and _is_bucketed(assigns[name]):
                continue
            issues.append(
                (expr.lineno,
                 f"device-derived '{name}' (synced at line "
                 f"{tainted[name]}) in a program cache key"))
    if not issues:
        return []
    issues = sorted(set(issues))
    detail = "; ".join(f"line {ln}: {msg}" for ln, msg in issues)
    return [Finding(
        "TL031", "error", f"{relpath}::{fn.name}",
        f"data-dependent shape enters a jitted signature unbucketed: "
        f"{detail} — pass device-derived sizes through "
        f"columnar/vector.py bucket_capacity (or a slot-cap helper) so "
        f"repeated batches reuse ONE compiled program")]


# ---------------------------------------------------------------------------
# TL032 — trace purity
# ---------------------------------------------------------------------------

_LIVE_CTX_NAMES = {"eval_ctx", "ctx"}
_TRACE_CTX_CALL = "_trace_ctx"


def _traced_defs(fn: ast.FunctionDef) -> List[ast.FunctionDef]:
    """Function defs inside `fn` whose bodies get traced: jitted directly
    (`jax.jit(f)` / decorator), through shard_map, or returned by a build
    closure handed to opjit._cached_call."""
    defs = _local_defs(fn)
    traced: Dict[str, ast.FunctionDef] = {}

    def mark(name: str) -> None:
        if name in defs:
            traced.setdefault(name, defs[name])

    for node in _walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = _last(_call_name(node))
        if callee in ("jit", "pjit", "shard_map", "pallas_call"):
            for a in node.args[:1]:
                if isinstance(a, ast.Name):
                    mark(a.id)
        elif callee == "_cached_call" and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Name):
            build = defs.get(node.args[1].id)
            if build is not None:
                for st in _walk(build):
                    if isinstance(st, ast.Return) \
                            and isinstance(st.value, ast.Name):
                        mark(st.value.id)
    # decorator form (top-level and nested)
    for name, d in defs.items():
        for dec in d.decorator_list:
            dn = _dotted(dec if not isinstance(dec, ast.Call) else dec.func)
            if _last(dn) in ("jit", "pjit") or ".jit" in dn:
                traced.setdefault(name, d)
    return list(traced.values())


def _enclosing_bindings(fn: ast.FunctionDef, traced: ast.FunctionDef
                        ) -> Tuple[Set[str], Set[str]]:
    """(params-of-enclosing-scopes, names bound via _trace_ctx) visible to
    the traced def as free variables."""
    params: Set[str] = set(_fn_params(fn))
    via_trace_ctx: Set[str] = set()
    for st in _walk(fn):
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and st is not fn and st is not traced:
            params.update(_fn_params(st))
        if isinstance(st, ast.Assign) and isinstance(st.value, ast.Call) \
                and _last(_call_name(st.value)) == _TRACE_CTX_CALL:
            for t in st.targets:
                via_trace_ctx.update(_assigned_names(t))
    return params, via_trace_ctx


def _lint_trace_purity(fn: ast.FunctionDef, mutable_globals: Set[str],
                       relpath: str, qual_prefix: str = "") -> List[Finding]:
    issues: List[Tuple[int, str]] = []
    for traced in _traced_defs(fn):
        local_names = set(_fn_params(traced))
        for st in _walk(traced):
            if isinstance(st, (ast.Assign, ast.AugAssign, ast.For,
                               ast.withitem)):
                tgts = []
                if isinstance(st, ast.Assign):
                    tgts = st.targets
                elif isinstance(st, ast.AugAssign):
                    tgts = [st.target]
                elif isinstance(st, ast.For):
                    tgts = [st.target]
                elif st.optional_vars is not None:
                    tgts = [st.optional_vars]
                for t in tgts:
                    local_names.update(_assigned_names(t))
            if isinstance(st, (ast.FunctionDef, ast.Lambda)):
                local_names.update(_fn_params(st))
                if isinstance(st, ast.FunctionDef):
                    local_names.add(st.name)
        enclosing_params, via_tctx = _enclosing_bindings(fn, traced)
        for node in _walk(traced):
            line = getattr(node, "lineno", traced.lineno)
            if isinstance(node, ast.Call):
                name = _call_name(node)
                last = _last(name)
                if name.startswith(_CLOCK_PREFIXES) \
                        or last in _CLOCK_CALLS:
                    issues.append((line, f"wall-clock read {name}(...)"))
                elif name.startswith(("random.", "np.random.",
                                      "numpy.random.", "uuid.")):
                    issues.append((line, f"host RNG {name}(...)"))
                elif _last(name) in _SYNC_SUFFIXES \
                        or name == "jax.device_get" \
                        or name in ("np.asarray", "np.array",
                                    "numpy.asarray", "numpy.array"):
                    issues.append(
                        (line, f"host sync {name}(...) inside the traced "
                               f"body (forces a trace-time transfer and a "
                               f"concretization error on device)"))
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "item" and not node.args:
                    issues.append((line, "host sync .item() inside the "
                                   "traced body"))
                elif last == "get" and isinstance(node.func, ast.Attribute) \
                        and "conf" in _dotted(node.func.value).lower():
                    issues.append(
                        (line, f"conf lookup "
                               f"{_dotted(node.func.value)}.get(...) "
                               f"captured at trace time"))
            elif isinstance(node, ast.Attribute) and node.attr == "conf" \
                    and isinstance(node.ctx, ast.Load):
                issues.append((line, f"live conf read "
                               f"{_dotted(node)} captured at trace time"))
            elif isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load) \
                    and node.id not in local_names:
                if node.id in mutable_globals:
                    issues.append(
                        (line, f"mutable module global '{node.id}' read "
                               f"inside the traced body (value frozen at "
                               f"trace time, races at runtime)"))
                elif node.id in _LIVE_CTX_NAMES \
                        and node.id in enclosing_params \
                        and node.id not in via_tctx:
                    issues.append(
                        (line, f"live session context '{node.id}' captured "
                               f"at trace time — conf state it carries is "
                               f"frozen into the FIRST traced program and "
                               f"silently reused for every other conf; "
                               f"rebind through opjit._trace_ctx() and put "
                               f"_conf_fp(eval_ctx) in the cache key"))
    if not issues:
        return []
    issues = sorted(set(issues))
    detail = "; ".join(f"line {ln}: {msg}" for ln, msg in issues)
    return [Finding(
        "TL032", "error", f"{relpath}::{qual_prefix}{fn.name}",
        f"impure traced closure: {detail}")]


# ---------------------------------------------------------------------------
# TL033 — donated-buffer safety
# ---------------------------------------------------------------------------


class _DonSpec:
    """Statically-resolved donation positions of a jitted program:
    `exact` positions plus an optional `floor` (positions >= floor are
    donated — the `_donate(range(a, b))` form, where only the start is a
    literal)."""

    __slots__ = ("exact", "floor")

    def __init__(self, exact: Set[int], floor: Optional[int] = None):
        self.exact = exact
        self.floor = floor

    def donated_args(self, call: ast.Call) -> List[ast.AST]:
        out = []
        pos = 0
        for a in call.args:
            if isinstance(a, ast.Starred):
                # a starred arg spans >= pos: donated if any exact
                # position or the floor can reach it
                if (self.floor is not None and True) \
                        or any(p >= pos for p in self.exact):
                    out.append(a.value)
                pos += 1  # at least one
                continue
            if pos in self.exact or (self.floor is not None
                                     and pos >= self.floor):
                out.append(a)
            pos += 1
        return out

    def merge(self, other: "_DonSpec") -> "_DonSpec":
        floor = self.floor if other.floor is None else (
            other.floor if self.floor is None
            else min(self.floor, other.floor))
        return _DonSpec(self.exact | other.exact, floor)


#: donation info: a _DonSpec, or a tuple of per-element infos, or None
_DonInfo = Union[_DonSpec, Tuple, None]


def _resolve_donate_expr(expr: ast.AST) -> Optional[_DonSpec]:
    """Positions from a donate_argnums expression.  Handles int/tuple
    literals, `_donate(<expr>)` wrappers, `range(a[, b])`, `tuple(...)`
    and `lit_tuple + tuple(range(a, b))`.  None when unresolvable (an
    unresolvable donation is NOT tracked — conservative silence beats a
    false post-read flag)."""
    if isinstance(expr, ast.Call) and _last(_call_name(expr)) in (
            "_donate", "tuple"):
        if not expr.args:
            return None
        return _resolve_donate_expr(expr.args[0])
    if isinstance(expr, ast.IfExp):
        # `_donate((..)) if grouped else ()`: the donating branch governs
        for branch in (expr.body, expr.orelse):
            got = _resolve_donate_expr(branch)
            if got is not None and (got.exact or got.floor is not None):
                return got
        return None
    if isinstance(expr, ast.Constant) and isinstance(expr.value, int):
        return _DonSpec({expr.value})
    if isinstance(expr, ast.Tuple):
        exact: Set[int] = set()
        for elt in expr.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                exact.add(elt.value)
            else:
                return None
        return _DonSpec(exact)
    if isinstance(expr, ast.Call) and _last(_call_name(expr)) == "range":
        start = expr.args[0] if len(expr.args) >= 2 else \
            ast.Constant(value=0)
        if len(expr.args) == 1:
            start = ast.Constant(value=0)
        if isinstance(start, ast.Constant) and isinstance(start.value, int):
            return _DonSpec(set(), floor=start.value)
        return None
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = _resolve_donate_expr(expr.left)
        right = _resolve_donate_expr(expr.right)
        if left is not None and right is not None:
            return left.merge(right)
        return None
    return None


def _jit_don_spec(call: ast.Call) -> Optional[_DonSpec]:
    """_DonSpec of a `jax.jit(...)` call, or None if not donating /
    unresolvable."""
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            return _resolve_donate_expr(kw.value)
    return None


class _FnDonSummary:
    """Per-module-function donation summary (TL020-style helper summary):
    what the function returns, donation-wise."""

    __slots__ = ("returns",)

    def __init__(self):
        self.returns: _DonInfo = None


def _merge_info(a: _DonInfo, b: _DonInfo) -> _DonInfo:
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, _DonSpec) and isinstance(b, _DonSpec):
        return a.merge(b)
    if isinstance(a, tuple) and isinstance(b, tuple) and len(a) == len(b):
        return tuple(_merge_info(x, y) for x, y in zip(a, b))
    return a  # shape conflict: keep the first (conservative)


def _donation_env(fn: ast.FunctionDef,
                  summaries: Dict[str, _FnDonSummary],
                  cache_info: Dict[str, _DonInfo],
                  param_info: Dict[str, _DonInfo]) -> Dict[str, _DonInfo]:
    """name -> donation info for locals of `fn` (single forward pass —
    builder results, cache loads, tuple unpacks, param bindings)."""
    env: Dict[str, _DonInfo] = dict(param_info)

    def info_of(expr: ast.AST) -> _DonInfo:
        if isinstance(expr, ast.Name):
            return env.get(expr.id)
        if isinstance(expr, ast.Call):
            cn = _call_name(expr)
            if _last(cn) in ("jit", "pjit"):
                return _jit_don_spec(expr)
            if isinstance(expr.func, ast.Attribute) \
                    and expr.func.attr == "get" \
                    and isinstance(expr.func.value, ast.Name):
                return cache_info.get(expr.func.value.id)
            summ = summaries.get(_last(cn))
            if summ is not None:
                return summ.returns
            return None
        if isinstance(expr, ast.Subscript) \
                and isinstance(expr.value, ast.Name):
            base = cache_info.get(expr.value.id)
            if base is None:
                base = env.get(expr.value.id)
            if isinstance(base, tuple) \
                    and isinstance(expr.slice, ast.Constant) \
                    and isinstance(expr.slice.value, int) \
                    and 0 <= expr.slice.value < len(base):
                return base[expr.slice.value]
            return base if isinstance(base, _DonSpec) else None
        if isinstance(expr, ast.Tuple):
            infos = tuple(info_of(e) for e in expr.elts)
            return infos if any(i is not None for i in infos) else None
        if isinstance(expr, ast.IfExp):
            return _merge_info(info_of(expr.body), info_of(expr.orelse))
        return None

    for st in _walk(fn):
        if not isinstance(st, ast.Assign):
            continue
        info = info_of(st.value)
        if info is None:
            continue
        for t in st.targets:
            if isinstance(t, ast.Name):
                env[t.id] = _merge_info(env.get(t.id), info)
            elif isinstance(t, (ast.Tuple, ast.List)) \
                    and isinstance(info, tuple) \
                    and len(t.elts) == len(info):
                for elt, i in zip(t.elts, info):
                    if isinstance(elt, ast.Name) and i is not None:
                        env[elt.id] = _merge_info(env.get(elt.id), i)
    return env


def _module_don_summaries(tree: ast.Module, caches: Set[str]
                          ) -> Tuple[Dict[str, _FnDonSummary],
                                     Dict[str, _DonInfo]]:
    """Fixpoint over return summaries + cache-dict content infos."""
    fns = {st.name: st for st in tree.body
           if isinstance(st, ast.FunctionDef)}
    summaries = {name: _FnDonSummary() for name in fns}
    cache_info: Dict[str, _DonInfo] = {}
    for _ in range(3):
        changed = False
        for name, fn in fns.items():
            env = _donation_env(fn, summaries, cache_info, {})
            ret: _DonInfo = None
            for st in _walk(fn):
                if isinstance(st, ast.Return) and st.value is not None:
                    if isinstance(st.value, ast.Name):
                        ret = _merge_info(ret, env.get(st.value.id))
                    elif isinstance(st.value, ast.Tuple):
                        infos = tuple(
                            env.get(e.id) if isinstance(e, ast.Name)
                            else None for e in st.value.elts)
                        if any(i is not None for i in infos):
                            ret = _merge_info(ret, infos)
                    elif isinstance(st.value, ast.Call):
                        cn = _last(_call_name(st.value))
                        if cn in ("jit", "pjit"):
                            ret = _merge_info(ret,
                                              _jit_don_spec(st.value))
                        elif cn in summaries:
                            ret = _merge_info(ret, summaries[cn].returns)
                # cache stores: CACHE[key] = donating-value
                if isinstance(st, ast.Assign):
                    for t in st.targets:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name) \
                                and t.value.id in caches:
                            v = st.value
                            vi: _DonInfo = None
                            if isinstance(v, ast.Name):
                                vi = env.get(v.id)
                            elif isinstance(v, ast.Call) \
                                    and _last(_call_name(v)) in ("jit",
                                                                 "pjit"):
                                vi = _jit_don_spec(v)
                            if vi is not None:
                                old = cache_info.get(t.value.id)
                                new = _merge_info(old, vi)
                                if repr_info(new) != repr_info(old):
                                    cache_info[t.value.id] = new
                                    changed = True
            old = summaries[name].returns
            new = _merge_info(old, ret)
            if repr_info(new) != repr_info(old):
                summaries[name].returns = new
                changed = True
        if not changed:
            break
    return summaries, cache_info


def repr_info(info: _DonInfo) -> str:
    if info is None:
        return "-"
    if isinstance(info, _DonSpec):
        return f"D({sorted(info.exact)},{info.floor})"
    return "(" + ",".join(repr_info(i) for i in info) + ")"


def _param_bindings(tree: ast.Module,
                    summaries: Dict[str, _FnDonSummary],
                    cache_info: Dict[str, _DonInfo]
                    ) -> Dict[str, Dict[str, _DonInfo]]:
    """fn-name -> {param -> info} from intramodule call sites (only kept
    when every call site agrees)."""
    fns = {st.name: st for st in tree.body
           if isinstance(st, ast.FunctionDef)}
    bound: Dict[str, Dict[str, List[_DonInfo]]] = {
        n: {} for n in fns}
    for caller in fns.values():
        env = _donation_env(caller, summaries, cache_info, {})
        for node in _walk(caller):
            if not isinstance(node, ast.Call):
                continue
            cn = _last(_call_name(node))
            callee = fns.get(cn)
            if callee is None:
                continue
            params = _fn_params(callee)
            for i, a in enumerate(node.args):
                if isinstance(a, ast.Starred) or i >= len(params):
                    break
                info = env.get(a.id) if isinstance(a, ast.Name) else None
                bound[cn].setdefault(params[i], []).append(info)
    out: Dict[str, Dict[str, _DonInfo]] = {}
    for name, per_param in bound.items():
        agreed: Dict[str, _DonInfo] = {}
        for param, infos in per_param.items():
            reprs = {repr_info(i) for i in infos}
            if len(reprs) == 1 and infos[0] is not None:
                agreed[param] = infos[0]
        if agreed:
            out[name] = agreed
    return out


def _stmt_sequence(fn: ast.FunctionDef) -> List[ast.stmt]:
    """Statements of `fn` in source order (flattened, loop bodies kept as
    units for the wrap-around scan)."""
    return list(fn.body)


class _DonatedCallSite:
    __slots__ = ("call", "stmt", "donated_names", "loop")

    def __init__(self, call, stmt, donated_names, loop):
        self.call = call
        self.stmt = stmt
        self.donated_names = donated_names
        self.loop = loop


def _find_donating_calls(fn: ast.FunctionDef, env: Dict[str, _DonInfo]
                         ) -> List[_DonatedCallSite]:
    # prune: the block scan below only matters if some call could donate —
    # a _cached_call dispatch or a callee with donation info
    for n in _walk(fn):
        if isinstance(n, ast.Call):
            fname = _last(_call_name(n))
            if fname == "_cached_call" or fname in env \
                    or (isinstance(n.func, ast.Name) and n.func.id in env):
                break
    else:
        return []
    sites: List[_DonatedCallSite] = []

    def check(root: ast.AST, stmt: ast.stmt,
              loop: Optional[ast.stmt]) -> None:
        for node in _walk_no_defs(root):
            if not isinstance(node, ast.Call):
                continue
            spec = None
            call = node
            fname = _last(_call_name(node))
            if fname == "_cached_call":
                # opjit's dispatch helper: donated positions index the
                # args TUPLE (3rd positional), not the call's own args
                dk = [kw.value for kw in node.keywords
                      if kw.arg == "donate_argnums"]
                if dk:
                    spec = _resolve_donate_expr(dk[0])
                    if spec is not None and len(node.args) >= 3:
                        call = _args_tuple_as_call(node.args[2], fn)
                        if call is None:
                            spec = None
            else:
                target = env.get(fname) if fname in env else None
                if isinstance(node.func, ast.Name):
                    target = env.get(node.func.id)
                if isinstance(target, _DonSpec):
                    spec = target
            if spec is None:
                continue
            names: Set[str] = set()
            for a in spec.donated_args(call):
                if isinstance(a, ast.Name):
                    names.add(a.id)
            if names:
                sites.append(_DonatedCallSite(node, stmt, names, loop))

    def scan_block(block: Sequence[ast.stmt],
                   loop: Optional[ast.stmt]) -> None:
        for st in block:
            if isinstance(st, (ast.For, ast.While, ast.If, ast.With,
                               ast.Try)):
                # check only the statement HEADER here; bodies are scanned
                # by recursion (so a dispatch inside a loop body is seen
                # exactly once, with `loop` = its innermost loop and
                # `stmt` = its own statement, keeping the rebind-kill and
                # wrap-around scans sound)
                headers: List[ast.AST] = []
                if isinstance(st, ast.For):
                    headers = [st.iter]
                elif isinstance(st, (ast.While, ast.If)):
                    headers = [st.test]
                elif isinstance(st, ast.With):
                    headers = [i.context_expr for i in st.items]
                for h in headers:
                    check(h, st, loop)
                inner = st if isinstance(st, (ast.For, ast.While)) else loop
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(st, attr, None)
                    if sub:
                        scan_block(sub, inner)
                for handler in getattr(st, "handlers", None) or ():
                    scan_block(handler.body, loop)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            else:
                check(st, st, loop)

    scan_block(fn.body, None)
    return sites


def _args_tuple_as_call(expr: ast.AST, fn: ast.FunctionDef
                        ) -> Optional[ast.Call]:
    """Model `tuple(args)` / a tuple display handed to _cached_call as a
    pseudo-call so _DonSpec.donated_args can index it."""
    if isinstance(expr, ast.Call) and _last(_call_name(expr)) == "tuple" \
            and expr.args:
        expr = expr.args[0]
    if isinstance(expr, ast.Name):
        resolved = _function_assign_map(fn).get(expr.id)
        if resolved is not None:
            expr = resolved
    elts = None
    if isinstance(expr, (ast.Tuple, ast.List)):
        elts = list(expr.elts)
    if elts is None:
        return None
    fake = ast.Call(func=ast.Name(id="<args>", ctx=ast.Load()),
                    args=elts, keywords=[])
    return fake


def _loads_before_store(stmts: Sequence[ast.stmt], names: Set[str],
                        issues: List[Tuple[int, str]],
                        start_after: Optional[ast.stmt] = None) -> Set[str]:
    """Scan `stmts` in order for Loads of `names`; a Store kills a name.
    Returns the names still live (not yet stored)."""
    live = set(names)
    seen_start = start_after is None
    for st in stmts:
        if not seen_start:
            if st is start_after:
                seen_start = True
            continue
        if not live:
            break
        # loads first, in AST order — but the assignment VALUE is
        # evaluated before its targets bind, so examine value loads, then
        # kill stored targets
        stored: Set[str] = set()
        for node in ast.walk(st):
            if isinstance(node, ast.Name) and node.id in live:
                if isinstance(node.ctx, ast.Load):
                    issues.append(
                        (node.lineno,
                         f"donated buffer '{node.id}' read after "
                         f"dispatch"))
                    live.discard(node.id)
                elif isinstance(node.ctx, ast.Store):
                    stored.add(node.id)
        live -= stored
    return live


def _lint_donation(fn: ast.FunctionDef,
                   env: Dict[str, _DonInfo],
                   module_globals: Set[str],
                   relpath: str) -> List[Finding]:
    issues: List[Tuple[int, str]] = []
    assigns = _function_assign_map(fn)
    sites = _find_donating_calls(fn, env)

    for site in sites:
        names = set(site.donated_names)
        # the sanctioned double-buffer rebind: `accs = comp(*accs)` —
        # the donated name is dead AND rebound in the same statement
        if isinstance(site.stmt, ast.Assign):
            for t in site.stmt.targets:
                names -= set(_assigned_names(t))
        # pooled/outliving refs at donated positions
        for name in sorted(names):
            src = assigns.get(name)
            if isinstance(src, ast.Subscript) \
                    and isinstance(src.value, ast.Name) \
                    and src.value.id in module_globals:
                issues.append(
                    (site.call.lineno,
                     f"donated buffer '{name}' is a ref into module-level "
                     f"container '{src.value.id}' — the pool now holds a "
                     f"dead buffer"))
        # stores of donated refs into outliving containers anywhere in fn
        for st in _walk(fn):
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    tgt_container = None
                    if isinstance(t, ast.Subscript) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id in module_globals:
                        tgt_container = t.value.id
                    elif isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        tgt_container = f"self.{t.attr}"
                    if tgt_container and isinstance(st.value, ast.Name) \
                            and st.value.id in names:
                        issues.append(
                            (st.lineno,
                             f"donated buffer '{st.value.id}' stored into "
                             f"outliving container '{tgt_container}'"))
        # post-dispatch reads: rest of the enclosing block, with one
        # wrap-around pass when the dispatch sits in a loop
        if site.loop is not None:
            body = site.loop.body
            live = _loads_before_store(body, names, issues,
                                       start_after=_enclosing_stmt(
                                           body, site.stmt))
            if live:
                live = _loads_before_store(body, live, issues)
        container = _containing_block(fn, site.stmt)
        if container is not None:
            _loads_before_store(container, names, issues,
                                start_after=_enclosing_stmt(container,
                                                            site.stmt))

    # with_device_retry over a donating callable with captured buffers
    defs = _local_defs(fn)
    for node in _walk(fn):
        if not isinstance(node, ast.Call) \
                or _last(_call_name(node)) != "with_device_retry" \
                or not node.args:
            continue
        target = node.args[0]
        callee: Optional[ast.FunctionDef] = None
        if isinstance(target, ast.Name) and target.id in defs:
            callee = defs[target.id]
        if callee is None:
            continue
        callee_locals: Set[str] = set(_fn_params(callee))
        for st in _walk(callee):
            if isinstance(st, ast.Assign):
                for t in st.targets:
                    callee_locals.update(_assigned_names(t))
        inner_env = {k: v for k, v in env.items()}
        for sub in _find_donating_calls(callee, inner_env):
            captured = sorted(n for n in sub.donated_names
                              if n not in callee_locals
                              or n in _fn_params(callee))
            if captured:
                issues.append(
                    (node.lineno,
                     f"donating dispatch (line {sub.call.lineno}) under "
                     f"with_device_retry donates captured buffer(s) "
                     f"{', '.join(captured)} — after a failed launch "
                     f"their state is undefined; re-stage fresh buffers "
                     f"from still-open spillables INSIDE the retried "
                     f"callable (shuffle/exchange.py run_collective "
                     f"discipline)"))

    if not issues:
        return []
    issues = sorted(set(issues))
    detail = "; ".join(f"line {ln}: {msg}" for ln, msg in issues)
    return [Finding(
        "TL033", "error", f"{relpath}::{fn.name}",
        f"donated-buffer misuse: {detail} — a buffer at a donate_argnums "
        f"position is dead after dispatch (docs/analysis.md donated-"
        f"buffer ownership model)")]


def _enclosing_stmt(block: Sequence[ast.stmt], stmt: ast.stmt
                    ) -> Optional[ast.stmt]:
    """The element of `block` that contains (or is) `stmt`."""
    for st in block:
        if st is stmt:
            return st
        for sub in ast.walk(st):
            if sub is stmt:
                return st
    return None


def _containing_block(fn: ast.FunctionDef, stmt: ast.stmt
                      ) -> Optional[Sequence[ast.stmt]]:
    """The innermost statement list of `fn` containing `stmt`."""
    result: Optional[Sequence[ast.stmt]] = None

    def visit(block: Sequence[ast.stmt]) -> None:
        nonlocal result
        for st in block:
            if st is stmt:
                result = block
                return
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    visit(sub)
            handlers = getattr(st, "handlers", None)
            if handlers:
                for h in handlers:
                    visit(h.body)
            items = getattr(st, "items", None)
            if items is not None:  # ast.With
                pass

    visit(fn.body)
    return result


# ---------------------------------------------------------------------------
# TL034 — plan-cache key surface
# ---------------------------------------------------------------------------

#: subpackages holding plan-fingerprint builders (the scheduler-owned
#: plan cache) — a separate surface from JIT_SUBPACKAGES because the
#: sanction rules differ (pinned identity is legal here, see below)
PLAN_KEY_SUBPACKAGES: Tuple[str, ...] = ("serving",)

#: a function that BUILDS plan-cache key material: the fingerprint
#: entry point and every ``*_sig`` helper it composes
_PLAN_KEY_FN = re.compile(r"(?:^|_)fingerprint(?:$|_)|sig$", re.I)

#: a container that PINS objects for the lifetime of a cache entry —
#: identity recorded alongside an append to one of these is stable
#: (the entry keeps the object alive, so its id() can never be recycled)
_PIN_CONTAINER = re.compile(r"rel_ids|pins|pinned", re.I)

#: a bare schema-ish collection (attribute lists carry expr_ids and
#: default reprs) — must pass through a ``*_sig``/``_safe_repr`` wrapper
#: before landing in key material
_SCHEMA_NAME = re.compile(r"(?:^|_)(?:schema|output|attrs|fields)$", re.I)


def _pin_sanctioned_names(fn: ast.FunctionDef) -> Set[str]:
    """Dotted names whose identity is pinned by this function: the `x` of
    ``pins.append(x)`` / ``rel_ids.append(id(x))`` / ``pins = [x, ...]``.
    ``id(x)`` for a pinned `x` is the sanctioned identity-fingerprint
    idiom (plan_cache._node_sig / fingerprint's mesh token)."""
    out: Set[str] = set()

    def record(arg: ast.AST) -> None:
        if isinstance(arg, ast.Call) and _last(_call_name(arg)) == "id" \
                and arg.args:
            arg = arg.args[0]
        name = _dotted(arg)
        if name:
            out.add(name)

    for st in _walk_no_defs(fn):
        if isinstance(st, ast.Call) and isinstance(st.func, ast.Attribute) \
                and st.func.attr in ("append", "add") \
                and _PIN_CONTAINER.search(_dotted(st.func.value)):
            for a in st.args:
                record(a)
        elif isinstance(st, (ast.Assign, ast.AnnAssign)):
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            value = st.value
            if value is None or not isinstance(value, (ast.List, ast.Tuple)):
                continue
            if any(_PIN_CONTAINER.search(_dotted(t)) for t in targets):
                for el in value.elts:
                    record(el)
    return out


def _key_material_values(fn: ast.FunctionDef) -> List[ast.AST]:
    """Expressions that land in this function's key material: values
    formatted into f-strings, args appended to token/part lists, and args
    of hashing calls."""
    out: List[ast.AST] = []
    for st in _walk_no_defs(fn):
        if isinstance(st, ast.FormattedValue):
            out.append(st.value)
        elif isinstance(st, ast.Call):
            name = _call_name(st)
            if isinstance(st.func, ast.Attribute) \
                    and st.func.attr in ("append", "extend", "join") \
                    and not _PIN_CONTAINER.search(_dotted(st.func.value)):
                out.extend(st.args)
            elif name.startswith("hashlib.") \
                    or _last(name) in ("sha1", "sha256", "md5", "blake2b"):
                out.extend(st.args)
    return out


def _lint_plan_key_fn(fn: ast.FunctionDef, relpath: str,
                      qual_prefix: str = "") -> List[Finding]:
    if not _PLAN_KEY_FN.search(fn.name):
        return []
    pinned = _pin_sanctioned_names(fn)
    issues: List[Tuple[int, str]] = []
    for node in _walk_no_defs(fn):
        line = getattr(node, "lineno", 0)
        if isinstance(node, ast.Call):
            name = _call_name(node)
            last = _last(name)
            if last in ("id", "hash") and node.args:
                arg = _dotted(node.args[0])
                if not arg or arg not in pinned:
                    issues.append((
                        line, f"unpinned identity {last}({arg or '...'}) — "
                        "identity may only key plan-cache material when the "
                        "object is pinned by the entry (rel_ids/pins)"))
            elif name.startswith(_CLOCK_PREFIXES) or last in _CLOCK_CALLS:
                issues.append((line, f"wall-clock read {name}(...)"))
            elif name.startswith(("uuid.", "random.", "np.random.",
                                  "numpy.random.")):
                issues.append((line, f"per-call random value {name}(...)"))
            elif last == "get" and isinstance(node.func, ast.Attribute) \
                    and "conf" in _dotted(node.func.value).lower():
                issues.append((
                    line, "live conf read "
                    f"{_dotted(node.func.value)}.get(...) inside a key "
                    "builder — key off the pre-filtered plan_relevant_conf "
                    "items instead"))
        elif isinstance(node, (ast.Name, ast.Attribute)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            ident = node.id if isinstance(node, ast.Name) else node.attr
            if _PER_QUERY_NAME.search(ident):
                issues.append((line, f"per-query value '{ident}' — "
                               "unbounded cardinality, the cache leaks"))
    for value in _key_material_values(fn):
        if isinstance(value, (ast.Name, ast.Attribute)):
            ident = value.id if isinstance(value, ast.Name) else value.attr
            if _SCHEMA_NAME.search(ident):
                issues.append((
                    getattr(value, "lineno", 0),
                    f"un-fingerprinted schema object '{_dotted(value)}' in "
                    "key material — wrap it (_attrs_sig/_safe_repr) so the "
                    "signature is value-stable, not repr-of-the-moment"))
    if not issues:
        return []
    issues = sorted(set(issues))
    detail = "; ".join(f"line {ln}: {msg}" for ln, msg in issues)
    return [Finding(
        "TL034", "error", f"{relpath}::{qual_prefix}{fn.name}",
        f"unstable plan-cache key component(s): {detail} — plan "
        f"fingerprints must be value-stable and bounded (structural "
        f"signatures + plan-relevant conf items; identity only when "
        f"entry-pinned); see docs/analysis.md cache-key design rules")]


def lint_plan_key_module(source: str, relpath: str) -> List[Finding]:
    """TL034 findings for one module's source."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return findings
    for st in tree.body:
        if isinstance(st, ast.FunctionDef):
            findings.extend(_lint_plan_key_fn(st, relpath))
        elif isinstance(st, ast.ClassDef):
            for sub in st.body:
                if isinstance(sub, ast.FunctionDef):
                    findings.extend(_lint_plan_key_fn(
                        sub, relpath, qual_prefix=f"{st.name}."))
    return findings


def lint_plan_key_tree(root: Optional[str] = None,
                       subpackages: Tuple[str, ...] = PLAN_KEY_SUBPACKAGES
                       ) -> List[Finding]:
    """Lint the plan-cache key surface of the shipped tree."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    for relpath, src in iter_module_sources(root, subpackages):
        findings.extend(lint_plan_key_module(src, relpath))
    return findings


# ---------------------------------------------------------------------------
# module entry points
# ---------------------------------------------------------------------------


def lint_jit_module(source: str, relpath: str) -> List[Finding]:
    """TL030/TL031/TL032/TL033 findings for one module's source."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return findings
    _WALK_CACHE.clear()  # per-module memos: previous tree's nodes are dead
    _ASSIGN_MAP_CACHE.clear()
    caches = _module_cache_dicts(tree)
    mutable = _mutable_globals(tree)
    summaries, cache_info = _module_don_summaries(tree, caches)
    params = _param_bindings(tree, summaries, cache_info)

    def lint_function(fn: ast.FunctionDef, qual_prefix: str = "") -> None:
        findings.extend(_lint_cache_keys(fn, caches, relpath))
        findings.extend(_lint_bucketing(fn, caches, relpath))
        findings.extend(_lint_trace_purity(fn, mutable, relpath,
                                           qual_prefix))
        env = _donation_env(fn, summaries, cache_info,
                            params.get(fn.name, {}))
        findings.extend(_lint_donation(fn, env, mutable | caches, relpath))

    for st in tree.body:
        if isinstance(st, ast.FunctionDef):
            lint_function(st)
        elif isinstance(st, ast.ClassDef):
            for sub in st.body:
                if isinstance(sub, ast.FunctionDef):
                    lint_function(sub, qual_prefix=f"{st.name}.")
    return findings


def lint_jit_tree(root: Optional[str] = None,
                  subpackages: Tuple[str, ...] = JIT_SUBPACKAGES
                  ) -> List[Finding]:
    """Lint the shipped tree (root defaults to the spark_rapids_tpu pkg)."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    for relpath, src in iter_module_sources(root, subpackages):
        findings.extend(lint_jit_module(src, relpath))
    return findings
