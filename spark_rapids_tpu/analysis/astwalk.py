"""AST machinery for tracelint: taint tracking + conditionality analysis.

The reference plugin catches declaration drift at build time with dedicated
static tooling (api_validation/ApiValidation.scala compares shim constructor
signatures; TypeChecks.scala is the single source of truth behind
supported_ops.md).  Our equivalent hazard after the opjit/fusion PRs is a
*performance* cliff: `plan/typechecks.py` declarations decide where
execs/opjit.py and execs/fusion.py split traces, and nothing checked the
declarations against the ~20 modules of actual `eval_tpu` implementations.

This module provides the shared walking machinery the detectors build on:

* **Taint** — which local names hold *device values*, with three kinds:
  ``COL`` (TpuColumnVector/TpuScalar results of ``eval_tpu`` /
  ``batch.column``), ``ARR`` (jax arrays: ``.data``/``.validity``/
  ``.offsets`` reads, jnp results over tainted inputs) and ``SEQ`` (a python
  container *of* device values — iterating one is a loop over columns, not a
  per-row loop).  Host-boundary ops are findings only when they consume a
  COL/ARR: ``np.asarray(lut)`` over a host table is fine,
  ``np.asarray(col.data)`` is a device→host sync.
* **Conditionality** — whether a statement runs on *every* execution of the
  function or only behind a branch.  The dominant idiom in expressions/ is a
  guarded device path with a host tail::

      if _ascii_dev(c):
          ...device kernel...
          return device_result
      return _string_result_from_arrow(...)   # conditional: behind the guard

  so code after an ``if`` whose body always returns/raises is the implicit
  ``else`` (conditional), as are ternary (``IfExp``) arms.
* **Scalar-fold untainting** — inside ``if isinstance(x, TpuScalar):`` the
  guarded names are host scalars; host work there is the constant-fold idiom
  (base.BinaryExpression) and never touches the device.
* **Helper/method summaries** — module functions and same-module class
  methods get (host-grade, returns-device, string-layout) summaries so call
  sites grade `_to_arrow_side(...)` or ``self._host_from_vals(...)``
  without inter-procedural dataflow.

Pure stdlib `ast`; never imports the analyzed module.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

# Verdicts, ordered from best to worst. `worst()` picks the max.
DEVICE = "device"                    # no host patterns at all: traceable
CONDITIONAL_HOST = "conditional-host"  # host work only behind branches
HOST = "host"                        # host boundary on every execution
UNTRACEABLE = "untraceable"          # value-dependent control flow / row loops

_VERDICT_RANK = {DEVICE: 0, CONDITIONAL_HOST: 1, HOST: 2, UNTRACEABLE: 3}

# taint kinds
COL = "col"    # TpuColumnVector / TpuScalar
ARR = "arr"    # jax array (.data / .validity / jnp result)
SEQ = "seq"    # python container of device values

DEVICE_KINDS = (COL, ARR)


def worst(*verdicts: str) -> str:
    return max(verdicts, key=_VERDICT_RANK.__getitem__, default=DEVICE)


#: attribute reads that are *structural* (static under jax tracing), so the
#: result of `tainted.attr` is NOT a device value
STRUCT_ATTRS = frozenset((
    "dtype", "shape", "ndim", "size", "num_rows", "capacity", "nullable",
    "name", "names", "precision", "scale", "np_dtype", "fields",
    "is_null", "value", "host_data", "host_capacity", "element_type",
    "key_type", "value_type",
))

#: attribute reads yielding device arrays off a device column
DEVICE_ARRAY_ATTRS = frozenset(("data", "validity", "offsets"))

#: attribute reads yielding nested device columns off a device column.
#: NOTE: `.children` is deliberately absent — on Expression nodes it is the
#: subexpression tuple (host objects), and that reading dominates.
DEVICE_COL_ATTRS = frozenset(("child",))

#: calls whose results are never device values (and whose arguments are
#: inspected structurally, not by value)
EXEMPT_CALLS = frozenset((
    "isinstance", "issubclass", "hasattr", "getattr", "setattr", "type",
    "len", "callable", "repr", "id", "super", "range", "enumerate",
    "sorted", "print", "str",
))

#: the audited device→host gate (columnar/vector.py): these BLOCK and sync,
#: but they record themselves in the profiling sync ledger — their results
#: are host values, and routing through them is exactly what TL011 asks for
AUDITED_SYNC_CALLS = frozenset((
    "audited_sync", "audited_sync_int", "audited_device_get",
))

#: host coercions: calling one of these on a device value syncs it to host
COERCION_CALLS = frozenset(("bool", "int", "float", "complex"))

#: method calls that cross the device→host boundary when the receiver is a
#: device value
HOST_METHODS = frozenset((
    "to_arrow", "to_numpy", "to_pylist", "as_py", "item", "tolist",
    "block_until_ready",
))

#: parameter names that are scalars/metadata, never device values, when
#: seeding helper analysis
SCALAR_PARAM_NAMES = frozenset((
    "self", "cls", "ctx", "conf", "n", "num_rows", "cap", "capacity",
    "seed", "name", "dtype", "dt", "scale", "precision", "idx", "i", "j",
    "ordinal", "path", "fmt", "pattern", "tz", "level", "default", "sep",
    "limit", "kind", "mode", "template", "out_names", "key", "keys_dtype",
    "expr", "e", "fn", "f", "pick", "op", "cmp_expr", "num_bits",
))

#: parameter names that are containers of device values
SEQ_PARAM_NAMES = frozenset((
    "cols", "columns", "vals", "values", "arrays", "parts", "exprs",
    "children", "batches", "leaves", "sides", "axes", "kids", "args",
))


def parse_module(source: str, path: str = "<string>") -> ast.Module:
    return ast.parse(source, filename=path)


@dataclass
class Detection:
    """One raw detector hit inside a function body."""
    detector: str
    line: int
    snippet: str
    conditional: bool
    message: str


@dataclass
class FunctionReport:
    """Detector output for one function body."""
    qualname: str
    detections: List[Detection] = field(default_factory=list)
    #: function reads ragged/string/nested layout off its inputs
    #: (`.offsets`, `.child`, string-kernel helpers) — such expressions never
    #: pass the opjit gate, so declaration conflicts are doc-mode findings,
    #: not perf errors
    string_layout: bool = False

    @property
    def verdict(self) -> str:
        v = DEVICE
        for d in self.detections:
            if d.detector in UNSAFE_DETECTORS:
                step = UNTRACEABLE if not d.conditional else CONDITIONAL_HOST
            else:
                step = HOST if not d.conditional else CONDITIONAL_HOST
            v = worst(v, step)
        return v


#: detectors whose *unconditional* hit means "cannot trace at all" rather
#: than "syncs to host" (the distinction only affects reporting text)
UNSAFE_DETECTORS = frozenset(("value-dependent-branch", "per-row-loop"))


@dataclass
class HelperSummary:
    """Summary of a module helper / same-module method used at call sites."""
    host_grade: Optional[str] = None   # None | CONDITIONAL_HOST | HOST
    returns_device: bool = False
    string_layout: bool = False

    def merge(self, other: "HelperSummary") -> "HelperSummary":
        grades = [g for g in (self.host_grade, other.host_grade) if g]
        return HelperSummary(
            host_grade=worst(*grades) if grades else None,
            returns_device=self.returns_device or other.returns_device,
            string_layout=self.string_layout or other.string_layout)


#: simple annotations marking a parameter as host scalar data
_SCALAR_ANNOTATIONS = frozenset(("int", "float", "bool", "str", "bytes"))


def seed_params(fn: ast.FunctionDef) -> Dict[str, str]:
    """Taint seeds for analyzing a helper/method in isolation: device-ish
    params by default, with name heuristics for scalars and containers.
    Parameters whose names end in ``_py``/``_np``/``_host`` (the codebase's
    already-materialized-data convention) and parameters annotated with a
    plain scalar type are host values, not device taints."""
    seeds: Dict[str, str] = {}
    for a in fn.args.args + fn.args.posonlyargs + fn.args.kwonlyargs:
        if a.arg in SCALAR_PARAM_NAMES:
            continue
        if a.arg.endswith(("_py", "_np", "_host")):
            continue
        ann = a.annotation
        if isinstance(ann, ast.Name) and ann.id in _SCALAR_ANNOTATIONS:
            continue
        seeds[a.arg] = SEQ if a.arg in SEQ_PARAM_NAMES else COL
    return seeds


class ModuleIndex:
    """Per-module context: imports, helper/method summaries, lock names."""

    def __init__(self, source: str, path: str = "<string>"):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = parse_module(source, path)
        self.import_aliases: Dict[str, str] = {}
        self.functions: Dict[str, ast.FunctionDef] = {}
        self.classes: Dict[str, ast.ClassDef] = {}
        self.lock_names: Set[str] = set()
        self.helpers: Dict[str, HelperSummary] = {}
        #: same-module class methods merged by bare name (conservative on
        #: collisions); eval-path methods excluded — they are the analysis
        #: TARGETS, not helpers
        self.methods: Dict[str, HelperSummary] = {}
        self._collect()
        self._summarize()

    # -- collection --------------------------------------------------------
    def _collect(self) -> None:
        # imports anywhere (expressions/ commonly imports pyarrow inside
        # function bodies)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.import_aliases[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    self.import_aliases[a.asname or a.name] = f"{mod}.{a.name}"
        for node in self.tree.body:
            if isinstance(node, ast.FunctionDef):
                self.functions[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node
            elif isinstance(node, ast.Assign):
                if _is_lock_ctor(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.lock_names.add(t.id)

    def root_module(self, name: str) -> str:
        """Resolve a local name to its imported dotted origin ('' if local)."""
        return self.import_aliases.get(name, "")

    def snippet(self, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()[:120]
        return ""

    # -- summaries (two passes so helper-calls-helper propagates) ----------
    _EXCLUDED_METHOD_NAMES = frozenset((
        "eval_tpu", "eval_cpu", "_compute", "__init__", "dtype", "pretty",
    ))

    def _summarize(self) -> None:
        from .detectors import scan_function  # detectors imports only astwalk
        for _ in range(2):
            for name, fn in self.functions.items():
                self.helpers[name] = self._summary_of(fn, name, scan_function)
            methods: Dict[str, HelperSummary] = {}
            for cname, cls in self.classes.items():
                for node in cls.body:
                    if not isinstance(node, ast.FunctionDef) \
                            or node.name in self._EXCLUDED_METHOD_NAMES:
                        continue
                    s = self._summary_of(node, f"{cname}.{node.name}",
                                         scan_function)
                    prev = methods.get(node.name)
                    methods[node.name] = s if prev is None else prev.merge(s)
            self.methods = methods

    def _summary_of(self, fn: ast.FunctionDef, qualname: str,
                    scan_function) -> HelperSummary:
        rep = scan_function(fn, self, taint_seeds=seed_params(fn),
                            qualname=qualname)
        grade = None
        if any(not d.conditional for d in rep.detections):
            grade = HOST
        elif rep.detections:
            grade = CONDITIONAL_HOST
        return HelperSummary(host_grade=grade,
                             returns_device=_returns_device(fn, self),
                             string_layout=rep.string_layout)


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in ("Lock", "RLock")
            ) or (isinstance(f, ast.Name) and f.id in ("Lock", "RLock"))


def _returns_device(fn: ast.FunctionDef, mod: "ModuleIndex") -> bool:
    """Does any `return` expression carry a device value derived from the
    (conservatively seeded) parameters?  Used so `if helper(col):` at a call
    site can be recognized as a value-dependent branch."""
    taint = TaintState(seed_params(fn), mod)
    out = [False]

    class V(ast.NodeVisitor):
        def visit_Assign(self, node: ast.Assign):
            taint.assign(node.targets, node.value)
            self.generic_visit(node)

        def visit_Return(self, node: ast.Return):
            # SEQ counts: `return arr, valid` tuples unpack to device values
            if node.value is not None \
                    and taint.kind_of(node.value) is not None:
                out[0] = True

        def visit_FunctionDef(self, node: ast.FunctionDef):
            return  # nested defs return separately

    for st in fn.body:
        V().visit(st)
    return out[0]


class TaintState:
    """Forward name-level taint: which locals hold device values, by kind."""

    def __init__(self, seeds: Dict[str, str], mod: ModuleIndex):
        self.kinds: Dict[str, str] = dict(seeds)
        self.mod = mod

    # -- queries -----------------------------------------------------------
    def is_device(self, node: ast.AST) -> bool:
        return self.kind_of(node) in DEVICE_KINDS

    def kind_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Name):
            return self.kinds.get(node.id)
        if isinstance(node, ast.Attribute):
            base = self.kind_of(node.value)
            if node.attr in DEVICE_ARRAY_ATTRS:
                return ARR if base in DEVICE_KINDS else None
            if node.attr in DEVICE_COL_ATTRS:
                return COL if base in DEVICE_KINDS else None
            if node.attr in STRUCT_ATTRS:
                return None
            return base
        if isinstance(node, ast.Subscript):
            base = self.kind_of(node.value)
            if base == SEQ:
                return COL
            return base
        if isinstance(node, ast.Call):
            return self.call_kind(node)
        if isinstance(node, ast.BinOp):
            return _first_kind(self.kind_of(node.left),
                               self.kind_of(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.kind_of(node.operand)
        if isinstance(node, ast.BoolOp):
            return _first_kind(*(self.kind_of(v) for v in node.values))
        if isinstance(node, ast.Compare):
            # comparisons over device arrays yield device bool arrays; `is`
            # / `is not` identity tests are structural host bools
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return None
            return _first_kind(self.kind_of(node.left),
                               *(self.kind_of(c) for c in node.comparators))
        if isinstance(node, ast.IfExp):
            return _first_kind(self.kind_of(node.body),
                               self.kind_of(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            if any(self.kind_of(e) in DEVICE_KINDS for e in node.elts):
                return SEQ
            return None
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            sub = TaintState(dict(self.kinds), self.mod)
            for gen in node.generators:
                k = sub.kind_of(gen.iter)
                sub._mark(gen.target, COL if k else None)
            if sub.kind_of(node.elt) in DEVICE_KINDS:
                return SEQ
            return None
        if isinstance(node, ast.Starred):
            return self.kind_of(node.value)
        if isinstance(node, ast.NamedExpr):
            return self.kind_of(node.value)
        return None

    def _args_device(self, node: ast.Call) -> bool:
        return any(self.kind_of(a) in DEVICE_KINDS for a in node.args) or any(
            k.value is not None and self.kind_of(k.value) in DEVICE_KINDS
            for k in node.keywords)

    def call_kind(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in EXEMPT_CALLS or f.id in COERCION_CALLS \
                    or f.id in AUDITED_SYNC_CALLS:
                return None  # audited gate: ledger-recorded host result
            if f.id in ("list", "tuple"):
                return SEQ if self._args_device(node) or any(
                    self.kind_of(a) == SEQ for a in node.args) else None
            summary = self.mod.helpers.get(f.id)
            if summary is not None:
                return COL if summary.returns_device else None
        if isinstance(f, ast.Attribute):
            if f.attr == "eval_tpu":
                return COL
            if f.attr == "column" and self.kind_of(f.value) is None:
                # batch.column(i) — `batch` is seeded COL at eval scan time,
                # so kind_of(batch)=COL handles it; this arm covers
                # untracked receivers conservatively as None
                pass
            if f.attr in HOST_METHODS:
                return None  # result is a host value
            root = _root_name(f)
            if root is not None:
                origin = self.mod.root_module(root)
                if origin.startswith("jax") or root in ("jnp", "jax", "lax"):
                    # jnp.* over runtime device data stays on device; jnp
                    # over constants is a trace-time constant.  A SEQ arg
                    # (jnp.concatenate([a, b])) carries device data too.
                    if self._args_device(node) or any(
                            self.kind_of(a) == SEQ for a in node.args):
                        return ARR
                    return None
                if origin.startswith(("numpy", "pyarrow")):
                    return None  # host result (the host *op* is the finding)
            if self.kind_of(f.value) in DEVICE_KINDS:
                # method on a device value (col.slice(...), arr.astype(...))
                return self.kind_of(f.value)
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                summary = self.mod.methods.get(f.attr)
                if summary is not None:
                    return COL if summary.returns_device else None
        # unknown callable: device args in, assume a device value out
        return COL if self._args_device(node) else None

    # -- updates -----------------------------------------------------------
    def assign(self, targets: Sequence[ast.AST], value: ast.AST) -> None:
        kind = self.kind_of(value)
        if isinstance(value, (ast.Tuple, ast.List)) \
                and len(targets) == 1 \
                and isinstance(targets[0], (ast.Tuple, ast.List)) \
                and len(targets[0].elts) == len(value.elts):
            # parallel unpack: a, b = x.data, y  — per-element kinds
            for t, v in zip(targets[0].elts, value.elts):
                self._mark(t, self.kind_of(v))
            return
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)) and kind in DEVICE_KINDS:
                # tuple unpack of a device-producing call: all targets device
                for e in t.elts:
                    self._mark(e, kind)
            else:
                self._mark(t, kind)

    def _mark(self, target: ast.AST, kind: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            if kind:
                self.kinds[target.id] = kind
            else:
                self.kinds.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mark(e, kind)
        elif isinstance(target, ast.Starred):
            self._mark(target.value, kind)
        # attribute/subscript targets: no name-level tracking


def _first_kind(*kinds: Optional[str]) -> Optional[str]:
    for k in kinds:
        if k in DEVICE_KINDS:
            return k
    for k in kinds:
        if k:
            return k
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost name of a dotted access (`pc.utf8_upper` -> 'pc')."""
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_name(node: ast.Call) -> Optional[str]:
    """Bare callee name of a call: `f(...)` -> 'f', `x.m(...)` -> 'm'.
    Shared by the concurrency/lifecycle/lock passes."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def lockish(name: str) -> bool:
    """Does a name look like a lock? One heuristic for every pass (TL010
    lock recognition, TL021/TL022 graph nodes, TL020's transparent
    lock-`with` scan) so a naming-pattern tweak cannot diverge them."""
    low = name.lower()
    return "lock" in low or "mutex" in low or low.endswith("_mu") \
        or low == "_mu"


def iter_module_sources(root=None, subpackages=(), modules=()):
    """Yield ``(relpath, source)`` for every module a tree-wide lint pass
    covers — the one walk shared by TL010/TL011/TL012/TL02x so an
    exclusion rule applies to every pass at once. ``root`` defaults to
    the spark_rapids_tpu package directory."""
    import os
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for sub in subpackages:
        d = os.path.join(root, sub)
        if not os.path.isdir(d):
            continue
        for fname in sorted(os.listdir(d)):
            if not fname.endswith(".py"):
                continue
            with open(os.path.join(d, fname)) as f:
                yield f"{sub}/{fname}", f.read()
    for fname in modules:
        path = os.path.join(root, fname)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            yield fname, f.read()


def terminates(body: Sequence[ast.stmt]) -> bool:
    """All paths through `body` leave the function/loop (return/raise/
    continue/break)."""
    for st in body:
        if isinstance(st, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
            return True
        if isinstance(st, ast.If) and st.orelse \
                and terminates(st.body) and terminates(st.orelse):
            return True
    return False


def may_terminate(body: Sequence[ast.stmt]) -> bool:
    """SOME path through `body` leaves the function — code after an `if`
    with such a body is not on every path (conditional).  Nested defs don't
    count: their returns leave the closure, not this function."""

    class _V(ast.NodeVisitor):
        found = False

        def visit_FunctionDef(self, node):  # don't descend into closures
            pass

        visit_AsyncFunctionDef = visit_FunctionDef
        visit_Lambda = visit_FunctionDef

        def visit_Return(self, node):
            self.found = True

        visit_Raise = visit_Return

    v = _V()
    for st in body:
        v.visit(st)
    return v.found


def isinstance_scalar_names(test: ast.AST) -> Set[str]:
    """Names proven to be TpuScalar by `isinstance(x, TpuScalar)` tests
    (possibly `and`-joined).  Inside such a branch the names hold host
    scalars, so host work on them is the constant-fold idiom, not a sync."""
    names: Set[str] = set()

    def scalar_check(call: ast.AST) -> Optional[str]:
        if not (isinstance(call, ast.Call) and isinstance(call.func, ast.Name)
                and call.func.id == "isinstance" and len(call.args) == 2):
            return None
        target, klass = call.args
        if not isinstance(target, ast.Name):
            return None
        kls = [klass] if not isinstance(klass, ast.Tuple) else list(klass.elts)
        for k in kls:
            nm = k.attr if isinstance(k, ast.Attribute) else (
                k.id if isinstance(k, ast.Name) else None)
            if nm == "TpuScalar":
                return target.id
        return None

    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            n = scalar_check(v)
            if n:
                names.add(n)
    else:
        n = scalar_check(test)
        if n:
            names.add(n)
    return names
