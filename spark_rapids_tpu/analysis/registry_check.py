"""Registry cross-check: static eval_tpu verdicts vs plan/typechecks.py.

The analogue of the reference's TypeChecks.scala being the single source of
truth: here `plan/typechecks.py` declarations (`host_assisted`) drive where
execs/opjit.py and execs/fusion.py split traces, so a wrong declaration is a
silent performance cliff.  This pass classifies every registered expression's
actual `eval_tpu` (and `_compute`) implementation with the AST detectors and
cross-checks the verdict against the registry:

* **TL001** (error)   declared device (`host_assisted=False`) but the
  implementation hits the host boundary *unconditionally* — opjit's first
  trace fails and the fingerprint is pinned eager per batch (the
  205s-vs-3s q3 regime) without anything saying so.
* **TL002** (warning) declared `host_assisted=True` but the implementation is
  fully device-traceable — the flag needlessly splits every fused segment
  the expression appears in.
* **TL003** (error)   implemented (`eval_tpu` overridden) in an expressions
  module but never registered — registry drift; the planner can't price it.
* **TL004** (info)    declared device with a *guarded* host fallback
  (conditional host path) — legitimate, surfaced for the docs' execution-mode
  column, never gated.
* **TL005** (error)   the dynamic `jax.eval_shape` probe disagrees with the
  static verdict (only with --corroborate; see probe.py).

Only *trace-relevant* expressions can raise TL001: their type signature must
include a fixed-width type and the implementation must not consume ragged
string/array layouts — everything else is rejected by the opjit gate long
before the declaration matters, so conflicts there are TL004 material.
"""

from __future__ import annotations

import ast
import inspect
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .astwalk import (CONDITIONAL_HOST, DEVICE, HOST, UNTRACEABLE,
                      FunctionReport, ModuleIndex, seed_params, worst)
from .detectors import scan_function

#: TypeEnum members the opjit gate can admit as a node output dtype
_FIXED_WIDTH_ENUMS = frozenset((
    "BOOLEAN", "BYTE", "SHORT", "INT", "LONG", "FLOAT", "DOUBLE",
    "DATE", "TIMESTAMP",
))

#: eval-path methods analyzed per class (effective implementation via MRO)
_EVAL_METHODS = ("eval_tpu", "_compute", "_dec128_eval")


@dataclass
class Finding:
    rule: str        # TL001..TL005 / TL010..TL012
    severity: str    # "error" | "warning" | "info"
    location: str    # "expressions/strings.py::Upper"
    message: str

    @property
    def key(self) -> str:
        """Stable baseline key (no line numbers: survives reformatting)."""
        return f"{self.rule} {self.location}"

    def render(self) -> str:
        return f"[{self.severity.upper():7s}] {self.rule} {self.location}: " \
               f"{self.message}"


@dataclass
class ExprReport:
    cls: type
    declared_host_assisted: bool
    verdict: str
    string_layout: bool
    trace_relevant: bool
    provenance: str
    reports: List[FunctionReport] = field(default_factory=list)

    @property
    def location(self) -> str:
        mod = self.cls.__module__.replace("spark_rapids_tpu.", "")
        return f"{mod}::{self.cls.__name__}"


_MODULE_CACHE: Dict[str, ModuleIndex] = {}


def _module_index_for(fn) -> Optional[ModuleIndex]:
    try:
        path = inspect.getfile(fn)
    except (TypeError, OSError):
        return None
    idx = _MODULE_CACHE.get(path)
    if idx is None:
        try:
            with open(path) as f:
                idx = ModuleIndex(f.read(), path)
        except (OSError, SyntaxError):
            return None
        _MODULE_CACHE[path] = idx
    return idx


def _method_ast(mod: ModuleIndex, fn) -> Optional[ast.FunctionDef]:
    qual = getattr(fn, "__qualname__", "")
    parts = qual.split(".")
    if len(parts) < 2:
        return mod.functions.get(parts[0]) if parts else None
    cls_name, meth = parts[-2], parts[-1]
    cls = mod.classes.get(cls_name)
    if cls is None:
        return None
    for node in cls.body:
        if isinstance(node, ast.FunctionDef) and node.name == meth:
            return node
    return None


def classify_class(cls: type) -> Tuple[str, bool, List[FunctionReport]]:
    """Static verdict for one expression class: worst verdict over its
    effective eval-path methods, resolved through the MRO so subclasses
    inherit e.g. BinaryExpression.eval_tpu + their own `_compute`."""
    from ..expressions.base import Expression
    verdict = DEVICE
    string_layout = False
    reports: List[FunctionReport] = []
    seen = set()
    for meth in _EVAL_METHODS:
        fn = getattr(cls, meth, None)
        if fn is None:
            continue
        fn = getattr(fn, "__func__", fn)
        base = getattr(Expression, meth, None)
        base = getattr(base, "__func__", base)
        if base is not None and fn is base:
            continue  # the NotImplementedError placeholder
        key = (getattr(fn, "__module__", ""), getattr(fn, "__qualname__", ""))
        if key in seen or not key[1]:
            continue
        seen.add(key)
        mod = _module_index_for(fn)
        if mod is None:
            continue
        node = _method_ast(mod, fn)
        if node is None:
            continue
        # seed from the method's own signature: eval_tpu(self, batch, ctx)
        # reduces to {"batch": COL}, while _compute(self, ldata, rdata, ...)
        # seeds its device-value operands too — host ops on them must not
        # be invisible to the detectors
        rep = scan_function(node, mod, taint_seeds=seed_params(node),
                            qualname=f"{cls.__name__}.{meth}")
        reports.append(rep)
        verdict = worst(verdict, rep.verdict)
        string_layout = string_layout or rep.string_layout
    return verdict, string_layout, reports


def _has_own_eval_tpu(cls: type) -> bool:
    from ..expressions.base import Expression
    return cls.eval_tpu is not Expression.eval_tpu


def _sig_fixed_width(rule) -> bool:
    sig = rule.type_sig
    if sig is None:
        return False
    return bool(set(sig.types) & _FIXED_WIDTH_ENUMS)


def analyze_registry() -> Tuple[List[ExprReport], List[Finding]]:
    """Classify every registered expression and cross-check declarations."""
    from ..plan.typechecks import all_expr_rules
    reports: List[ExprReport] = []
    findings: List[Finding] = []
    for cls, rule in sorted(all_expr_rules().items(),
                            key=lambda kv: kv[0].__name__):
        if getattr(cls, "unevaluable", False) or not _has_own_eval_tpu(cls):
            # no kernel of its own: driven by an exec or priced via
            # host_assisted/CPU fallback — api_validation covers the contract
            continue
        verdict, string_layout, fn_reports = classify_class(cls)
        trace_relevant = _sig_fixed_width(rule) and not string_layout
        rep = ExprReport(cls=cls, declared_host_assisted=rule.host_assisted,
                         verdict=verdict, string_layout=string_layout,
                         trace_relevant=trace_relevant,
                         provenance=getattr(rule, "provenance", "?"),
                         reports=fn_reports)
        reports.append(rep)
        findings.extend(_cross_check(rep))
    findings.extend(_drift_check(set(all_expr_rules())))
    return reports, findings


def _cross_check(rep: ExprReport) -> List[Finding]:
    out: List[Finding] = []
    declared_at = f" (declared at {rep.provenance})"
    if not rep.declared_host_assisted:
        if rep.verdict in (HOST, UNTRACEABLE) and rep.trace_relevant:
            why = "; ".join(
                f"{d.detector}@{d.line}" for r in rep.reports
                for d in r.detections if not d.conditional)[:160]
            out.append(Finding(
                "TL001", "error", rep.location,
                f"declared device but eval_tpu hits the host boundary "
                f"unconditionally ({why}) — opjit pins it eager per batch; "
                f"flag host_assisted=True or fix the kernel{declared_at}"))
        elif rep.verdict in (CONDITIONAL_HOST, HOST, UNTRACEABLE):
            out.append(Finding(
                "TL004", "info", rep.location,
                f"device-declared with a guarded host fallback "
                f"(verdict: {rep.verdict}); fine — surfaced for the "
                f"execution-mode docs column"))
    else:
        if rep.verdict == DEVICE:
            # only a real split cost when the expression could actually
            # appear in a trace; ragged/string ops are informational
            sev = "warning" if rep.trace_relevant else "info"
            out.append(Finding(
                "TL002", sev, rep.location,
                f"declared host_assisted but the implementation is fully "
                f"device-traceable — the flag splits every fused segment "
                f"containing it; drop it{declared_at}"))
    return out


def _drift_check(registered: set) -> List[Finding]:
    """TL003: concrete expression classes with their own eval_tpu that were
    never registered (the planner can neither price nor gate them)."""
    import importlib
    import pkgutil

    from .. import expressions as _exprs_pkg
    from ..expressions.base import Expression

    findings: List[Finding] = []
    mod_names = [m.name for m in pkgutil.iter_modules(_exprs_pkg.__path__)
                 if m.name != "base"]
    modules = []
    for name in sorted(mod_names):
        try:
            modules.append(importlib.import_module(
                f"{_exprs_pkg.__name__}.{name}"))
        except ImportError:
            continue
    for module in modules:
        for name, cls in sorted(vars(module).items()):
            if not (isinstance(cls, type) and issubclass(cls, Expression)):
                continue
            if cls.__module__ != module.__name__ or name.startswith("_"):
                continue
            if cls in registered or getattr(cls, "unevaluable", False):
                continue
            if "eval_tpu" not in cls.__dict__:
                continue  # inherits: the defining base carries the contract
            if any(issubclass(r, cls) and r is not cls for r in registered):
                continue  # abstract base of registered implementations
            findings.append(Finding(
                "TL003", "error",
                f"{cls.__module__.replace('spark_rapids_tpu.', '')}::{name}",
                "implements eval_tpu but is not registered in "
                "plan/typechecks.py — registry drift (planner cannot "
                "price it)"))
    return findings


def scan_kernels() -> Dict[str, Dict[str, str]]:
    """Classify every public module-level function under kernels/ (the
    tentpole also covers kernel implementations, not just expressions).
    Returns {module: {function: verdict}}.  Kernels that legitimately cross
    the host boundary (json host patches, regex host fallbacks) show up as
    host/conditional-host — informational, surfaced by tracelint --verbose,
    never gated: a kernel's host-ness is priced by the expression that calls
    it, which the registry cross-check covers."""
    import os

    from .detectors import scan_function
    from .astwalk import seed_params
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "kernels")
    out: Dict[str, Dict[str, str]] = {}
    for fname in sorted(os.listdir(root)):
        if not fname.endswith(".py") or fname == "__init__.py":
            continue
        path = os.path.join(root, fname)
        with open(path) as f:
            src = f.read()
        try:
            mod = ModuleIndex(src, path)
        except SyntaxError:
            continue
        verdicts: Dict[str, str] = {}
        for name, fn in mod.functions.items():
            if name.startswith("_"):
                continue
            rep = scan_function(fn, mod, taint_seeds=seed_params(fn),
                                qualname=name)
            verdicts[name] = rep.verdict
        out[f"kernels/{fname}"] = verdicts
    return out


def execution_modes() -> Dict[type, str]:
    """Per registered expression: the execution-mode string for
    docs/supported_ops.md (sourced from analyzer verdict + registry flag)."""
    from ..plan.typechecks import all_expr_rules
    modes: Dict[type, str] = {}
    for cls, rule in all_expr_rules().items():
        if getattr(cls, "unevaluable", False):
            modes[cls] = "exec-driven"
        elif not _has_own_eval_tpu(cls):
            modes[cls] = "host-assisted" if rule.host_assisted else "cpu fallback"
        elif rule.host_assisted:
            modes[cls] = "host-assisted"
        else:
            verdict, _, _ = classify_class(cls)
            # UNTRACEABLE here means data-dependent guards selecting between
            # a device kernel and a host fallback (the op still runs its
            # device path eagerly) — "host" would misdescribe it
            modes[cls] = {DEVICE: "device",
                          CONDITIONAL_HOST: "device / host fallback",
                          HOST: "host",
                          UNTRACEABLE: "device / host fallback"}[verdict]
    return modes
