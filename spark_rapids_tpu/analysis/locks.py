"""Lock-discipline lint (rules **TL021** / **TL022**): no blocking work
under a process-wide lock, and a declared global lock order.

PR 2's bounded pool made every exchange map task a sibling of every other
query's tasks; ROADMAP item 1 multiplies that by N sessions. Two static
properties keep that safe:

**TL021** — a blocking operation executed while holding a *process-wide*
lock (a module-level ``Lock``/``RLock`` or a class-level singleton
``_lock``). Blocking here means the audited device→host syncs
(``audited_sync*`` / ``audited_device_get``), collective waits
(``block_until_ready``), pool joins (``result()`` / ``join()`` /
``futures.wait`` / ``shutdown(wait=True)``), semaphore acquisition and
``time.sleep``-style backoff. Any of these under the opjit/compiled/mesh
program-cache locks, the metric locks or the manager locks stalls every
sibling on the PR 2 pool for the full wait. Instance locks
(``self._mat_lock`` — per-exchange memoization) are out of TL021's scope:
they serialize one object, not the process. Same-module helper/method
summaries make the check one level interprocedural.

**TL022** — lock-order cycles. The pass builds the global lock graph:

* nodes: module-level locks (``module.py::_LOCK``), class-attribute locks
  (``Class._lock``) and instance-attribute locks merged by attribute name
  under their class (``HbmBudget._alloc_lock``);
* edges: a ``with`` on lock A whose body acquires lock B — lexically, or
  through a call whose summary (same-module, plus the curated
  cross-module table below) says it acquires B.

The graph is checked against :data:`LOCK_ORDER`, the **declared partial
order** (outermost level first). Every edge must go from a lower level to
a strictly higher one; re-acquiring the *same* lock is allowed only for
locks constructed as ``RLock``. A lock missing from the declared order is
itself a finding: the order is the documentation the next acquire site
needs (docs/analysis.md mirrors it).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .astwalk import call_name as _call_name, lockish as _lockish
from .registry_check import Finding

#: packages/modules the lint covers
LOCKS_SUBPACKAGES: Tuple[str, ...] = ("execs", "shuffle", "memory",
                                      "parallel", "io", "chaos", "obs",
                                      "serving")
LOCKS_MODULES: Tuple[str, ...] = ("session.py", "filecache.py",
                                  "profiling.py", "failure.py")

#: blocking call names for TL021 (syntactic, receiver-independent)
BLOCKING_CALLS = frozenset((
    "audited_sync", "audited_sync_int", "audited_device_get",
    "block_until_ready", "sleep", "result", "join", "wait",
    "with_device_retry", "collective_wait",
))
#: blocking METHOD names that need a plausibly-blocking receiver to avoid
#: false positives on str.join etc.
_RECEIVER_SENSITIVE = frozenset(("join", "result", "wait"))

#: the declared global lock order, OUTERMOST level first. An acquire edge
#: must go strictly downward in this list. Kept in code (not a data file)
#: so a new lock fails TL022 until its place in the order is declared —
#: mirrored in docs/analysis.md. Lookup is most-specific-first: an exact
#: ``Class.attr`` entry beats a bare ``attr`` entry, so per-class
#: exceptions (``QueryTracer._mu`` as a terminal leaf) coexist with the
#: generic ``_mu`` level.
LOCK_ORDER: Tuple[Tuple[str, ...], ...] = (
    # L0 — long-held orchestration locks: exchange materialization /
    # broadcast build serialize whole stages and call into everything below
    ("_mat_lock", "_broadcast_lock"),
    # L1 — the buffer-catalog singleton ctor (wires the HBM spill callback,
    # so its get() reaches L2/L4 while constructing)
    ("TpuBufferCatalog._lock",),
    # L2 — spillable registration (RLock: the HBM spill callback re-enters
    # it on the allocating thread)
    ("_reg_lock",),
    # L3 — HBM accounting (RLock; held across the synchronous spill drain)
    ("_alloc_lock",),
    # L4 — remaining singleton get() locks (ctor-only critical sections)
    ("HbmBudget._lock", "TpuSemaphore._lock", "TpuShuffleManager._lock",
     "MeshContext._lock", "MemoryCleaner._lock", "TpuDeviceManager._lock",
     "FileCache._lock", "IciShuffleCatalog._lock",
     "ShuffleHeartbeatManager._lock", "FaultInjector._cls_lock",
     "TaskMetricsRegistry._lock", "SyncLedger._lock"),
    # L4b — obs query-lifecycle lock: commits the active-query gauge into
    # the registry structure lock (L5) while held, so an interleaved
    # begin/end pair can never publish a stale count
    ("_QL_LOCK",),
    # L4c — the query scheduler's admission lock (serving/scheduler.py):
    # same discipline as _QL_LOCK — the queue-depth gauge commits into
    # the registry structure lock (L5) under it; grant WAITS happen on
    # per-ticket events OUTSIDE it, chaos/flight emission after release.
    # QueryContext._mu needs no entry: it falls through to the generic
    # `_mu` leaf level (state flips only, emission outside the lock).
    ("QueryScheduler._mu", "QueryScheduler._cls_lock"),
    # L5 — state/stats/program-cache leaf locks: short critical sections
    # that publish precomputed values (_REG_LOCK: the obs tracer registry
    # + metrics-registry structure locks)
    ("_state_lock", "_id_lock", "_stats_lock", "_mu", "_LOCK",
     "_CACHE_LOCK", "_STATS_LOCK", "_STAGE_FN_LOCK", "_JOIN_CACHE_LOCK",
     "_DIM_CACHE_LOCK", "_lock", "_evict_lock", "_REG_LOCK"),
    # L6 — observability/chaos terminals: reached from every layer above
    # (event emission, fault injection), acquire nothing themselves
    ("QueryTracer._mu", "FaultInjector._mu", "SyncLedger._mu",
     "TaskMetricsRegistry._mu"),
)

#: curated cross-module acquire summaries: callable name -> lock ids it
#: may acquire while running (one level deep is enough — the graph edges
#: compose). Kept minimal: only APIs commonly called under other locks.
CROSS_MODULE_ACQUIRES: Dict[str, Tuple[str, ...]] = {
    "allocate": ("_alloc_lock",),
    "free": ("_alloc_lock",),
    "add_batch": ("_reg_lock", "_alloc_lock"),
    "get_batch": ("_reg_lock",),
    "synchronous_spill": ("_reg_lock",),
    "acquire_if_necessary": ("_state_lock",),
    "release_if_necessary": ("_state_lock",),
    "record_external_dispatch": ("_LOCK",),
    "put_block": ("IciShuffleCatalog._mu", "_reg_lock", "_alloc_lock"),
    "inject": ("FaultInjector._cls_lock", "FaultInjector._mu"),
    "corrupt_bytes": ("FaultInjector._cls_lock", "FaultInjector._mu"),
    "event": ("QueryTracer._mu",),
    "record_sync": ("SyncLedger._lock", "SyncLedger._mu",
                    "TaskMetricsRegistry._lock", "TaskMetricsRegistry._mu"),
}

#: singleton classes whose ``X.get()`` briefly takes the class get-lock —
#: resolved cross-module by receiver name (`HbmBudget.get()` under the
#: catalog's _reg_lock is a real _reg_lock → HbmBudget._lock edge)
KNOWN_SINGLETONS: Dict[str, str] = {
    "HbmBudget": "HbmBudget._lock",
    "TpuBufferCatalog": "TpuBufferCatalog._lock",
    "TpuSemaphore": "TpuSemaphore._lock",
    "TpuShuffleManager": "TpuShuffleManager._lock",
    "MeshContext": "MeshContext._lock",
    "MemoryCleaner": "MemoryCleaner._lock",
    "FileCache": "FileCache._lock",
    "IciShuffleCatalog": "IciShuffleCatalog._lock",
    "ShuffleHeartbeatManager": "ShuffleHeartbeatManager._lock",
    "FaultInjector": "FaultInjector._cls_lock",
    "QueryTracer": "QueryTracer._cls_lock",
    "TaskMetricsRegistry": "TaskMetricsRegistry._lock",
    "SyncLedger": "SyncLedger._lock",
    "TpuDeviceManager": "TpuDeviceManager._lock",
}

class _LockDef:
    __slots__ = ("ident", "rlock", "module_level", "class_level")

    def __init__(self, ident: str, rlock: bool, module_level: bool,
                 class_level: bool = False):
        self.ident = ident
        self.rlock = rlock
        self.module_level = module_level
        self.class_level = class_level

    @property
    def process_wide(self) -> bool:
        """Module-level locks and class-ATTRIBUTE locks (the singleton
        `_lock = threading.Lock()` pattern) gate the whole process; locks
        assigned per instance in a method serialize one object only."""
        return self.module_level or self.class_level


def _is_lock_ctor(node: ast.AST) -> Optional[bool]:
    """None if not a lock constructor; else True for RLock."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None)
    if name in ("Lock", "RLock"):
        return name == "RLock"
    return None


def _collect_locks(tree: ast.Module, relpath: str) -> Dict[str, _LockDef]:
    """All lock definitions in the module, keyed by identity:
    module-level ``relpath::NAME``, class/instance attrs ``Class.attr``."""
    out: Dict[str, _LockDef] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign):
            rl = _is_lock_ctor(node.value)
            if rl is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = _LockDef(f"{relpath}::{t.id}", rl, True)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.Assign):
                    rl = _is_lock_ctor(sub.value)
                    if rl is not None:
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                out[f"{node.name}.{t.id}"] = _LockDef(
                                    f"{node.name}.{t.id}", rl, False,
                                    class_level=True)
                elif isinstance(sub, ast.FunctionDef):
                    for n in ast.walk(sub):
                        if isinstance(n, ast.Assign):
                            rl = _is_lock_ctor(n.value)
                            if rl is None:
                                continue
                            for t in n.targets:
                                if isinstance(t, ast.Attribute) and \
                                        isinstance(t.value, ast.Name) and \
                                        t.value.id in ("self", "cls"):
                                    out[f"{node.name}.{t.attr}"] = _LockDef(
                                        f"{node.name}.{t.attr}", rl, False)
    return out


class _Edge:
    __slots__ = ("src", "dst", "location", "line")

    def __init__(self, src: str, dst: str, location: str, line: int):
        self.src = src
        self.dst = dst
        self.location = location
        self.line = line


def _level_of(ident: str) -> Optional[int]:
    """Declared level of a lock identity. Module-level locks match by bare
    name (``x.py::_LOCK`` → ``_LOCK``); attribute locks first by
    ``Class.attr`` then by bare attr."""
    bare = ident.split("::")[-1]
    attr = bare.split(".")[-1]
    for lvl, names in enumerate(LOCK_ORDER):
        if bare in names:
            return lvl
    for lvl, names in enumerate(LOCK_ORDER):
        if attr in names:
            return lvl
    return None


class _ModuleLockScan:
    """One module's TL021 hits + TL022 edges."""

    def __init__(self, tree: ast.Module, relpath: str):
        self.tree = tree
        self.relpath = relpath
        self.locks = _collect_locks(tree, relpath)
        self.class_names = {n.name for n in tree.body
                            if isinstance(n, ast.ClassDef)}
        #: (class|None, fn name) -> lock identities it may acquire
        #: (transitive within the module, 2 passes). Qualified keys avoid
        #: name collisions (dict ``.get()`` vs a singleton classmethod
        #: ``get``).
        self.acquires: Dict[Tuple[Optional[str], str], Set[str]] = {}
        #: (class|None, fn name) -> blocking-op description (TL021 summary)
        self.blocks: Dict[Tuple[Optional[str], str], Optional[str]] = {}
        self.findings: List[Finding] = []
        self.edges: List[_Edge] = []
        self._summarize()

    # -- lock identity at a with-site ---------------------------------------
    def _lock_ident(self, expr: ast.AST,
                    cls_name: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Call):
            expr = expr.func
        if isinstance(expr, ast.Name):
            if expr.id in self.locks:
                return self.locks[expr.id].ident
            if _lockish(expr.id):
                return f"{self.relpath}::{expr.id}"
            return None
        if isinstance(expr, ast.Attribute) and _lockish(expr.attr):
            base = expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls"):
                owner = cls_name or "?"
            elif isinstance(base, ast.Name):
                owner = base.id  # ClassName._lock or instance var
            else:
                owner = "?"
            key = f"{owner}.{expr.attr}"
            if key in self.locks:
                return self.locks[key].ident
            return key
        return None

    def _is_rlock(self, ident: str) -> bool:
        bare = ident.split("::")[-1]
        for d in self.locks.values():
            if d.ident == ident or d.ident.endswith(bare):
                return d.rlock
        # unknown definition site: attribute-name heuristic (the two RLocks
        # in the tree are _alloc_lock/_reg_lock; anything else is a Lock)
        return bare.split(".")[-1] in ("_alloc_lock", "_reg_lock")

    # -- call resolution ----------------------------------------------------
    def _call_acquires(self, node: ast.Call,
                       current_cls: Optional[str]) -> Set[str]:
        """Lock identities a call may take: curated cross-module table,
        singleton ``X.get()``, and same-module summaries resolved by
        QUALIFIED name (receiver ``self``/``cls`` → the current class, a
        class Name → that class, a plain Name → a module function; an
        arbitrary receiver like ``self._entries.get`` resolves to nothing —
        dict methods must not inherit a classmethod's summary)."""
        nm = _call_name(node)
        out: Set[str] = set()
        if nm is None:
            return out
        if nm in CROSS_MODULE_ACQUIRES:
            out.update(CROSS_MODULE_ACQUIRES[nm])
        f = node.func
        if isinstance(f, ast.Name):
            if f.id in self.class_names:
                out.update(self.acquires.get((f.id, "__init__"), ()))
            else:
                out.update(self.acquires.get((None, nm), ()))
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            recv = f.value.id
            if recv in ("self", "cls"):
                out.update(self.acquires.get((current_cls, nm), ()))
            elif recv in self.class_names:
                out.update(self.acquires.get((recv, nm), ()))
            elif recv in KNOWN_SINGLETONS and nm == "get":
                out.add(KNOWN_SINGLETONS[recv])
        return out

    def _call_blocks(self, node: ast.Call,
                     current_cls: Optional[str]) -> Optional[str]:
        nm = _call_name(node)
        if nm is None:
            return None
        f = node.func
        key = None
        if isinstance(f, ast.Name):
            key = (None, nm)
        elif isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id in ("self", "cls"):
                key = (current_cls, nm)
            elif f.value.id in self.class_names:
                key = (f.value.id, nm)
        sub = self.blocks.get(key) if key else None
        return f"{nm}() which blocks via {sub}" if sub else None

    # -- summaries ----------------------------------------------------------
    def _summarize(self) -> None:
        fns = []
        for node in self.tree.body:
            if isinstance(node, ast.FunctionDef):
                fns.append((node, None))
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.FunctionDef):
                        fns.append((sub, node.name))
        for _ in range(2):
            for fn, cls in fns:
                acq: Set[str] = set()
                blocking: Optional[str] = None
                for node in ast.walk(fn):
                    if isinstance(node, ast.With):
                        for item in node.items:
                            ident = self._lock_ident(item.context_expr, cls)
                            if ident:
                                acq.add(ident)
                    elif isinstance(node, ast.Call):
                        acq.update(self._call_acquires(node, cls))
                        b = self._blocking_name(node) \
                            or self._call_blocks(node, cls)
                        if b:
                            blocking = blocking or b
                self.acquires[(cls, fn.name)] = acq
                self.blocks[(cls, fn.name)] = blocking

    def _blocking_name(self, node: ast.Call) -> Optional[str]:
        nm = _call_name(node)
        if nm not in BLOCKING_CALLS:
            return None
        if nm in _RECEIVER_SENSITIVE:
            # f.result(), t.join(), ev.wait(): require a Name/attr receiver
            # that is not a string-ish join idiom (", ".join)
            if not isinstance(node.func, ast.Attribute):
                return None
            if isinstance(node.func.value, ast.Constant):
                return None
        return nm

    # -- the walk -----------------------------------------------------------
    def run(self) -> None:
        def walk(body: Iterable[ast.stmt], prefix: str,
                 cls: Optional[str]) -> None:
            for node in body:
                if isinstance(node, ast.FunctionDef):
                    qual = f"{prefix}{node.name}"
                    self._scan_fn(node, qual, cls)
                    walk(node.body, f"{qual}.", cls)
                elif isinstance(node, ast.ClassDef):
                    walk(node.body, f"{prefix}{node.name}.", node.name)

        walk(self.tree.body, "", None)

    def _scan_fn(self, fn: ast.FunctionDef, qual: str,
                 cls: Optional[str]) -> None:
        self._scan_block(fn.body, [], qual, cls)

    def _scan_block(self, body: Iterable[ast.stmt], held: List[str],
                    qual: str, cls: Optional[str]) -> None:
        for st in body:
            if isinstance(st, ast.FunctionDef):
                continue  # nested defs are their own (unlocked) scope
            if isinstance(st, ast.With):
                # items of ONE `with A, B:` acquire in order — B nests
                # under A exactly like the two-statement form, so the
                # held stack grows item by item
                inner = list(held)
                for item in st.items:
                    ident = self._lock_ident(item.context_expr, cls)
                    if ident:
                        if inner and inner[-1] != ident:
                            self.edges.append(_Edge(
                                inner[-1], ident,
                                f"{self.relpath}::{qual}", st.lineno))
                        if ident in inner:
                            if not self._is_rlock(ident):
                                self.findings.append(Finding(
                                    "TL022", "error",
                                    f"{self.relpath}::{qual}",
                                    f"re-acquiring non-reentrant lock "
                                    f"{ident} already held (line "
                                    f"{st.lineno}) — self-deadlock"))
                        else:
                            inner.append(ident)
                self._scan_block(st.body, inner, qual, cls)
                continue
            if held:
                self._check_blocking(st, held, qual, cls)
                self._check_called_acquires(st, held, qual, cls)
            for sub_body in _sub_bodies(st):
                self._scan_block(sub_body, held, qual, cls)

    def _check_blocking(self, st: ast.stmt, held: List[str],
                        qual: str, cls: Optional[str]) -> None:
        # only process-wide locks gate TL021
        wide = [h for h in held if self._is_process_wide(h)]
        if not wide:
            return
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                b = self._blocking_name(node) or self._call_blocks(node,
                                                                   cls)
                if b:
                    self.findings.append(Finding(
                        "TL021", "error", f"{self.relpath}::{qual}",
                        f"blocking operation {b} at line {node.lineno} "
                        f"while holding process-wide lock {wide[-1]} — "
                        f"every sibling task on the pool stalls for the "
                        f"full wait; release the lock first (compute "
                        f"outside, publish under the lock)"))

    def _is_process_wide(self, ident: str) -> bool:
        bare = ident.split("::")[-1]
        if "::" in ident:  # module-level lock
            return True
        d = self.locks.get(bare)
        if d is not None:
            return d.process_wide
        for ld in self.locks.values():
            if ld.ident == ident:
                return ld.process_wide
        return False

    def _check_called_acquires(self, st: ast.stmt, held: List[str],
                               qual: str, cls: Optional[str]) -> None:
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            for ident in self._call_acquires(node, cls):
                if ident in held:
                    continue  # reentrancy handled at with-sites
                self.edges.append(_Edge(held[-1], ident,
                                        f"{self.relpath}::{qual}",
                                        node.lineno))


def _sub_bodies(st: ast.stmt):
    for attr in ("body", "orelse", "finalbody"):
        b = getattr(st, attr, None)
        if b:
            yield b
    for h in getattr(st, "handlers", ()) or ():
        yield h.body


def _check_order(edges: Sequence[_Edge]) -> List[Finding]:
    """Declared-partial-order + cycle check over the merged lock graph."""
    findings: List[Finding] = []
    seen: Set[Tuple[str, str, str]] = set()
    graph: Dict[str, Set[str]] = {}
    for e in edges:
        graph.setdefault(e.src, set()).add(e.dst)
        key = (e.src, e.dst, e.location)
        if key in seen:
            continue
        seen.add(key)
        ls, ld = _level_of(e.src), _level_of(e.dst)
        if ls is None:
            findings.append(Finding(
                "TL022", "error", e.location,
                f"lock {e.src} (held at line {e.line}) is not in the "
                f"declared lock order (analysis/locks.py LOCK_ORDER) — "
                f"declare its level before nesting other locks under it"))
            continue
        if ld is None:
            findings.append(Finding(
                "TL022", "error", e.location,
                f"lock {e.dst} (acquired at line {e.line} under {e.src}) "
                f"is not in the declared lock order (analysis/locks.py "
                f"LOCK_ORDER)"))
            continue
        if ld <= ls:
            findings.append(Finding(
                "TL022", "error", e.location,
                f"lock-order violation at line {e.line}: {e.dst} "
                f"(level {ld}) acquired while holding {e.src} "
                f"(level {ls}) — the declared order requires strictly "
                f"outer→inner nesting (see docs/analysis.md)"))
    # cycle check independent of the declared levels (same-level cycles)
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = 1
        stack.append(n)
        for m in graph.get(n, ()):  # pragma: no branch
            if color.get(m, 0) == 1:
                return stack[stack.index(m):] + [m]
            if color.get(m, 0) == 0:
                cyc = dfs(m)
                if cyc:
                    return cyc
        stack.pop()
        color[n] = 2
        return None

    for n in sorted(graph):
        if color.get(n, 0) == 0:
            cyc = dfs(n)
            if cyc:
                findings.append(Finding(
                    "TL022", "error", "locks::global-graph",
                    f"lock-order cycle: {' -> '.join(cyc)} — two threads "
                    f"taking these in opposite order deadlock"))
                break
    return findings


def lint_locks_module(source: str, relpath: str
                      ) -> Tuple[List[Finding], List[_Edge]]:
    """TL021 findings + raw lock-graph edges for one module."""
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError:
        return [], []
    scan = _ModuleLockScan(tree, relpath)
    scan.run()
    # dedupe per (rule, location, message)
    seen: Set[Tuple[str, str, str]] = set()
    out: List[Finding] = []
    for f in scan.findings:
        k = (f.rule, f.location, f.message)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out, scan.edges


def lint_locks_tree(root: Optional[str] = None,
                    subpackages: Tuple[str, ...] = LOCKS_SUBPACKAGES,
                    modules: Tuple[str, ...] = LOCKS_MODULES
                    ) -> List[Finding]:
    """TL021 over every module + TL022 over the merged global lock graph."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    edges: List[_Edge] = []
    for relpath, src in iter_module_sources(root, subpackages, modules):
        fs, es = lint_locks_module(src, relpath)
        findings.extend(fs)
        edges.extend(es)
    findings.extend(_check_order(edges))
    return findings
