"""Resource-lifetime lint (rules **TL020** / **TL023**): leak-freedom on
every path, and chaos coverage of the unwind paths the proof relies on.

ROADMAP item 1 (concurrent multi-tenant serving) needs "zero permit/HBM
leaks" with many sessions sharing one device pool.  Today that property is
only checked *dynamically* — chaos soaks assert `MemoryCleaner` growth is
zero and every `TpuSemaphore` permit returns.  The reference plugin enforces
the discipline structurally (`RapidsBufferCatalog` ownership,
`GpuSemaphore` acquire/release pairing, the `Retryable` contract); this
pass enforces it statically, before the scheduler multiplies acquire sites:

**TL020** — a resource acquisition whose release is not guaranteed on all
paths *including exception paths*.  Tracked acquisitions:

* ``SpillableColumnarBatch(...)`` (cleaner-registered; close() frees the
  catalog handle + HBM)
* ``OutOfCoreSorter(...)`` (owns a list of spillable runs)
* ``FileCache...range_reader(...)`` / ``RangeReader(...)`` / ``open(...)``
  (open file handles)
* ``ThreadPoolExecutor(...)`` (worker threads), ``prefetch_iterator(...)``
  (producer thread)
* ``obs.begin_query(...)`` (arms the process-wide tracer: a missed
  ``end_query`` leaves every later query untraced)
* ``TpuSemaphore...acquire_if_necessary(ctx)`` on a **locally created**
  ``TaskContext`` (the permit releases via the completion listener, so the
  guarantee is ``ctx.complete()`` in a ``finally``; a ctx received as a
  parameter is caller-owned)

A tracked acquisition is accepted when it is

* the context expression of a ``with`` (RAII), or
* released (``close``/``shutdown``/``complete``/``end_query``/a helper
  whose summary releases its parameter) in a ``finally`` whose ``try``
  covers the acquisition — or begins after it with only non-raising
  statements in between, or
* released in straight-line code with **no raise-capable statement**
  between acquisition and release, or
* ownership-transferred: returned/yielded, stored on ``self``/into a
  container that is itself released or returned, or passed to a recognized
  ownership-taking sink (``with_retry``/``with_retry_no_split`` close their
  spillable; the shuffle catalogs own committed blocks).

Helper summaries (same-module functions/methods, two passes like
astwalk's) make the check interprocedural: a ``finally`` calling
``self._finish_query_profile(qroot, ...)`` counts as releasing ``qroot``
because that method passes it to ``end_query``.

**TL023** — resource-scope chaos coverage: inside a TL020-tracked scope
(the ``try`` body protecting a tracked resource, or a resource ``with``
body), every raise-capable *external boundary* (raw file IO, device
dispatch waits) must sit under a registered chaos site from
``chaos/injector.py``'s ``ALL_SITES`` — either the callable is known to
inject one internally (the WIRED table below, validated against
``ALL_SITES`` at import), or an ``inject("site")`` call covers the scope.
Otherwise the unwind path the TL020 verdict just proved safe can never be
*exercised* by the soaks — an untestable proof rots.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .astwalk import call_name as _call_name, lockish as _lockish
from .registry_check import Finding

#: packages/modules the lint covers (relative to the spark_rapids_tpu root)
LIFECYCLE_SUBPACKAGES: Tuple[str, ...] = ("execs", "shuffle", "memory",
                                          "parallel", "io", "serving")
LIFECYCLE_MODULES: Tuple[str, ...] = ("session.py", "filecache.py")

#: constructor / factory names that ACQUIRE a resource, -> (kind, releases)
RESOURCE_CTORS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "SpillableColumnarBatch": ("spillable", ("close",)),
    "OutOfCoreSorter": ("ooc-sorter", ("close",)),
    "RangeReader": ("file-handle", ("close",)),
    "DeviceFileDecoder": ("file-handle", ("close",)),
    "open": ("file-handle", ("close",)),
    "ThreadPoolExecutor": ("thread-pool", ("shutdown",)),
    "prefetch_iterator": ("prefetch", ("close",)),
    "begin_query": ("query-trace", ()),  # released via end_query(name)
    # a QueryContext registers itself in the scheduler's session index at
    # submit time — leaked unclosed, session.cancel()/stop() and the
    # postmortem's queued/running listing would name it forever
    # (serving/query_context.py; close is idempotent)
    "QueryContext": ("query-ctx", ("close",)),
}

#: attribute-call acquirers (receiver-independent): x.range_reader(...)
RESOURCE_METHODS: Dict[str, Tuple[str, Tuple[str, ...]]] = {
    "range_reader": ("file-handle", ("close",)),
}

#: functions that release the resource passed as their first argument
RELEASE_FUNCS = frozenset(("end_query",))

#: method names that release their receiver
RELEASE_METHODS = frozenset(("close", "shutdown", "complete", "unpersist",
                             "stop"))

#: callables that take OWNERSHIP of a resource argument (close it on every
#: path themselves — with_retry's finally, the catalogs' handle ownership)
TRANSFER_SINKS = frozenset((
    "with_retry", "with_retry_no_split", "split_in_half",
    "materialize_spillable_counts",  # reads only, never escapes/raises
))

#: call names that never raise for our purposes (safe between an
#: acquisition and its release/transfer)
_SAFE_CALLS = frozenset((
    "len", "int", "float", "bool", "str", "isinstance", "issubclass",
    "getattr", "hasattr", "id", "range", "enumerate", "zip", "list",
    "dict", "tuple", "set", "sorted", "min", "max", "repr", "type",
))
_SAFE_METHODS = frozenset((
    "append", "add", "get", "items", "keys", "values", "extend", "pop",
    "setdefault", "discard",
))

# --- TL023 tables -----------------------------------------------------------

#: raise-capable external boundaries: direct calls by (dotted-suffix) name
BOUNDARY_CALLS = {
    "open": "io", "copyfile": "io", "replace": "io", "unlink": "io",
    "mkstemp": "io", "makedirs": "io",
    "read_table": "io", "write_table": "io", "read_row_groups": "io",
    "block_until_ready": "dispatch", "device_put": "dispatch",
}

#: callables KNOWN to run under a registered chaos site internally (the
#: site each maps to is asserted to exist in chaos.injector.ALL_SITES)
WIRED_CALLS: Dict[str, str] = {
    # device work: every opjit/compiled launch injects device.dispatch,
    # and with_device_retry heals transients around it
    "execute_partition": "device.dispatch",
    "execute_partitions": "device.dispatch",
    "with_device_retry": "device.dispatch",
    "decode_row_group": "scan.read",
    # spill tiers: writes inject spill.to_host/to_disk; unspill reads what
    # to_disk corrupted (checksum verified)
    "get_batch": "spill.to_disk",
    "add_batch": "hbm.alloc",
    "allocate": "hbm.alloc",
    "synchronous_spill": "spill.to_host",
    # shuffle planes
    "write_map_output": "shuffle.write",
    "iter_partition": "shuffle.read",
    "iter_partition_sources": "shuffle.read",
    "iter_blocks": "ici.fetch",
    "put_block": "ici.fetch",
    "mesh_hash_exchange": "mesh.link",
    "mesh_single_exchange": "mesh.link",
    # scan byte ranges (RangeReader.read injects scan.read itself, but
    # bare `.read` is far too generic a name to waive a whole scope on —
    # only the distinctive entry points are wired)
    "read_range": "scan.read",
    # query lifecycle (serving/): submission runs under the scheduler's
    # admission site, and every cooperative checkpoint doubles as the
    # `query.cancel` chaos site — the cancellation unwind paths TL020
    # proves ARE exercisable
    "submit_and_run": "sched.admit",
    "checkpoint": "query.cancel",
    # load shedding (docs/serving.md): both shed paths — running victim
    # and queued victim — fire the sched.shed site before arming the token
    "_shed_victim": "sched.shed",
    "_try_shed_queued": "sched.shed",
}


def _validate_wired_sites() -> None:
    """The WIRED table is a contract against the injector's registry: a
    typo'd or stale site name here would silently waive TL023 coverage."""
    from ..chaos.injector import ALL_SITES
    unknown = sorted((set(WIRED_CALLS.values())
                      | set(BOUNDARY_SITE_HINTS.values()))
                     - set(ALL_SITES))
    assert not unknown, f"lifecycle WIRED sites not in ALL_SITES: {unknown}"


#: per boundary class, the site a fix would typically register under
BOUNDARY_SITE_HINTS = {"io": "scan.read", "dispatch": "device.dispatch"}


def _summary_of_call(summaries: Dict[str, "_FnSummary"],
                     call: ast.Call) -> Optional["_FnSummary"]:
    """Same-module summary for a call site. Plain-name calls resolve by
    function name; attribute calls resolve ONLY when the receiver is
    ``self``/``cls`` — `d.get(k)` must never inherit a summary from an
    unrelated module function named ``get`` (the locks pass qualifies its
    keys for exactly the same reason)."""
    nm = _call_name(call)
    if nm is None:
        return None
    f = call.func
    if isinstance(f, ast.Name):
        return summaries.get(nm)
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id in ("self", "cls"):
        return summaries.get(nm)
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


class _Acquisition:
    __slots__ = ("kind", "releases", "name", "node")

    def __init__(self, kind: str, releases: Tuple[str, ...],
                 name: Optional[str], node: ast.AST):
        self.kind = kind
        self.releases = releases
        self.name = name            # bound local name, if any
        self.node = node


class _FnSummary:
    """Interprocedural summary of one module function / method."""

    __slots__ = ("releases_params", "returns_resource", "injects")

    def __init__(self):
        self.releases_params: Set[str] = set()   # param names it releases
        self.returns_resource: Optional[Tuple[str, Tuple[str, ...]]] = None
        self.injects: Set[str] = set()           # chaos sites it injects


def _collect_functions(tree: ast.Module):
    """(qualname, FunctionDef, class_name) for every def in the module."""
    out = []
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            out.append((node.name, node, None))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, ast.FunctionDef):
                    out.append((f"{node.name}.{sub.name}", sub, node.name))
    return out


def _summarize(fn: ast.FunctionDef,
               summaries: Dict[str, _FnSummary]) -> _FnSummary:
    s = _FnSummary()
    params = {a.arg for a in fn.args.args + fn.args.posonlyargs
              + fn.args.kwonlyargs}
    acquired_names: Dict[str, Tuple[str, Tuple[str, ...]]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            nm = _call_name(node)
            if nm is None:
                continue
            if nm == "inject" and node.args and isinstance(
                    node.args[0], ast.Constant):
                s.injects.add(str(node.args[0].value))
            # x.close() / end_query(x) releasing a parameter
            if isinstance(node.func, ast.Attribute) \
                    and nm in RELEASE_METHODS \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in params:
                s.releases_params.add(node.func.value.id)
            if nm in RELEASE_FUNCS and node.args \
                    and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in params:
                s.releases_params.add(node.args[0].id)
            # transitive: helper(qroot) where helper releases its param
            sub = _summary_of_call(summaries, node)
            if sub is not None and sub.releases_params:
                for a in node.args:
                    if isinstance(a, ast.Name) and a.id in params:
                        s.releases_params.add(a.id)
                s.injects |= sub.injects
        elif isinstance(node, ast.Assign):
            v = node.value
            if isinstance(v, ast.Call):
                nm = _call_name(v)
                res = RESOURCE_CTORS.get(nm) if nm else None
                if res is None and nm in RESOURCE_METHODS:
                    res = RESOURCE_METHODS[nm]
                if res is not None:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            acquired_names[t.id] = res
        elif isinstance(node, ast.Return) and isinstance(node.value,
                                                         ast.Name):
            if node.value.id in acquired_names:
                s.returns_resource = acquired_names[node.value.id]
        elif isinstance(node, ast.Return) and isinstance(node.value,
                                                         ast.Call):
            nm = _call_name(node.value)
            if nm in RESOURCE_CTORS:
                s.returns_resource = RESOURCE_CTORS[nm]
            elif nm in RESOURCE_METHODS:
                s.returns_resource = RESOURCE_METHODS[nm]
    return s


def _merge_summaries(a: _FnSummary, b: _FnSummary) -> _FnSummary:
    """Same bare name on different classes: conservative merge — a param
    counts as released only if EVERY same-named method releases it (the
    release side accepts code, so union would hide leaks); resource
    returns and injects widen (the flagging/coverage side)."""
    m = _FnSummary()
    m.releases_params = a.releases_params & b.releases_params
    m.returns_resource = a.returns_resource or b.returns_resource
    m.injects = a.injects | b.injects
    return m


def _module_summaries(tree: ast.Module) -> Dict[str, _FnSummary]:
    fns = _collect_functions(tree)
    summaries: Dict[str, _FnSummary] = {}
    for _ in range(2):  # two passes so helper-calls-helper propagates
        fresh: Dict[str, _FnSummary] = {}
        for qual, fn, _cls in fns:
            s = _summarize(fn, summaries)
            fresh[qual] = s
            prev = fresh.get(fn.name)
            fresh[fn.name] = s if prev is None or prev is fresh[qual] \
                else _merge_summaries(prev, s)
        summaries = fresh
    return summaries


def _is_safe_stmt(st: ast.stmt) -> bool:
    """No raise-capable work: assignments/expressions whose calls are all
    trivial. Compound statements are raise-capable (their bodies run
    arbitrary code). Release calls (``x.close()``) count as safe — closing
    one resource between acquiring and transferring another is the normal
    hand-over sequence and presumed non-raising."""
    if isinstance(st, (ast.Pass, ast.Break, ast.Continue, ast.Global,
                       ast.Nonlocal, ast.Import, ast.ImportFrom)):
        return True
    if not isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                           ast.Expr, ast.Return, ast.Yield)):
        return False
    for node in ast.walk(st):
        if isinstance(node, ast.Call):
            nm = _call_name(node)
            if isinstance(node.func, ast.Attribute):
                if nm not in _SAFE_METHODS and nm not in RELEASE_METHODS:
                    return False
            elif nm not in _SAFE_CALLS:
                return False
        elif isinstance(node, (ast.Raise, ast.Await)):
            return False
    return True


def _handler_releases_and_reraises(tr: ast.Try, name: str,
                                   releases: Tuple[str, ...],
                                   summaries: Dict[str, _FnSummary],
                                   containers: Dict[str, Set[str]]) -> bool:
    """``except BaseException: name.close(); raise`` — the equivalent of a
    finally for a resource the success path goes on to transfer."""
    for h in tr.handlers:
        if not any(isinstance(s, ast.Raise) and s.exc is None
                   for s in h.body):
            continue
        if _releases_name(h.body, name, releases, summaries, containers):
            return True
    return False


def _lockish_with(st: ast.With) -> bool:
    """A `with` whose every item is a lock/metric-timer style context that
    cannot own our resource: scanning through it keeps straight-line
    visibility (`with self._mu: self._blocks[k] = sb`)."""
    for item in st.items:
        expr = item.context_expr
        if isinstance(expr, ast.Call):
            nm = _call_name(expr)
            if nm in ("timed", "sync_scope", "span", "trace_scope",
                      "nullcontext", "retry_scope"):
                continue
            return False
        name = expr.attr if isinstance(expr, ast.Attribute) else (
            expr.id if isinstance(expr, ast.Name) else "")
        if not _lockish(name):
            return False
    return True


def _releases_name(body: List[ast.stmt], name: str,
                   releases: Tuple[str, ...],
                   summaries: Dict[str, _FnSummary],
                   containers: Dict[str, Set[str]]) -> bool:
    """Does `body` (recursively) release `name` — directly, through a
    releasing helper, or by iterating a container `name` was stored in and
    closing the elements?"""
    roots = {name} | {c for c, members in containers.items()
                      if name in members}
    for st in body:
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            nm = _call_name(node)
            if isinstance(node.func, ast.Attribute) \
                    and (nm in releases or nm in RELEASE_METHODS):
                recv = node.func.value
                if isinstance(recv, ast.Name) and recv.id in roots:
                    return True
            if nm in RELEASE_FUNCS and any(
                    isinstance(a, ast.Name) and a.id in roots
                    for a in node.args):
                return True
            sub = _summary_of_call(summaries, node)
            if sub is not None and sub.releases_params and any(
                    isinstance(a, ast.Name) and a.id in roots
                    for a in node.args):
                return True
        # container iteration: for g in groups: ... sb.close() — any close
        # inside a for whose iterated root is one of ours counts
        for node in ast.walk(st):
            if isinstance(node, ast.For) \
                    and _names_in(node.iter) & roots:
                for sub_node in ast.walk(node):
                    if isinstance(sub_node, ast.Call) \
                            and isinstance(sub_node.func, ast.Attribute) \
                            and (sub_node.func.attr in releases
                                 or sub_node.func.attr in RELEASE_METHODS):
                        return True
    return False


class _FnScan:
    """TL020/TL023 scan of one function body."""

    def __init__(self, mod_lines: List[str], qualname: str, relpath: str,
                 summaries: Dict[str, _FnSummary],
                 findings: List[Finding]):
        self.lines = mod_lines
        self.qualname = qualname
        self.relpath = relpath
        self.summaries = summaries
        self.findings = findings
        self.params: Set[str] = set()
        #: container name -> resource names appended into it
        self.containers: Dict[str, Set[str]] = {}
        #: names known to be containers (list/dict literals)
        self.container_names: Set[str] = set()
        self.transferred_containers: Set[str] = set()

    # -- entry --------------------------------------------------------------
    def run(self, fn: ast.FunctionDef) -> None:
        self.params = {a.arg for a in fn.args.args + fn.args.posonlyargs
                       + fn.args.kwonlyargs}
        self._prescan_containers(fn)
        self._scan_block(fn.body, try_stack=[], in_tracked_scope=False,
                         cont=[], covered=False)

    def _scope_has_inject(self, stmts: List[ast.stmt]) -> bool:
        """Any chaos-injectable raise site in the scope — a direct
        ``inject()``, a same-module helper whose summary injects, or a
        call from the cross-module WIRED table (APIs that run under a
        registered site internally). TL023 coverage is scope-granular:
        one registered raise site per tracked scope makes the unwind
        path exercisable."""
        for st in stmts:
            for node in ast.walk(st):
                if isinstance(node, ast.Call):
                    nm = _call_name(node)
                    if nm == "inject" or nm in WIRED_CALLS:
                        return True
                    sub = _summary_of_call(self.summaries, node)
                    if sub is not None and sub.injects:
                        return True
        return False

    def _prescan_containers(self, fn: ast.FunctionDef) -> None:
        self.local_ctxs: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.FunctionDef) and node is not fn:
                continue
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, (ast.List, ast.Dict, ast.ListComp,
                                 ast.DictComp)):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.container_names.add(t.id)
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and _call_name(node.value) == "TaskContext":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.local_ctxs.add(t.id)
            if isinstance(node, (ast.Return, ast.Yield)) \
                    and node.value is not None:
                for nm in _names_in(node.value):
                    self.transferred_containers.add(nm)
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute):
                        for nm in _names_in(node.value):
                            self.transferred_containers.add(nm)

    # -- acquisition discovery ---------------------------------------------
    def _acq_of_call(self, call: ast.Call) -> Optional[_Acquisition]:
        nm = _call_name(call)
        if nm is None:
            return None
        if isinstance(call.func, ast.Name) and nm in RESOURCE_CTORS:
            kind, rel = RESOURCE_CTORS[nm]
            return _Acquisition(kind, rel, None, call)
        if isinstance(call.func, ast.Attribute):
            if nm in RESOURCE_METHODS:
                kind, rel = RESOURCE_METHODS[nm]
                return _Acquisition(kind, rel, None, call)
            if nm in RESOURCE_CTORS and nm == "begin_query":
                return _Acquisition("query-trace", (), None, call)
        sub = _summary_of_call(self.summaries, call)
        if sub is not None and sub.returns_resource is not None:
            kind, rel = sub.returns_resource
            return _Acquisition(kind, rel, None, call)
        return None

    def _flag(self, acq: _Acquisition, why: str) -> None:
        line = getattr(acq.node, "lineno", 0)
        snippet = self.lines[line - 1].strip()[:100] \
            if 1 <= line <= len(self.lines) else ""
        self.findings.append(Finding(
            "TL020", "error", f"{self.relpath}::{self.qualname}",
            f"{acq.kind} acquired at line {line} ({snippet!r}) {why} — "
            f"release it in a finally/with, or transfer ownership "
            f"(return/store/recognized sink)"))

    # -- block scan ---------------------------------------------------------
    def _scan_block(self, stmts: List[ast.stmt], try_stack: List[ast.Try],
                    in_tracked_scope: bool, cont: List[List[ast.stmt]],
                    covered: bool) -> None:
        for i, st in enumerate(stmts):
            # the continuation a child block sees: the rest of THIS block,
            # then the enclosing continuations (straight-line visibility
            # across compound-statement boundaries)
            sub_cont = [stmts[i + 1:]] + cont
            if isinstance(st, ast.Try):
                tracked = in_tracked_scope or self._finally_releases_any(st)
                cov = covered or (tracked and self._scope_has_inject(
                    st.body + st.finalbody))
                self._scan_block(st.body, try_stack + [st], tracked,
                                 sub_cont, cov)
                for h in st.handlers:
                    self._scan_block(h.body, try_stack, in_tracked_scope,
                                     sub_cont, cov)
                self._scan_block(st.orelse, try_stack + [st], tracked,
                                 sub_cont, cov)
                self._scan_block(st.finalbody, try_stack, in_tracked_scope,
                                 sub_cont, cov)
                continue
            if isinstance(st, ast.With):
                tracked = in_tracked_scope
                for item in st.items:
                    if isinstance(item.context_expr, ast.Call) \
                            and self._acq_of_call(item.context_expr):
                        tracked = True  # with-managed resource scope
                cov = covered or (tracked and self._scope_has_inject(
                    st.body))
                if in_tracked_scope and not cov:
                    # boundaries in the with ITEMS themselves (`with
                    # open(...)` inside a tracked try)
                    for item in st.items:
                        self._check_boundaries(item.context_expr)
                self._scan_block(st.body, try_stack, tracked, sub_cont,
                                 cov)
                continue
            if isinstance(st, (ast.If,)):
                self._scan_block(st.body, try_stack, in_tracked_scope,
                                 sub_cont, covered)
                self._scan_block(st.orelse, try_stack, in_tracked_scope,
                                 sub_cont, covered)
                continue
            if isinstance(st, (ast.For, ast.While)):
                # no continuation into post-loop code: a per-iteration
                # acquisition must settle inside the iteration (a release
                # after the loop covers only the last one)
                self._scan_block(st.body, try_stack, in_tracked_scope, [],
                                 covered)
                self._scan_block(st.orelse, try_stack, in_tracked_scope,
                                 sub_cont, covered)
                continue
            if isinstance(st, ast.FunctionDef):
                # nested def: scanned as its own scope by the module walk
                continue
            self._scan_stmt(st, stmts, i, try_stack, in_tracked_scope,
                            cont, covered)

    def _finally_releases_any(self, tr: ast.Try) -> bool:
        if not tr.finalbody:
            return False
        for node in ast.walk(ast.Module(body=tr.finalbody,
                                        type_ignores=[])):
            if isinstance(node, ast.Call):
                nm = _call_name(node)
                if nm in RELEASE_METHODS or nm in RELEASE_FUNCS:
                    return True
                sub = _summary_of_call(self.summaries, node)
                if sub is not None and sub.releases_params:
                    return True
        return False

    # -- statement-level acquisition handling -------------------------------
    def _scan_stmt(self, st: ast.stmt, block: List[ast.stmt], idx: int,
                   try_stack: List[ast.Try], in_tracked_scope: bool,
                   cont: List[List[ast.stmt]], covered: bool) -> None:
        if in_tracked_scope and not covered:
            self._check_boundaries(st)
        # semaphore permit on a LOCALLY CREATED TaskContext (a ctx the
        # caller handed in — incl. closure ctxs of nested defs — is
        # caller-owned and completes there)
        for node in ast.walk(st):
            if isinstance(node, ast.Call) \
                    and _call_name(node) == "acquire_if_necessary" \
                    and node.args and isinstance(node.args[0], ast.Name):
                ctx_name = node.args[0].id
                if ctx_name not in self.local_ctxs:
                    continue
                acq = _Acquisition("semaphore-permit", ("complete",),
                                   ctx_name, node)
                if not self._release_guaranteed(acq, block, idx, try_stack,
                                                cont):
                    self._flag(acq, "holds a device permit whose "
                               "ctx.complete() is not guaranteed on "
                               "exception paths")
        if isinstance(st, (ast.Assign, ast.AnnAssign)):
            value = st.value
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            if isinstance(value, ast.Call):
                acq = self._acq_of_call(value)
                if acq is not None:
                    name = targets[0].id \
                        if len(targets) == 1 \
                        and isinstance(targets[0], ast.Name) else None
                    if name is None:
                        if all(isinstance(t, (ast.Attribute, ast.Subscript))
                               for t in targets):
                            return  # stored on self/container: transferred
                        self._flag(acq, "is never bound to a releasable "
                                   "name")
                        return
                    acq.name = name
                    if not self._release_guaranteed(acq, block, idx,
                                                    try_stack, cont):
                        self._flag(acq, "has no guaranteed release on "
                                   "exception paths")
                else:
                    self._check_inline_acquisitions(value)
            return
        if isinstance(st, ast.Expr):
            v = st.value
            delegated = isinstance(v, (ast.Yield, ast.YieldFrom))
            if delegated and isinstance(v.value, ast.Call):
                v = v.value
            if isinstance(v, ast.Call):
                acq = self._acq_of_call(v)
                if acq is not None and not delegated:
                    # a bare discarded `SpillableColumnarBatch(b)`;
                    # `yield (from) ACQ(...)` hands it to the consumer —
                    # GeneratorExit/close reaches the delegate's finally
                    self._flag(acq, "is never bound to a releasable name")
                elif acq is None:
                    self._check_inline_acquisitions(v)
            return
        if isinstance(st, ast.Return) and isinstance(st.value, ast.Call):
            # `return ACQ(...)` transfers; inline args inside still checked
            self._check_inline_acquisitions(st.value)

    def _check_inline_acquisitions(self, call: ast.Call) -> None:
        """ACQ(...) passed directly as an argument: fine into a transfer
        sink or container append; a leak anywhere else."""
        nm = _call_name(call)
        for a in list(call.args) + [k.value for k in call.keywords]:
            if not isinstance(a, ast.Call):
                continue
            acq = self._acq_of_call(a)
            if acq is None:
                self._check_inline_acquisitions(a)
                continue
            if nm in TRANSFER_SINKS:
                continue
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in ("append", "add", "put"):
                continue
            sub = _summary_of_call(self.summaries, call)
            if sub is not None and sub.releases_params:
                continue  # the callee closes what it is handed
            self._flag(acq, f"is passed straight into {nm}() which is not "
                       f"a recognized ownership sink")

    # -- the disposition decision ------------------------------------------
    def _release_guaranteed(self, acq: _Acquisition, block: List[ast.stmt],
                            idx: int, try_stack: List[ast.Try],
                            cont: List[List[ast.stmt]]) -> bool:
        name = acq.name
        assert name is not None
        # 1. a finally (or a close-and-reraise handler) on the enclosing-try
        #    stack releases it: the exception path is covered from here on
        for tr in try_stack:
            if tr.finalbody and _releases_name(
                    tr.finalbody, name, acq.releases, self.summaries,
                    self.containers):
                return True
            if _handler_releases_and_reraises(tr, name, acq.releases,
                                              self.summaries,
                                              self.containers):
                return True
        # 2. straight-line follow-up: the rest of this block, then the
        #    enclosing continuations (crossing with/if/try boundaries the
        #    scan entered)
        verdict = self._scan_followup(block[idx + 1:], acq)
        if verdict is not None:
            return verdict
        for seq in cont:
            verdict = self._scan_followup(seq, acq)
            if verdict is not None:
                return verdict
        return False

    def _scan_followup(self, stmts: List[ast.stmt],
                       acq: _Acquisition) -> Optional[bool]:
        """True/False once decided; None to keep scanning the enclosing
        continuation."""
        name = acq.name
        for st in stmts:
            if isinstance(st, ast.With) and _lockish_with(st):
                # transparent: `with self._mu: self._blocks[k] = sb`
                sub = self._scan_followup(st.body, acq)
                if sub is not None:
                    return sub
                continue
            disp = self._stmt_disposition(st, acq)
            if disp in ("released", "transferred", "try-release"):
                return True
            if disp is not None and disp.startswith("container:"):
                c = disp.split(":", 1)[1]
                self.containers.setdefault(c, set()).add(name)
                if c in self.transferred_containers:
                    return True
                continue
            if not _is_safe_stmt(st):
                # raise-capable work before any release/transfer: the
                # exception path leaks
                return False
        return None

    def _stmt_disposition(self, st: ast.stmt,
                          acq: _Acquisition) -> Optional[str]:
        name = acq.name
        if isinstance(st, ast.Return):
            if st.value is not None and name in _names_in(st.value):
                return "transferred"
            return None
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Yield):
            if st.value.value is not None \
                    and name in _names_in(st.value.value):
                return "transferred"
            return None
        if isinstance(st, ast.Try):
            # acquisition immediately followed by a try whose finally — or
            # whose close-and-reraise handler — releases it
            if st.finalbody and _releases_name(
                    st.finalbody, name, acq.releases, self.summaries,
                    self.containers):
                return "try-release"
            if _handler_releases_and_reraises(st, name, acq.releases,
                                              self.summaries,
                                              self.containers):
                return "try-release"
            return None
        if isinstance(st, ast.Assign):
            # self.x = name / container[k] = name → ownership transfer
            if name in _names_in(st.value):
                for t in st.targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        return "transferred"
            return None
        if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            call = st.value
            nm = _call_name(call)
            arg_names = set()
            for a in list(call.args) + [k.value for k in call.keywords]:
                arg_names |= _names_in(a)
            if isinstance(call.func, ast.Attribute):
                recv = call.func.value
                if nm in acq.releases or nm in RELEASE_METHODS:
                    if isinstance(recv, ast.Name) and recv.id == name:
                        return "released"
                if nm in ("append", "add", "put") and name in arg_names:
                    root = recv
                    while isinstance(root, (ast.Subscript, ast.Attribute)):
                        root = root.value
                    if isinstance(root, ast.Name):
                        if isinstance(call.func.value, ast.Attribute) or \
                                root.id in self.container_names or \
                                root.id in self.params:
                            return f"container:{root.id}" \
                                if root.id in self.container_names \
                                else "transferred"
                    return "transferred"
            if nm in RELEASE_FUNCS and name in arg_names:
                return "released"
            if nm in TRANSFER_SINKS and name in arg_names:
                return "transferred"
            sub = _summary_of_call(self.summaries, call)
            if sub is not None and sub.releases_params \
                    and name in arg_names:
                return "released"
            return None
        return None

    # -- TL023 --------------------------------------------------------------
    def _check_boundaries(self, st: ast.stmt) -> None:
        for node in ast.walk(st):
            if not isinstance(node, ast.Call):
                continue
            nm = _call_name(node)
            if nm is None or nm not in BOUNDARY_CALLS:
                continue
            if self._covered_by_inject(st):
                continue
            klass = BOUNDARY_CALLS[nm]
            hint = BOUNDARY_SITE_HINTS.get(klass, "a registered site")
            line = getattr(node, "lineno", 0)
            self.findings.append(Finding(
                "TL023", "error", f"{self.relpath}::{self.qualname}",
                f"raise-capable {klass} boundary `{nm}` at line {line} "
                f"inside a resource-tracked scope has no registered chaos "
                f"site — the unwind path TL020 just proved safe cannot be "
                f"exercised by the soaks; route it through a chaos-wired "
                f"API or inject() under `{hint}`"))

    def _covered_by_inject(self, st: ast.stmt) -> bool:
        """Same-statement coverage (the scope-level flag handles the
        rest): an adjacent inject()/wired call in the statement."""
        for node in ast.walk(st):
            if isinstance(node, ast.Call):
                nm = _call_name(node)
                if nm == "inject" or nm in WIRED_CALLS:
                    return True
                sub = _summary_of_call(self.summaries, node)
                if sub is not None and sub.injects:
                    return True
        return False


def _check_owner_class(cls: ast.ClassDef, relpath: str,
                       findings: List[Finding]) -> None:
    """A class that stores a tracked resource on ``self`` has taken
    ownership: it must expose a release method (``close``/``shutdown``/
    ``unpersist``/``__exit__``) so ITS owner can uphold TL020 — a resource
    parked on an attribute of a close-less class is a leak with extra
    steps (the DeviceFileDecoder shape: an open RangeReader pinned until
    GC)."""
    stored: List[Tuple[str, int, str]] = []  # (attr, line, kind)
    has_release = False
    for node in cls.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        if node.name in RELEASE_METHODS or node.name == "__exit__":
            has_release = True
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) \
                    and isinstance(sub.value, ast.Call):
                nm = _call_name(sub.value)
                res = RESOURCE_CTORS.get(nm) if nm else None
                if res is None and nm in RESOURCE_METHODS:
                    res = RESOURCE_METHODS[nm]
                if res is None:
                    continue
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        stored.append((t.attr, sub.lineno, res[0]))
    if stored and not has_release:
        attr, line, kind = stored[0]
        findings.append(Finding(
            "TL020", "error", f"{relpath}::{cls.name}",
            f"class stores a {kind} on self.{attr} (line {line}) but "
            f"defines no close/shutdown/__exit__ — its owner cannot "
            f"release the resource, so every instance leaks it until GC"))


def lint_lifecycle_module(source: str, relpath: str) -> List[Finding]:
    """TL020/TL023 findings for one module's source."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError:
        return findings
    _validate_wired_sites()
    lines = source.splitlines()
    summaries = _module_summaries(tree)

    def walk(body, prefix: str) -> None:
        for node in body:
            if isinstance(node, ast.FunctionDef):
                qual = f"{prefix}{node.name}"
                _FnScan(lines, qual, relpath, summaries, findings).run(node)
                walk(node.body, f"{qual}.")
            elif isinstance(node, ast.ClassDef):
                _check_owner_class(node, relpath, findings)
                walk(node.body, f"{prefix}{node.name}.")

    walk(tree.body, "")
    # one finding per (rule, location): dedupe repeated per-line hits so the
    # baseline key granularity matches the other TL rules
    seen: Set[Tuple[str, str, str]] = set()
    out: List[Finding] = []
    for f in findings:
        k = (f.rule, f.location, f.message)
        if k in seen:
            continue
        seen.add(k)
        out.append(f)
    return out


def lint_lifecycle_tree(root: Optional[str] = None,
                        subpackages: Tuple[str, ...] = LIFECYCLE_SUBPACKAGES,
                        modules: Tuple[str, ...] = LIFECYCLE_MODULES
                        ) -> List[Finding]:
    """Lint the shipped tree (root defaults to the spark_rapids_tpu pkg)."""
    from .astwalk import iter_module_sources
    findings: List[Finding] = []
    for relpath, src in iter_module_sources(root, subpackages, modules):
        findings.extend(lint_lifecycle_module(src, relpath))
    return findings
