"""Trace-safety & registry-consistency static analysis (tracelint).

Public surface for tools/tracelint.py, tools/gen_docs.py and the tests:

* :func:`analyze_registry` — classify every registered expression's
  ``eval_tpu`` and cross-check against plan/typechecks.py (TL001–TL004).
* :func:`lint_tree` — concurrency lint over shuffle/, memory/, execs/
  (TL010).
* :func:`lint_sync_tree` — blocking device→host syncs outside the audited
  ledger gate in execs/ and shuffle/ (TL011).
* :func:`lint_obs_tree` — span/event emission discipline in execs/,
  shuffle/ and memory/: route through the obs API, never sync inside an
  event argument (TL012).
* :func:`lint_lifecycle_tree` — resource-lifetime pass over execs/,
  shuffle/, memory/, parallel/, io/ and session.py: leak-freedom on all
  paths incl. exceptions (TL020) and chaos coverage of the unwind paths
  (TL023).
* :func:`lint_locks_tree` — lock discipline: no blocking op under a
  process-wide lock (TL021), global lock graph vs the declared partial
  order (TL022).
* :func:`lint_jit_tree` — program-cache & dispatch discipline over the
  cached-program surfaces: cache-key stability (TL030), static-shape
  bucketing (TL031), trace purity (TL032), donated-buffer safety
  (TL033).
* :func:`lint_plan_key_tree` — plan-cache key stability over serving/:
  unpinned identity, per-query values, live conf reads and bare schema
  objects inside fingerprint/``*_sig`` builders (TL034).
* :func:`corroborate` — dynamic ``jax.eval_shape`` probe vs the static
  verdicts (TL005).
* :func:`scan_source` / :func:`scan_function` — detector layer over raw
  source (test fixtures, kernel modules).
* :func:`execution_modes` — per-expression execution-mode strings for
  docs/supported_ops.md.

See docs/analysis.md for the verdict taxonomy and the baseline workflow.
"""

from .astwalk import (CONDITIONAL_HOST, DEVICE, HOST, UNTRACEABLE, Detection,
                      FunctionReport, ModuleIndex, worst)
from .concurrency import lint_module_source, lint_tree
from .detectors import DETECTOR_IDS, scan_function, scan_source
from .jitlint import (lint_jit_module, lint_jit_tree, lint_plan_key_module,
                      lint_plan_key_tree)
from .lifecycle import lint_lifecycle_module, lint_lifecycle_tree
from .locks import LOCK_ORDER, lint_locks_module, lint_locks_tree
from .obslint import lint_obs_module, lint_obs_tree
from .registry_check import (ExprReport, Finding, analyze_registry,
                             classify_class, execution_modes)
from .syncs import lint_sync_module, lint_sync_tree

__all__ = [
    "CONDITIONAL_HOST", "DEVICE", "HOST", "LOCK_ORDER", "UNTRACEABLE",
    "Detection", "DETECTOR_IDS", "ExprReport", "Finding", "FunctionReport",
    "ModuleIndex", "analyze_registry", "classify_class", "corroborate",
    "execution_modes", "lint_jit_module", "lint_jit_tree",
    "lint_lifecycle_module", "lint_lifecycle_tree",
    "lint_locks_module", "lint_locks_tree", "lint_module_source",
    "lint_obs_module", "lint_obs_tree", "lint_plan_key_module",
    "lint_plan_key_tree", "lint_sync_module",
    "lint_sync_tree", "lint_tree", "scan_function", "scan_source", "worst",
]


def corroborate(reports):
    # jax import deferred: the static passes must work without touching jax
    from .probe import corroborate as _c
    return _c(reports)
