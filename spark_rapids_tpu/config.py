"""Typed configuration registry for the TPU accelerator.

TPU-native re-design of the reference's `RapidsConf` system
(/root/reference/sql-plugin/src/main/scala/com/nvidia/spark/rapids/RapidsConf.scala:126-235
entry-builder DSL; 236 `spark.rapids.*` entries). We keep the same design: typed entries
declared once with docs/defaults, a session-level immutable snapshot re-read per query,
`internal`/`startup_only`/`commonly_used` attributes, and markdown doc generation
(reference `RapidsConf.help`, RapidsConf.scala:2318).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional


def _parse_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    s = str(v).strip().lower()
    if s in ("true", "1", "yes", "on"):
        return True
    if s in ("false", "0", "no", "off"):
        return False
    raise ValueError(f"invalid boolean config value: {v!r}")


_SIZE_SUFFIXES = {
    "b": 1,
    "k": 1 << 10, "kb": 1 << 10, "kib": 1 << 10,
    "m": 1 << 20, "mb": 1 << 20, "mib": 1 << 20,
    "g": 1 << 30, "gb": 1 << 30, "gib": 1 << 30,
    "t": 1 << 40, "tb": 1 << 40, "tib": 1 << 40,
}


def parse_bytes(v: Any) -> int:
    """Parse '512m', '1g', '1024' into a byte count (reference: byteStringAsBytes)."""
    if isinstance(v, (int, float)):
        return int(v)
    s = str(v).strip().lower()
    num, suffix = s, ""
    for i, ch in enumerate(s):
        if not (ch.isdigit() or ch == "." or (ch == "-" and i == 0)):
            num, suffix = s[:i], s[i:].strip()
            break
    if suffix and suffix not in _SIZE_SUFFIXES:
        raise ValueError(f"invalid byte-size suffix in config value: {v!r}")
    return int(float(num) * _SIZE_SUFFIXES.get(suffix, 1))


@dataclass
class ConfEntry:
    key: str
    doc: str
    default: Any
    converter: Callable[[Any], Any]
    internal: bool = False
    startup_only: bool = False
    commonly_used: bool = False
    checker: Optional[Callable[[Any], None]] = None

    def get(self, settings: Dict[str, str]) -> Any:
        raw = settings.get(self.key)
        if raw is None:
            return self.default
        val = self.converter(raw)
        if self.checker is not None:
            self.checker(val)
        return val


class _ConfBuilder:
    """Mirrors the reference's `conf("key").doc(...).booleanConf.createWithDefault(...)`."""

    def __init__(self, registry: "ConfRegistry", key: str):
        self._registry = registry
        self._key = key
        self._doc = ""
        self._internal = False
        self._startup_only = False
        self._commonly_used = False
        self._checker: Optional[Callable[[Any], None]] = None

    def doc(self, text: str) -> "_ConfBuilder":
        self._doc = text
        return self

    def internal(self) -> "_ConfBuilder":
        self._internal = True
        return self

    def startup_only(self) -> "_ConfBuilder":
        self._startup_only = True
        return self

    def commonly_used(self) -> "_ConfBuilder":
        self._commonly_used = True
        return self

    def check(self, fn: Callable[[Any], None]) -> "_ConfBuilder":
        self._checker = fn
        return self

    def _create(self, default: Any, converter: Callable[[Any], Any]) -> ConfEntry:
        entry = ConfEntry(
            key=self._key, doc=self._doc, default=default, converter=converter,
            internal=self._internal, startup_only=self._startup_only,
            commonly_used=self._commonly_used, checker=self._checker)
        self._registry.register(entry)
        return entry

    def boolean(self, default: bool) -> ConfEntry:
        return self._create(default, _parse_bool)

    def integer(self, default: int) -> ConfEntry:
        return self._create(default, lambda v: int(str(v), 0))

    def double(self, default: float) -> ConfEntry:
        return self._create(default, float)

    def string(self, default: Optional[str]) -> ConfEntry:
        return self._create(default, str)

    def bytes(self, default: int) -> ConfEntry:
        return self._create(default, parse_bytes)

    def string_list(self, default: List[str]) -> ConfEntry:
        return self._create(
            default,
            lambda v: [s.strip() for s in str(v).split(",") if s.strip()] if not isinstance(v, list) else v)


class ConfRegistry:
    def __init__(self) -> None:
        self.entries: Dict[str, ConfEntry] = {}

    def conf(self, key: str) -> _ConfBuilder:
        return _ConfBuilder(self, key)

    def register(self, entry: ConfEntry) -> None:
        if entry.key in self.entries:
            raise ValueError(f"duplicate config key {entry.key}")
        self.entries[entry.key] = entry

    def help_markdown(self, include_internal: bool = False) -> str:
        """Generate docs/configs.md content (reference RapidsConf.scala:2318)."""
        lines = [
            "# TPU Accelerator Configuration",
            "",
            "| Name | Description | Default | Applicable at |",
            "|---|---|---|---|",
        ]
        for key in sorted(self.entries):
            e = self.entries[key]
            if e.internal and not include_internal:
                continue
            when = "Startup" if e.startup_only else "Runtime"
            doc = str(e.doc).replace("|", "\\|")  # keep table cells aligned
            lines.append(f"| {e.key} | {doc} | {e.default} | {when} |")
        lines += ["", _PATHS_DOC]
        return "\n".join(lines) + "\n"


#: prose section appended to the generated config docs (kept here so
#: docs/configs.md regenerates from one source of truth)
_PATHS_DOC = """## General vs compiled execution paths

Every query runs on one of two device execution strategies:

* **Compiled whole-stage paths** (`spark.rapids.tpu.agg.compiledStage.enabled`,
  `spark.rapids.tpu.join.compiledStage.enabled`) fuse an entire eligible
  pipeline (scan → filter → project → group-by, or a star-join probe chain)
  into ONE jitted XLA program per batch shape. They are the fastest option but
  only engage inside a narrow eligibility window (device-pure fixed-width
  expressions, small key domains / unique build keys, no ANSI); anything else
  falls back transparently.
* **The general path** executes operator by operator (project, filter,
  shuffled join, sort-based aggregate, exchange). With
  `spark.rapids.tpu.opjit.enabled` (default on) each operator's per-batch
  device transform is itself jit-compiled and cached process-wide, keyed by a
  structural fingerprint of its expression forest plus the bucketed batch
  shape (`spark.rapids.tpu.opjit.cacheSize` bounds the LRU). Unlike the
  compiled stages this imposes no eligibility window: host-assisted
  expressions split the trace at the host boundary (the device-pure subtrees
  run compiled, the host patch stays eager), and anything that cannot trace
  at all simply stays on the eager path with identical results.

The compiled stages engage first when eligible; the opjit cache accelerates
everything they leave behind, so dispatch-bound workloads no longer pay one
host→device round trip per expression node.

With `spark.rapids.tpu.opjit.fuseStages` (default on) the general path goes
one step further: maximal chains of adjacent project/filter operators are
collapsed at plan time into ONE fused segment whose whole expression
pipeline (every projection forest plus the AND of every filter predicate)
traces into a single cached executable — a batch then flows through the
entire chain in one dispatch instead of one per operator. Host-assisted or
otherwise untraceable operators split the segment at the operator boundary
(the device-pure prefix/suffix stay fused, the offending operator runs on
its per-operator program), and a segment whose first trace fails degrades
to the per-operator programs with bit-identical results.

## Dispatch accounting

On the tunneled TPU every program launch pays a large fixed dispatch+sync
cost, so the number of *launches per batch* — not kernel time — decides
general-path wall time. The opjit cache tracks it:

* `opJitCacheHits` / `opJitCacheMisses` (per-operator metrics and the
  process-wide `opjit.cache_stats()`): one hit or miss is recorded per
  *program dispatch* through the cache. Eager-pinned fingerprints record
  nothing — their work runs as raw per-op launches.
* `cache_stats()["calls_by_kind"]` breaks dispatches down by program kind:
  `segment` (a fused stage segment: the whole project/filter chain in one
  launch), `project` / `filter` (single-operator programs), `joinenc`
  (both join sides' key encode in one launch), `exchsplit` (the exchange
  map side's hash-partition encode+split pair in one launch), `pids`
  (hash partitioner alone, e.g. under the mesh collective), `aggsort` /
  `aggreduce` (the sort-based aggregate's two phases), plus the
  whole-stage and partition-grouped kinds: `joinprobe` / `joinemit` (a
  fused segment's streamed-side join probe and pair-emit+downstream
  halves), `aggstage` (the grouped aggregate's whole update as one
  launch), `segmentg` (one fused segment over a GROUP of partitions'
  batches) and `exchsplitg` (the hash encode+split of a whole partition
  group with one bounds readback).
* With fusion on, a fully-fused N-operator chain contributes ONE `segment`
  dispatch per batch; with fusion off the same chain contributes N
  `project`/`filter` dispatches. bench.py's q3_general detail reports the
  per-run deltas so the reduction is directly visible.
* `opJitTraceTime` isolates first-sight compile cost from steady-state
  dispatch cost; steady state should be all hits.

## Per-plan and per-partition dispatch

The compiled stages reach O(exchanges) launches by construction; the
general path reaches it by composing four mechanisms, each with its own
toggle, all default-on:

* **Segments across joins** (`spark.rapids.tpu.opjit.fuseJoins`): a fused
  stage segment absorbs a streamed-side inner equi-join at its bottom. The
  build side materializes ONCE per partition — segment build children get
  the `RequireSingleBatch` coalesce goal (or arrive host-concatenated from
  an exchange read) — and each probe batch runs exactly TWO launches
  (`joinprobe`: upstream chain + key encode + hash-range probe; `joinemit`:
  pair expansion + verification + both-side gather + the flattened
  downstream chain + one compaction), split only at the inherent
  candidate-count sync. String keys, non-inner join types, oversized
  builds (which need sub-partitioning) and host-assisted expressions
  delegate that partition to the original join operator unchanged.
* **Segments across partial aggregation**
  (`spark.rapids.tpu.opjit.fuseAggs`): a grouped hash-aggregate at the top
  of a segment — or standing alone — runs its whole update (key eval,
  encode, stable sort, segment boundaries, every measure update and
  finalization, group-key gather) as ONE `aggstage` launch with a
  capacity-bucketed group table, so the group count stays a DEVICE scalar
  instead of syncing between the old sort and reduce phases. Unsupported
  aggregates degrade to the two-phase path with identical results.
* **Batched multi-partition dispatch**
  (`spark.rapids.tpu.dispatch.partitionBatch`, default 8): the per-
  *partition* launch axis folds the same way the per-operator axis did.
  The exchange map side schedules partition GROUPS: member partitions'
  same-layout batches run one grouped segment program (`segmentg`) through
  the child's `execute_partitions` entry point, their hash encode+split
  plans run one grouped launch (`exchsplitg`), and ALL member split bounds
  ride one device→host readback. One TPU-semaphore permit gates the whole
  group (member task contexts are adopted onto it), and block identity is
  unchanged — each member still commits under its own map id, so reduce
  reads and lineage recovery never observe the grouping. Set to 1 for the
  per-partition behavior.
* **Pipelined group scheduling**: the shuffle pipeline pool
  (`spark.rapids.tpu.shuffle.pipeline.*`) submits partition groups, not
  single partitions, as its schedulable units, so retry, chaos injection
  and cancellation wrap a whole group exactly like they wrapped one map.

tests/test_whole_stage_dispatch.py locks the result in: a q3-shaped
general-path plan must show only whole-stage dispatch kinds, a total
launch count bounded by a small constant per exchange, and bit-identical
results against every degraded configuration.

## Batch coalescing

Small batches multiply every per-batch cost above. With
`spark.rapids.tpu.coalesce.enabled` (default on) the plan pass inserts
`TpuCoalesceBatchesExec` ahead of batch-hungry operators — joins,
aggregates, sorts, and fused segments — concatenating device batches up to
`spark.rapids.sql.batchSizeBytes` / `batchSizeRows` (spill-aware: pending
inputs are held as `SpillableColumnarBatch` so HBM pressure can evict them
mid-concat). Join build sides use a `RequireSingleBatch`-style goal. The
same targets drive HOST-side coalescing of fetched shuffle blocks: the
exchange reduce path and `HostToDeviceExec` concatenate Arrow tables to
target size *before* the H→D upload, so one upload and one downstream
dispatch replace one per block (reference `GpuShuffleCoalesceExec`).

## Dispatch & sync accounting

Besides dispatch counts, every BLOCKING device→host transfer (a
`np.asarray`/`.item()`/`jax.device_get` of a device value — each one a full
round trip through the tunnel) is attributed to the operator that caused it
via the process-wide **sync ledger** (`profiling.SyncLedger`). All blocking
syncs in the engine route through one audited helper
(`columnar/vector.py: audited_sync*`), enforced statically by tracelint
rule TL011; the ledger records `{operator: {kind: count}}` where kind names
the reason (`rows` — a compaction/filter row count, `bounds` — exchange
split bounds, `pairs` — join pair count, `chars` — string gather sizing,
`batch` — batch materialization at the D→H boundary, ...).

* `SyncLedger.get().snapshot()` returns per-operator counts;
  `total()` the process-wide sum. bench.py's q3_general detail reports the
  per-run delta next to `opJitDispatchesByKind`.
* With deferred compaction + coalescing on, a healthy general-path run
  shows blocking syncs per partition bounded by O(exchanges) — one `bounds`
  sync per map batch and one `batch` materialization per boundary — not
  O(operators×batches). A regression shows up as a per-operator `rows`
  count that scales with batch count.
* `TpuMetric` row counts accumulate device-side when a batch's row count is
  still deferred (`add_lazy`) and materialize at metric read time (query
  end), so metric bookkeeping itself never forces a sync.

## Query timeline tracing

`spark.rapids.tpu.trace.enabled` arms the per-query span/event tracer
(`spark_rapids_tpu/obs/`): one ring-buffered, thread-aware record per query
tying every operator's time to its dispatches, blocking syncs, HBM
allocations/spills/semaphore waits, shuffle map/reduce/fetch-retries,
transient-error retries and chaos injections. Tracing is CONCURRENT: each
query gets its own tracer routed by thread-local scopes (up to
`spark.rapids.tpu.trace.maxConcurrentQueries` at once; a query beyond the
cap runs untraced and increments the `trace.dropped_queries` registry
counter — never silently). Three views export from the same record: a
Chrome trace (perfetto / `chrome://tracing`),
`session.explain("metrics")` (the executed plan annotated per node with its
actual metrics, dispatch and sync counts), and the machine-readable
diagnostics bundle `session.last_query_profile()` whose per-operator counts
reconcile against its OWN query's `calls_by_kind` / sync-ledger deltas even
when other queries run concurrently. See docs/observability.md for the span
model, event taxonomy and bundle schema.

## Always-on metrics + crash flight recorder

Independent of tracing, the `spark.rapids.tpu.obs.*` surface keeps the
serving-era aggregate layer always on: a process-wide metrics registry
(`spark.rapids.tpu.obs.metrics.enabled`, default on — query latency and
rows/s log2-bucket histograms with p50/p95/p99 readouts, HBM high-water and
pressure counters, spill bytes, cache hit rates, device-retry/chaos/fetch-
retry counts; read via `session.metrics_snapshot()` or `python -m
tools.obs_report`) and a crash flight recorder
(`spark.rapids.tpu.obs.flightRecorderEvents`) whose ring of recent notable
events lands — together with a full registry snapshot and HBM/semaphore/
spill state — in a postmortem bundle under
`spark.rapids.tpu.obs.postmortemDir` whenever a fatal device error, an
exhausted transient-retry loop, or a genuine HBM budget OOM kills a query.
docs/observability.md documents the registry naming scheme and the
postmortem schema; `python -m tools.bench_diff` gates one bench round
against the previous one on these numbers.

## Mesh efficiency profiler + collective watchdog

On a mesh session, every collective exchange additionally records a
per-exchange efficiency profile (`spark_rapids_tpu/obs/mesh_profile.py`):
the phase walls (host staging / program launch / collective wait /
compact), the per-chip send/recv rows and bytes from the already-synced
sizing counters (ZERO extra device syncs), and a skew table — max/median
per-chip rows, the imbalance factor, and the straggler chip id when one
chip's share exceeds `spark.rapids.tpu.obs.meshStragglerFactor` × the
median. Profiles land in `last_query_profile()['mesh']`,
`session.metrics_snapshot()` (with the `mesh.skew_imbalance` /
`mesh.straggler_wait_ms` registry histograms), `python -m tools.obs_report
--mesh`, and the MULTICHIP bench's per-query `efficiency_attribution`. A
collective blocked past `spark.rapids.tpu.obs.collectiveWatchdogMs` trips
the watchdog WHILE still waiting (flight-recorder event +
`mesh.watchdog_fired` counter — a hung chip is otherwise indistinguishable
from a slow one); past `spark.rapids.tpu.obs.collectiveWatchdogFatalMs` it
dumps a postmortem bundle. Mesh-session exchanges routed per-map record
WHY (`mesh.per_map_exchange{reason}`, `explain("metrics")`
`per_map=` annotations). See docs/observability.md "Mesh profiling".

## Device parquet decode

With `spark.rapids.tpu.parquet.deviceDecode.enabled` (default on) parquet
scans stop decoding on the host: the host does only footer/row-group
metadata, the Thrift page-header walk, page decompression, and the
RLE/bit-packed run-header walk, then stages raw page bytes into HBM and
runs ONE cached decode program per row group (bit-unpacking, RLE/dictionary
run expansion, dictionary gather, definition-level → validity expansion
with null compaction into the padded batch layout, PLAIN fixed-width
reinterpret — the reference's semaphore-then-cuDF-device-decode shape,
GpuParquetScan.scala:1983). Launches are recorded under the
`parquet_decode` kind in the dispatch accounting, so a scan costs
O(row-groups) dispatches, not O(pages) or O(columns). BYTE_ARRAY
string/binary columns decode into the engine's offsets+bytes device
layout (PLAIN length-prefix walks host-side, dictionary pages ship raw
bytes + the index run table; the device program cumsums row lengths into
int32 offsets and byte-gathers the chars), and RLE_DICTIONARY string
columns surface the parquet dictionary as a device `dict_encoding` so
string group keys feed the key-encode programs as int32 codes. Columns
the device cannot decode (nested, INT96, FIXED_LEN_BYTE_ARRAY, exotic
encodings) automatically
demote to per-column host pyarrow decode zipped into the same batch;
corrupt/truncated pages heal per row group via host re-read
(`spark.rapids.tpu.parquet.deviceDecode.verify` adds a paranoid
bit-identity cross-check); encrypted files raise the reference's clean
message naming the file and the CPU fallback route. Coverage matrix and
fallback rules: docs/io.md.

## Mesh data plane (sharded multi-chip execution)

With `spark.rapids.tpu.mesh.enabled` and `spark.rapids.shuffle.mode=ICI` a
session becomes a MESH SESSION: the planner re-plans hash exchanges to
exactly mesh-size reduce partitions (`spark.rapids.tpu.mesh.alignPartitions`)
and marks every fixed-width exchange collective, so each one materializes
as ONE `lax.all_to_all` (hash) or shard-0 funnel (single) over the
interconnect (`spark.rapids.tpu.mesh.collectiveExchange.enabled`) instead
of per-map catalog puts — the reference's UCX transport re-expressed as an
XLA collective. Exchange-time per-shard row/byte counters double as the
AQE partition statistics (no block is ever fetched to answer planning),
the session's root pull batches every chip's partition into one grouped
launch (`spark.rapids.tpu.dispatch.partitionBatch`), collective launches
land in the dispatch accounting under the `mesh_collective` kind inside
`mesh.exchange` timeline spans, and the lost-shard / slow-link chaos sites
(`mesh.shard`, `mesh.link`) heal through the same FetchFailed lineage
recovery as any lost map. String/binary payloads ride the collective as
int32 dictionary codes plus ONE broadcast dictionary per exchange
(`spark.rapids.tpu.exchange.dictionaryEncode.enabled` — the analogue of
the reference's compressed shuffle batches): the map side encodes across
all shards, the reduce side decodes on read with a device gather and
keeps the codes as each column's `dict_encoding` for downstream group
keys; an exchange past the cardinality/2^31-byte guards
(`spark.rapids.tpu.exchange.dictionaryEncode.maxCardinality`) falls back
per-map with reason `dictionary_overflow`. Only nested or host-only
payloads transparently keep the per-map
device-resident path. Design, fault model and the MULTICHIP bench:
docs/distributed.md.

## Robustness

Batch-level work survives memory pressure via spill + retry/split
(`spark.rapids.memory.*`), transient XLA errors heal through bounded
backoff (`spark.rapids.tpu.deviceRetry.*`), shuffle blocks carry xxhash64
checksums whose mismatch triggers lineage re-materialization
(`spark.rapids.tpu.shuffle.checksum.enabled`,
`spark.rapids.tpu.shuffle.fetchRetry.maxAttempts`), and the whole stack is
validated under the seeded chaos fault injector
(`spark.rapids.tpu.test.chaos.*`). The unified story — sites, fault kinds,
and recovery paths — is in docs/robustness.md.

## Query lifecycle & multi-tenant scheduling

Every query submits through the process-wide scheduler service
(serving/scheduler.py — many session frontends, one device owner):

* **Admission control.** A submission enters a bounded FIFO queue
  (`spark.rapids.tpu.sched.maxQueuedQueries`) drained round-robin across
  sessions; it is admitted when a concurrency slot is free
  (`spark.rapids.tpu.sched.maxConcurrentQueries`) and HBM usage is under
  `spark.rapids.tpu.sched.hbmAdmissionWatermark` × budget (waived when
  nothing is running). Past the queue bound, submission fails fast with
  the typed `QueryQueueFull` backpressure error — load sheds at the
  front door instead of stacking working sets until HBM pressure OOMs
  every query on the device.
* **Deadlines & cancellation.** Each query carries a cancel token and an
  optional deadline (`spark.rapids.tpu.query.timeoutMs`,
  `df.collect(timeout=seconds)`, `session.cancel()`). Cancellation is
  cooperative: checkpoints at every task boundary (partition-task start,
  batch pull, exchange map task, reduce fetch, mesh collective launch,
  UDF worker round-trip) observe the token and unwind through the
  TL020-audited release paths, so a cancelled or timed-out query returns
  ALL permits, HBM, spill files and its tracer to baseline.
* **Fault isolation.** A fatal device error (or an exhausted per-query
  retry budget, `spark.rapids.tpu.query.retryBudget`) fails that query
  alone: with concurrent queries in flight the process is NOT exited —
  the failure is quarantined (postmortem dump + `query.quarantined`
  counter) and healthy neighbors run to completion.

State machine, cancellation semantics, and the fault-isolation matrix:
docs/robustness.md "Query lifecycle".
"""

REGISTRY = ConfRegistry()
_conf = REGISTRY.conf

# ---------------------------------------------------------------------------
# Core enablement (reference RapidsConf.scala: spark.rapids.sql.enabled et al.)
# ---------------------------------------------------------------------------
SQL_ENABLED = _conf("spark.rapids.sql.enabled").doc(
    "Enable (true) or disable (false) TPU acceleration of SQL plans."
).commonly_used().boolean(True)

SQL_MODE = _conf("spark.rapids.sql.mode").doc(
    "executeOnTPU runs converted plans on the TPU; explainOnly only reports what would "
    "run on the TPU (reference GpuOverrides.scala:4579-4584) and executes on CPU."
).check(lambda v: None if v in ("executeontpu", "explainonly", "executeOnTPU", "explainOnly")
        else (_ for _ in ()).throw(ValueError(f"invalid sql.mode {v}"))).string("executeOnTPU")

EXPLAIN = _conf("spark.rapids.sql.explain").doc(
    "NONE, NOT_ON_TPU (log reasons operators fall back to CPU) or ALL."
).commonly_used().string("NOT_ON_TPU")

TEST_ASSERT_ON_TPU = _conf("spark.rapids.sql.test.enabled").doc(
    "Testing only: fail if any operator in the plan did not convert to the TPU "
    "(reference GpuTransitionOverrides.assertIsOnTheGpu, GpuTransitionOverrides.scala:616)."
).internal().boolean(False)

ALLOW_CPU_FALLBACK_EXPRS = _conf("spark.rapids.sql.cpuExpressions.enabled").doc(
    "Allow individual expressions without a TPU kernel to run on the host inside a "
    "TPU-resident plan (per-expression fallback)."
).boolean(True)

INCOMPATIBLE_OPS = _conf("spark.rapids.sql.incompatibleOps.enabled").doc(
    "Enable operators whose results differ from Spark in corner cases "
    "(reference RapidsConf incompatibleOps)."
).boolean(True)

ANSI_ENABLED = _conf("spark.sql.ansi.enabled").doc(
    "ANSI mode: arithmetic overflow and invalid casts raise instead of returning null."
).boolean(False)

CASE_SENSITIVE = _conf("spark.sql.caseSensitive").doc(
    "Case-sensitive attribute resolution."
).boolean(False)

SESSION_TZ = _conf("spark.sql.session.timeZone").doc(
    "Session timezone for timestamp semantics."
).string("UTC")

# ---------------------------------------------------------------------------
# Batching / memory (reference RapidsConf.scala:544-567, 464, 508)
# ---------------------------------------------------------------------------
CONCURRENT_TPU_TASKS = _conf("spark.rapids.tpu.concurrentTpuTasks").doc(
    "Number of concurrent tasks that may hold TPU HBM at once; gated by the TPU "
    "semaphore (reference GpuSemaphore, RapidsConf.scala:544-551 default 2)."
).commonly_used().integer(2)

BATCH_SIZE_BYTES = _conf("spark.rapids.sql.batchSizeBytes").doc(
    "Target size in bytes of output batches (reference GPU_BATCH_SIZE_BYTES default 1GiB "
    "max 2GiB, RapidsConf.scala:559-567). Smaller default on TPU: static-shape compilation "
    "favors stable bucketed capacities."
).commonly_used().bytes(512 * 1024 * 1024)

BATCH_SIZE_ROWS = _conf("spark.rapids.sql.batchSizeRows").doc(
    "Target maximum rows per columnar batch."
).integer(1 << 20)

HBM_ALLOC_FRACTION = _conf("spark.rapids.memory.tpu.allocFraction").doc(
    "Fraction of TPU HBM budgeted for columnar data (reference RMM_ALLOC_FRACTION, "
    "RapidsConf.scala:464). XLA owns the physical allocator; this bounds our accounting."
).startup_only().double(0.75)

HOST_SPILL_STORAGE_SIZE = _conf("spark.rapids.memory.host.spillStorageSize").doc(
    "Amount of host memory used to cache spilled device batches before disk "
    "(reference HOST_SPILL_STORAGE_SIZE, RapidsConf.scala:508)."
).startup_only().bytes(1 << 30)

LEAK_TRACKING_DEBUG = _conf("spark.rapids.memory.debug.leakTracking").doc(
    "Capture creation stacks for every registered device resource and "
    "raise on double-close (reference MemoryCleaner leak tracking, "
    "Plugin.scala:581-596). Always-on cheap tracking reports leak counts "
    "at shutdown even when this is off.").boolean(False)

OOM_RETRY_MAX = _conf("spark.rapids.memory.tpu.oomMaxRetries").doc(
    "Retries of an allocation after synchronizing + spilling before declaring OOM."
).integer(3)

TASK_RETRY_LIMIT = _conf("spark.rapids.memory.tpu.taskRetryLimit").doc(
    "How many times the task-level retry framework re-runs a batch on "
    "TpuRetryOOM (splitting on TpuSplitAndRetryOOM) before giving up "
    "(reference RmmRapidsRetryIterator bound)."
).integer(8)

BUCKET_PADDING = _conf("spark.rapids.tpu.batch.bucketPadding.enabled").doc(
    "Pad batch capacities to power-of-two buckets to bound XLA recompilation under "
    "data-dependent row counts (TPU-specific; no reference analogue — cuDF kernels "
    "accept dynamic sizes, XLA does not)."
).boolean(True)

COALESCE_ENABLED = _conf("spark.rapids.tpu.coalesce.enabled").doc(
    "Batch coalescing for the general path (reference GpuCoalesceBatches + "
    "GpuShuffleCoalesceExec): concatenate undersized batches up to "
    "spark.rapids.sql.batchSizeBytes / batchSizeRows before batch-hungry "
    "operators (joins, aggregates, sorts, fused segments), and concatenate "
    "fetched shuffle blocks HOST-side to the same target before the "
    "host→device upload. On a high-dispatch-latency link every batch pays "
    "a fixed launch+sync cost, so fewer, fuller batches are the difference "
    "between O(batches) and O(exchanges) round trips per operator."
).commonly_used().boolean(True)

DEFERRED_COMPACTION = _conf(
    "spark.rapids.tpu.batch.deferredCompaction.enabled").doc(
    "Defer the filter/join compaction row-count sync: `compact` keeps the "
    "bucketed padded capacity and carries the kept-row count as a DEVICE "
    "scalar, so a filter→project→serialize chain syncs once at the "
    "exchange/collect boundary (the count rides the same device_get as the "
    "data) instead of one blocking scalar read per batch per operator. "
    "Consumers that need the host row count materialize it transparently; "
    "results are bit-identical either way."
).boolean(True)

# ---------------------------------------------------------------------------
# Shuffle (reference RapidsConf.scala:1663-1677, 1855-1866)
# ---------------------------------------------------------------------------
SHUFFLE_MODE = _conf("spark.rapids.shuffle.mode").doc(
    "MULTITHREADED (host Arrow-serialized shuffle files, parallel writer/reader threads) "
    "or ICI (device-resident all-to-all over the TPU interconnect within a mesh) "
    "(reference SHUFFLE_MANAGER_MODE: MULTITHREADED/UCX/CACHE_ONLY)."
).string("MULTITHREADED")

SHUFFLE_WRITER_THREADS = _conf("spark.rapids.shuffle.multiThreaded.writer.threads").doc(
    "Threads for the multithreaded shuffle writer (reference RapidsConf.scala:1855)."
).integer(8)

MESH_ENABLED = _conf("spark.rapids.tpu.mesh.enabled").doc(
    "Execute hash exchanges as one collective all_to_all over a "
    "jax.sharding.Mesh when the device topology allows it (the UCX-mode data "
    "plane of the reference, shuffle-plugin/UCXShuffleTransport.scala, "
    "re-expressed as an XLA collective over ICI). Requires "
    "spark.rapids.shuffle.mode=ICI and shuffle partitions == mesh size."
).boolean(False)

MESH_SIZE = _conf("spark.rapids.tpu.mesh.size").doc(
    "Mesh size (number of devices) for the collective exchange; 0 = all "
    "visible devices."
).integer(0)

MESH_COLLECTIVE_ENABLED = _conf(
    "spark.rapids.tpu.mesh.collectiveExchange.enabled").doc(
    "Materialize eligible exchanges of a mesh session as ONE fabric "
    "collective (lax.all_to_all for hash partitioning, the shard-0 funnel "
    "for single partitioning) instead of per-map catalog puts. Off keeps "
    "the per-map device-resident ICI path (every block still device-side, "
    "but one materialization per map partition). Requires "
    "spark.rapids.tpu.mesh.enabled and spark.rapids.shuffle.mode=ICI."
).boolean(True)

EXCHANGE_DICT_ENCODE_ENABLED = _conf(
    "spark.rapids.tpu.exchange.dictionaryEncode.enabled").doc(
    "Let string/binary exchange payloads ride the mesh collective as "
    "fixed-width int32 dictionary codes plus ONE per-exchange broadcast "
    "dictionary (the TPU analogue of the reference's compressed shuffle "
    "batches, RapidsShuffleCompression): the map side dictionary-encodes "
    "each string column across all shards, the lax.all_to_all moves only "
    "the codes, and the reduce side decodes on read with a device gather "
    "— the rebuilt columns keep the codes as their dict_encoding so "
    "string-keyed downstream aggregation consumes them directly. Requires "
    "a mesh session; exchanges whose dictionary trips the cardinality or "
    "2^31-byte guards fall back to the per-map path with reason "
    "dictionary_overflow. Off = string-payload exchanges always ride the "
    "per-map device-resident path."
).boolean(True)

EXCHANGE_DICT_MAX_CARDINALITY = _conf(
    "spark.rapids.tpu.exchange.dictionaryEncode.maxCardinality").doc(
    "Cardinality guard for spark.rapids.tpu.exchange.dictionaryEncode."
    "enabled: an exchange whose string columns hold more distinct values "
    "than this (or more than 2^31 distinct bytes — the int32 offsets "
    "range) is not worth a broadcast dictionary and falls back to the "
    "per-map path (reason dictionary_overflow in "
    "mesh.per_map_exchange{reason} and explain(\"metrics\"))."
).integer(1 << 20)

MESH_ALIGN_PARTITIONS = _conf(
    "spark.rapids.tpu.mesh.alignPartitions").doc(
    "When a mesh session is active, the planner re-plans hash exchanges to "
    "exactly mesh-size reduce partitions so every exchange is collective-"
    "eligible (the on-device murmur3 % n routing must match the shard "
    "count). Partition count is an execution detail — results are "
    "identical at any count — so mesh sessions stop depending on the user "
    "hand-tuning spark.sql.shuffle.partitions to the topology."
).boolean(True)

EXCHANGE_OVERLAP_ENABLED = _conf(
    "spark.rapids.tpu.exchange.overlap.enabled").doc(
    "Segment eligible collective exchanges so segment k+1's all_to_all is "
    "in flight on the fabric while the fused post-collective compact "
    "consumes segment k (exchange/compute overlap, "
    "parallel/mesh.py). Every segment scatters to the same final row "
    "positions the unsegmented program uses, so results are bit-identical "
    "at any segment count; the exchange still records exactly ONE "
    "mesh_collective launch (segments count under mesh_overlap_segment). "
    "Correctness-first default: off — each exchange runs as one fused "
    "program."
).boolean(False)

EXCHANGE_OVERLAP_SEGMENTS = _conf(
    "spark.rapids.tpu.exchange.overlap.segments").doc(
    "Segment count K for spark.rapids.tpu.exchange.overlap.enabled: the "
    "collective payload splits into K slot-axis segments, double-buffered "
    "so at most one segment's transfer overlaps one segment's compact. "
    "Values <= 1 disable segmentation."
).integer(2)

EXCHANGE_OVERLAP_MIN_ROWS = _conf(
    "spark.rapids.tpu.exchange.overlap.minSlotRows").doc(
    "Minimum per-bucket slot capacity (rows) for the segmented overlap "
    "path to engage: below it, per-segment launch overhead dominates "
    "whatever transfer time the overlap could hide and the exchange runs "
    "unsegmented (the sizing sync already knows the capacity, so the "
    "decision costs nothing)."
).integer(1024)

COMPILED_AGG_ENABLED = _conf("spark.rapids.tpu.agg.compiledStage.enabled").doc(
    "Fuse eligible scan->filter->project->groupBy pipelines into ONE jitted "
    "XLA program with a direct-indexed group table (small key domains only). "
    "Eliminates per-expression dispatch latency — the TPU analogue of the "
    "reference's fused aggregation iterator chain "
    "(GpuAggregateExec.scala:549). Ineligible or overflowing stages fall "
    "back to the general sort-based aggregate transparently."
).boolean(True)

COMPILED_AGG_MAX_GROUPS = _conf("spark.rapids.tpu.agg.compiled.maxGroups").doc(
    "Largest combined group-key domain the compiled aggregation stage may "
    "direct-index; beyond this the general sort-based path runs."
).integer(4096)

OPJIT_ENABLED = _conf("spark.rapids.tpu.opjit.enabled").doc(
    "Jit-compile the GENERAL execution path's per-operator device "
    "transforms (projection/filter expression forests, join key encoding, "
    "hash partitioning, the sort-based aggregate's sort and reduce phases) "
    "into XLA executables cached process-wide by a structural fingerprint "
    "plus bucketed batch shape. Collapses the eager path's per-op dispatch "
    "storm (each ~100ms through the tunnel) into one launch per operator "
    "per batch shape. Unlike the compiled whole-stage paths there is no "
    "eligibility window: subtrees that cannot trace (host-assisted "
    "expressions, ANSI host-sync checks, string kernels sizing on data) "
    "split the trace at the host boundary and stay eager."
).commonly_used().boolean(True)

OPJIT_CACHE_SIZE = _conf("spark.rapids.tpu.opjit.cacheSize").doc(
    "LRU bound on the general-path executable cache "
    "(spark.rapids.tpu.opjit.enabled); evicting an entry drops its "
    "compiled program."
).integer(256)

OPJIT_FUSE_STAGES = _conf("spark.rapids.tpu.opjit.fuseStages").doc(
    "Whole-stage segment fusion for the general path: collapse maximal "
    "chains of adjacent project/filter operators into one fused segment "
    "whose entire expression pipeline traces into a SINGLE cached "
    "executable per batch shape — one dispatch per batch for the whole "
    "chain instead of one per operator. Host-assisted expressions split "
    "the segment at the operator boundary (device-pure prefix/suffix stay "
    "fused); untraceable segments degrade to the per-operator programs "
    "with identical results. Requires spark.rapids.tpu.opjit.enabled."
).commonly_used().boolean(True)

OPJIT_FUSE_JOINS = _conf("spark.rapids.tpu.opjit.fuseJoins").doc(
    "Let fused stage segments absorb an inner equi-join: the build side "
    "materializes ONCE per partition (one cached build program: key eval + "
    "encode + hash + sort), and each probe batch runs the upstream "
    "projection/filter chain, probe-key encode and hash-range probe as one "
    "cached program, then pair expansion, verification, both-side gathers "
    "and the downstream chain as a second — two launches plus the inherent "
    "pair-count sync per probe batch instead of one launch per operator. "
    "String keys, residual-match-sensitive join types and host-assisted "
    "expressions degrade to the per-operator join with identical results. "
    "Requires spark.rapids.tpu.opjit.fuseStages."
).commonly_used().boolean(True)

OPJIT_FUSE_AGGS = _conf("spark.rapids.tpu.opjit.fuseAggs").doc(
    "Run the sort-based grouped aggregate's whole update — grouping-key "
    "eval, encode, stable sort, segment boundaries, every measure update "
    "and finalization, and the group-key gather — as ONE cached executable "
    "with a fixed-size (input-capacity-bucketed) group table, so the group "
    "count stays a DEVICE scalar instead of syncing between the sort and "
    "reduce phases. Fused stage segments also absorb such an aggregate as "
    "their final stage. Unsupported aggregates (collect/percentile "
    "family, decimal accumulators, variable-width inputs) degrade to the "
    "two-phase aggsort/aggreduce path with identical results. Requires "
    "spark.rapids.tpu.opjit.enabled."
).commonly_used().boolean(True)

DISPATCH_PARTITION_BATCH = _conf(
    "spark.rapids.tpu.dispatch.partitionBatch").doc(
    "Batched multi-partition dispatch: the exchange map side and the fused "
    "segment executor process up to this many partitions per program "
    "launch — member batches enter ONE cached grouped program (each padded "
    "to its capacity bucket; a composite member×partition sort key keeps "
    "per-partition identity) so the hash-partition encode+split pair and "
    "the segment transform launch once per partition GROUP, and the split "
    "bounds of the whole group ride one device→host readback. The shuffle "
    "pipeline pool schedules partition groups instead of single "
    "partitions. 1 disables grouping (per-partition dispatch, the PR 2 "
    "behavior); block identity, ordering and lineage recovery are "
    "unchanged either way."
).commonly_used().integer(8)

SHUFFLE_PIPELINE_ENABLED = _conf(
    "spark.rapids.tpu.shuffle.pipeline.enabled").doc(
    "Pipelined exchange materialization: run a shuffle's map tasks "
    "concurrently through a bounded thread pool (device work gated by the "
    "TPU semaphore) so one map's deferred host commit I/O overlaps the "
    "next map's device work, and prefetch the reduce side's "
    "deserialize+upload while downstream computes (reference "
    "RapidsShuffleThreadedWriterBase / ...ReaderBase)."
).commonly_used().boolean(True)

SHUFFLE_PIPELINE_MAP_THREADS = _conf(
    "spark.rapids.tpu.shuffle.pipeline.mapThreads").doc(
    "Maximum concurrent map tasks while materializing one exchange "
    "(spark.rapids.tpu.shuffle.pipeline.enabled). Device-side concurrency "
    "is still bounded by spark.rapids.tpu.concurrentTpuTasks; extra "
    "threads overlap host serialization and file I/O with device work."
).integer(4)

SHUFFLE_PIPELINE_PREFETCH = _conf(
    "spark.rapids.tpu.shuffle.pipeline.prefetchDepth").doc(
    "How many reduce-side shuffle blocks the exchange read path "
    "deserializes and uploads ahead of the consumer "
    "(spark.rapids.tpu.shuffle.pipeline.enabled). 0 disables read-side "
    "prefetch."
).integer(2)

PARQUET_CHUNK_BYTES = _conf(
    "spark.rapids.sql.reader.chunked.maxDecodeBytes").doc(
    "PERFILE parquet reads stream row groups in chunks whose compressed "
    "footprint stays under this many bytes, bounding host decode memory "
    "(reference chunked reader, GpuParquetScan + "
    "spark.rapids.sql.reader.chunked). 0 disables chunking."
).integer(256 << 20)

PARQUET_REBASE_MODE_READ = _conf(
    "spark.rapids.sql.parquet.datetimeRebaseModeInRead").doc(
    "Rebase handling for parquet files WITHOUT the Spark legacy-calendar "
    "footer marker: CORRECTED reads values as proleptic Gregorian (modern "
    "writers), LEGACY forces the hybrid Julian->proleptic rebase. Marked "
    "files always rebase (reference datetimeRebaseUtils.scala)."
).string("CORRECTED")

PARQUET_DEVICE_DECODE_ENABLED = _conf(
    "spark.rapids.tpu.parquet.deviceDecode.enabled").doc(
    "Decode parquet pages ON DEVICE for the flat fixed-width column "
    "classes (PLAIN / RLE_DICTIONARY / RLE int32/int64/float/double/"
    "boolean/date/timestamp-micros, with definition-level nulls): the host "
    "does only footer/row-group metadata, the page-header walk and page "
    "decompression, then stages raw page bytes into HBM and runs ONE "
    "cached decode program per row group (reference GpuParquetScan "
    "semaphore-then-cuDF-decode). Columns the device cannot decode "
    "(strings, nested, INT96, exotic encodings) automatically demote to "
    "host pyarrow decode per column and zip into the same batch; decode "
    "errors heal per row group via host re-read. Note: the device path "
    "streams files serially per partition, one row group at a time — "
    "spark.rapids.sql.format.parquet.reader.type and the chunked-reader "
    "byte limit govern the HOST path only (per-row-group staging is the "
    "device path's memory bound, the reference's chunked-decode shape). "
    "Off = the original whole-table host pyarrow decode + upload path."
).boolean(True)

PARQUET_DEVICE_DECODE_VERIFY = _conf(
    "spark.rapids.tpu.parquet.deviceDecode.verify").doc(
    "Paranoia cross-check for spark.rapids.tpu.parquet.deviceDecode."
    "enabled: after each device-decoded row group, re-decode the same "
    "columns with host pyarrow and require bit-identical results; a "
    "mismatch (e.g. corrupted staged bytes that slipped past the "
    "structural page checks) falls the row group back to the host decode. "
    "Debug/soak tool — roughly doubles scan cost."
).boolean(False)

COMPILED_JOIN_ENABLED = _conf(
    "spark.rapids.tpu.join.compiledStage.enabled").doc(
    "Fuse eligible star-shaped join pipelines "
    "(fact scan->filter->project -> chain of many-to-one equi-joins -> "
    "groupBy) into ONE jitted XLA program per fact batch: dimension tables "
    "build as sorted device arrays, the fact side probes them with "
    "searchsorted + gather inside the trace, and the aggregation groups by "
    "the dimension row index (dense codes, segment reductions). Kills the "
    "per-partition program-launch storm of the shuffled-join path on "
    "high-dispatch-latency links. Ineligible stages (non-equi conditions, "
    "duplicate build keys, outer joins) fall back transparently."
).boolean(True)

COMPILED_JOIN_MAX_DIM_ROWS = _conf(
    "spark.rapids.tpu.join.compiled.maxDimRows").doc(
    "Largest build-side (dimension) row count the compiled join stage will "
    "materialize as device probe arrays; beyond this the general shuffled "
    "join path runs."
).integer(1 << 22)

SHUFFLE_READER_THREADS = _conf("spark.rapids.shuffle.multiThreaded.reader.threads").doc(
    "Threads for the multithreaded shuffle reader (reference RapidsConf.scala:1866)."
).integer(8)

SHUFFLE_COMPRESSION_CODEC = _conf("spark.rapids.shuffle.compression.codec").doc(
    "Codec for shuffle batch buffers: none, zstd, lz4 (reference nvcomp LZ4/ZSTD codecs)."
).string("zstd")

SHUFFLE_PARTITIONS = _conf("spark.sql.shuffle.partitions").doc(
    "Default number of shuffle partitions."
).integer(16)

# ---------------------------------------------------------------------------
# I/O (reference RapidsConf.scala:1067-1088 and chunked-reader confs)
# ---------------------------------------------------------------------------
PARQUET_READER_TYPE = _conf("spark.rapids.sql.format.parquet.reader.type").doc(
    "AUTO, PERFILE, COALESCING or MULTITHREADED multi-file reader strategy "
    "(reference GpuMultiFileReader, RapidsConf.scala:1067-1088)."
).string("AUTO")

MULTITHREAD_READ_NUM_THREADS = _conf("spark.rapids.sql.multiThreadedRead.numThreads").doc(
    "Thread-pool size for multithreaded file reading."
).integer(8)

PARQUET_ENABLED = _conf("spark.rapids.sql.format.parquet.enabled").doc(
    "Enable TPU parquet scans/writes.").boolean(True)
CSV_ENABLED = _conf("spark.rapids.sql.format.csv.enabled").doc(
    "Enable TPU CSV scans.").boolean(True)
JSON_ENABLED = _conf("spark.rapids.sql.format.json.enabled").doc(
    "Enable TPU JSON scans.").boolean(True)
ORC_ENABLED = _conf("spark.rapids.sql.format.orc.enabled").doc(
    "Enable TPU ORC scans/writes.").boolean(True)
AVRO_ENABLED = _conf("spark.rapids.sql.format.avro.enabled").doc(
    "Enable TPU Avro scans.").boolean(True)
HIVE_TEXT_ENABLED = _conf("spark.rapids.sql.format.hive.text.enabled").doc(
    "Enable TPU Hive delimited-text scans/writes.").boolean(True)
AQE_COALESCE_ENABLED = _conf(
    "spark.sql.adaptive.coalescePartitions.enabled").doc(
    "Coalesce small shuffle partitions after materialization using map "
    "output sizes (reference GpuCustomShuffleReaderExec / AQE coalesced "
    "partition specs).").boolean(False)
AQE_ADVISORY_PARTITION_BYTES = _conf(
    "spark.sql.adaptive.advisoryPartitionSizeInBytes").doc(
    "Target combined size of a coalesced shuffle-read partition."
).bytes(64 * (1 << 20))
AQE_SKEW_JOIN_ENABLED = _conf(
    "spark.sql.adaptive.skewJoin.enabled").doc(
    "Split skewed shuffle partitions into map-range slices on one join side "
    "and replicate the other side's matching partition (reference "
    "OptimizeSkewedJoin + PartialReducerPartitionSpec).").boolean(False)
AQE_SKEW_THRESHOLD = _conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes").doc(
    "A shuffle partition is skew-eligible only above this size."
).bytes(256 * (1 << 20))
AQE_SKEW_FACTOR = _conf(
    "spark.sql.adaptive.skewJoin.skewedPartitionFactor").doc(
    "A partition is skewed when larger than this factor times the median "
    "partition size (and above the threshold)."
).integer(5)
CACHE_BATCH_ROWS = _conf("spark.rapids.sql.cache.batchSizeRows").doc(
    "Rows per parquet-compressed cached batch in df.cache() (reference "
    "ParquetCachedBatchSerializer per-batch encoding)."
).integer(1 << 18)
CACHE_HOST_LIMIT = _conf("spark.rapids.sql.cache.hostMemoryLimit").doc(
    "Host-memory budget for cached-relation blobs; overflow spills whole "
    "compressed batches to local disk (0 disables the cap)."
).bytes(0)
FILECACHE_ENABLED = _conf("spark.rapids.filecache.enabled").doc(
    "Cache remote scan inputs (s3/gs/hdfs/...) on local disk (reference: "
    "the spark-rapids-private FileCache; SURVEY.md §1 notes the TPU build "
    "implements it directly).").boolean(False)
FILECACHE_PATH = _conf("spark.rapids.filecache.path").doc(
    "Local directory for the file cache (defaults to a temp dir)."
).string(None)
FILECACHE_MAX_BYTES = _conf("spark.rapids.filecache.maxBytes").doc(
    "File-cache size budget; least-recently-used files are evicted."
).bytes(100 * (1 << 30))
CORE_DUMP_DIR = _conf("spark.rapids.tpu.coreDump.dir").doc(
    "When set, fatal device errors write a diagnostic bundle (device "
    "topology, HBM accounting, task metrics, traceback) here before the "
    "executor exits (reference GpuCoreDumpHandler + "
    "spark.rapids.gpu.coreDump.*).").string(None)
FATAL_ERROR_EXIT = _conf("spark.rapids.tpu.fatalError.exit").doc(
    "Exit the process on a fatal device error so a cluster manager can "
    "reschedule (reference RapidsExecutorPlugin.logGpuDebugInfoAndExit). "
    "Off by default: this engine runs in the driver process, so exiting "
    "would kill the user's application — enable it only when running as a "
    "managed executor.").boolean(False)
DEBUG_DUMP_PATH = _conf("spark.rapids.sql.debug.dumpPath").doc(
    "When set, operators dump their last good batch to parquet under this "
    "directory on failure (reference DumpUtils.scala).").string(None)
OPTIMIZER_ENABLED = _conf("spark.rapids.sql.optimizer.enabled").doc(
    "Cost-based optimizer: revert plan sections whose estimated TPU cost "
    "(incl. transitions) exceeds the CPU cost (reference "
    "CostBasedOptimizer.scala).").boolean(False)
OPTIMIZER_CPU_ROW_COST = _conf(
    "spark.rapids.sql.optimizer.cpu.exec.defaultRowCost").doc(
    "Default per-row CPU operator cost for the CBO.").double(0.0002)
OPTIMIZER_TPU_ROW_COST = _conf(
    "spark.rapids.sql.optimizer.tpu.exec.defaultRowCost").doc(
    "Default per-row TPU operator cost for the CBO.").double(0.0001)
OPTIMIZER_TRANSITION_ROW_COST = _conf(
    "spark.rapids.sql.optimizer.transitionRowCost").doc(
    "Per-row cost charged for each row↔columnar transition at a section "
    "boundary. Kept low by default: every pipeline here starts host-side, "
    "so the upload edge is priced as one amortized copy, not a per-operator "
    "penalty.").double(0.00002)
LOGICAL_COLUMN_PRUNING = _conf(
    "spark.rapids.tpu.optimizer.columnPruning.enabled").doc(
    "Logical column pruning: the planner inserts projections restricted "
    "to the columns an operator's ancestors actually reference, so "
    "exchanges carry fixed-width/dict-coded payloads without hand-written "
    "selects (docs/serving.md \"Plan cache & logical optimizer\")."
).boolean(True)
LOGICAL_PUSHDOWN = _conf(
    "spark.rapids.tpu.optimizer.pushdown.enabled").doc(
    "Logical filter/projection pushdown through explicit exchanges "
    "(hash-partitioned Repartition) and pure-rename projections, so rows "
    "are dropped before they are shuffled."
).boolean(True)
LOGICAL_JOIN_STRATEGY = _conf(
    "spark.rapids.tpu.optimizer.joinStrategy.enabled").doc(
    "Cost-based build-side choice: swap a join's inputs when the "
    "row-count estimate (plan/cbo.py RowCountPlanVisitor) says the left "
    "side is much smaller than the right, so the smaller side becomes "
    "the build/broadcast side (reference CostBasedOptimizer.scala). The "
    "original output column order is restored by a projection."
).boolean(True)
LOGICAL_JOIN_SWAP_RATIO = _conf(
    "spark.rapids.tpu.optimizer.joinStrategy.swapRatio").doc(
    "Hysteresis for the cost-based build-side swap: the estimated right "
    "(build) side must exceed the left side by this factor before the "
    "sides are swapped, so near-equal estimates (which are noisy) never "
    "flip the plan shape."
).double(1.5)
PLAN_CACHE_ENABLED = _conf("spark.rapids.tpu.plan.cache.enabled").doc(
    "Process-wide plan cache owned by the serving scheduler: a "
    "normalized-logical-plan + schema + conf fingerprint maps to the "
    "fully converted physical plan with literal parameter slots; hits "
    "bypass physical planning and override conversion and only re-bind "
    "literal slots (docs/serving.md \"Plan cache & logical optimizer\")."
).boolean(True)
PLAN_CACHE_MAX_ENTRIES = _conf("spark.rapids.tpu.plan.cache.maxEntries").doc(
    "Plan-cache capacity; least-recently-used entries are evicted past "
    "this bound."
).integer(256)
UDF_COMPILER_ENABLED = _conf("spark.rapids.sql.udfCompiler.enabled").doc(
    "Translate row python UDF bytecode into columnar device expressions "
    "where possible (reference udf-compiler/ LogicalPlanRules); "
    "untranslatable UDFs keep the row fallback.").boolean(False)
PYTHON_UDF_WORKERS = _conf("spark.rapids.sql.python.numWorkers").doc(
    "Number of separate python worker processes for pandas/arrow UDF "
    "execution (Arrow-IPC exchange; reference GpuArrowEvalPythonExec + "
    "python/rapids/worker.py). 0 runs UDFs in-process. UDFs that cannot "
    "pickle always run in-process.").integer(0)
CONCURRENT_PYTHON_WORKERS = _conf(
    "spark.rapids.python.concurrentPythonWorkers").doc(
    "Admission semaphore: how many python UDF workers may run "
    "concurrently (reference PythonWorkerSemaphore.scala:98). 0 means "
    "as many as numWorkers.").integer(0)

# ---------------------------------------------------------------------------
# Operator toggles (reference: spark.rapids.sql.exec.* generated per rule)
# ---------------------------------------------------------------------------
HASH_AGG_ENABLED = _conf("spark.rapids.sql.exec.HashAggregateExec").doc(
    "Enable TPU hash aggregation.").boolean(True)
IN_MEMORY_SCAN_ENABLED = _conf("spark.rapids.sql.exec.InMemoryTableScanExec").doc(
    "Enable the TPU device-cached relation scan.").boolean(True)
SORT_ENABLED = _conf("spark.rapids.sql.exec.SortExec").doc(
    "Enable TPU sort.").boolean(True)
JOIN_ENABLED = _conf("spark.rapids.sql.exec.ShuffledHashJoinExec").doc(
    "Enable TPU shuffled hash join.").boolean(True)
BROADCAST_JOIN_ENABLED = _conf("spark.rapids.sql.exec.BroadcastHashJoinExec").doc(
    "Enable TPU broadcast hash join.").boolean(True)
WINDOW_ENABLED = _conf("spark.rapids.sql.exec.WindowExec").doc(
    "Enable TPU window functions.").boolean(True)
PROJECT_ENABLED = _conf("spark.rapids.sql.exec.ProjectExec").doc(
    "Enable TPU projection.").boolean(True)
RANGE_ENABLED = _conf("spark.rapids.sql.exec.RangeExec").doc(
    "Enable TPU range.").boolean(True)
UNION_ENABLED = _conf("spark.rapids.sql.exec.UnionExec").doc(
    "Enable TPU union.").boolean(True)
LOCAL_LIMIT_ENABLED = _conf("spark.rapids.sql.exec.LocalLimitExec").doc(
    "Enable TPU local limit.").boolean(True)
GLOBAL_LIMIT_ENABLED = _conf("spark.rapids.sql.exec.GlobalLimitExec").doc(
    "Enable TPU global limit.").boolean(True)
TOPN_ENABLED = _conf("spark.rapids.sql.exec.TakeOrderedAndProjectExec").doc(
    "Enable TPU top-N (sort+limit fusion).").boolean(True)
SAMPLE_ENABLED = _conf("spark.rapids.sql.exec.SampleExec").doc(
    "Enable TPU sampling.").boolean(True)
BNLJ_ENABLED = _conf("spark.rapids.sql.exec.BroadcastNestedLoopJoinExec").doc(
    "Enable TPU broadcast nested-loop join.").boolean(True)
EXCHANGE_ENABLED = _conf("spark.rapids.sql.exec.ShuffleExchangeExec").doc(
    "Enable TPU shuffle exchange.").boolean(True)
FILE_SCAN_ENABLED = _conf("spark.rapids.sql.exec.FileSourceScanExec").doc(
    "Enable TPU file-source scans.").boolean(True)
GENERATE_ENABLED = _conf("spark.rapids.sql.exec.GenerateExec").doc(
    "Enable TPU generate (explode/posexplode/stack/json_tuple).").boolean(True)
EXPAND_ENABLED = _conf("spark.rapids.sql.exec.ExpandExec").doc(
    "Enable TPU expand (grouping sets).").boolean(True)
FILTER_ENABLED = _conf("spark.rapids.sql.exec.FilterExec").doc(
    "Enable TPU filter.").boolean(True)

CARTESIAN_ENABLED = _conf("spark.rapids.sql.exec.CartesianProductExec").doc(
    "Enable the TPU cartesian product.").boolean(True)
WRITE_EXEC_ENABLED = _conf("spark.rapids.sql.exec.DataWritingCommandExec").doc(
    "Enable the TPU data-writing command (writes run through the override "
    "engine with tagging and metrics).").boolean(True)
SUBQUERY_BROADCAST_ENABLED = _conf(
    "spark.rapids.sql.exec.SubqueryBroadcastExec").doc(
    "Enable the TPU subquery broadcast (dynamic partition pruning key "
    "collection).").boolean(True)
SYMMETRIC_JOIN_ENABLED = _conf(
    "spark.rapids.sql.join.useShuffledSymmetricHashJoin").doc(
    "Use the symmetric shuffled hash join, which picks the build side "
    "per partition by materialized size instead of always building on the "
    "right (reference GpuShuffledSymmetricHashJoinExec)."
).boolean(True)
PARQUET_WRITE_ENABLED = _conf(
    "spark.rapids.sql.format.parquet.write.enabled").doc(
    "Enable accelerated parquet writes.").boolean(True)
ORC_WRITE_ENABLED = _conf("spark.rapids.sql.format.orc.write.enabled").doc(
    "Enable accelerated ORC writes.").boolean(True)

STABLE_SORT = _conf("spark.rapids.sql.stableSort.enabled").doc(
    "Force stable sorts (reference RapidsConf stableSort)."
).boolean(False)

AUTO_BROADCAST_JOIN_THRESHOLD = _conf("spark.sql.autoBroadcastJoinThreshold").doc(
    "Broadcast the build side of an equi-join when its estimated size is below "
    "this many bytes (-1 disables)."
).bytes(10 * 1024 * 1024)

JOIN_SIZED_BUILD_HEURISTIC = _conf("spark.rapids.sql.join.buildSideRows.max").doc(
    "Max build-side rows before a shuffled hash join sub-partitions its inputs "
    "(reference GpuSubPartitionHashJoin)."
).integer(1 << 22)

# ---------------------------------------------------------------------------
# Metrics / profiling / debug (reference GpuExec.scala:41-61, profiler.scala)
# ---------------------------------------------------------------------------
METRICS_LEVEL = _conf("spark.rapids.sql.metrics.level").doc(
    "ESSENTIAL, MODERATE, or DEBUG metric verbosity (reference GpuMetric levels)."
).string("MODERATE")

PROFILE_PATH_PREFIX = _conf("spark.rapids.profile.pathPrefix").doc(
    "If set, write jax profiler traces for task execution under this path "
    "(reference spark.rapids.profile.* CUPTI profiler)."
).string(None)

TRACE_ENABLED = _conf("spark.rapids.tpu.trace.enabled").doc(
    "Query timeline tracing (docs/observability.md): record a span tree "
    "per query — query → partition task → operator → shuffle map task — "
    "with instant events for opjit/compiled dispatches (kind + cache "
    "hit/miss), audited device→host syncs, HBM alloc/spill/semaphore "
    "waits, shuffle map/reduce/fetch-retry, transient device-error "
    "retries, and chaos injections. Exported as Chrome trace-event JSON "
    "(perfetto-loadable), session.explain(\"metrics\"), and the "
    "session.last_query_profile() diagnostics bundle. Near-zero overhead "
    "when off (a module-flag check per site)."
).commonly_used().boolean(False)

TRACE_BUFFER_EVENTS = _conf("spark.rapids.tpu.trace.bufferEvents").doc(
    "Ring-buffer capacity of the query tracer in records (one span costs "
    "two records, one instant event one). On overflow the oldest records "
    "are overwritten and the diagnostics bundle reports the drop count "
    "(its reconciliation downgrades to 'overflow' instead of disagreeing "
    "silently)."
).integer(262144)

TRACE_CATEGORIES = _conf("spark.rapids.tpu.trace.categories").doc(
    "Comma-separated event/span categories to record (op, task, dispatch, "
    "sync, memory, shuffle, shuffle.map, retry, chaos); empty records "
    "everything. Note that filtering out 'dispatch' or 'sync' makes the "
    "bundle's reconciliation against calls_by_kind / the SyncLedger "
    "report a mismatch by construction."
).string_list([])

TRACE_TAG = _conf("spark.rapids.tpu.trace.tag").doc(
    "Stem prefix for traced-query names and their artifact files "
    "(<tag>-<n>.trace.json instead of query-<n>.trace.json) — bench.py "
    "tags each stage so artifacts from different stages never collide."
).string(None)

TRACE_DIR = _conf("spark.rapids.tpu.trace.dir").doc(
    "When set (and tracing is enabled), every traced query writes its "
    "Chrome trace (<query>.trace.json) and diagnostics bundle "
    "(<query>.profile.json) under this directory; the paths are recorded "
    "in last_query_profile()['artifacts']. bench.py points this at its "
    "artifact directory so each stage ships a loadable trace."
).string(None)

TRACE_MAX_CONCURRENT = _conf(
    "spark.rapids.tpu.trace.maxConcurrentQueries").doc(
    "Capacity cap on simultaneously traced queries (each armed tracer "
    "owns one ring buffer of bufferEvents records). Tracing is per-query: "
    "N concurrent sessions each trace their own query with independent "
    "span trees and reconciliation. A query arriving beyond the cap runs "
    "untraced and increments the always-on trace.dropped_queries registry "
    "counter — never a silent drop (docs/observability.md)."
).integer(16)

OBS_METRICS_ENABLED = _conf("spark.rapids.tpu.obs.metrics.enabled").doc(
    "The always-on process-wide metrics registry (docs/observability.md "
    "\"Metrics registry\"): counters, gauges and log2-bucket histograms — "
    "query latency p50/p95/p99 and rows/s, HBM high-water and pressure "
    "events, spill bytes, cache hit rates, device-retry and chaos counts. "
    "Read via session.metrics_snapshot() or `python -m tools.obs_report`. "
    "The hot path is one dict lookup plus an in-place add; disable only "
    "to rule the registry out while debugging."
).boolean(True)

OBS_FLIGHT_EVENTS = _conf("spark.rapids.tpu.obs.flightRecorderEvents").doc(
    "Ring capacity of the always-on crash flight recorder (notable events "
    "only: query begin/end, chaos injections, device retries, HBM "
    "pressure/OOM, disk spills, fetch retries). The last events land in "
    "the postmortem bundle when a query dies hard."
).integer(512)

OBS_COLLECTIVE_WATCHDOG_MS = _conf(
    "spark.rapids.tpu.obs.collectiveWatchdogMs").doc(
    "Collective watchdog (docs/observability.md \"Mesh profiling\"): a "
    "mesh collective exchange whose launch+wait window exceeds this many "
    "milliseconds emits a flight-recorder event (mesh.watchdog) and the "
    "mesh.watchdog_fired registry counter WHILE the wait is still "
    "blocked — on real hardware a hung chip manifests exactly as an "
    "unbounded collective wait, and without the watchdog it is "
    "indistinguishable from a slow one. 0 disables."
).integer(30000)

OBS_COLLECTIVE_WATCHDOG_FATAL_MS = _conf(
    "spark.rapids.tpu.obs.collectiveWatchdogFatalMs").doc(
    "When > 0, a collective still blocked after this many milliseconds "
    "dumps a postmortem bundle under spark.rapids.tpu.obs.postmortemDir "
    "(the incident artifact exists even if the process never returns "
    "from the wait) and counts mesh.watchdog_fatal. Keep well above "
    "collectiveWatchdogMs; 0 (default) disables the fatal tier."
).integer(0)

OBS_MESH_STRAGGLER_FACTOR = _conf(
    "spark.rapids.tpu.obs.meshStragglerFactor").doc(
    "Straggler threshold for the mesh efficiency profiler: an exchange "
    "whose heaviest chip receives more than this multiple of the median "
    "per-chip rows reports that chip as the straggler (skew table in "
    "last_query_profile()['mesh'] and the MULTICHIP summary) and feeds "
    "the mesh.straggler_wait_ms histogram."
).double(2.0)

OBS_POSTMORTEM_DIR = _conf("spark.rapids.tpu.obs.postmortemDir").doc(
    "When set, a fatal device error, an exhausted transient-retry loop, "
    "or a genuine HBM budget OOM writes a postmortem bundle "
    "(postmortem-<reason>-<ms>.json) under this directory: the flight "
    "recorder's last-K events, the full metrics-registry snapshot, "
    "HBM/semaphore/spill state, the active query names and the failure "
    "itself (docs/observability.md \"Postmortem bundle\")."
).string(None)

TEST_RETRY_OOM_INJECTION = _conf("spark.rapids.memory.tpu.state.debug.retryOomInjection").doc(
    "Testing only: inject TpuRetryOOM/TpuSplitAndRetryOOM at allocation points "
    "(reference RmmSpark.forceRetryOOM test hooks)."
).internal().string(None)

# ---------------------------------------------------------------------------
# Robustness: transient device-error retry, shuffle integrity, and the seeded
# chaos fault-injection harness (docs/robustness.md; reference
# RmmSpark.forceRetryOOM / the spark-rapids fault-injection tool, SURVEY §7)
# ---------------------------------------------------------------------------
DEVICE_RETRY_MAX_ATTEMPTS = _conf("spark.rapids.tpu.deviceRetry.maxAttempts").doc(
    "How many times a device dispatch (opjit program call, compiled-stage "
    "launch, ICI block fetch, pipelined shuffle map task) is re-attempted "
    "after a TRANSIENT device/runtime error (XLA status UNAVAILABLE, "
    "RESOURCE_EXHAUSTED, ABORTED, CANCELLED) before the error propagates. "
    "Fatal statuses (INTERNAL, DATA_LOSS, ...) are never retried — they go "
    "straight to the fatal-failure hook (spark.rapids.tpu.coreDump.dir)."
).integer(4)

DEVICE_RETRY_BACKOFF_BASE_MS = _conf(
    "spark.rapids.tpu.deviceRetry.backoffBaseMs").doc(
    "Base delay of the transient-device-error retry backoff; attempt n "
    "sleeps min(base * 2^(n-1), backoffMaxMs) scaled by a random jitter in "
    "[0.5, 1.0]. Blocked time accumulates in the deviceRetryBlockTimeNs "
    "task metric."
).double(10.0)

DEVICE_RETRY_BACKOFF_MAX_MS = _conf(
    "spark.rapids.tpu.deviceRetry.backoffMaxMs").doc(
    "Upper bound on a single transient-retry backoff sleep."
).double(2000.0)

# ---------------------------------------------------------------------------
# Query lifecycle & multi-tenant scheduler (docs/robustness.md "Query
# lifecycle"; serving/scheduler.py — the GpuSemaphore-admission analogue
# lifted from per-task to per-query, SURVEY §2.4/§7)
# ---------------------------------------------------------------------------
QUERY_TIMEOUT_MS = _conf("spark.rapids.tpu.query.timeoutMs").doc(
    "Default per-query deadline in milliseconds (0 disables). A query "
    "past its deadline is cancelled COOPERATIVELY: the next checkpoint "
    "(partition-task start, batch pull, exchange map task / reduce "
    "fetch, mesh collective launch, UDF worker round-trip) raises "
    "QueryDeadlineExceeded and the unwind releases every permit, HBM "
    "byte, spill file and the query's tracer. df.collect(timeout=seconds)"
    " overrides it per call; session.cancel() cancels without a deadline."
).commonly_used().integer(0)

QUERY_RETRY_BUDGET = _conf("spark.rapids.tpu.query.retryBudget").doc(
    "Total TRANSIENT device-error retries one query may consume across "
    "all of its tasks (each site's attempts stay bounded by "
    "spark.rapids.tpu.deviceRetry.maxAttempts). Past the budget the next "
    "transient error fails that query alone — a flapping query cannot "
    "sit in retry/backoff loops holding the shared pool's permits while "
    "healthy queries queue behind it."
).integer(64)

SCHED_MAX_CONCURRENT = _conf(
    "spark.rapids.tpu.sched.maxConcurrentQueries").doc(
    "How many admitted queries may execute concurrently against the "
    "device pool (the per-query analogue of concurrentTpuTasks: admitted "
    "queries' tasks still contend on the TpuSemaphore). Queued "
    "submissions past this bound wait FIFO with round-robin fairness "
    "across sessions."
).commonly_used().integer(8)

SCHED_MAX_QUEUE = _conf("spark.rapids.tpu.sched.maxQueuedQueries").doc(
    "Bound on the scheduler's admission queue across all sessions. A "
    "submission past the bound is rejected immediately with the typed "
    "QueryQueueFull backpressure error — shedding load at the front door "
    "instead of stacking working sets until HBM pressure OOMs every "
    "query on the device."
).integer(64)

SCHED_HBM_WATERMARK = _conf(
    "spark.rapids.tpu.sched.hbmAdmissionWatermark").doc(
    "Admit a queued query only while HbmBudget usage is at or below this "
    "fraction of the budget (and a concurrency slot is free). Waived "
    "when no query is running, so admission always makes progress even "
    "if parked state keeps usage high."
).double(0.9)

QUERY_PRIORITY = _conf("spark.rapids.tpu.query.priority").doc(
    "SLO priority class for this session's queries: 'interactive', "
    "'batch' or 'background' (docs/serving.md). Admission is strict "
    "class precedence with earliest-deadline-first within a class; "
    "under sustained overload the scheduler sheds the LOWEST queued or "
    "running class first, returning a typed QueryShed result with a "
    "retry-after hint. df.collect(priority=...) overrides per call."
).commonly_used().string("interactive")

SCHED_CLASS_AGING_MS = _conf("spark.rapids.tpu.sched.classAgingMs").doc(
    "Anti-starvation bound for the SLO class queues: a ticket queued "
    "longer than this is promoted over class precedence (oldest such "
    "ticket first), so background work still drains under a persistent "
    "interactive load. 0 disables aging (strict precedence only)."
).double(10000.0)

SCHED_TENANT_HBM_QUOTA = _conf(
    "spark.rapids.tpu.sched.tenantHbmQuota").doc(
    "Per-tenant HBM quota as a fraction of the HbmBudget, layered ON TOP "
    "of the global admission watermark: a session whose live queries' "
    "attributed device bytes exceed quota x budget has its next query "
    "queue (sched.quota_defer_total) even when the device has headroom. "
    "<= 0 disables per-tenant quotas (the default)."
).double(0.0)

SCHED_SHED_AFTER_MS = _conf("spark.rapids.tpu.sched.shedAfterMs").doc(
    "Sustained-overload load-shedding bound: when a queued query has "
    "waited past this with every concurrency slot held and a STRICTLY "
    "lower class running, the scheduler sheds the lowest running class "
    "through the cooperative cancel token (one victim per admission "
    "pass; the unwind is the TL020-proven release path). The shed "
    "client gets a typed QueryShed result with a retry-after hint. "
    "0 disables overload shedding; queue-full shedding of a strictly "
    "lower queued class is always on."
).double(5000.0)

SHUFFLE_CHECKSUM_ENABLED = _conf(
    "spark.rapids.tpu.shuffle.checksum.enabled").doc(
    "Embed an xxhash64 checksum in every serialized shuffle block and "
    "verify it on read (the Spark analogue is SPARK-35275 shuffle "
    "checksums). A mismatched or truncated block raises FetchFailedError "
    "so the exchange re-materializes the producing map task instead of "
    "surfacing an arbitrary deserialization error."
).boolean(True)

SHUFFLE_FETCH_RETRY_MAX = _conf(
    "spark.rapids.tpu.shuffle.fetchRetry.maxAttempts").doc(
    "How many times a reduce task re-materializes lost/corrupted map "
    "outputs (FetchFailedError) before giving up; the final error chains "
    "the last FetchFailedError as its cause (Spark: stage-retry bound)."
).integer(4)

CHAOS_ENABLED = _conf("spark.rapids.tpu.test.chaos.enabled").doc(
    "Testing only: arm the seeded chaos fault injector. Named injection "
    "sites woven through the stack (hbm.alloc, spill.to_host, "
    "spill.to_disk, device.dispatch, shuffle.serialize, shuffle.write, "
    "shuffle.read, ici.fetch, pipeline.task) draw from per-site PRNGs and "
    "raise configured fault kinds at the configured probability "
    "(docs/robustness.md)."
).boolean(False)

CHAOS_SEED = _conf("spark.rapids.tpu.test.chaos.seed").doc(
    "Chaos injector seed. Each site derives an independent deterministic "
    "PRNG stream from (seed, site), so a run's injection trace is "
    "replayable per site regardless of thread interleaving."
).integer(0)

CHAOS_SITES = _conf("spark.rapids.tpu.test.chaos.sites").doc(
    "Comma-separated injection sites to arm; empty means every site."
).string_list([])

CHAOS_KINDS = _conf("spark.rapids.tpu.test.chaos.kinds").doc(
    "Comma-separated fault kinds to draw from (retry_oom, split_oom, "
    "transient, fatal, corrupt, truncate, io_error, latency); empty means "
    "every kind applicable at the site. OOM kinds only fire inside a "
    "retry-framework scope (where they are healable by design); corrupt/"
    "truncate only apply at byte-stream sites."
).string_list([])

CHAOS_PROBABILITY = _conf("spark.rapids.tpu.test.chaos.probability").doc(
    "Per-site-visit probability of injecting a fault."
).double(0.05)

CHAOS_MAX_INJECTIONS = _conf("spark.rapids.tpu.test.chaos.maxInjections").doc(
    "Cap on total randomized injections per configure (0 = unbounded) — a "
    "guardrail so high probabilities cannot starve a query forever."
).integer(0)

CHAOS_LATENCY_MS = _conf("spark.rapids.tpu.test.chaos.latencyMs").doc(
    "Upper bound of the injected delay for the `latency` fault kind."
).double(2.0)


# ---------------------------------------------------------------------------
# Device-subset sizing knobs (kernels consult these through the session's
# apply_kernel_tunables at session construction)
# ---------------------------------------------------------------------------

REGEX_MAX_DEVICE_ROW_BYTES = _conf(
    "spark.rapids.sql.regexp.maxDeviceRowBytes").doc(
    "Longest string row the device regex DFA walks (rlike); longer rows "
    "route the batch to the host engine (reference "
    "spark.rapids.sql.regexp.enabled + RegexComplexityEstimator sizing)."
).integer(4096)

REGEX_MAX_SPAN_ROW_BYTES = _conf(
    "spark.rapids.sql.regexp.maxSpanRowBytes").doc(
    "Longest string row for device regexp_replace/extract span matching "
    "(the walk is O(bytes x row_len))."
).integer(512)

JSON_DEVICE_SCAN_MAX_ROW_BYTES = _conf(
    "spark.rapids.sql.json.maxDeviceRowBytes").doc(
    "Longest JSON document the device get_json_object scan processes; "
    "longer rows route to the host engine."
).integer(4096)

HASH_DEVICE_MAX_STRING_BYTES = _conf(
    "spark.rapids.tpu.hash.maxDeviceStringBytes").doc(
    "Longest string a device hash kernel (murmur3/xxhash64/hive-hash) "
    "processes with the padded byte-matrix loop; columns with longer rows "
    "hash on the host (O(rows x max_len) device cost)."
).integer(4096)

REGEX_MAX_DFA_STATES = _conf(
    "spark.rapids.tpu.regex.maxDfaStates").doc(
    "Upper bound on device regex DFA states; patterns compiling larger "
    "fall back to the host engine (reference regex transpiler state cap)."
).integer(128)

COMPILED_JOIN_DIM_CACHE_SIZE = _conf(
    "spark.rapids.tpu.join.compiled.dimCacheSize").doc(
    "LRU entries in the cross-execution dimension build cache of the "
    "compiled star-join stage; each entry pins its HBM key/payload arrays."
).integer(8)

EXECUTOR_HEARTBEAT_TIMEOUT_SECONDS = _conf(
    "spark.rapids.shuffle.executor.heartbeatTimeoutSeconds").doc(
    "A multi-process executor worker missing heartbeats for this long is "
    "declared lost and its tasks re-run (reference "
    "RapidsShuffleHeartbeatManager intervals)."
).double(3.0)

UDF_WORKER_TIMEOUT_SECONDS = _conf(
    "spark.rapids.sql.python.workerTimeoutSeconds").doc(
    "Seconds a python UDF may run in its worker before the worker is "
    "killed and replaced (reference python worker watchdog)."
).integer(120)

SHUFFLE_HEARTBEAT_TIMEOUT_SECONDS = _conf(
    "spark.rapids.shuffle.heartbeat.timeoutSeconds").doc(
    "Peer liveness window for the shuffle heartbeat registry; peers silent "
    "longer than this are reported lost and their map outputs invalidated "
    "(reference RapidsShuffleHeartbeatManager timeout)."
).integer(30)

CAST_FLOAT_TO_STRING_ENABLED = _conf(
    "spark.rapids.sql.castFloatToString.enabled").doc(
    "Enable float->string casts on TPU (Java-exact shortest-round-trip "
    "formatting; reference castFloatToString incompatibility switch)."
).boolean(True)

CAST_STRING_TO_FLOAT_ENABLED = _conf(
    "spark.rapids.sql.castStringToFloat.enabled").doc(
    "Enable string->float casts on TPU (reference castStringToFloat "
    "incompatibility switch)."
).boolean(True)

CAST_STRING_TO_TIMESTAMP_ENABLED = _conf(
    "spark.rapids.sql.castStringToTimestamp.enabled").doc(
    "Enable string->timestamp casts on TPU (reference "
    "castStringToTimestamp incompatibility switch)."
).boolean(True)

VARIABLE_FLOAT_AGG_ENABLED = _conf(
    "spark.rapids.sql.variableFloatAgg.enabled").doc(
    "Allow float aggregations whose result can vary run to run with "
    "parallelism (sum/avg ordering; reference variableFloatAgg switch). "
    "When false, float sum/avg aggregations fall back to the CPU."
).boolean(True)

BUCKETING_WRITE_ENABLED = _conf(
    "spark.rapids.sql.format.write.bucketing.enabled").doc(
    "Enable bucketBy writes (per-bucket files with a bucket-spec sidecar; "
    "reference GpuFileFormatWriter bucketing)."
).boolean(True)

BUCKETING_READ_PRUNE_ENABLED = _conf(
    "spark.rapids.sql.format.read.bucketPruning.enabled").doc(
    "Prune bucketed files by equality filters on the bucket column at scan "
    "time (reference GpuFileSourceScanExec bucket pruning)."
).boolean(True)


class RapidsConf:
    """Immutable snapshot of settings, one per query compilation.

    Reference: `new RapidsConf(plan.conf)` per-query (GpuOverrides.scala:4565).
    """

    def __init__(self, settings: Optional[Dict[str, str]] = None):
        self._settings = dict(settings or {})
        self._cache: Dict[str, Any] = {}

    def get(self, entry: ConfEntry) -> Any:
        if entry.key not in self._cache:
            self._cache[entry.key] = entry.get(self._settings)
        return self._cache[entry.key]

    def get_raw(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._settings.get(key, default)

    def is_op_enabled(self, key: str, default: bool = True) -> bool:
        raw = self._settings.get(key)
        return default if raw is None else _parse_bool(raw)

    # Convenience accessors used on hot paths
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain_only(self) -> bool:
        return str(self.get(SQL_MODE)).lower() == "explainonly"

    @property
    def ansi_enabled(self) -> bool:
        return self.get(ANSI_ENABLED)

    @property
    def batch_size_rows(self) -> int:
        return self.get(BATCH_SIZE_ROWS)

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    def with_overrides(self, **kv: str) -> "RapidsConf":
        s = dict(self._settings)
        s.update({k.replace("__", "."): v for k, v in kv.items()})
        return RapidsConf(s)


def declare_expression_flags(names) -> None:
    """One `spark.rapids.sql.expression.<Name>` boolean entry per registered
    expression rule — the reference generates exactly this conf per
    GpuOverrides rule and lists them in the RapidsConf docs. The tagging
    layer (plan/meta.py) consults these keys on every wrapped expression;
    declaring them here types and documents them. Called by
    plan/typechecks.py once its rule registry is populated."""
    for n in sorted(set(names)):
        key = f"spark.rapids.sql.expression.{n}"
        if key in REGISTRY.entries:
            continue
        _conf(key).doc(f"Enable expression {n} on TPU.").boolean(True)


_DEFAULT = RapidsConf()


def default_conf() -> RapidsConf:
    return _DEFAULT
