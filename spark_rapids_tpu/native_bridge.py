"""ctypes bridge to the C++ host runtime (native/src/native.cpp).

The reference consumes its native kernels through JNI (`ai.rapids.cudf`,
spark-rapids-jni); here the host-side native surface (Spark-exact murmur3,
fixed-width row conversion, zstd block codec) loads via ctypes, auto-building
with `make -C native` on first use. Every caller has a pure-python fallback, so
a missing toolchain degrades performance, not correctness.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

log = logging.getLogger("spark_rapids_tpu")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO_PATH = os.path.join(_REPO_ROOT, "native", "build", "libsr_tpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_load_failed = False

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u32p = ctypes.POINTER(ctypes.c_uint32)
_f32p = ctypes.POINTER(ctypes.c_float)
_f64p = ctypes.POINTER(ctypes.c_double)


def _build() -> bool:
    mk = os.path.join(_REPO_ROOT, "native")
    try:
        subprocess.run(["make", "-C", mk], check=True, capture_output=True,
                       timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception as e:  # noqa: BLE001 - degrade to python fallback
        log.warning("native build failed (%s); using python fallbacks", e)
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_SO_PATH) and not _build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError as e:
            log.warning("cannot load native lib: %s", e)
            _load_failed = True
            return None
        for name, args, res in [
            ("murmur3_i32", [_i32p, _u8p, ctypes.c_int64, _u32p], None),
            ("murmur3_i64", [_i64p, _u8p, ctypes.c_int64, _u32p], None),
            ("murmur3_f32", [_f32p, _u8p, ctypes.c_int64, _u32p], None),
            ("murmur3_f64", [_f64p, _u8p, ctypes.c_int64, _u32p], None),
            ("murmur3_str", [_i32p, _u8p, _u8p, ctypes.c_int64, _u32p], None),
            ("pmod_partition", [_u32p, ctypes.c_int64, ctypes.c_int32, _i32p], None),
            ("zstd_compress_bound", [ctypes.c_int64], ctypes.c_int64),
            ("zstd_compress",
             [_u8p, ctypes.c_int64, _u8p, ctypes.c_int64, ctypes.c_int32],
             ctypes.c_int64),
            ("zstd_decompress",
             [_u8p, ctypes.c_int64, _u8p, ctypes.c_int64], ctypes.c_int64),
        ]:
            fn = getattr(lib, name)
            fn.argtypes = args
            fn.restype = res
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def _ptr(arr: np.ndarray, ptype):
    return arr.ctypes.data_as(ptype)


def murmur3_column(dtype_kind: str, values: np.ndarray,
                   validity: Optional[np.ndarray],
                   seeds: np.ndarray,
                   offsets: Optional[np.ndarray] = None,
                   chars: Optional[np.ndarray] = None) -> bool:
    """In-place update of seeds (uint32). Returns False if native unavailable."""
    lib = get_lib()
    if lib is None:
        return False
    n = len(seeds)
    v = _ptr(np.ascontiguousarray(validity, np.uint8), _u8p) \
        if validity is not None else ctypes.cast(None, _u8p)
    sp = _ptr(seeds, _u32p)
    if dtype_kind == "i32":
        lib.murmur3_i32(_ptr(np.ascontiguousarray(values, np.int32), _i32p), v, n, sp)
    elif dtype_kind == "i64":
        lib.murmur3_i64(_ptr(np.ascontiguousarray(values, np.int64), _i64p), v, n, sp)
    elif dtype_kind == "f32":
        lib.murmur3_f32(_ptr(np.ascontiguousarray(values, np.float32), _f32p), v, n, sp)
    elif dtype_kind == "f64":
        lib.murmur3_f64(_ptr(np.ascontiguousarray(values, np.float64), _f64p), v, n, sp)
    elif dtype_kind == "str":
        lib.murmur3_str(_ptr(np.ascontiguousarray(offsets, np.int32), _i32p),
                        _ptr(np.ascontiguousarray(chars, np.uint8), _u8p),
                        v, n, sp)
    else:
        return False
    return True


def zstd_compress(data: bytes, level: int = 1) -> Optional[bytes]:
    lib = get_lib()
    if lib is None:
        return None
    src = np.frombuffer(data, np.uint8)
    bound = lib.zstd_compress_bound(len(data))
    dst = np.empty(bound, np.uint8)
    r = lib.zstd_compress(_ptr(src, _u8p), len(data), _ptr(dst, _u8p),
                          bound, level)
    if r < 0:
        return None
    return dst[:r].tobytes()


def zstd_decompress(data: bytes, raw_len: int) -> Optional[bytes]:
    lib = get_lib()
    if lib is None:
        return None
    src = np.frombuffer(data, np.uint8)
    dst = np.empty(raw_len, np.uint8)
    r = lib.zstd_decompress(_ptr(src, _u8p), len(data), _ptr(dst, _u8p), raw_len)
    if r < 0:
        return None
    return dst[:r].tobytes()
