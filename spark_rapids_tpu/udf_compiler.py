"""UDF compiler: Python bytecode → columnar expression tree.

Reference: udf-compiler/ (5809 LoC) — CFG extraction (CFG.scala) + abstract
interpretation of JVM opcodes rebuilding Catalyst expressions
(CatalystExpressionBuilder.scala:45), injected as a logical rule
(LogicalPlanRules.scala:29) behind `spark.rapids.sql.udfCompiler.enabled`.

TPU analogue: abstract interpretation of CPython bytecode (`dis`) over a
symbolic value stack. Straight-line arithmetic/comparison/boolean logic,
conditional expressions (both branches executed symbolically and merged with
`If`), `is None` tests, `in (tuple)` membership, math.* / builtins calls.
Anything else — loops, stores, attribute access, unknown globals — makes
`compile_python_udf` return None and the UDF stays a row-python fallback,
mirroring the reference's bail-to-CPU contract.
"""

from __future__ import annotations

import dis
import math
from typing import Any, Callable, List, Optional, Sequence

from .expressions.arithmetic import (Abs, Add, Divide, IntegralDivide,
                                     Multiply, Remainder, Subtract, UnaryMinus)
from .expressions.base import Expression, Literal
from .expressions.bitwise import (BitwiseAnd, BitwiseNot, BitwiseOr,
                                  BitwiseXor, ShiftLeft, ShiftRight)
from .expressions.cast import Cast
from .expressions.conditional import Greatest, If, Least
from .expressions.mathexprs import (Acos, Asin, Atan, Atan2, Cbrt, Ceil, Cos,
                                    Cosh, Exp, Expm1, Floor, Log, Log1p, Log2,
                                    Log10, Pow, Signum, Sin, Sinh, Sqrt, Tan,
                                    Tanh)
from .expressions.nullexprs import IsNotNull, IsNull
from .expressions.predicates import (EqualTo, GreaterThan, GreaterThanOrEqual,
                                     In, LessThan, LessThanOrEqual, Not)
from .types import BooleanType, DataType

_MAX_STEPS = 500


class _Bail(Exception):
    """Untranslatable construct — fall back to the row UDF."""


def _bin(cls):
    return lambda a, b: cls(a, b)


_MATH_FNS = {
    math.sqrt: lambda x: Sqrt(x),
    math.exp: lambda x: Exp(x),
    math.expm1: lambda x: Expm1(x),
    math.log: lambda x, *rest: Log(x) if not rest else Divide(Log(x),
                                                              Log(rest[0])),
    math.log10: lambda x: Log10(x),
    math.log2: lambda x: Log2(x),
    math.log1p: lambda x: Log1p(x),
    math.sin: lambda x: Sin(x),
    math.cos: lambda x: Cos(x),
    math.tan: lambda x: Tan(x),
    math.asin: lambda x: Asin(x),
    math.acos: lambda x: Acos(x),
    math.atan: lambda x: Atan(x),
    math.atan2: lambda y, x: Atan2(y, x),
    math.sinh: lambda x: Sinh(x),
    math.cosh: lambda x: Cosh(x),
    math.tanh: lambda x: Tanh(x),
    math.floor: lambda x: Floor(x),
    math.ceil: lambda x: Ceil(x),
    math.pow: lambda a, b: Pow(a, b),
    math.cbrt: lambda x: Cbrt(x),
    math.fabs: lambda x: Abs(x),
    abs: lambda x: Abs(x),
    max: lambda *xs: Greatest(*xs),
    min: lambda *xs: Least(*xs),
}

def _is_float(dt: DataType) -> bool:
    from .types import DoubleType, FloatType
    return isinstance(dt, (DoubleType, FloatType))


def _promote(a: Expression, b: Expression):
    """Python numeric semantics: any float operand → double math; integer
    math widens to long (Python ints don't overflow at 32 bits)."""
    from .types import DoubleType, IntegralType, LongType
    target: DataType
    if _is_float(a.dtype) or _is_float(b.dtype):
        target = DoubleType()
    elif isinstance(a.dtype, IntegralType) and isinstance(b.dtype,
                                                          IntegralType):
        target = LongType()
    else:
        return a, b
    if a.dtype != target:
        a = Cast(a, target)
    if b.dtype != target:
        b = Cast(b, target)
    return a, b


def _py_arith(cls):
    def build(a, b):
        a, b = _promote(a, b)
        return cls(a, b)
    return build


def _py_truediv(a, b):
    from .types import DoubleType
    if not _is_float(a.dtype):
        a = Cast(a, DoubleType())
    if not _is_float(b.dtype):
        b = Cast(b, DoubleType())
    return Divide(a, b)  # Python / is always float division


def _py_floordiv(a, b):
    from .types import DoubleType, IntegralType
    if isinstance(a.dtype, IntegralType) and isinstance(b.dtype,
                                                        IntegralType):
        # exact integer path (doubles lose precision past 2^53): Spark's
        # integral divide truncates toward zero, Python floors — subtract 1
        # when the remainder is non-zero and the signs differ
        a, b = _promote(a, b)
        q = IntegralDivide(a, b)
        r = Remainder(a, b)
        zero = Literal(0)
        signs_differ = Not(EqualTo(LessThan(a, zero), LessThan(b, zero)))
        adjust = If(Not(EqualTo(r, zero)), signs_differ, Literal(False))
        return If(adjust, Subtract(q, Literal(1)), q)
    e = Floor(_py_truediv(a, b))  # Python // floors; Spark floor(double)→long
    return Cast(e, DoubleType())


def _py_mod(a, b):
    # Python % sign follows the divisor; Spark Remainder follows the
    # dividend: ((a % b) + b) % b matches Python for both signs (and stays
    # exact on the integer path).
    from .types import IntegralType
    a, b = _promote(a, b)
    if isinstance(a.dtype, IntegralType) and isinstance(b.dtype,
                                                        IntegralType):
        return Remainder(Add(Remainder(a, b), b), b)
    q = _py_floordiv(a, b)
    if q.dtype != a.dtype:
        q = Cast(q, a.dtype)
    return Subtract(a, Multiply(q, b))


def _py_shift(cls):
    def build(a, b):
        from .types import IntegralType, LongType
        if isinstance(a.dtype, IntegralType) and \
                not isinstance(a.dtype, LongType):
            a = Cast(a, LongType())  # Python ints don't wrap at 32 bits
        return cls(a, b)
    return build


_BINOPS = {
    "+": _py_arith(Add), "-": _py_arith(Subtract), "*": _py_arith(Multiply),
    "/": _py_truediv, "//": _py_floordiv, "%": _py_mod,
    "**": _py_arith(Pow), "&": _bin(BitwiseAnd), "|": _bin(BitwiseOr),
    "^": _bin(BitwiseXor), "<<": _py_shift(ShiftLeft),
    ">>": _py_shift(ShiftRight),
}


def _py_cmp(cls, nan_result: bool = False, null_result: Optional[bool] = None):
    """Python/IEEE comparison semantics: any NaN operand makes <,<=,>,>=,==
    False and != True (Spark instead orders NaN largest, hence the explicit
    guard). For == / !=, a None operand yields False / True in Python while
    SQL yields NULL — null_result pins the Python answer."""
    def build(a, b):
        from .expressions.nullexprs import IsNaN
        from .expressions.predicates import Or
        a, b = _promote(a, b)
        e: Expression = cls(a, b)
        nan_checks = [IsNaN(x) for x in (a, b) if _is_float(x.dtype)]
        if nan_checks:
            any_nan = nan_checks[0] if len(nan_checks) == 1 \
                else Or(nan_checks[0], nan_checks[1])
            e = If(any_nan, Literal(nan_result), e)
        if null_result is not None:
            from .expressions.predicates import And
            null_checks = [IsNull(x) for x in (a, b) if x.nullable]
            if null_checks:
                any_null = null_checks[0] if len(null_checks) == 1 \
                    else Or(null_checks[0], null_checks[1])
                e = If(any_null, Literal(null_result), e)
                # Python: None == None is True, None != None is False — the
                # inverse of the any-null answer; guard both-null first
                if len(null_checks) == 2:
                    e = If(And(null_checks[0], null_checks[1]),
                           Literal(not null_result), e)
        return e
    return build


_CMPOPS = {
    "<": _py_cmp(LessThan), "<=": _py_cmp(LessThanOrEqual),
    "==": _py_cmp(EqualTo, null_result=False),
    "!=": _py_cmp(lambda x, y: Not(EqualTo(x, y)), nan_result=True,
                  null_result=True),
    ">": _py_cmp(GreaterThan), ">=": _py_cmp(GreaterThanOrEqual),
}


def _as_expr(v: Any) -> Expression:
    if isinstance(v, Expression):
        return v
    if callable(v):
        raise _Bail("callable left on stack")
    return Literal(v)


def _truthy(v: Any) -> Expression:
    e = _as_expr(v)
    if not isinstance(e.dtype, BooleanType):
        raise _Bail("non-boolean branch condition")
    return e


class _SymExec:
    def __init__(self, fn: Callable, args: Sequence[Expression]):
        self.fn = fn
        self.args = list(args)
        code = fn.__code__
        if code.co_argcount != len(args):
            raise _Bail("arity mismatch")
        self.instrs = list(dis.get_instructions(fn))
        self.by_offset = {i.offset: idx for idx, i in enumerate(self.instrs)}
        self.steps = 0

    def resolve_global(self, name: str) -> Any:
        if name in self.fn.__globals__:
            return self.fn.__globals__[name]
        import builtins
        if hasattr(builtins, name):
            return getattr(builtins, name)
        raise _Bail(f"unknown global {name}")

    def run(self, idx: int, stack: List[Any]) -> Expression:
        instrs = self.instrs
        while True:
            self.steps += 1
            if self.steps > _MAX_STEPS:
                raise _Bail("too many steps (loop?)")
            instr = instrs[idx]
            op = instr.opname
            if op in ("RESUME", "NOP", "CACHE", "PRECALL", "PUSH_NULL",
                      "EXTENDED_ARG", "MAKE_CELL", "COPY_FREE_VARS"):
                idx += 1
            elif op == "RETURN_VALUE":
                return _as_expr(stack.pop())
            elif op == "RETURN_CONST":
                return Literal(instr.argval)
            elif op in ("LOAD_FAST", "LOAD_FAST_CHECK",
                        "LOAD_FAST_AND_CLEAR"):
                vi = self.fn.__code__.co_varnames.index(instr.argval)
                if vi >= len(self.args):
                    raise _Bail("local variable store/load unsupported")
                stack.append(self.args[vi])
                idx += 1
            elif op == "LOAD_CONST":
                stack.append(instr.argval)
                idx += 1
            elif op == "LOAD_DEREF":
                # closure cell holding a plain scalar → literal
                names = (self.fn.__code__.co_cellvars
                         + self.fn.__code__.co_freevars)
                ci = names.index(instr.argval)
                cells = (self.fn.__closure__ or ())
                if ci >= len(cells):
                    raise _Bail("cellvar unsupported")
                stack.append(cells[ci].cell_contents)
                idx += 1
            elif op in ("LOAD_GLOBAL", "LOAD_NAME"):
                stack.append(self.resolve_global(instr.argval))
                idx += 1
            elif op == "LOAD_ATTR":
                base = stack.pop()
                if isinstance(base, Expression):
                    raise _Bail("attribute access on column")
                stack.append(getattr(base, instr.argval.strip("()")
                                     if isinstance(instr.argval, str)
                                     else instr.argval))
                idx += 1
            elif op == "LOAD_METHOD":
                base = stack.pop()
                stack.append(getattr(base, instr.argval))
                idx += 1
            elif op == "BINARY_OP":
                rhs, lhs = stack.pop(), stack.pop()
                sym = instr.argrepr.rstrip("=") or instr.argrepr
                if sym not in _BINOPS:
                    raise _Bail(f"binary op {instr.argrepr}")
                if isinstance(lhs, Expression) or isinstance(rhs, Expression):
                    stack.append(_BINOPS[sym](_as_expr(lhs), _as_expr(rhs)))
                else:  # pure-constant folding on host
                    stack.append(self._const_binop(sym, lhs, rhs))
                idx += 1
            elif op == "COMPARE_OP":
                rhs, lhs = stack.pop(), stack.pop()
                sym = instr.argrepr.replace("bool(", "").rstrip(")")
                if sym not in _CMPOPS:
                    raise _Bail(f"compare {instr.argrepr}")
                stack.append(_CMPOPS[sym](_as_expr(lhs), _as_expr(rhs)))
                idx += 1
            elif op == "IS_OP":
                rhs, lhs = stack.pop(), stack.pop()
                if rhs is not None:
                    raise _Bail("'is' against non-None")
                e = IsNull(_as_expr(lhs))
                stack.append(Not(e) if instr.arg == 1 else e)
                idx += 1
            elif op == "CONTAINS_OP":
                container, needle = stack.pop(), stack.pop()
                if isinstance(container, Expression):
                    raise _Bail("'in' over a column")
                items = [Literal(x) for x in container]
                ne = _as_expr(needle)
                e: Expression = In(ne, items)
                if ne.nullable:
                    # Python: None in (…) → False (SQL IN would give NULL)
                    e = If(IsNull(ne), Literal(False), e)
                stack.append(Not(e) if instr.arg == 1 else e)
                idx += 1
            elif op == "UNARY_NEGATIVE":
                from .types import IntegralType, LongType
                e = _as_expr(stack.pop())
                if isinstance(e.dtype, IntegralType) and \
                        not isinstance(e.dtype, LongType):
                    e = Cast(e, LongType())  # Python ints don't wrap at 32 bit
                stack.append(UnaryMinus(e))
                idx += 1
            elif op == "UNARY_NOT":
                stack.append(Not(_truthy(stack.pop())))
                idx += 1
            elif op == "UNARY_INVERT":
                stack.append(BitwiseNot(_as_expr(stack.pop())))
                idx += 1
            elif op == "COPY":
                stack.append(stack[-instr.arg])
                idx += 1
            elif op == "SWAP":
                stack[-1], stack[-instr.arg] = stack[-instr.arg], stack[-1]
                idx += 1
            elif op == "POP_TOP":
                stack.pop()
                idx += 1
            elif op in ("JUMP_FORWARD", "JUMP_ABSOLUTE"):
                idx = self.by_offset[instr.argval]
            elif op == "JUMP_BACKWARD":
                raise _Bail("loop")
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE"):
                cond = _truthy(stack.pop())
                jump_idx = self.by_offset[instr.argval]
                fall = self.run(idx + 1, list(stack))
                jumped = self.run(jump_idx, list(stack))
                if op == "POP_JUMP_IF_FALSE":
                    return If(cond, fall, jumped)
                return If(cond, jumped, fall)
            elif op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                v = _as_expr(stack.pop())
                cond = IsNull(v)
                jump_idx = self.by_offset[instr.argval]
                fall = self.run(idx + 1, list(stack))
                jumped = self.run(jump_idx, list(stack))
                if op == "POP_JUMP_IF_NONE":
                    return If(cond, jumped, fall)
                return If(cond, fall, jumped)
            elif op == "CALL":
                # NULL sentinels (PUSH_NULL / LOAD_GLOBAL push-null bit) are
                # never materialized on our symbolic stack, so the layout here
                # is simply [callable, arg0..argN-1]
                argc = instr.arg
                call_args = [stack.pop() for _ in range(argc)][::-1]
                callee = stack.pop()
                builder = _MATH_FNS.get(callee)
                if builder is None:
                    raise _Bail(f"call to {callee}")
                if all(not isinstance(a, Expression) for a in call_args):
                    stack.append(callee(*call_args))  # pure-constant call
                else:
                    stack.append(builder(*[_as_expr(a) for a in call_args]))
                idx += 1
            elif op == "KW_NAMES":
                raise _Bail("keyword arguments")
            else:
                raise _Bail(f"opcode {op}")

    @staticmethod
    def _const_binop(sym: str, a, b):
        import operator
        ops = {"+": operator.add, "-": operator.sub, "*": operator.mul,
               "/": operator.truediv, "//": operator.floordiv,
               "%": operator.mod, "**": operator.pow, "&": operator.and_,
               "|": operator.or_, "^": operator.xor, "<<": operator.lshift,
               ">>": operator.rshift}
        return ops[sym](a, b)


def compile_python_udf(fn: Callable, children: Sequence[Expression],
                       return_type: DataType) -> Optional[Expression]:
    """Try to rebuild `fn` as a columnar expression over `children`;
    None ⇒ keep the row-python fallback (reference bail contract)."""
    try:
        ex = _SymExec(fn, children)
        result = ex.run(0, [])
    except _Bail:
        return None
    except Exception:  # malformed bytecode patterns: never break planning
        return None
    if result.dtype != return_type:
        result = Cast(result, return_type)
    return result


def rewrite_compiled_udfs(expr: Expression, conf) -> Expression:
    """transformUp replacing RowPythonUDF nodes whose lambdas compile
    (reference LogicalPlanRules injection point)."""
    from .udf import RowPythonUDF

    def replace(e: Expression) -> Optional[Expression]:
        if isinstance(e, RowPythonUDF) and getattr(e, "row_fn", None):
            return compile_python_udf(e.row_fn, list(e.children), e.dtype)
        return None

    return expr.transform(replace)
