"""Expression/exec registries + type-support matrix (reference TypeChecks.scala +
the rule registries in GpuOverrides.scala:769-905).

`register_expr` is the analogue of `expr[INPUT](...)` (GpuOverrides.scala:769):
each registration carries the TypeSig its TPU kernel supports; the doc generator
(docs_gen) emits docs/supported_ops.md from this table, mirroring
SupportedOpsDocs (TypeChecks.scala:1709).
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional, Type

from ..types import TypeSig, TypeSigs

_EXPR_RULES: Dict[type, "ExprRule"] = {}


class ExprRule:
    def __init__(self, cls: type, type_sig: Optional[TypeSig], desc: str,
                 incompat: Optional[str] = None, host_assisted: bool = False,
                 provenance: str = "?"):
        self.cls = cls
        self.type_sig = type_sig
        self.desc = desc
        self.incompat = incompat
        self.host_assisted = host_assisted  # correct but runs partly on host
        #: file:line of the register_expr call — tools/tracelint.py points
        #: its declaration-conflict findings here so a wrong host_assisted
        #: flag is a one-click fix (reference: supported_ops.md rows link
        #: back to the GpuOverrides expr[...] registration)
        self.provenance = provenance


def _caller_provenance() -> str:
    f = sys._getframe(2)
    return f"{os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"


def register_expr(cls: type, type_sig: Optional[TypeSig], desc: str,
                  incompat: Optional[str] = None,
                  host_assisted: bool = False) -> None:
    _EXPR_RULES[cls] = ExprRule(cls, type_sig, desc, incompat, host_assisted,
                                provenance=_caller_provenance())


def is_expr_registered(cls: type) -> bool:
    return cls in _EXPR_RULES


def expr_sig_for(cls: type) -> Optional[TypeSig]:
    r = _EXPR_RULES.get(cls)
    return r.type_sig if r else None


def all_expr_rules() -> Dict[type, ExprRule]:
    return dict(_EXPR_RULES)


def _register_builtin_exprs() -> None:
    from ..expressions import (arithmetic as A, base as B, cast as C,
                               conditional as CO, hashexprs as H,
                               mathexprs as M, nullexprs as N, predicates as P,
                               strings as S)
    sig_num = TypeSigs.numeric
    sig_cmp = TypeSigs.comparable
    sig_all = TypeSigs.all_basic + TypeSigs.NULL

    sig_all_nested = TypeSigs.nested_common + TypeSigs.NULL
    register_expr(B.Literal, sig_all_nested, "literal value")
    register_expr(B.AttributeReference, sig_all_nested, "column reference")
    register_expr(B.Alias, sig_all_nested, "named expression")
    register_expr(C.Cast, sig_all, "cast between types")

    # add/sub/mul cover decimal128 via the two-int64-limb kernels
    # (kernels/decimal128.py, reference spark-rapids-jni DecimalUtils)
    for cls in (A.Add, A.Subtract, A.Multiply):
        register_expr(cls, sig_num + TypeSigs.DECIMAL_128,
                      f"{cls.__name__.lower()} of numerics (incl. decimal128)")
    register_expr(A.Divide, sig_num, "fractional division")
    register_expr(A.IntegralDivide, sig_num, "integral division")
    register_expr(A.Remainder, sig_num, "remainder (java sign semantics)")
    register_expr(A.Pmod, sig_num, "positive modulus")
    register_expr(A.UnaryMinus, sig_num, "negation")
    register_expr(A.UnaryPositive, sig_num, "unary plus")
    register_expr(A.Abs, sig_num, "absolute value")

    for cls in (P.EqualTo, P.EqualNullSafe, P.LessThan, P.LessThanOrEqual,
                P.GreaterThan, P.GreaterThanOrEqual):
        register_expr(cls, TypeSigs.BOOLEAN, f"comparison {cls.symbol}")
    register_expr(P.And, TypeSigs.BOOLEAN, "logical AND (Kleene)")
    register_expr(P.Or, TypeSigs.BOOLEAN, "logical OR (Kleene)")
    register_expr(P.Not, TypeSigs.BOOLEAN, "logical NOT")
    register_expr(P.In, TypeSigs.BOOLEAN, "IN (literal list)")

    register_expr(N.IsNull, TypeSigs.BOOLEAN, "IS NULL")
    register_expr(N.IsNotNull, TypeSigs.BOOLEAN, "IS NOT NULL")
    register_expr(N.IsNaN, TypeSigs.BOOLEAN, "IS NaN")
    register_expr(N.Coalesce, sig_cmp, "first non-null")
    register_expr(N.NaNvl, TypeSigs.fp, "NaN replacement")

    register_expr(CO.If, sig_cmp, "if/else")
    register_expr(CO.CaseWhen, sig_cmp, "CASE WHEN")
    register_expr(CO.Greatest, sig_cmp, "row-wise greatest")
    register_expr(CO.Least, sig_cmp, "row-wise least")

    for cls in (M.Sqrt, M.Cbrt, M.Exp, M.Expm1, M.Sin, M.Cos, M.Tan, M.Asin,
                M.Acos, M.Atan, M.Sinh, M.Cosh, M.Tanh, M.Log, M.Log10, M.Log2,
                M.Log1p, M.Pow, M.Atan2, M.Signum, M.Floor, M.Ceil, M.Round):
        register_expr(cls, TypeSigs.numeric + TypeSigs.fp,
                      f"math fn {cls.__name__.lower()}")

    register_expr(H.Murmur3Hash, TypeSigs.integral, "spark murmur3 hash")
    register_expr(H.XxHash64, TypeSigs.integral,
                  "spark xxhash64 (device XXH64 over HBM bytes)",
                  incompat="decimal inputs via host path")
    register_expr(H.HiveHash, TypeSigs.integral,
                  "hive bucketing hash (device 31h+b fold)",
                  incompat="nested inputs via host path")

    from ..expressions import datetime as DT
    for cls in (DT.Year, DT.Month, DT.DayOfMonth, DT.Quarter, DT.DayOfWeek,
                DT.WeekDay, DT.DayOfYear, DT.WeekOfYear, DT.Hour, DT.Minute,
                DT.Second, DT.DateDiff):
        register_expr(cls, TypeSigs.integral, f"datetime field {cls.__name__.lower()}")
    register_expr(DT.LastDay, TypeSigs.DATE, "last day of month")
    register_expr(DT.DateAdd, TypeSigs.DATE, "date add/sub days")
    register_expr(DT.AddMonths, TypeSigs.DATE, "add months (day-clamped)")
    register_expr(DT.UnixTimestampFromTs, TypeSigs.integral, "unix seconds")
    register_expr(DT.ToUnixMicros, TypeSigs.integral, "unix micros")

    register_expr(S.Length, TypeSigs.integral, "string char length")
    register_expr(S.Upper, TypeSigs.STRING, "uppercase",
                  incompat="non-ASCII handled via host path")
    register_expr(S.Lower, TypeSigs.STRING, "lowercase",
                  incompat="non-ASCII handled via host path")
    register_expr(S.StartsWith, TypeSigs.BOOLEAN, "prefix test")
    register_expr(S.EndsWith, TypeSigs.BOOLEAN, "suffix test")
    register_expr(S.Contains, TypeSigs.BOOLEAN,
                  "substring test (device window match)",
                  incompat="non-literal pattern via host path")
    register_expr(S.Substring, TypeSigs.STRING, "substring (device ragged gather)",
                  incompat="non-ASCII / non-literal pos via host path")
    register_expr(S.ConcatStr, TypeSigs.STRING,
                  "string concat (device multi-source gather)")
    for cls in (S.StringRepeat, S.StringReplace, S.SubstringIndex):
        register_expr(cls, TypeSigs.STRING,
                      f"string fn {cls.__name__.lower()} (device, UTF-8 safe)",
                      incompat="non-literal arguments via host path")
    for cls in (S.Trim, S.LTrim, S.RTrim, S.Reverse, S.InitCap, S.LPad,
                S.RPad, S.StringTranslate):
        register_expr(cls, TypeSigs.STRING,
                      f"string fn {cls.__name__.lower()} (device)",
                      incompat="non-ASCII handled via host path")
    register_expr(S.StringLocate, TypeSigs.integral,
                  "locate/instr (device first-match)",
                  incompat="non-ASCII handled via host path")
    register_expr(S.ConcatWs, TypeSigs.STRING,
                  "concat_ws (device)",
                  incompat="array args / non-literal separator via host path")
    register_expr(S.StringSplit, TypeSigs.nested_common,
                  "split to array (device scan for literal delimiters)",
                  incompat="regex patterns / limit=0 via host path")
    register_expr(S.OctetLength, TypeSigs.integral,
                  "byte length (device offsets math)")
    register_expr(S.BitLength, TypeSigs.integral,
                  "bit length (device offsets math)")
    register_expr(S.FormatNumber, TypeSigs.STRING, "format_number",
                  host_assisted=True)
    register_expr(S.Conv, TypeSigs.STRING, "base conversion",
                  host_assisted=True)
    register_expr(S.StringToMap, TypeSigs.nested_common, "str_to_map",
                  host_assisted=True)

    from ..expressions import urlexprs as URL
    register_expr(URL.ParseUrl, TypeSigs.STRING, "parse_url",
                  incompat="urllib leniency differs from java.net.URI",
                  host_assisted=True)

    from ..expressions import regex as RX
    register_expr(RX.RLike, TypeSigs.BOOLEAN,
                  "regex match: literal rewrite or compiled byte-DFA on "
                  "device (kernels/regex_dfa.py); out-of-subset patterns "
                  "fall back to the host engine",
                  incompat="out-of-subset patterns run on host")
    register_expr(RX.RegexpReplace, TypeSigs.STRING,
                  "regex replace: DFA span matching + device byte assembly "
                  "(kernels/regex_dfa.py); out-of-subset patterns / group "
                  "refs fall back to the host engine",
                  incompat="out-of-subset patterns run on host")
    register_expr(RX.RegexpExtract, TypeSigs.STRING,
                  "regex extract: group 0 via device DFA span matching; "
                  "capture groups on the host engine",
                  incompat="capture groups run on host")
    register_expr(RX.Like, TypeSigs.BOOLEAN,
                  "SQL LIKE (device segment matcher)",
                  incompat="non-ASCII handled via host path")
    register_expr(RX.RegexpExtractAll, TypeSigs.nested_common,
                  "regexp_extract_all", host_assisted=True)

    from ..expressions import collections as CL
    sig_nested = TypeSigs.nested_common
    register_expr(CL.Size, TypeSigs.integral,
                  "size of array/map (device offsets math)",
                  incompat="map inputs via host path")
    register_expr(CL.GetArrayItem, sig_nested, "array[i] access (flat gather)",
                  incompat="non-fixed-width elements via host path")
    register_expr(CL.ElementAt, sig_nested,
                  "element_at (array 1-based / map key)",
                  incompat="maps / non-fixed-width elements via host path")
    register_expr(CL.ArrayContains, TypeSigs.BOOLEAN,
                  "array_contains (segment reduce)",
                  incompat="column-valued needle via host path")
    register_expr(CL.ArrayPosition, TypeSigs.integral,
                  "array_position (segment reduce)",
                  incompat="column-valued needle via host path")
    register_expr(CL.ArrayMin, sig_nested, "array_min (nulls skipped, NaN greatest)")
    register_expr(CL.ArrayMax, sig_nested, "array_max (nulls skipped, NaN greatest)")
    register_expr(CL.CreateArray, sig_nested, "array(...) constructor")
    for cls in (CL.SortArray, CL.ArrayDistinct, CL.ArrayUnion,
                CL.ArrayIntersect, CL.ArrayExcept, CL.ArraysOverlap):
        register_expr(cls, sig_nested,
                      f"array fn {cls.__name__} (device ragged sort/search)",
                      incompat="non-fixed-width elements via host path")
    for cls in (CL.ArrayRepeat, CL.Slice, CL.ConcatArrays, CL.Flatten,
                CL.Sequence, CL.ArrayReverse):
        register_expr(cls, sig_nested,
                      f"array fn {cls.__name__} (device ragged gather)",
                      incompat="non-fixed-width elements via host path")
    for cls in (CL.ArrayJoin, CL.ArraysZip):
        register_expr(cls, sig_nested, f"array fn {cls.__name__}",
                      host_assisted=True)
    for cls in (CL.MapKeys, CL.MapValues):
        register_expr(cls, sig_nested,
                      f"map fn {cls.__name__} (device zero-copy child)")
    register_expr(CL.GetMapValue, sig_nested,
                  "map fn GetMapValue (device segment lookup)",
                  incompat="string/nested keys via host path")
    for cls in (CL.CreateMap, CL.MapConcat, CL.MapFromArrays):
        register_expr(cls, sig_nested, f"map fn {cls.__name__}",
                      host_assisted=True)
    register_expr(CL.LambdaFunction, TypeSigs.all, "lambda function")
    register_expr(CL.NamedLambdaVariable, TypeSigs.all, "lambda variable")
    register_expr(CL.ArrayTransform, sig_nested,
                  "transform(arr, lambda) — flat-element XLA eval")
    register_expr(CL.ArrayExists, TypeSigs.BOOLEAN, "exists(arr, pred)")
    register_expr(CL.ArrayForAll, TypeSigs.BOOLEAN, "forall(arr, pred)")
    register_expr(CL.ArrayFilter, sig_nested, "filter(arr, pred)")
    register_expr(CL.ArrayAggregate, sig_nested, "aggregate/reduce fold",
                  host_assisted=True)
    register_expr(CL.ZipWith, sig_nested, "zip_with", host_assisted=True)

    for cls in (M.Asinh, M.Acosh, M.Atanh, M.Cot, M.ToDegrees, M.ToRadians,
                M.Rint, M.Hypot):
        register_expr(cls, TypeSigs.numeric + TypeSigs.fp,
                      f"math fn {cls.__name__.lower()}")
    register_expr(M.Logarithm, TypeSigs.fp, "log(base, x) — null on domain error")
    register_expr(M.BRound, TypeSigs.numeric, "bround (HALF_EVEN)")

    from ..expressions import misc as MISC
    register_expr(MISC.SparkPartitionID, TypeSigs.integral,
                  "spark_partition_id()")
    register_expr(MISC.MonotonicallyIncreasingID, TypeSigs.integral,
                  "monotonically_increasing_id()")
    register_expr(MISC.Rand, TypeSigs.fp, "rand(seed) — device threefry PRNG",
                  incompat="sequence differs from Spark XORShiftRandom")
    register_expr(MISC.InputFileName, TypeSigs.STRING, "input_file_name()")
    register_expr(MISC.InputFileBlockStart, TypeSigs.integral,
                  "input_file_block_start()")
    register_expr(MISC.InputFileBlockLength, TypeSigs.integral,
                  "input_file_block_length()")

    register_expr(N.AtLeastNNonNulls, TypeSigs.BOOLEAN,
                  "at-least-n-non-nulls filter (na.drop)")
    register_expr(N.KnownNotNull, sig_all_nested, "known-not-null marker")
    register_expr(N.KnownFloatingPointNormalized, TypeSigs.fp,
                  "known-normalized marker (passthrough)")
    register_expr(N.NormalizeNaNAndZero, TypeSigs.fp,
                  "NaN/-0.0 canonicalization")
    register_expr(P.InSet, TypeSigs.BOOLEAN, "IN over a literal set (isin)")

    register_expr(S.Ascii, TypeSigs.integral, "ascii (device first byte)",
                  incompat="non-ASCII handled via host path")
    register_expr(S.StringInstr, TypeSigs.integral,
                  "instr (device first-match)",
                  incompat="non-ASCII handled via host path")
    register_expr(H.Md5, TypeSigs.STRING, "md5 hex digest", host_assisted=True)

    register_expr(DT.DateSub, TypeSigs.DATE, "date_sub")
    for cls in (DT.SecondsToTimestamp, DT.MillisToTimestamp,
                DT.MicrosToTimestamp):
        register_expr(cls, TypeSigs.TIMESTAMP,
                      f"{cls.__name__.lower()} (device scaling)")
    register_expr(DT.FromUnixTime, TypeSigs.STRING,
                  "from_unixtime (device byte assembly, session tz)",
                  incompat="non-numeric pattern tokens via host path")
    register_expr(DT.DateFormatClass, TypeSigs.STRING,
                  "date_format (device byte assembly, session tz)",
                  incompat="non-numeric pattern tokens via host path")
    register_expr(DT.ToUnixTimestamp, TypeSigs.integral,
                  "to_unix_timestamp (device for ts/date)",
                  incompat="string parsing via host path, UTC only")
    register_expr(DT.UnixTimestamp, TypeSigs.integral,
                  "unix_timestamp (device for ts/date)",
                  incompat="string parsing via host path, UTC only")

    register_expr(CL.ArrayRemove, sig_nested,
                  "array_remove (device for fixed-width + literal)",
                  incompat="non-fixed-width / column needle via host path")
    register_expr(CL.MapEntries, sig_nested,
                  "map fn MapEntries (device zero-copy entries struct)")
    register_expr(CL.MapFilter, sig_nested,
                  "map fn MapFilter (device flat-entry predicate + compact)",
                  incompat="non-fixed-width entries via host path")
    register_expr(CL.TransformValues, sig_nested,
                  "map fn TransformValues (device flat-entry lambda)",
                  incompat="non-fixed-width entries via host path")
    register_expr(CL.TransformKeys, sig_nested, "map fn TransformKeys",
                  host_assisted=True)
    for cls in (CL.GetStructField, CL.GetArrayStructFields,
                CL.CreateNamedStruct):
        register_expr(cls, sig_nested,
                      f"struct fn {cls.__name__} (device child-column "
                      "tuples, cuDF STRUCT ColumnView analogue)",
                      incompat="map-typed fields via host path")

    # aggregate functions (reference GpuOverrides expr[Sum]/expr[Max]/... —
    # each aggregate is an expression rule in its own right)
    from ..expressions import aggregates as AGG
    register_expr(AGG.Sum, TypeSigs.numeric, "sum aggregate (overflow-checked)")
    register_expr(AGG.Average, TypeSigs.numeric, "average aggregate")
    register_expr(AGG.Min, TypeSigs.comparable, "min aggregate")
    register_expr(AGG.Max, TypeSigs.comparable, "max aggregate")
    register_expr(AGG.Count, TypeSigs.integral, "count aggregate")
    register_expr(AGG.CountDistinct, TypeSigs.integral, "count(distinct)")
    register_expr(AGG.First, TypeSigs.all_basic + TypeSigs.NULL,
                  "first(ignoreNulls) aggregate")
    register_expr(AGG.Last, TypeSigs.all_basic + TypeSigs.NULL,
                  "last(ignoreNulls) aggregate")
    register_expr(AGG.StddevPop, TypeSigs.fp, "stddev_pop (Welford merge)")
    register_expr(AGG.StddevSamp, TypeSigs.fp, "stddev_samp (Welford merge)")
    register_expr(AGG.VariancePop, TypeSigs.fp, "var_pop (Welford merge)")
    register_expr(AGG.VarianceSamp, TypeSigs.fp, "var_samp (Welford merge)")
    register_expr(AGG.Corr, TypeSigs.fp, "corr aggregate")
    register_expr(AGG.CovPopulation, TypeSigs.fp, "covar_pop aggregate")
    register_expr(AGG.CovSample, TypeSigs.fp, "covar_samp aggregate")
    register_expr(AGG.Percentile, TypeSigs.fp, "exact percentile (device sort)")
    register_expr(AGG.ApproximatePercentile, TypeSigs.fp,
                  "approx_percentile (t-digest style merge)",
                  incompat="approximation differs from Spark's t-digest")
    register_expr(AGG.CollectList, TypeSigs.nested_common, "collect_list")
    register_expr(AGG.CollectSet, TypeSigs.nested_common, "collect_set")
    from ..expressions import bloom as BLOOM
    register_expr(BLOOM.BloomFilterAggregate, TypeSigs.BINARY,
                  "bloom_filter_agg (device murmur3 bitset)")

    # window functions (reference expr[Rank]/expr[Lag]/... in GpuOverrides)
    from .. import window as WIN
    register_expr(WIN.WindowExpression, TypeSigs.all_basic + TypeSigs.NULL,
                  "windowed aggregate/function application")
    register_expr(WIN.RowNumber, TypeSigs.integral, "row_number()")
    register_expr(WIN.Rank, TypeSigs.integral, "rank()")
    register_expr(WIN.DenseRank, TypeSigs.integral, "dense_rank()")
    register_expr(WIN.NTile, TypeSigs.integral, "ntile(n)")
    register_expr(WIN.PercentRank, TypeSigs.fp, "percent_rank()")
    register_expr(WIN.CumeDist, TypeSigs.fp, "cume_dist()")
    register_expr(WIN.Lag, TypeSigs.all_basic + TypeSigs.NULL,
                  "lag(col, offset, default)")
    register_expr(WIN.Lead, TypeSigs.all_basic + TypeSigs.NULL,
                  "lead(col, offset, default)")

    from ..expressions import generators as GEN2
    register_expr(GEN2.ReplicateRows, TypeSigs.all_basic + TypeSigs.NULL,
                  "replicate_rows generator (device gather)")
    register_expr(GEN2.MultiAlias, TypeSigs.all_basic + TypeSigs.NULL,
                  "multi-output alias")
    register_expr(GEN2.GroupingExpr, TypeSigs.all_basic + TypeSigs.NULL,
                  "grouping set marker")

    from ..expressions import bitwise as BW
    for cls in (BW.BitwiseAnd, BW.BitwiseOr, BW.BitwiseXor):
        register_expr(cls, TypeSigs.integral, f"bitwise {cls.symbol}")
    register_expr(BW.BitwiseNot, TypeSigs.integral, "bitwise NOT")
    register_expr(BW.BitwiseCount, TypeSigs.integral, "bit_count")
    for cls in (BW.ShiftLeft, BW.ShiftRight, BW.ShiftRightUnsigned):
        register_expr(cls, TypeSigs.integral, f"shift {cls.symbol}")

    from ..expressions import generators as G
    register_expr(G.Explode, TypeSigs.nested_common + TypeSigs.NULL,
                  "explode/posexplode generator")
    register_expr(G.Stack, TypeSigs.all_basic + TypeSigs.NULL,
                  "stack generator")
    register_expr(G.GroupingID, TypeSigs.integral,
                  "grouping_id (lowered to the Expand gid column)")

    from ..expressions import json as J
    register_expr(J.GetJsonObject, TypeSigs.STRING,
                  "get_json_object: single-name paths via the validating "
                  "device JSON scan (kernels/json_scan.py) with per-row "
                  "host fallback; multi-step paths on the host engine",
                  incompat="multi-step paths run on host")
    register_expr(J.JsonToStructs, TypeSigs.nested_common,
                  "from_json (PERMISSIVE): one device scan per schema key, "
                  "device int/bool/string coercion, per-row host patch",
                  incompat="float/date/nested schema fields via host path")
    register_expr(J.StructsToJson, TypeSigs.STRING,
                  "to_json (device byte assembly for int/bool/string "
                  "structs; escape-needing rows host-patched)",
                  incompat="float/date/nested fields via host path")
    register_expr(J.JsonTuple, TypeSigs.STRING,
                  "json_tuple generator (device scan per field)",
                  incompat="floats/nested values host-rendered per row")

    from ..expressions import bloom as BF
    register_expr(BF.BloomFilterMightContain, TypeSigs.BOOLEAN,
                  "bloom-filter membership probe", host_assisted=True)

    from ..expressions import zorder as Z
    register_expr(Z.InterleaveBits, TypeSigs.BINARY,
                  "z-order bit interleave (delta OPTIMIZE ZORDER)")
    register_expr(Z.HilbertLongIndex, TypeSigs.integral,
                  "hilbert-curve clustering index")

    from .. import udf as U
    register_expr(U.TpuColumnarUDF, TypeSigs.all, "columnar device UDF (RapidsUDF)")
    register_expr(U.ArrowPandasUDF, TypeSigs.all, "arrow/pandas UDF",
                  host_assisted=True)
    register_expr(U.RowPythonUDF, TypeSigs.all, "row python UDF",
                  host_assisted=True)


_register_builtin_exprs()

# declare the typed per-expression enable flags for every registered rule
# (reference: one generated spark.rapids.sql.expression.* conf per rule)
from ..config import declare_expression_flags as _declare_flags  # noqa: E402

_declare_flags(c.__name__ for c in _EXPR_RULES)


def conf_gate_reason(e, conf):
    """Config-driven expression gates beyond the per-class enable switch
    (reference RapidsConf incompatibility switches: castFloatToString,
    castStringToFloat, castStringToTimestamp, variableFloatAgg)."""
    from ..config import (CAST_FLOAT_TO_STRING_ENABLED,
                          CAST_STRING_TO_FLOAT_ENABLED,
                          CAST_STRING_TO_TIMESTAMP_ENABLED,
                          VARIABLE_FLOAT_AGG_ENABLED)
    from ..expressions.aggregates import Average, Sum
    from ..expressions.cast import Cast
    from ..types import (DoubleType, FloatType, StringType, TimestampType)
    if isinstance(e, Cast) and e.children:
        src = e.children[0].dtype
        dst = e.dtype
        if isinstance(src, (FloatType, DoubleType)) \
                and isinstance(dst, StringType) \
                and not conf.get(CAST_FLOAT_TO_STRING_ENABLED):
            return ("float-to-string cast disabled via "
                    f"{CAST_FLOAT_TO_STRING_ENABLED.key}")
        if isinstance(src, StringType) \
                and isinstance(dst, (FloatType, DoubleType)) \
                and not conf.get(CAST_STRING_TO_FLOAT_ENABLED):
            return ("string-to-float cast disabled via "
                    f"{CAST_STRING_TO_FLOAT_ENABLED.key}")
        if isinstance(src, StringType) \
                and isinstance(dst, TimestampType) \
                and not conf.get(CAST_STRING_TO_TIMESTAMP_ENABLED):
            return ("string-to-timestamp cast disabled via "
                    f"{CAST_STRING_TO_TIMESTAMP_ENABLED.key}")
    if isinstance(e, (Sum, Average)) and e.children \
            and isinstance(e.children[0].dtype, (FloatType, DoubleType)) \
            and not conf.get(VARIABLE_FLOAT_AGG_ENABLED):
        return ("float aggregation result can vary with parallelism; "
                f"disabled via {VARIABLE_FLOAT_AGG_ENABLED.key}")
    return None
