"""Cost-based optimizer: revert plan sections not worth moving to the TPU.

Reference: CostBasedOptimizer.scala (`CostBasedOptimizer:54`,
`CpuCostModel:284`, `GpuCostModel:334`, `RowCountPlanVisitor:437`) — an
optional pass over the tagged meta tree that estimates per-section CPU vs
accelerator cost (including row/columnar transition overhead at the section
boundaries) and marks sections that are cheaper on CPU with an
`[optimization]`-prefixed fallback reason. Disabled by default, like the
reference (`spark.rapids.sql.optimizer.enabled`).
"""

from __future__ import annotations

from typing import List, Optional

from ..config import (OPTIMIZER_CPU_ROW_COST, OPTIMIZER_ENABLED,
                      OPTIMIZER_TPU_ROW_COST, OPTIMIZER_TRANSITION_ROW_COST,
                      RapidsConf)
from .meta import PlanMeta


class RowCountPlanVisitor:
    """reference RowCountPlanVisitor (CostBasedOptimizer.scala:437):
    bottom-up cardinality estimate with per-operator selectivity defaults."""

    FILTER_SELECTIVITY = 0.5
    AGG_RATIO = 0.1
    FILE_ROW_BYTES = 100.0

    @classmethod
    def estimate(cls, plan, _cache: Optional[dict] = None) -> float:
        """Memoized per optimize() pass — _section_costs revisits nodes, and
        FileScan estimates stat the filesystem."""
        if _cache is not None and id(plan) in _cache:
            return _cache[id(plan)]
        v = cls._estimate(plan, _cache)
        if _cache is not None:
            _cache[id(plan)] = v
        return v

    @classmethod
    def _estimate(cls, plan, _cache) -> float:
        import os
        name = type(plan).__name__
        children = [cls.estimate(c, _cache) for c in plan.children]
        child = children[0] if children else 0.0
        if name.endswith("LocalTableScanExec"):
            t = getattr(plan, "table", None)
            return float(t.num_rows) if t is not None else 1000.0
        if name.endswith("RangeExec"):
            try:
                return float(max(0, (plan.end - plan.start) // plan.step))
            except Exception:
                return 1000.0
        if "FileScan" in name:
            total = 0
            for p in getattr(plan, "paths", []):
                try:
                    total += os.path.getsize(p)
                except OSError:
                    total += 1 << 20
            return max(1.0, total / cls.FILE_ROW_BYTES)
        if "Filter" in name:
            return child * cls.FILTER_SELECTIVITY
        if "Aggregate" in name:
            return max(1.0, child * cls.AGG_RATIO)
        if "Join" in name:
            return max(children) if children else child
        if "Union" in name:
            return float(sum(children))
        if "Limit" in name or "TopN" in name:
            n = getattr(plan, "n", None)
            return float(n) if n is not None else child
        if "Sample" in name:
            return child * getattr(plan, "fraction", 1.0)
        return child


def estimate_logical_rows(plan) -> Optional[float]:
    """Cardinality estimate over the LOGICAL plan (plan/logical.py nodes),
    reusing RowCountPlanVisitor's selectivity defaults. Used by the logical
    optimizer's cost-based join choice, where no physical plan exists yet.
    Returns None when nothing about the subtree can be sized."""
    import os
    name = type(plan).__name__
    children = [estimate_logical_rows(c) for c in plan.children]
    child = children[0] if children else None
    V = RowCountPlanVisitor
    if name in ("LocalRelation", "CachedRelation"):
        t = getattr(plan, "table", None)
        return float(t.num_rows) if t is not None else None
    if name == "DeviceCachedRelation":
        n = getattr(plan, "num_rows", None)
        n = n() if callable(n) else n
        return float(n) if n is not None else None
    if name == "Range":
        try:
            return float(max(0, (plan.end - plan.start) // plan.step))
        except Exception:
            return None
    if name == "FileScan":
        if plan.fmt == "parquet":
            total_rows = 0
            try:
                import pyarrow.parquet as pq
                for p in plan.paths:
                    total_rows += pq.ParquetFile(p).metadata.num_rows
                return float(total_rows)
            except Exception:
                pass
        total = 0
        for p in plan.paths:
            try:
                total += os.path.getsize(p)
            except OSError:
                total += 1 << 20
        return max(1.0, total / V.FILE_ROW_BYTES)
    if child is None:
        return None
    if name == "Filter":
        return child * V.FILTER_SELECTIVITY
    if name == "Aggregate":
        return max(1.0, child * V.AGG_RATIO)
    if name == "Join":
        sized = [c for c in children if c is not None]
        return max(sized) if sized else None
    if name == "Union":
        return float(sum(c for c in children if c is not None))
    if name == "Limit":
        n = getattr(plan, "n", None)
        return float(min(n, child)) if n is not None else child
    if name == "Sample":
        return child * getattr(plan, "fraction", 1.0)
    return child


#: per-dtype row-width heuristic for logical size estimates: fixed-width
#: types by storage width, variable-width by a typical payload
_VAR_WIDTH_BYTES = 24.0


def _attr_width(dtype) -> float:
    w = getattr(dtype, "byte_width", None)
    if isinstance(w, (int, float)) and w > 0:
        return float(w)
    tname = type(dtype).__name__
    if "Boolean" in tname or "Byte" in tname:
        return 1.0
    if "Short" in tname:
        return 2.0
    if "Int" in tname or "Float" in tname or "Date" in tname:
        return 4.0
    return _VAR_WIDTH_BYTES if ("String" in tname or "Binary" in tname
                                or "Array" in tname or "Map" in tname
                                or "Struct" in tname) else 8.0


def estimate_logical_bytes(plan) -> Optional[float]:
    """Estimated materialized size of a logical subtree's output: estimated
    rows x per-dtype width of the output schema. Drives the build-side swap
    and the broadcast-vs-shuffled fallback when ``estimated_size_bytes``
    cannot size the physical build side."""
    rows = estimate_logical_rows(plan)
    if rows is None:
        return None
    try:
        row_bytes = sum(_attr_width(a.dtype) for a in plan.output)
    except Exception:
        return None
    return rows * max(1.0, row_bytes)


def _op_weight(plan) -> float:
    """Relative per-row operator weight (joins/sorts/aggs cost more than
    projections; mirrors the reference's per-operator cost overrides)."""
    name = type(plan).__name__
    if "Join" in name:
        return 4.0
    if "Sort" in name or "TopN" in name:
        return 3.0
    if "Aggregate" in name or "Window" in name:
        return 3.0
    if "Exchange" in name:
        return 2.0
    return 1.0


class CostBasedOptimizer:
    @staticmethod
    def optimize(meta: PlanMeta, conf: RapidsConf) -> List[str]:
        """Walk section roots; revert sections whose estimated TPU cost
        (incl. boundary transitions) exceeds the CPU cost. Returns the list
        of applied optimizations (for explain/tests)."""
        applied: List[str] = []
        CostBasedOptimizer._walk(meta, None, conf, applied, {})
        return applied

    @staticmethod
    def _walk(meta: PlanMeta, parent: Optional[PlanMeta], conf: RapidsConf,
              applied: List[str], cache: dict) -> None:
        is_section_root = meta.can_this_be_replaced and (
            parent is None or not parent.can_this_be_replaced)
        if is_section_root:
            cpu, tpu = CostBasedOptimizer._section_costs(meta, conf,
                                                         at_root=True,
                                                         cache=cache)
            if tpu >= cpu:
                reason = (f"[optimization] section {type(meta.plan).__name__} "
                          f"not worth moving to TPU "
                          f"(cpu={cpu:.2f} <= tpu={tpu:.2f})")
                CostBasedOptimizer._revert(meta, reason)
                applied.append(reason)
        for c in meta.child_plans:
            CostBasedOptimizer._walk(c, meta, conf, applied, cache)

    @staticmethod
    def _section_costs(meta: PlanMeta, conf: RapidsConf, at_root: bool,
                       cache: dict) -> tuple:
        rows = RowCountPlanVisitor.estimate(meta.plan, cache)
        w = _op_weight(meta.plan)
        cpu = rows * w * conf.get(OPTIMIZER_CPU_ROW_COST)
        tpu = rows * w * conf.get(OPTIMIZER_TPU_ROW_COST)
        trans = conf.get(OPTIMIZER_TRANSITION_ROW_COST)
        if at_root:
            tpu += rows * trans  # columnar→row at the section's top edge
        for c in meta.child_plans:
            if c.can_this_be_replaced:
                ccpu, ctpu = CostBasedOptimizer._section_costs(c, conf, False,
                                                               cache)
                cpu += ccpu
                tpu += ctpu
            else:
                # row→columnar transition where a CPU child feeds the section
                crows = RowCountPlanVisitor.estimate(c.plan, cache)
                tpu += crows * trans
        return cpu, tpu

    @staticmethod
    def _revert(meta: PlanMeta, reason: str) -> None:
        meta.will_not_work_on_tpu(reason)
        for c in meta.child_plans:
            if c.can_this_be_replaced:
                CostBasedOptimizer._revert(meta=c, reason=reason)


def apply_cbo(meta: PlanMeta, conf: RapidsConf) -> List[str]:
    if not conf.get(OPTIMIZER_ENABLED):
        return []
    return CostBasedOptimizer.optimize(meta, conf)
