"""Logical plan nodes + analysis (attribute resolution, type coercion).

This stands in for Spark Catalyst's analyzed logical plan: the thing our planner
lowers to physical operators that the override layer then retargets to TPU.
The reference plugs into Catalyst and never owns this layer; a standalone
framework must, so this is intentionally a compact analyzer (resolution by name
→ AttributeReference with expr_ids; Spark's implicit-cast coercion rules).
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from ..expressions.base import (Alias, AttributeReference, Expression, Literal,
                                UnresolvedAttribute, output_name)
from ..expressions.cast import Cast
from ..expressions import arithmetic as A
from ..expressions import predicates as P
from ..types import (BooleanT, DataType, DecimalType, DoubleT, FractionalType,
                     IntegralType, LongT, NullType, NumericType, StringType,
                     StructField, StructType, numeric_promote)


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def output(self) -> List[AttributeReference]:
        raise NotImplementedError

    def schema(self) -> StructType:
        return StructType([StructField(a.name, a.dtype, a.nullable)
                           for a in self.output])

    def resolve_name(self, name: str, case_sensitive: bool = False) -> AttributeReference:
        matches = [a for a in self.output
                   if (a.name == name if case_sensitive else a.name.lower() == name.lower())]
        if not matches:
            raise ValueError(f"cannot resolve column {name!r}; "
                             f"available: {[a.name for a in self.output]}")
        if len(matches) > 1:
            raise ValueError(f"ambiguous column {name!r}")
        return matches[0]

    def tree_string(self, indent: int = 0) -> str:
        lines = ["  " * indent + self.node_desc()]
        for c in self.children:
            lines.append(c.tree_string(indent + 1))
        return "\n".join(lines)

    def node_desc(self) -> str:
        return type(self).__name__


class LocalRelation(LogicalPlan):
    """In-memory Arrow table, optionally pre-split into partitions."""

    def __init__(self, table, num_partitions: int = 1):
        import pyarrow as pa
        from ..types import from_arrow
        self.table = table
        self.num_partitions = num_partitions
        self._output = [AttributeReference(f.name, from_arrow(f.type), True)
                        for f in table.schema]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def node_desc(self) -> str:
        return f"LocalRelation[{self.table.num_rows} rows]"


class FileScan(LogicalPlan):
    """Lazy file-source relation (reference GpuFileSourceScanExec / v2 scans)."""

    def __init__(self, paths, fmt: str, schema_attrs=None, options=None,
                 num_partitions=None):
        self.paths = list(paths)
        self.fmt = fmt
        self.options = dict(options or {})
        self.num_partitions = num_partitions
        if schema_attrs is None:
            schema_attrs = self._infer_schema()
        self._output = schema_attrs

    def _infer_schema(self):
        from ..types import from_arrow
        import pyarrow as pa
        p = self.paths[0]
        if self.fmt == "parquet":
            import pyarrow.parquet as pq
            try:
                sch = pq.read_schema(p)
            except Exception:
                # encrypted inputs fail here first (before any scan):
                # surface the reference's clean message instead of
                # pyarrow's cryptic one (GpuParquetScan.scala:590)
                from ..io.device_decode import (ParquetEncryptedException,
                                               detect_encryption,
                                               encrypted_message)
                reason = detect_encryption(p)
                if reason is not None:
                    raise ParquetEncryptedException(
                        encrypted_message(p, reason)) from None
                raise
        elif self.fmt == "orc":
            import pyarrow.orc as paorc
            sch = paorc.ORCFile(p).schema
        elif self.fmt == "csv":
            import pyarrow.csv as pacsv
            header = str(self.options.get("header", "false")).lower() == "true"
            sep = self.options.get("sep", self.options.get("delimiter", ","))
            ropts = pacsv.ReadOptions(autogenerate_column_names=not header)
            popts = pacsv.ParseOptions(delimiter=sep)
            sch = pacsv.read_csv(p, read_options=ropts,
                                 parse_options=popts).schema
        elif self.fmt == "json":
            import pyarrow.json as pajson
            sch = pajson.read_json(p).schema
        elif self.fmt == "avro":
            from ..io.avro import read_header, schema_to_arrow
            with open(p, "rb") as f:
                avro_schema, _, _, _ = read_header(f)
            sch = pa.schema([(fl["name"], schema_to_arrow(fl["type"]))
                             for fl in avro_schema["fields"]])
        elif self.fmt == "hivetext":
            from ..io.hive_text import infer_hive_schema
            sch = infer_hive_schema(p, self.options)
        else:
            raise ValueError(f"unknown format {self.fmt}")
        attrs = [AttributeReference(f.name, from_arrow(f.type), True)
                 for f in sch]
        # hive-layout partition columns discovered by the reader: appended
        # after the data columns, Spark's partitioned-read column order
        for name, dtype in self.options.get("__partition_cols__", ()):
            attrs.append(AttributeReference(name, dtype, True))
        return attrs

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def node_desc(self) -> str:
        return f"FileScan[{self.fmt}, {len(self.paths)} files]"


class Range(LogicalPlan):
    """spark.range analogue (reference GpuRangeExec)."""

    def __init__(self, start: int, end: int, step: int = 1, num_partitions: int = 1):
        self.start, self.end, self.step = start, end, step
        self.num_partitions = num_partitions
        self._output = [AttributeReference("id", LongT, False)]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def node_desc(self) -> str:
        return f"Range({self.start}, {self.end}, step={self.step})"


class Project(LogicalPlan):
    def __init__(self, exprs: Sequence[Expression], child: LogicalPlan):
        self.children = (child,)
        self.exprs = [_aliased(resolve_expression(e, child)) for e in exprs]
        self._output = [AttributeReference(output_name(e), e.dtype, e.nullable,
                                           expr_id=_reuse_id(e))
                        for e in self.exprs]

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def node_desc(self) -> str:
        return f"Project[{', '.join(e.pretty() for e in self.exprs)}]"


def _reuse_id(e: Expression) -> Optional[int]:
    """Pass-through attributes keep their expr_id so chains of projects resolve."""
    if isinstance(e, AttributeReference):
        return e.expr_id
    if isinstance(e, Alias) and isinstance(e.child, AttributeReference):
        return None
    return None


class Filter(LogicalPlan):
    def __init__(self, condition: Expression, child: LogicalPlan):
        self.children = (child,)
        cond = resolve_expression(condition, child)
        if not isinstance(cond.dtype, type(BooleanT)):
            cond = Cast(cond, BooleanT)
        self.condition = cond

    @property
    def child(self) -> LogicalPlan:
        return self.children[0]

    @property
    def output(self) -> List[AttributeReference]:
        return self.child.output

    def node_desc(self) -> str:
        return f"Filter[{self.condition.pretty()}]"


class Limit(LogicalPlan):
    def __init__(self, n: int, child: LogicalPlan, offset: int = 0):
        self.children = (child,)
        self.n = n
        self.offset = offset

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output

    def node_desc(self) -> str:
        return f"Limit[{self.n}]"


class Sample(LogicalPlan):
    """df.sample (reference GpuSampleExec / GpuFastSampleExec,
    basicPhysicalOperators.scala:873,948)."""

    def __init__(self, child: LogicalPlan, fraction: float,
                 with_replacement: bool = False, seed: Optional[int] = None):
        self.children = (child,)
        self.fraction = float(fraction)
        self.with_replacement = bool(with_replacement)
        if seed is None:
            import random
            seed = random.randrange(1 << 31)  # pyspark draws a random seed
        self.seed = int(seed)

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output

    def node_desc(self) -> str:
        r = ", replace" if self.with_replacement else ""
        return f"Sample[{self.fraction}{r}, seed={self.seed}]"


class Union(LogicalPlan):
    def __init__(self, plans: Sequence[LogicalPlan]):
        self.children = tuple(plans)
        first = plans[0]
        for p in plans[1:]:
            if len(p.output) != len(first.output):
                raise ValueError("UNION requires same number of columns")
        self._output = [AttributeReference(a.name, a.dtype,
                                           any(p.output[i].nullable for p in plans))
                        for i, a in enumerate(first.output)]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output


class SortOrder:
    def __init__(self, child: Expression, ascending: bool = True,
                 nulls_first: Optional[bool] = None):
        self.child = child
        self.ascending = ascending
        # Spark default: NULLS FIRST for ASC, NULLS LAST for DESC
        self.nulls_first = nulls_first if nulls_first is not None else ascending

    def pretty(self) -> str:
        d = "ASC" if self.ascending else "DESC"
        n = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.child.pretty()} {d} {n}"


class Sort(LogicalPlan):
    def __init__(self, order: Sequence[SortOrder], global_sort: bool,
                 child: LogicalPlan):
        self.children = (child,)
        self.order = [SortOrder(resolve_expression(o.child, child), o.ascending,
                                o.nulls_first) for o in order]
        self.global_sort = global_sort

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output

    def node_desc(self) -> str:
        return f"Sort[{', '.join(o.pretty() for o in self.order)}]"


class WindowOp(LogicalPlan):
    """Window evaluation node: output = child.output + one column per window
    expression (Spark extracts window expressions from Project the same way)."""

    def __init__(self, window_exprs, child: LogicalPlan):
        from ..window import WindowExpression, WindowSpec
        self.children = (child,)
        resolved = []
        for we in window_exprs:
            fn = resolve_expression(we.function, child)
            spec = we.spec
            new_spec = WindowSpec(
                [resolve_expression(p, child) for p in spec.partition_by],
                [SortOrder(resolve_expression(o.child, child), o.ascending,
                           o.nulls_first) for o in spec.order_by],
                spec.frame, spec.frame_type)
            nwe = WindowExpression(fn, new_spec)
            if hasattr(we.function, "offset"):
                nwe.function.offset = we.function.offset
                nwe.function.default = we.function.default
            resolved.append(nwe)
        self.window_exprs = resolved
        self._win_attrs = [AttributeReference(f"_we{i}", w.dtype, w.nullable)
                           for i, w in enumerate(resolved)]

    @property
    def window_attrs(self) -> List[AttributeReference]:
        return self._win_attrs

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output + self._win_attrs

    def node_desc(self) -> str:
        return f"Window[{', '.join(w.pretty() for w in self.window_exprs)}]"


class Aggregate(LogicalPlan):
    """Group-by aggregate. agg_exprs are Alias(AggregateFunction(...)) or
    grouping attributes."""

    def __init__(self, grouping: Sequence[Expression], aggregates: Sequence[Expression],
                 child: LogicalPlan):
        self.children = (child,)
        self.grouping = [resolve_expression(g, child) for g in grouping]
        self.aggregates = [_aliased(resolve_expression(a, child)) for a in aggregates]
        self._output = [AttributeReference(output_name(e), e.dtype, e.nullable)
                        for e in list(self.grouping) + list(self.aggregates)]

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def node_desc(self) -> str:
        g = ", ".join(e.pretty() for e in self.grouping)
        a = ", ".join(e.pretty() for e in self.aggregates)
        return f"Aggregate[groupBy=({g}) agg=({a})]"


class Join(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan, join_type: str,
                 left_keys: Sequence[Expression] = (),
                 right_keys: Sequence[Expression] = (),
                 condition: Optional[Expression] = None):
        self.children = (left, right)
        self.join_type = join_type.lower().replace("_", "")
        self.left_keys = [resolve_expression(k, left) for k in left_keys]
        self.right_keys = [resolve_expression(k, right) for k in right_keys]
        self.condition = (resolve_expression(condition, _JoinScope(left, right))
                          if condition is not None else None)

    @property
    def left(self) -> LogicalPlan:
        return self.children[0]

    @property
    def right(self) -> LogicalPlan:
        return self.children[1]

    @property
    def output(self) -> List[AttributeReference]:
        jt = self.join_type
        if jt in ("inner", "cross"):
            return self.left.output + self.right.output
        if jt in ("leftouter", "left"):
            return self.left.output + [_as_nullable(a) for a in self.right.output]
        if jt in ("rightouter", "right"):
            return [_as_nullable(a) for a in self.left.output] + self.right.output
        if jt in ("fullouter", "outer", "full"):
            return ([_as_nullable(a) for a in self.left.output]
                    + [_as_nullable(a) for a in self.right.output])
        if jt in ("leftsemi", "semi", "leftanti", "anti"):
            return self.left.output
        raise ValueError(f"unknown join type {self.join_type}")

    def node_desc(self) -> str:
        keys = ", ".join(f"{l.pretty()}={r.pretty()}"
                         for l, r in zip(self.left_keys, self.right_keys))
        return f"Join[{self.join_type}]({keys})"


def _as_nullable(a: AttributeReference) -> AttributeReference:
    return AttributeReference(a.name, a.dtype, True, expr_id=a.expr_id)


class _JoinScope(LogicalPlan):
    def __init__(self, left: LogicalPlan, right: LogicalPlan):
        self.children = (left, right)

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output + self.children[1].output


class Generate(LogicalPlan):
    """Generator node: output = child.output ++ generator columns
    (Spark GenerateExec; reference GpuGenerateExec.scala)."""

    def __init__(self, generator, child: LogicalPlan,
                 gen_names: Optional[Sequence[str]] = None):
        from ..expressions.generators import Generator
        self.children = (child,)
        gen = generator.with_children(
            [resolve_expression(c, child) for c in generator.children])
        assert isinstance(gen, Generator)
        self.generator = gen
        schema = gen.element_schema()
        if gen_names is None:
            gen_names = [n for n, _, _ in schema]
        if len(gen_names) != len(schema):
            raise ValueError(
                f"generator produces {len(schema)} columns, got names {gen_names}")
        self.gen_names = list(gen_names)
        self._gen_attrs = [AttributeReference(nm, dt, nl)
                           for nm, (_, dt, nl) in zip(self.gen_names, schema)]

    @property
    def generator_output(self) -> List[AttributeReference]:
        return self._gen_attrs

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output + self._gen_attrs

    def node_desc(self) -> str:
        return f"Generate[{self.generator.pretty()}]"


class Expand(LogicalPlan):
    """Row multiplexer for grouping sets (Spark ExpandExec; reference
    GpuExpandExec.scala): each projection emits one output row per input row."""

    def __init__(self, projections: Sequence[Sequence[Expression]],
                 output_attrs: Sequence[AttributeReference],
                 child: LogicalPlan, resolve: bool = True):
        self.children = (child,)
        if resolve:
            self.projections = [[resolve_expression(e, child) for e in p]
                                for p in projections]
        else:
            self.projections = [list(p) for p in projections]
        self._output = list(output_attrs)

    @property
    def output(self) -> List[AttributeReference]:
        return self._output

    def node_desc(self) -> str:
        return f"Expand[{len(self.projections)} projections]"


class Repartition(LogicalPlan):
    """Exchange request: hash/range/round-robin/single
    (reference GpuOverrides `parts` registry, GpuOverrides.scala:3876)."""

    def __init__(self, child: LogicalPlan, num_partitions: int,
                 partitioning: str = "roundrobin",
                 keys: Sequence[Expression] = ()):
        self.children = (child,)
        self.num_partitions = num_partitions
        self.partitioning = partitioning
        self.keys = [resolve_expression(k, child) for k in keys]

    @property
    def output(self) -> List[AttributeReference]:
        return self.children[0].output


# ---------------------------------------------------------------------------
# Resolution + Spark implicit type coercion
# ---------------------------------------------------------------------------

def _aliased(e: Expression) -> Expression:
    if isinstance(e, (Alias, AttributeReference)):
        return e
    return Alias(e, output_name(e))


def resolve_expression(expr: Expression, scope: LogicalPlan) -> Expression:
    def rule(e: Expression):
        if isinstance(e, UnresolvedAttribute):
            return scope.resolve_name(e.name)
        return None

    resolved = expr.transform(rule)
    return coerce_types(resolved)


def coerce_types(expr: Expression) -> Expression:
    """Insert implicit casts per Spark's binary-op coercion rules."""

    def rule(e: Expression):
        if isinstance(e, A.Divide):
            l, r = e.children
            lt, rt = l.dtype, r.dtype
            if isinstance(lt, IntegralType) or isinstance(rt, IntegralType) \
                    or lt != rt:
                if not isinstance(lt, DecimalType) and not isinstance(rt, DecimalType):
                    return A.Divide(_cast_if(l, DoubleT), _cast_if(r, DoubleT))
            return None
        if isinstance(e, (A.Add, A.Subtract, A.Multiply, A.Remainder, A.Pmod,
                          P.EqualTo, P.EqualNullSafe, P.LessThan, P.LessThanOrEqual,
                          P.GreaterThan, P.GreaterThanOrEqual)):
            l, r = e.children
            lt, rt = l.dtype, r.dtype
            if lt == rt:
                return None
            common = _common_type(lt, rt)
            if common is None:
                return None
            return e.with_children([_cast_if(l, common), _cast_if(r, common)])
        return None

    return expr.transform(rule)


def _cast_if(e: Expression, to: DataType) -> Expression:
    return e if e.dtype == to else Cast(e, to)


def _common_type(a: DataType, b: DataType) -> Optional[DataType]:
    from ..types import (DateT, StringT, TimestampT)
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    if isinstance(a, NumericType) and isinstance(b, NumericType) \
            and not isinstance(a, DecimalType) and not isinstance(b, DecimalType):
        return numeric_promote(a, b)
    if isinstance(a, StringType) and isinstance(b, NumericType):
        return DoubleT if not isinstance(b, DecimalType) else b
    if isinstance(b, StringType) and isinstance(a, NumericType):
        return DoubleT if not isinstance(a, DecimalType) else a
    if {type(a), type(b)} == {type(DateT), type(TimestampT)}:
        return TimestampT
    return None
