"""TpuOverrides: the plan-override engine retargeting CPU operators to TPU.

Reference: GpuOverrides.scala (apply:4557, wrapAndTagPlan:4358, doConvertPlan:4364,
applyOverrides:4685) + GpuTransitionOverrides.scala (insert transitions at
CPU↔device boundaries). Flow:
  CPU physical plan → wrap in PlanMeta tree → tag (reasons) → convert supported
  subtrees to Tpu execs → insert HostToDevice/DeviceToHost at boundaries →
  explain/fallback reporting (spark.rapids.sql.explain) and explainOnly mode.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Type

from ..config import (EXPLAIN, FILTER_ENABLED, PROJECT_ENABLED, RapidsConf,
                      SQL_ENABLED, TEST_ASSERT_ON_TPU)
from ..execs import basic as TB
from ..execs import cpu as CE
from ..execs.base import CpuExec, PhysicalPlan, TpuExec
from ..execs.transitions import DeviceToHostExec, HostToDeviceExec
from .meta import PlanMeta

log = logging.getLogger("spark_rapids_tpu")


class ExecRule:
    """Replacement rule for one CPU exec class (reference `exec[INPUT](...)`,
    GpuOverrides.scala:817).

    `tpu_cls` (dotted path under spark_rapids_tpu, e.g. "execs.sort.
    TpuSortExec") names the converted operator and `metrics` the operator
    metrics the rule promises it registers beyond the base set —
    tools/api_validation.py resolves the class lazily and fails the build
    when a declared name is missing from the class's metric registration
    (the reference validates exec signatures per shim the same way)."""

    def __init__(self, cpu_cls: type, desc: str, conf_key: str,
                 tag: Callable[[PlanMeta], None],
                 convert: Callable[[PlanMeta, List[PhysicalPlan]], PhysicalPlan],
                 tpu_cls: Optional[str] = None,
                 metrics: tuple = ()):
        self.cpu_cls = cpu_cls
        self.desc = desc
        self.conf_key = conf_key
        self._tag = tag
        self._convert = convert
        self.tpu_cls = tpu_cls
        self.metrics = tuple(metrics)

    def tag(self, meta: PlanMeta) -> None:
        if not meta.conf.is_op_enabled(self.conf_key, True):
            meta.will_not_work_on_tpu(f"disabled via {self.conf_key}")
        self._tag(meta)

    def convert(self, meta: PlanMeta, children: List[PhysicalPlan]) -> PhysicalPlan:
        children = [ensure_device(c) for c in children]
        return self._convert(meta, children)


def ensure_device(plan: PhysicalPlan) -> PhysicalPlan:
    if plan.is_tpu:
        return plan
    return HostToDeviceExec(plan)


def ensure_host(plan: PhysicalPlan) -> PhysicalPlan:
    if plan.is_tpu:
        return DeviceToHostExec(plan)
    return plan


_EXEC_RULES: Dict[type, ExecRule] = {}


def register_exec(cpu_cls: type, desc: str, conf_key: str, tag=None,
                  convert=None, tpu_cls=None, metrics=()):
    _EXEC_RULES[cpu_cls] = ExecRule(cpu_cls, desc, conf_key,
                                    tag or (lambda m: None), convert,
                                    tpu_cls=tpu_cls, metrics=metrics)


def exec_rules() -> Dict[type, ExecRule]:
    return dict(_EXEC_RULES)


# ---------------------------------------------------------------------------
# Built-in rules
# ---------------------------------------------------------------------------

def _tag_project(meta: PlanMeta) -> None:
    meta.add_exprs(meta.plan.exprs)


def _convert_project(meta: PlanMeta, children):
    p = meta.plan
    return TB.TpuProjectExec(p.exprs, children[0], p.output)


def _tag_filter(meta: PlanMeta) -> None:
    meta.add_exprs([meta.plan.condition])


def _convert_filter(meta: PlanMeta, children):
    return TB.TpuFilterExec(meta.plan.condition, children[0])


def _convert_scan(meta: PlanMeta, children):
    # local table scan stays host-side; upload happens via transition
    raise AssertionError("scan conversion handled via transition")


register_exec(CE.CpuProjectExec, "projection", "spark.rapids.sql.exec.ProjectExec",
              _tag_project, _convert_project,
              tpu_cls="execs.basic.TpuProjectExec")
register_exec(CE.CpuFilterExec, "filter", "spark.rapids.sql.exec.FilterExec",
              _tag_filter, _convert_filter,
              tpu_cls="execs.basic.TpuFilterExec")
register_exec(
    CE.CpuRangeExec, "range", "spark.rapids.sql.exec.RangeExec",
    lambda m: None,
    lambda m, ch: TB.TpuRangeExec(m.plan.start, m.plan.end, m.plan.step,
                                  m.plan.num_partitions(), m.plan.output))

from ..execs.transitions import CpuDeviceScanExec as _CpuDevScan  # noqa: E402


def _convert_device_scan(meta: PlanMeta, ch):
    from ..execs.transitions import TpuDeviceScanExec
    return TpuDeviceScanExec(meta.plan.batches, meta.plan.output)


register_exec(_CpuDevScan, "device-cached scan",
              "spark.rapids.sql.exec.InMemoryTableScanExec",
              lambda m: None, _convert_device_scan)
register_exec(
    CE.CpuUnionExec, "union", "spark.rapids.sql.exec.UnionExec",
    lambda m: None,
    lambda m, ch: TB.TpuUnionExec(ch, m.plan.output))
register_exec(
    CE.CpuLocalLimitExec, "local limit", "spark.rapids.sql.exec.LocalLimitExec",
    lambda m: None,
    lambda m, ch: TB.TpuLocalLimitExec(m.plan.n, ch[0]))
register_exec(
    CE.CpuGlobalLimitExec, "global limit", "spark.rapids.sql.exec.GlobalLimitExec",
    lambda m: None,
    lambda m, ch: TB.TpuGlobalLimitExec(m.plan.n, ch[0], m.plan.offset))


register_exec(
    CE.CpuTopNExec, "top-N (sort+limit fusion)",
    "spark.rapids.sql.exec.TakeOrderedAndProjectExec",
    lambda m: m.add_exprs([o.child for o in m.plan.order]),
    lambda m, ch: _TpuTopN(m.plan.n, m.plan.order, ch[0], m.plan.offset),
    tpu_cls="execs.sort.TpuTopNExec", metrics=("sortTime",))


def _TpuTopN(n, order, child, offset):
    from ..execs.sort import TpuTopNExec
    return TpuTopNExec(n, order, child, offset)


def _register_sample():
    from ..execs.sample import CpuSampleExec, TpuSampleExec
    register_exec(
        CpuSampleExec, "sample", "spark.rapids.sql.exec.SampleExec",
        lambda m: None,
        lambda m, ch: TpuSampleExec(m.plan.fraction, m.plan.with_replacement,
                                    m.plan.seed, ch[0]),
        tpu_cls="execs.sample.TpuSampleExec", metrics=("sampleTime",))


_register_sample()


def _tag_sort(meta: PlanMeta) -> None:
    meta.add_exprs([o.child for o in meta.plan.order])


def _convert_sort(meta: PlanMeta, ch):
    from ..execs.sort import TpuSortExec
    return TpuSortExec(meta.plan.order, meta.plan.global_sort, ch[0])


register_exec(CE.CpuSortExec, "sort", "spark.rapids.sql.exec.SortExec",
              _tag_sort, _convert_sort,
              tpu_cls="execs.sort.TpuSortExec", metrics=("sortTime",))


def _tag_aggregate(meta: PlanMeta) -> None:
    from ..execs.aggregates import split_result_exprs
    from ..expressions.aggregates import AggregateFunction
    p = meta.plan
    meta.add_exprs(p.grouping)
    agg_fns, result_exprs = split_result_exprs(p.aggregates)
    supported = {"sum", "count", "min", "max", "avg", "first", "last",
                 "stddev_samp", "stddev_pop", "var_samp", "var_pop",
                 "collect_list", "collect_set", "percentile",
                 "approx_percentile", "covar_samp", "covar_pop", "corr",
                 "bloom_filter"}
    from .typechecks import conf_gate_reason
    for fn in agg_fns:
        if fn.update_op not in supported:
            meta.will_not_work_on_tpu(
                f"aggregate {type(fn).__name__} is not supported on TPU")
        gate = conf_gate_reason(fn, meta.conf)
        if gate:
            meta.will_not_work_on_tpu(gate)
        for c in fn.children:
            meta.add_exprs([c])
    meta.add_exprs(result_exprs)


def _convert_aggregate(meta: PlanMeta, ch):
    from ..execs.aggregates import TpuHashAggregateExec
    p = meta.plan
    return TpuHashAggregateExec(p.grouping, p.aggregates, ch[0], p.output,
                                per_partition=p.per_partition)


from ..execs.aggregates import CpuHashAggregateExec as _CpuAgg  # noqa: E402

register_exec(_CpuAgg, "hash aggregate", "spark.rapids.sql.exec.HashAggregateExec",
              _tag_aggregate, _convert_aggregate,
              tpu_cls="execs.aggregates.TpuHashAggregateExec",
              metrics=("sortTime", "reduceTime", "numGroups"))


def _tag_hash_join(meta: PlanMeta) -> None:
    p = meta.plan
    meta.add_exprs(p.left_keys)
    meta.add_exprs(p.right_keys)
    if p.condition is not None:
        meta.add_exprs([p.condition])


def _convert_hash_join(meta: PlanMeta, ch):
    from ..config import SYMMETRIC_JOIN_ENABLED
    from ..execs.joins import (_MIRROR_JOIN, TpuShuffledHashJoinExec,
                               TpuShuffledSymmetricHashJoinExec)
    p = meta.plan
    ch = _maybe_coordinated_readers(meta, ch)
    if meta.conf.get(SYMMETRIC_JOIN_ENABLED) and p.join_type in _MIRROR_JOIN:
        return TpuShuffledSymmetricHashJoinExec(
            ch[0], ch[1], p.join_type, p.left_keys, p.right_keys,
            p.condition, p.output, per_partition=p.per_partition)
    return TpuShuffledHashJoinExec(ch[0], ch[1], p.join_type, p.left_keys,
                                   p.right_keys, p.condition, p.output,
                                   per_partition=p.per_partition)


def _maybe_coordinated_readers(meta: PlanMeta, ch):
    """Wrap a co-partitioned join's two exchanges in coordinated AQE readers
    (shared coalesce + skew-split specs — reference OptimizeSkewedJoin /
    CoalesceShufflePartitions planning GpuCustomShuffleReaderExec)."""
    from ..config import (AQE_ADVISORY_PARTITION_BYTES, AQE_COALESCE_ENABLED,
                          AQE_SKEW_FACTOR, AQE_SKEW_JOIN_ENABLED,
                          AQE_SKEW_THRESHOLD)
    from ..shuffle.aqe import (JoinReaderCoordinator,
                               TpuCoordinatedShuffleReaderExec)
    from ..shuffle.exchange import TpuShuffleExchangeExec
    p = meta.plan
    coalesce = meta.conf.get(AQE_COALESCE_ENABLED)
    skew = meta.conf.get(AQE_SKEW_JOIN_ENABLED)
    if not (coalesce or skew):
        return ch
    if not (getattr(p, "per_partition", False)
            and isinstance(ch[0], TpuShuffleExchangeExec)
            and isinstance(ch[1], TpuShuffleExchangeExec)
            and ch[0].partitioning == "hash"
            and ch[1].partitioning == "hash"):
        return ch
    coord = JoinReaderCoordinator(
        ch[0], ch[1], p.join_type,
        meta.conf.get(AQE_ADVISORY_PARTITION_BYTES),
        meta.conf.get(AQE_SKEW_THRESHOLD) if skew else (1 << 62),
        meta.conf.get(AQE_SKEW_FACTOR), coalesce=bool(coalesce))
    l = TpuCoordinatedShuffleReaderExec(ch[0], coord, 0, conf=meta.conf)
    r = TpuCoordinatedShuffleReaderExec(ch[1], coord, 1, conf=meta.conf)
    return [l, r]


def _tag_bnlj(meta: PlanMeta) -> None:
    if meta.plan.condition is not None:
        meta.add_exprs([meta.plan.condition])


def _convert_bnlj(meta: PlanMeta, ch):
    from ..execs.joins import TpuBroadcastNestedLoopJoinExec
    p = meta.plan
    return TpuBroadcastNestedLoopJoinExec(ch[0], ch[1], p.join_type,
                                          p.condition, p.output)


from ..execs.joins import (CpuBroadcastNestedLoopJoinExec as _CpuBnlj,  # noqa: E402
                           CpuShuffledHashJoinExec as _CpuShj)

register_exec(_CpuShj, "shuffled hash join",
              "spark.rapids.sql.exec.ShuffledHashJoinExec",
              _tag_hash_join, _convert_hash_join,
              tpu_cls="execs.joins.TpuShuffledHashJoinExec",
              metrics=("buildTime", "joinTime", "numPairs"))
def _convert_broadcast_join(meta: PlanMeta, ch):
    from ..execs.broadcast import TpuBroadcastHashJoinExec
    p = meta.plan
    return TpuBroadcastHashJoinExec(ch[0], ch[1], p.join_type, p.left_keys,
                                    p.right_keys, p.condition, p.output)


from ..execs.broadcast import CpuBroadcastHashJoinExec as _CpuBhj  # noqa: E402

register_exec(_CpuBhj, "broadcast hash join",
              "spark.rapids.sql.exec.BroadcastHashJoinExec",
              _tag_hash_join, _convert_broadcast_join,
              tpu_cls="execs.broadcast.TpuBroadcastHashJoinExec",
              metrics=("buildTime", "joinTime", "numPairs"))
register_exec(_CpuBnlj, "broadcast nested loop join",
              "spark.rapids.sql.exec.BroadcastNestedLoopJoinExec",
              _tag_bnlj, _convert_bnlj)


def _convert_cartesian(meta: PlanMeta, ch):
    from ..execs.joins import TpuCartesianProductExec
    p = meta.plan
    return TpuCartesianProductExec(ch[0], ch[1], p.condition, p.output)


from ..execs.joins import CpuCartesianProductExec as _CpuCart  # noqa: E402

register_exec(_CpuCart, "cartesian product",
              "spark.rapids.sql.exec.CartesianProductExec",
              _tag_bnlj, _convert_cartesian,
              tpu_cls="execs.joins.TpuCartesianProductExec",
              metrics=("joinTime", "numPairs"))


def _tag_write(meta: PlanMeta) -> None:
    from ..config import ORC_WRITE_ENABLED, PARQUET_WRITE_ENABLED
    fmt = meta.plan.spec.fmt
    keys = {"parquet": PARQUET_WRITE_ENABLED, "orc": ORC_WRITE_ENABLED}
    entry = keys.get(fmt)
    if entry is not None and not meta.conf.get(entry):
        meta.will_not_work_on_tpu(f"{fmt} writes disabled via {entry.key}")


def _convert_write(meta: PlanMeta, ch):
    from ..execs.write import TpuDataWritingCommandExec
    return TpuDataWritingCommandExec(ch[0], meta.plan.spec)


from ..execs.write import CpuDataWritingCommandExec as _CpuWrite  # noqa: E402

register_exec(_CpuWrite, "data writing command",
              "spark.rapids.sql.exec.DataWritingCommandExec",
              _tag_write, _convert_write,
              tpu_cls="execs.write.TpuDataWritingCommandExec",
              metrics=("writeTime", "numFiles", "numWrittenRows"))


def _convert_subquery_broadcast(meta: PlanMeta, ch):
    from ..execs.subquery import TpuSubqueryBroadcastExec
    return TpuSubqueryBroadcastExec(ch[0], meta.plan.key_ordinal)


from ..execs.subquery import CpuSubqueryBroadcastExec as _CpuSubq  # noqa: E402

register_exec(_CpuSubq, "subquery broadcast (DPP key collection)",
              "spark.rapids.sql.exec.SubqueryBroadcastExec",
              None, _convert_subquery_broadcast)


def _tag_exchange(meta: PlanMeta) -> None:
    meta.add_exprs(meta.plan.keys)


def _mesh_align_consistent(meta: PlanMeta) -> bool:
    """May this exchange re-plan to mesh-size partitions without breaking
    co-partitioning? A join pairs partition i of both inputs, so BOTH of
    its exchanges must make the same alignment decision — each side
    independently checks every sibling exchange's static eligibility and
    aligns only when all would. Non-join parents have no pairing
    constraint."""
    from ..parallel.mesh import collective_payload
    from ..shuffle.exchange import CpuShuffleExchangeExec
    parent = meta.parent
    if parent is None or "Join" not in type(parent.plan).__name__:
        return True
    for sib in parent.child_plans:
        sp = sib.plan
        if isinstance(sp, CpuShuffleExchangeExec) \
                and sp.partitioning == "hash" \
                and collective_payload(sp.output, meta.conf) is None:
            return False
    return True


def _convert_exchange(meta: PlanMeta, ch):
    from ..config import (AQE_COALESCE_ENABLED,
                          AQE_ADVISORY_PARTITION_BYTES,
                          MESH_ALIGN_PARTITIONS, MESH_COLLECTIVE_ENABLED)
    from ..parallel.mesh import collective_payload, mesh_session_active
    from ..shuffle.exchange import (TpuShuffleExchangeExec,
                                    TpuShuffleReaderExec)
    p = meta.plan
    n_out = p.num_partitions()
    # mesh session (docs/distributed.md): the planner — not a runtime
    # probe — selects the collective data plane. Hash exchanges re-plan to
    # mesh-size partitions (alignPartitions) so the on-device murmur3 % n
    # routing matches the shard count, and eligible exchanges carry
    # `collective_planned` so materialization runs ONE fabric collective.
    # String payloads are eligible via the dictionary-encode pass
    # (collective_payload == "dict"): the fabric carries int32 codes plus
    # one broadcast dictionary instead of raw bytes.
    ms = mesh_session_active(meta.conf)
    mesh = ms if meta.conf.get(MESH_COLLECTIVE_ENABLED) else None
    payload = collective_payload(ch[0].output, meta.conf) \
        if mesh is not None else None
    eligible = mesh is not None \
        and p.partitioning in ("hash", "single") \
        and payload is not None
    if eligible and p.partitioning == "hash" \
            and meta.conf.get(MESH_ALIGN_PARTITIONS) \
            and _mesh_align_consistent(meta):
        n_out = mesh.devices.size
    exch = TpuShuffleExchangeExec(ch[0], p.partitioning, p.keys, n_out)
    if eligible and (p.partitioning == "single"
                     or n_out == mesh.devices.size):
        exch.collective_planned = True
    elif ms is not None:
        # plan-time "why not collective" (obs/mesh_profile.py): a mesh
        # session routed this exchange per-map — say why in the plan
        # (node_desc → explain("metrics")) instead of a code comment
        if mesh is None:
            reason = "collective_conf_off"
        elif p.partitioning not in ("hash", "single"):
            reason = f"partitioning_{p.partitioning}"
        elif collective_payload(ch[0].output, meta.conf) is None:
            reason = "string_or_nested_payload"
        else:
            reason = "partitions_misaligned"
        exch._collective_reason = reason
    # AQE partition coalescing (reference GpuCustomShuffleReaderExec).
    # NOT applied when the exchange feeds a co-partitioned join: each side
    # would coalesce on its own sizes and partition i of the left would no
    # longer hold the same key hashes as partition i of the right (Spark's
    # AQE coordinates both sides through the query stage; we keep the safe
    # subset — aggregates and other single-input consumers).
    parent_plan = meta.parent.plan if meta.parent is not None else None
    feeds_join = parent_plan is not None and \
        "Join" in type(parent_plan).__name__
    if meta.conf.get(AQE_COALESCE_ENABLED) and p.partitioning == "hash" \
            and not feeds_join:
        return TpuShuffleReaderExec(
            exch, meta.conf.get(AQE_ADVISORY_PARTITION_BYTES),
            conf=meta.conf)
    return exch


from ..shuffle.exchange import CpuShuffleExchangeExec as _CpuExch  # noqa: E402

register_exec(_CpuExch, "shuffle exchange",
              "spark.rapids.sql.exec.ShuffleExchangeExec",
              _tag_exchange, _convert_exchange,
              tpu_cls="shuffle.exchange.TpuShuffleExchangeExec",
              metrics=("partitionTime", "serializationTime",
                       "deserializationTime", "dictionaryEncodeTime"))


def _tag_file_scan(meta: PlanMeta) -> None:
    from ..config import (CSV_ENABLED, JSON_ENABLED, ORC_ENABLED,
                          PARQUET_ENABLED)
    fmt_keys = {"parquet": PARQUET_ENABLED, "csv": CSV_ENABLED,
                "json": JSON_ENABLED, "orc": ORC_ENABLED}
    entry = fmt_keys.get(meta.plan.fmt)
    if entry is not None and not meta.conf.get(entry):
        meta.will_not_work_on_tpu(f"{meta.plan.fmt} scans disabled via {entry.key}")


def _convert_file_scan(meta: PlanMeta, ch):
    from ..io.parquet import TpuFileScanExec
    p = meta.plan
    return TpuFileScanExec(p.paths, p.fmt, p.output,
                           pushed_filters=p.pushed_filters, options=p.options,
                           num_partitions=p.num_partitions())


from ..io.parquet import CpuFileScanExec as _CpuScan  # noqa: E402

register_exec(_CpuScan, "file scan", "spark.rapids.sql.exec.FileSourceScanExec",
              _tag_file_scan, _convert_file_scan,
              tpu_cls="io.parquet.TpuFileScanExec",
              metrics=("scanTime", "uploadTime", "filesRead"))


def _tag_window(meta: PlanMeta) -> None:
    from ..expressions.aggregates import AggregateFunction
    from ..window import (CumeDist, DenseRank, Lag, Lead, NTile, PercentRank,
                          Rank, RowNumber,
                          UNBOUNDED_FOLLOWING, UNBOUNDED_PRECEDING, CURRENT_ROW)
    for we in meta.plan.window_exprs:
        fn = we.function
        if isinstance(fn, AggregateFunction):
            if fn.update_op not in ("sum", "count", "avg", "min", "max",
                                    "collect_list", "collect_set"):
                meta.will_not_work_on_tpu(
                    f"window aggregate {type(fn).__name__} not supported on TPU")
            # bounded min/max frames run via the sparse-table range reduce
            # (TpuWindowExec._bounded_minmax); collect_list lowers to a
            # ragged gather for running/whole-partition frames and the
            # host-assisted oracle otherwise (collect_set always host)
            for c in fn.children:
                meta.add_exprs([c])
        elif not isinstance(fn, (RowNumber, Rank, DenseRank, Lead, Lag,
                                 NTile, PercentRank, CumeDist)):
            meta.will_not_work_on_tpu(
                f"window function {type(fn).__name__} not supported on TPU")
        meta.add_exprs(we.spec.partition_by)
        meta.add_exprs([o.child for o in we.spec.order_by])


def _convert_window(meta: PlanMeta, ch):
    from ..execs.window import TpuWindowExec
    return TpuWindowExec(meta.plan.window_exprs, ch[0], meta.plan.output)


from ..execs.window import CpuWindowExec as _CpuWin  # noqa: E402

register_exec(_CpuWin, "window", "spark.rapids.sql.exec.WindowExec",
              _tag_window, _convert_window,
              tpu_cls="execs.window.TpuWindowExec")


def _tag_generate(meta: PlanMeta) -> None:
    from ..expressions.generators import Explode, Stack
    from ..expressions.json import JsonTuple
    gen = meta.plan.generator
    if not isinstance(gen, (Explode, Stack, JsonTuple)):
        meta.will_not_work_on_tpu(
            f"generator {type(gen).__name__} is not supported on TPU")
    meta.add_exprs(list(gen.children))


def _convert_generate(meta: PlanMeta, ch):
    from ..execs.generate import TpuGenerateExec
    p = meta.plan
    return TpuGenerateExec(p.generator, p.gen_names, ch[0], p.output)


def _tag_expand(meta: PlanMeta) -> None:
    for proj in meta.plan.projections:
        meta.add_exprs(proj)


def _convert_expand(meta: PlanMeta, ch):
    from ..execs.generate import TpuExpandExec
    return TpuExpandExec(meta.plan.projections, ch[0], meta.plan.output)


from ..execs.generate import (CpuExpandExec as _CpuExpand,  # noqa: E402
                              CpuGenerateExec as _CpuGen)

register_exec(_CpuGen, "generate", "spark.rapids.sql.exec.GenerateExec",
              _tag_generate, _convert_generate,
              tpu_cls="execs.generate.TpuGenerateExec",
              metrics=("numInputRows",))
register_exec(_CpuExpand, "expand", "spark.rapids.sql.exec.ExpandExec",
              _tag_expand, _convert_expand)


def wrap_and_tag_plan(plan: PhysicalPlan, conf: RapidsConf) -> PlanMeta:
    """reference wrapAndTagPlan (GpuOverrides.scala:4358)."""
    rule = _EXEC_RULES.get(type(plan))
    meta = PlanMeta(plan, conf, rule)
    meta.child_plans = [wrap_and_tag_plan(c, conf) for c in plan.children]
    for cm in meta.child_plans:
        cm.parent = meta
    return meta


class TpuOverrides:
    """reference GpuOverrides.apply (GpuOverrides.scala:4557)."""

    @staticmethod
    def apply(plan: PhysicalPlan, conf: RapidsConf) -> PhysicalPlan:
        if not conf.get(SQL_ENABLED):
            return plan
        meta = wrap_and_tag_plan(plan, conf)
        meta.tag_for_tpu()
        from .cbo import apply_cbo
        for opt in apply_cbo(meta, conf):
            log.info(opt)
        explain = str(conf.get(EXPLAIN)).upper()
        if explain in ("NOT_ON_TPU", "ALL"):
            reasons: List[str] = []
            meta.collect_fallback_reasons(reasons)
            for r in reasons:
                log.info(r)
        if conf.explain_only:
            reasons = []
            meta.collect_fallback_reasons(reasons)
            return plan  # explainOnly: report, execute on CPU
        converted = meta.convert_if_needed()
        final = TpuTransitionOverrides.apply(converted, conf)
        from ..execs.compiled import compile_agg_stages
        from ..execs.compiled_join import compile_join_agg_stages
        final = compile_agg_stages(compile_join_agg_stages(final, conf), conf)
        # whole-stage segment fusion for whatever the compiled stages left
        # on the general path (execs/fusion.py): adjacent project/filter
        # chains — plus an inner-join probe at the segment bottom
        # (opjit.fuseJoins) and a trailing grouped aggregate at its top
        # (opjit.fuseAggs) — collapse into one segment between exchanges
        from ..execs.fusion import fuse_stage_segments
        final = fuse_stage_segments(final, conf)
        # batch coalescing (execs/coalesce.py): small batches concatenate up
        # to the batch-size targets ahead of batch-hungry operators — runs
        # last so fused segments are insertion targets too (a segment that
        # absorbed a join gets require_single on its build children)
        from ..execs.coalesce import insert_coalesce
        return insert_coalesce(final, conf)

    @staticmethod
    def explain_plan(plan: PhysicalPlan, conf: RapidsConf) -> str:
        """reference ExplainPlan.explainCatalystSQLPlan."""
        meta = wrap_and_tag_plan(plan, conf)
        meta.tag_for_tpu()
        reasons: List[str] = []
        meta.collect_fallback_reasons(reasons)
        if not reasons:
            return "The whole plan can run on the TPU"
        return "\n".join(reasons)


class TpuTransitionOverrides:
    """reference GpuTransitionOverrides.scala: final boundary fixups + the
    everything-on-TPU test assertion (assertIsOnTheGpu:616)."""

    @staticmethod
    def apply(plan: PhysicalPlan, conf: RapidsConf) -> PhysicalPlan:
        plan = _collapse_transitions(plan)
        plan = ensure_host(plan)  # query output is host rows
        if conf.get(TEST_ASSERT_ON_TPU):
            TpuTransitionOverrides.assert_is_on_tpu(plan)
        return plan

    @staticmethod
    def assert_is_on_tpu(plan: PhysicalPlan) -> None:
        allowed_cpu = (DeviceToHostExec, HostToDeviceExec,
                       CE.CpuLocalTableScanExec, CE.CpuCachedScanExec)
        for node in plan.collect_nodes():
            if isinstance(node, CpuExec) and not isinstance(node, allowed_cpu):
                raise AssertionError(
                    f"Part of the plan is not columnar: {node.node_desc()}\n"
                    + plan.tree_string())


def _collapse_transitions(plan: PhysicalPlan) -> PhysicalPlan:
    """Remove HostToDevice(DeviceToHost(x)) → x and vice versa."""
    new_children = [_collapse_transitions(c) for c in plan.children]
    if isinstance(plan, HostToDeviceExec) and isinstance(new_children[0], DeviceToHostExec):
        return new_children[0].children[0]
    if isinstance(plan, DeviceToHostExec) and isinstance(new_children[0], HostToDeviceExec):
        return new_children[0].children[0]
    if all(a is b for a, b in zip(new_children, plan.children)):
        return plan
    import copy
    new = copy.copy(plan)
    new.children = new_children
    return new
