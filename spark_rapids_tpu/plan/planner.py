"""Planner: analyzed logical plan → CPU physical plan.

Stands in for Spark's SparkPlanner (the reference never owns this; a standalone
framework must). The produced plan is all-CPU; TpuOverrides then retargets it,
matching the reference's flow where Spark plans first and the plugin rewrites
(SURVEY.md §3.2).
"""

from __future__ import annotations

from typing import List

from ..config import RapidsConf
from ..execs import cpu as CE
from ..execs.base import PhysicalPlan
from . import logical as L


def _compile_udfs(exprs, conf: RapidsConf):
    """Reference udf-compiler LogicalPlanRules hook: rewrite row python UDFs
    into columnar expression trees when the compiler is enabled."""
    from ..config import UDF_COMPILER_ENABLED
    if not conf.get(UDF_COMPILER_ENABLED):
        return list(exprs)
    from ..udf_compiler import rewrite_compiled_udfs
    return [rewrite_compiled_udfs(e, conf) for e in exprs]


def plan_physical(plan: L.LogicalPlan, conf: RapidsConf) -> PhysicalPlan:
    from ..io.cache import CachedRelation, DeviceCachedRelation
    if isinstance(plan, CachedRelation):
        from ..execs.cpu import CpuCachedScanExec
        return CpuCachedScanExec(plan, plan.output)
    if isinstance(plan, DeviceCachedRelation):
        from ..execs.transitions import CpuDeviceScanExec
        return CpuDeviceScanExec(plan.batches(), plan.output)
    if isinstance(plan, L.LocalRelation):
        return CE.CpuLocalTableScanExec(plan.table, plan.num_partitions, plan.output)
    if isinstance(plan, L.Range):
        return CE.CpuRangeExec(plan.start, plan.end, plan.step,
                               plan.num_partitions, plan.output)
    if isinstance(plan, L.FileScan):
        from ..io.parquet import CpuFileScanExec
        return CpuFileScanExec(plan.paths, plan.fmt, plan.output,
                               options=plan.options,
                               num_partitions=plan.num_partitions)
    if isinstance(plan, L.Project):
        child = plan_physical(plan.child, conf)
        return CE.CpuProjectExec(_compile_udfs(plan.exprs, conf), child,
                                 plan.output)
    if isinstance(plan, L.Filter):
        child = plan_physical(plan.child, conf)
        if isinstance(plan.child, L.FileScan):
            # predicate pushdown: route pushable conjuncts to row-group pruning,
            # keep the exact Filter above (reference GpuParquetFileFilterHandler)
            from ..io.base_scan import pushable, split_conjuncts
            from ..io.parquet import CpuFileScanExec
            conjuncts = split_conjuncts(plan.condition)
            pushed = [c for c in conjuncts if pushable(c)]
            if pushed and isinstance(child, CpuFileScanExec):
                child = CpuFileScanExec(child.paths, child.fmt, child.output,
                                        pushed_filters=pushed,
                                        options=child.options,
                                        num_partitions=child.num_partitions())
        return CE.CpuFilterExec(_compile_udfs([plan.condition], conf)[0],
                                child)
    if isinstance(plan, L.Limit):
        inner = plan.children[0]
        if isinstance(inner, L.Sort) and inner.global_sort:
            # Limit(Sort) → TopN (reference TakeOrderedAndProject/GpuTopN):
            # per-partition top-N + merge instead of a global sort
            child = plan_physical(inner.children[0], conf)
            return CE.CpuTopNExec(plan.n, inner.order, child, plan.offset)
        child = plan_physical(inner, conf)
        # local limit must keep offset+n rows — the global stage still has
        # `offset` rows to skip
        return CE.CpuGlobalLimitExec(
            plan.n, CE.CpuLocalLimitExec(plan.n + plan.offset, child),
            plan.offset)
    if isinstance(plan, L.Sample):
        from ..execs.sample import CpuSampleExec
        child = plan_physical(plan.children[0], conf)
        return CpuSampleExec(plan.fraction, plan.with_replacement, plan.seed,
                             child)
    if isinstance(plan, L.Union):
        children = [plan_physical(c, conf) for c in plan.children]
        return CE.CpuUnionExec(children, plan.output)
    if isinstance(plan, L.Sort):
        child = plan_physical(plan.children[0], conf)
        return CE.CpuSortExec(plan.order, plan.global_sort, child)
    if isinstance(plan, L.Aggregate):
        from ..config import SHUFFLE_PARTITIONS
        from ..execs.aggregates import CpuHashAggregateExec
        from ..shuffle.exchange import CpuShuffleExchangeExec
        child = plan_physical(plan.children[0], conf)
        if plan.grouping and child.num_partitions() > 1:
            # distribute by grouping keys so each output partition holds whole
            # groups (Spark: partial agg → Exchange(hash) → final agg; partial
            # state compaction before the exchange is a planned optimization)
            n = min(conf.get(SHUFFLE_PARTITIONS), max(child.num_partitions(), 2))
            child = CpuShuffleExchangeExec(child, "hash", plan.grouping, n)
            return CpuHashAggregateExec(plan.grouping, plan.aggregates, child,
                                        plan.output, per_partition=True)
        return CpuHashAggregateExec(plan.grouping, plan.aggregates, child,
                                    plan.output)
    if isinstance(plan, L.Join):
        from ..config import SHUFFLE_PARTITIONS
        from ..execs.joins import (CpuBroadcastNestedLoopJoinExec,
                                   CpuShuffledHashJoinExec)
        from ..shuffle.exchange import CpuShuffleExchangeExec
        left = plan_physical(plan.left, conf)
        right = plan_physical(plan.right, conf)
        if plan.left_keys:
            from ..config import AUTO_BROADCAST_JOIN_THRESHOLD
            from ..execs.broadcast import (BROADCAST_RIGHT_TYPES,
                                           CpuBroadcastHashJoinExec,
                                           estimated_size_bytes)
            threshold = conf.get(AUTO_BROADCAST_JOIN_THRESHOLD)
            r_size = estimated_size_bytes(right)
            if r_size is None:
                # broadcast-vs-shuffled decided by ESTIMATED size, not only
                # a directly measurable build side: fall back to the CBO's
                # logical cardinality estimate (reference
                # CostBasedOptimizer.scala RowCountPlanVisitor)
                from ..config import LOGICAL_JOIN_STRATEGY
                from .cbo import estimate_logical_bytes
                if conf.get(LOGICAL_JOIN_STRATEGY):
                    r_size = estimate_logical_bytes(plan.right)
            _plan_dpp(plan, left, right, conf, threshold, r_size)
            if (threshold > 0 and r_size is not None and r_size <= threshold
                    and plan.join_type in BROADCAST_RIGHT_TYPES
                    and left.num_partitions() > 1):
                return CpuBroadcastHashJoinExec(
                    left, right, plan.join_type, plan.left_keys,
                    plan.right_keys, plan.condition, plan.output)
            if left.num_partitions() > 1 or right.num_partitions() > 1:
                n = min(conf.get(SHUFFLE_PARTITIONS),
                        max(left.num_partitions(), right.num_partitions(), 2))
                left = CpuShuffleExchangeExec(left, "hash", plan.left_keys, n)
                right = CpuShuffleExchangeExec(right, "hash", plan.right_keys, n)
                return CpuShuffledHashJoinExec(left, right, plan.join_type,
                                               plan.left_keys, plan.right_keys,
                                               plan.condition, plan.output,
                                               per_partition=True)
            return CpuShuffledHashJoinExec(left, right, plan.join_type,
                                           plan.left_keys, plan.right_keys,
                                           plan.condition, plan.output)
        if plan.join_type in ("inner", "cross"):
            from ..config import AUTO_BROADCAST_JOIN_THRESHOLD
            from ..execs.broadcast import estimated_size_bytes
            from ..execs.joins import CpuCartesianProductExec
            threshold = conf.get(AUTO_BROADCAST_JOIN_THRESHOLD)
            r_size = estimated_size_bytes(right)
            # neither side broadcastable → dedicated pairwise-partition
            # product (Spark CartesianProductExec), not a broadcast NLJ
            if threshold > 0 and r_size is not None and r_size > threshold:
                return CpuCartesianProductExec(left, right, plan.condition,
                                               plan.output)
        return CpuBroadcastNestedLoopJoinExec(left, right, plan.join_type,
                                              plan.condition, plan.output)
    if isinstance(plan, L.Generate):
        from ..execs.generate import CpuGenerateExec
        child = plan_physical(plan.children[0], conf)
        return CpuGenerateExec(plan.generator, plan.gen_names, child, plan.output)
    if isinstance(plan, L.Expand):
        from ..execs.generate import CpuExpandExec
        child = plan_physical(plan.children[0], conf)
        return CpuExpandExec(plan.projections, child, plan.output)
    if isinstance(plan, L.WindowOp):
        from ..execs.window import CpuWindowExec
        child = plan_physical(plan.children[0], conf)
        return CpuWindowExec(plan.window_exprs, child, plan.output)
    if isinstance(plan, L.Repartition):
        from ..shuffle.exchange import plan_cpu_exchange
        return plan_cpu_exchange(plan, conf)
    raise NotImplementedError(f"no physical plan for {type(plan).__name__}")


def _plan_dpp(join_plan, left_phys, right_phys, conf, threshold, r_size) -> None:
    """Dynamic partition pruning (reference GpuSubqueryBroadcastExec +
    DynamicPruningExpression): when an equi-join key is a hive partition
    column of a scan on the probe side and the build side is small, attach a
    runtime subquery that collects the build side's distinct keys so the scan
    skips partitions before any IO. Pruning the left side is sound for join
    types that cannot resurrect unmatched left rows."""
    from ..config import SUBQUERY_BROADCAST_ENABLED
    from ..execs.subquery import (CpuSubqueryBroadcastExec,
                                  plan_dynamic_pruning)
    from ..io.parquet import FileScanBase
    if not conf.get(SUBQUERY_BROADCAST_ENABLED):
        return
    if join_plan.join_type not in ("inner", "leftsemi", "semi"):
        return
    if threshold <= 0 or r_size is None or r_size > threshold:
        return
    scans = [n for n in left_phys.collect_nodes()
             if isinstance(n, FileScanBase)
             and n.options.get("__partition_cols__")]
    if not scans:
        return
    for lk, rk in zip(join_plan.left_keys, join_plan.right_keys):
        name = getattr(lk, "name", None)
        key_id = getattr(lk, "expr_id", None)
        if name is None or key_id is None:
            continue
        ordinal = next((i for i, a in enumerate(right_phys.output)
                        if a.expr_id == getattr(rk, "expr_id", None)), None)
        if ordinal is None:
            continue
        subq = None  # one shared key collection per join key
        for scan in scans:
            # the join key must BE this scan's partition-column attribute
            # (expr_id match) — a name-only match would prune unrelated
            # scans that happen to share the partition column's name
            if not any(a.expr_id == key_id and a.name == name
                       for a in scan.output):
                continue
            if any(name == pc for pc, _ in
                   scan.options.get("__partition_cols__", ())):
                if subq is None:
                    subq = CpuSubqueryBroadcastExec(right_phys, ordinal)
                plan_dynamic_pruning(scan.options, name, subq)
