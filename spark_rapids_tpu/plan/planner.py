"""Planner: analyzed logical plan → CPU physical plan.

Stands in for Spark's SparkPlanner (the reference never owns this; a standalone
framework must). The produced plan is all-CPU; TpuOverrides then retargets it,
matching the reference's flow where Spark plans first and the plugin rewrites
(SURVEY.md §3.2).
"""

from __future__ import annotations

from typing import List

from ..config import RapidsConf
from ..execs import cpu as CE
from ..execs.base import PhysicalPlan
from . import logical as L


def plan_physical(plan: L.LogicalPlan, conf: RapidsConf) -> PhysicalPlan:
    if isinstance(plan, L.LocalRelation):
        return CE.CpuLocalTableScanExec(plan.table, plan.num_partitions, plan.output)
    if isinstance(plan, L.Range):
        return CE.CpuRangeExec(plan.start, plan.end, plan.step,
                               plan.num_partitions, plan.output)
    if isinstance(plan, L.Project):
        child = plan_physical(plan.child, conf)
        return CE.CpuProjectExec(plan.exprs, child, plan.output)
    if isinstance(plan, L.Filter):
        child = plan_physical(plan.child, conf)
        return CE.CpuFilterExec(plan.condition, child)
    if isinstance(plan, L.Limit):
        child = plan_physical(plan.children[0], conf)
        return CE.CpuGlobalLimitExec(plan.n, CE.CpuLocalLimitExec(plan.n, child),
                                     plan.offset)
    if isinstance(plan, L.Union):
        children = [plan_physical(c, conf) for c in plan.children]
        return CE.CpuUnionExec(children, plan.output)
    if isinstance(plan, L.Sort):
        child = plan_physical(plan.children[0], conf)
        return CE.CpuSortExec(plan.order, plan.global_sort, child)
    if isinstance(plan, L.Aggregate):
        from ..execs.aggregates import plan_cpu_aggregate
        return plan_cpu_aggregate(plan, conf)
    if isinstance(plan, L.Join):
        from ..execs.joins import plan_cpu_join
        return plan_cpu_join(plan, conf)
    if isinstance(plan, L.Repartition):
        from ..shuffle.exchange import plan_cpu_exchange
        return plan_cpu_exchange(plan, conf)
    raise NotImplementedError(f"no physical plan for {type(plan).__name__}")
