"""Logical optimizer: rule pipeline over plan/logical.py, run before
``plan_physical``.

Reference: the plugin never owns Catalyst's optimizer, but its
CostBasedOptimizer.scala (SURVEY §2.1) is the template for plan-shaping
decisions made from cardinality estimates; this module is the standalone
framework's analogue, with three first rules:

* **pushdown** — Filter and pruning-Project operators sitting ON TOP of
  a ``Repartition`` move BELOW it, so rows are dropped and payloads
  narrowed before the exchange materializes them (``_convert_exchange``
  moves whatever payload it is handed).
* **joinStrategy** — build-side swap for inner equi-joins when
  ``plan/cbo.py``'s logical cardinality estimate says the right (build)
  side is larger than the left by ``joinStrategy.swapRatio``; a
  restoring Project keeps the original output column order.
* **columnPruning** — top-down required-column analysis through
  Project/Filter/Aggregate/Join down to the scans: ``FileScan`` output is
  narrowed in place, in-memory relations (whose scan execs always yield
  full-width batches) get a pass-through Project, and wide Join/Aggregate
  inputs are wrapped so exchange payloads carry exactly the referenced
  columns — no hand-written selects.

Every rule preserves expression OBJECT identity for unchanged subtrees
(``Expression.transform`` contract) and attribute ``expr_id``s for
rebuilt nodes — ``bind_references`` resolves strictly by expr_id, and the
plan cache's parameter-slot rebinding pairs literal objects by identity.
Nodes a rule created or modified carry the rule name in ``_opt_rules``
(surfaced by ``explain()``).
"""

from __future__ import annotations

import copy
from typing import List, Optional, Set, Tuple

from ..config import (LOGICAL_COLUMN_PRUNING, LOGICAL_JOIN_STRATEGY,
                      LOGICAL_JOIN_SWAP_RATIO, LOGICAL_PUSHDOWN, RapidsConf)
from ..expressions.base import AttributeReference
from . import logical as L

RULE_PRUNE = "ColumnPruning"
RULE_PUSHDOWN = "PushdownThroughExchange"
RULE_JOIN = "CostBasedJoin"


def _tag(node, rule: str):
    rules = list(getattr(node, "_opt_rules", ()))
    if rule not in rules:
        rules.append(rule)
    node._opt_rules = rules
    return node


def _refs(e) -> Set[int]:
    """expr_ids of every attribute an expression (or SortOrder) references."""
    if e is None:
        return set()
    if isinstance(e, L.SortOrder):
        return _refs(e.child)
    return {a.expr_id for a in
            e.collect(lambda x: isinstance(x, AttributeReference))}


def _refs_all(exprs) -> Set[int]:
    out: Set[int] = set()
    for e in exprs:
        out |= _refs(e)
    return out


def _out_ids(plan: L.LogicalPlan) -> Set[int]:
    return {a.expr_id for a in plan.output}


def _is_pruning_project(p: L.Project) -> bool:
    """A Project that only selects existing columns (no computation)."""
    return all(isinstance(e, AttributeReference) for e in p.exprs)


def _passthrough_project(child: L.LogicalPlan, keep_ids: Set[int],
                         rule: str) -> L.LogicalPlan:
    """Wrap ``child`` in a Project selecting only ``keep_ids`` (child
    output order). Pass-through attributes keep their expr_ids
    (Project._reuse_id), so ancestors still bind."""
    kept = [a for a in child.output if a.expr_id in keep_ids]
    if not kept:
        kept = child.output[:1]
    if len(kept) == len(child.output):
        return child
    return _tag(L.Project(kept, child), rule)


def _rebuild_with_children(plan: L.LogicalPlan, children) -> L.LogicalPlan:
    """Shallow-copy a node with new children, keeping every resolved field
    (exprs, output attrs) object-identical — never re-runs __init__, which
    would mint fresh expr_ids."""
    if all(a is b for a, b in zip(children, plan.children)) \
            and len(children) == len(plan.children):
        return plan
    new = copy.copy(plan)
    new.children = tuple(children)
    return new


# ---------------------------------------------------------------------------
# Rule: filter / pruning-projection pushdown through Repartition
# ---------------------------------------------------------------------------

def _pushdown_exchange(plan: L.LogicalPlan, applied: Set[str]) -> L.LogicalPlan:
    children = [_pushdown_exchange(c, applied) for c in plan.children]
    plan = _rebuild_with_children(plan, children)

    if isinstance(plan, L.Filter) and isinstance(plan.child, L.Repartition):
        # Filter(Repartition(c)) -> Repartition(Filter(c)): the exchange
        # moves only surviving rows. Output sets are identical (both
        # follow the grandchild), and hash keys see the same columns.
        rep = plan.child
        new_filter = _tag(_rebuild_with_children(plan, (rep.children[0],)),
                          RULE_PUSHDOWN)
        new_rep = _tag(_rebuild_with_children(rep, (new_filter,)),
                       RULE_PUSHDOWN)
        applied.add(RULE_PUSHDOWN)
        return _pushdown_exchange(new_rep, applied)

    if isinstance(plan, L.Project) and isinstance(plan.child, L.Repartition) \
            and _is_pruning_project(plan):
        rep = plan.child
        keep = {e.expr_id for e in plan.exprs}
        if _refs_all(rep.keys) <= keep:
            # Project(Repartition(c)) -> Repartition(Project(c)): a pure
            # column-pruning select narrows the exchange payload; legal
            # only while the partitioning keys survive the projection.
            new_proj = _tag(_rebuild_with_children(plan, (rep.children[0],)),
                            RULE_PUSHDOWN)
            new_rep = _tag(_rebuild_with_children(rep, (new_proj,)),
                           RULE_PUSHDOWN)
            applied.add(RULE_PUSHDOWN)
            return new_rep
    return plan


# ---------------------------------------------------------------------------
# Rule: cost-based build-side swap (inner equi-joins)
# ---------------------------------------------------------------------------

def _join_swap(plan: L.LogicalPlan, conf: RapidsConf,
               applied: Set[str]) -> L.LogicalPlan:
    children = [_join_swap(c, conf, applied) for c in plan.children]
    plan = _rebuild_with_children(plan, children)

    if not (isinstance(plan, L.Join) and plan.join_type == "inner"
            and plan.left_keys and not getattr(plan, "_opt_swapped", False)):
        return plan
    from .cbo import estimate_logical_bytes
    est_l = estimate_logical_bytes(plan.left)
    est_r = estimate_logical_bytes(plan.right)
    ratio = conf.get(LOGICAL_JOIN_SWAP_RATIO)
    if est_l is None or est_r is None or est_r <= est_l * ratio:
        return plan
    # Build side (right) estimated larger: swap so the smaller side is
    # built/broadcast. Keys/condition are already resolved, so the Join
    # constructor keeps the same expression objects; a restoring Project
    # of the ORIGINAL output attrs keeps the parent-visible column order.
    original = plan.output
    swapped = L.Join(plan.right, plan.left, "inner",
                     plan.right_keys, plan.left_keys, plan.condition)
    swapped._opt_swapped = True
    _tag(swapped, RULE_JOIN)
    restore = _tag(L.Project(original, swapped), RULE_JOIN)
    applied.add(RULE_JOIN)
    return restore


# ---------------------------------------------------------------------------
# Rule: logical column pruning
# ---------------------------------------------------------------------------

def _prune(plan: L.LogicalPlan, required: Optional[Set[int]],
           applied: Set[str]) -> L.LogicalPlan:
    """required=None means "every output column" (the query root, or a
    parent we cannot analyze)."""
    if isinstance(plan, L.Project):
        if required is None:
            kept_ix = list(range(len(plan.exprs)))
        else:
            kept_ix = [i for i, a in enumerate(plan._output)
                       if a.expr_id in required]
            if not kept_ix:
                kept_ix = [0]
        kept_exprs = [plan.exprs[i] for i in kept_ix]
        child = _prune(plan.child, _refs_all(kept_exprs), applied)
        if len(kept_ix) == len(plan.exprs):
            return _rebuild_with_children(plan, (child,))
        new = object.__new__(L.Project)
        new.children = (child,)
        new.exprs = kept_exprs
        new._output = [plan._output[i] for i in kept_ix]
        applied.add(RULE_PRUNE)
        return _tag(new, RULE_PRUNE)

    if isinstance(plan, L.Filter):
        need = None if required is None \
            else (required | _refs(plan.condition))
        child = _prune(plan.child, need, applied)
        return _rebuild_with_children(plan, (child,))

    if isinstance(plan, (L.Limit, L.Sample)):
        child = _prune(plan.children[0], required, applied)
        return _rebuild_with_children(plan, (child,))

    if isinstance(plan, L.Sort):
        need = None if required is None \
            else (required | _refs_all(plan.order))
        child = _prune(plan.children[0], need, applied)
        return _rebuild_with_children(plan, (child,))

    if isinstance(plan, L.Repartition):
        need = None if required is None \
            else (required | _refs_all(plan.keys))
        child = _prune(plan.children[0], need, applied)
        return _rebuild_with_children(plan, (child,))

    if isinstance(plan, L.Aggregate):
        n_group = len(plan.grouping)
        if required is None:
            kept_ix = list(range(len(plan.aggregates)))
        else:
            # grouping columns always stay (they define the groups and
            # lead the output); unreferenced aggregate columns drop
            kept_ix = [i for i in range(len(plan.aggregates))
                       if plan._output[n_group + i].expr_id in required]
            if not kept_ix and not plan.grouping:
                kept_ix = [0]
        kept_aggs = [plan.aggregates[i] for i in kept_ix]
        need = _refs_all(plan.grouping) | _refs_all(kept_aggs)
        child = _prune(plan.children[0], need, applied)
        if need and any(a.expr_id not in need for a in child.output):
            child = _passthrough_project(child, need, RULE_PRUNE)
            applied.add(RULE_PRUNE)
        if len(kept_ix) == len(plan.aggregates):
            return _rebuild_with_children(plan, (child,))
        new = object.__new__(L.Aggregate)
        new.children = (child,)
        new.grouping = plan.grouping
        new.aggregates = kept_aggs
        new._output = (plan._output[:n_group]
                       + [plan._output[n_group + i] for i in kept_ix])
        applied.add(RULE_PRUNE)
        return _tag(new, RULE_PRUNE)

    if isinstance(plan, L.Join):
        key_cond = (_refs_all(plan.left_keys) | _refs_all(plan.right_keys)
                    | _refs(plan.condition))
        want = None if required is None else (required | key_cond)
        new_children = []
        for side in plan.children:
            side_ids = _out_ids(side)
            side_need = None if want is None else (want & side_ids)
            pruned = _prune(side, side_need, applied)
            if side_need and any(a.expr_id not in side_need
                                 for a in pruned.output):
                # the side's scan could not narrow itself (in-memory
                # relation, opaque subtree): project it down so the join
                # exchange carries only referenced columns
                pruned = _passthrough_project(pruned, side_need, RULE_PRUNE)
                applied.add(RULE_PRUNE)
            new_children.append(pruned)
        return _rebuild_with_children(plan, new_children)

    if isinstance(plan, L.FileScan):
        if required is None:
            return plan
        kept = [a for a in plan._output if a.expr_id in required]
        if not kept:
            kept = plan._output[:1]
        if len(kept) == len(plan._output):
            return plan
        new = copy.copy(plan)
        new._output = kept
        applied.add(RULE_PRUNE)
        return _tag(new, RULE_PRUNE)

    # Opaque nodes (Union/WindowOp/Generate/Expand/relations/unknown):
    # no pruning below — recurse only to keep the tree intact, and let a
    # wrapping parent (Join/Aggregate) project the output down instead.
    return plan


# ---------------------------------------------------------------------------
# Pipeline
# ---------------------------------------------------------------------------

def optimize_logical(plan: L.LogicalPlan,
                     conf: RapidsConf) -> Tuple[L.LogicalPlan, List[str]]:
    """Run the enabled rules; returns (optimized plan, applied rule names).
    Disabled (or no-op) pipelines return the input plan unchanged, so
    rules-off parity is the identity."""
    applied: Set[str] = set()
    if conf.get(LOGICAL_PUSHDOWN):
        plan = _pushdown_exchange(plan, applied)
    if conf.get(LOGICAL_JOIN_STRATEGY):
        plan = _join_swap(plan, conf, applied)
    if conf.get(LOGICAL_COLUMN_PRUNING):
        plan = _prune(plan, None, applied)
    return plan, sorted(applied)


def explain_logical(plan: L.LogicalPlan, indent: int = 0) -> str:
    """tree_string with per-node optimizer-rule annotations."""
    desc = plan.node_desc()
    rules = getattr(plan, "_opt_rules", ())
    if rules:
        desc += f"  [rules: {', '.join(rules)}]"
    lines = ["  " * indent + desc]
    for c in plan.children:
        lines.append(explain_logical(c, indent + 1))
    return "\n".join(lines)
