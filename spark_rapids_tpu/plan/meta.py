"""Meta/tagging framework: wraps each physical node & expression with conversion
state and cannot-run-on-TPU reasons.

Reference: RapidsMeta.scala (RapidsMeta:83, SparkPlanMeta:598, BaseExprMeta:1058).
The meta tree is built over the CPU physical plan; `tag_for_tpu` records reasons;
`convert_if_needed` produces the TPU plan where possible, keeping CPU subtrees
otherwise (per-operator fallback — the plugin's core contract).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Type

from ..config import RapidsConf
from ..expressions.base import (Alias, AttributeReference, Expression, Literal)
from ..types import TypeSig
from .typechecks import expr_sig_for, is_expr_registered


class RapidsMeta:
    def __init__(self, conf: RapidsConf):
        self.conf = conf
        self.reasons: List[str] = []

    def will_not_work_on_tpu(self, reason: str) -> None:
        if reason not in self.reasons:
            self.reasons.append(reason)

    @property
    def can_this_be_replaced(self) -> bool:
        return not self.reasons


class ExprMeta(RapidsMeta):
    """Per-expression meta (reference BaseExprMeta:1058)."""

    def __init__(self, expr: Expression, conf: RapidsConf, parent=None):
        super().__init__(conf)
        self.expr = expr
        self.parent = parent
        self.child_exprs = [ExprMeta(c, conf, self) for c in expr.children]

    def tag_for_tpu(self) -> None:
        e = self.expr
        if not is_expr_registered(type(e)):
            self.will_not_work_on_tpu(
                f"expression {type(e).__name__} is not supported on TPU")
        else:
            sig = expr_sig_for(type(e))
            if sig is not None:
                try:
                    r = sig.check(e.dtype)
                except NotImplementedError:
                    r = None
                if r is not None:
                    self.will_not_work_on_tpu(
                        f"expression {type(e).__name__} produces an unsupported type: {r}")
            if not getattr(e, "tpu_supported", True):
                self.will_not_work_on_tpu(
                    f"expression {type(e).__name__} is disabled on TPU")
            key = f"spark.rapids.sql.expression.{type(e).__name__}"
            if not self.conf.is_op_enabled(key, True):
                self.will_not_work_on_tpu(
                    f"expression {type(e).__name__} has been disabled via {key}")
            from .typechecks import conf_gate_reason
            gate = conf_gate_reason(e, self.conf)
            if gate:
                self.will_not_work_on_tpu(gate)
        for c in self.child_exprs:
            c.tag_for_tpu()

    @property
    def can_expr_tree_be_replaced(self) -> bool:
        return self.can_this_be_replaced and all(
            c.can_expr_tree_be_replaced for c in self.child_exprs)

    def collect_reasons(self, out: List[str]) -> None:
        for r in self.reasons:
            out.append(f"@Expression {self.expr.pretty()}: {r}")
        for c in self.child_exprs:
            c.collect_reasons(out)


class PlanMeta(RapidsMeta):
    """Per-operator meta (reference SparkPlanMeta:598)."""

    def __init__(self, plan, conf: RapidsConf, rule=None, parent=None):
        super().__init__(conf)
        self.plan = plan
        self.rule = rule
        self.parent = parent
        self.child_plans: List["PlanMeta"] = []
        self.expr_metas: List[ExprMeta] = []
        self.converted = None  # set by convert_if_needed

    def add_exprs(self, exprs: Sequence[Expression]) -> None:
        self.expr_metas.extend(ExprMeta(e, self.conf, self) for e in exprs)

    def tag_for_tpu(self) -> None:
        if self.rule is None:
            self.will_not_work_on_tpu(
                f"no TPU replacement rule for {type(self.plan).__name__}")
        else:
            self.rule.tag(self)
        for em in self.expr_metas:
            em.tag_for_tpu()
            if not em.can_expr_tree_be_replaced:
                inner: List[str] = []
                em.collect_reasons(inner)
                for r in inner:
                    self.will_not_work_on_tpu(r)
        for c in self.child_plans:
            c.tag_for_tpu()

    def convert_if_needed(self):
        converted_children = [c.convert_if_needed() for c in self.child_plans]
        if self.can_this_be_replaced and self.rule is not None:
            self.converted = self.rule.convert(self, converted_children)
            return self.converted
        # stay on CPU: re-wire with (possibly converted) children — but a CPU node
        # needs CPU children, so transition layer will fix boundaries; here we keep
        # original CPU node if all children stayed CPU, else rebuild via transitions
        from ..execs.base import CpuExec
        from ..execs.transitions import DeviceToHostExec
        new_children = []
        for orig_child, conv in zip(self.child_plans, converted_children):
            if conv.is_tpu:
                new_children.append(DeviceToHostExec(conv))
            else:
                new_children.append(conv)
        if all(a is b.plan for a, b in zip(self.plan.children, self.child_plans)) \
                and not any(c.is_tpu for c in converted_children):
            self.converted = self.plan
        else:
            self.converted = _rewire(self.plan, new_children)
        return self.converted

    def collect_fallback_reasons(self, out: List[str]) -> None:
        if self.reasons and self.rule is not None or self.reasons:
            for r in self.reasons:
                out.append(f"!Exec {type(self.plan).__name__} cannot run on TPU: {r}")
        for c in self.child_plans:
            c.collect_fallback_reasons(out)


def _rewire(plan, new_children):
    import copy
    new = copy.copy(plan)
    new.children = list(new_children)
    return new
