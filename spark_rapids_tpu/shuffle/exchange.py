"""Shuffle exchange execs: repartition data across N output partitions.

Reference: GpuShuffleExchangeExecBase.scala (prepareBatchShuffleDependency:277 —
partition on device then hand slices to the shuffle manager) + ShuffledBatchRDD.
Map side runs once per exchange (memoized, like Spark materializing a shuffle
stage); reduce side reads its partition's blocks through the multithreaded
manager and re-uploads to device.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from ..columnar.batch import TpuColumnarBatch
from ..config import SHUFFLE_PARTITIONS
from ..expressions.base import AttributeReference, Expression
from ..obs import flight, metrics
from ..obs import tracer as obs
from ..serving import query_context as qlc
from .manager import TpuShuffleManager
from .partitioner import (hash_partition_ids, hash_split_parts,
                          hash_split_parts_grouped, np_hash_partition_ids,
                          round_robin_partition_ids, split_by_partition)
from ..execs.base import (CpuExec, PhysicalPlan, TaskContext, TpuExec, bind_all)


class _DictionaryOverflow(Exception):
    """A collective exchange's string payload is not worth a broadcast
    dictionary (cardinality guard, or >2^31 distinct bytes — beyond the
    int32 offsets range); the exchange falls back to the per-map path
    with reason ``dictionary_overflow``."""


class _ExchangeBase:
    """Shared map-side materialization (runs once, guarded)."""

    def _init_exchange(self, partitioning: str, keys, num_partitions: int):
        self.partitioning = partitioning
        self.keys = keys
        self._n_out = num_partitions
        self._mat_lock = threading.Lock()
        self._shuffle_id: Optional[int] = None
        self._n_maps = 0

    def num_partitions(self) -> int:
        return self._n_out

    def _shuffle_mode(self, ctx: TaskContext) -> str:
        from ..config import SHUFFLE_MODE
        return str(ctx.conf.get(SHUFFLE_MODE)).upper()

    def _map_task_threads(self, ctx: TaskContext) -> int:
        from ..config import (SHUFFLE_PIPELINE_ENABLED,
                              SHUFFLE_PIPELINE_MAP_THREADS)
        if not ctx.conf.get(SHUFFLE_PIPELINE_ENABLED):
            return 1
        return max(1, int(ctx.conf.get(SHUFFLE_PIPELINE_MAP_THREADS)))

    def _prefetch_depth(self, ctx: TaskContext) -> int:
        from ..config import (SHUFFLE_PIPELINE_ENABLED,
                              SHUFFLE_PIPELINE_PREFETCH)
        if not ctx.conf.get(SHUFFLE_PIPELINE_ENABLED):
            return 0
        return max(0, int(ctx.conf.get(SHUFFLE_PIPELINE_PREFETCH)))

    def _ensure_materialized(self, ctx: TaskContext) -> None:
        with self._mat_lock:
            if self._shuffle_id is not None:
                return
            mgr = TpuShuffleManager.get(ctx.conf)
            sid = mgr.new_shuffle_id()
            child = self.children[0]
            # map-task spans on pool threads (empty span stacks) nest under
            # this materialization span via the captured parent id; the
            # query lifecycle binding rides along the same way, so a
            # cancel/deadline trips map tasks on pool threads too
            self._obs_parent = obs.current_span()
            self._query_ctx = qlc.current()
            try:
                with obs.span(f"exchange s{sid} materialize", cat="shuffle",
                              shuffle=sid) as mat_span:
                    if mat_span is not None:
                        self._obs_parent = mat_span
                    if self._try_materialize_collective(sid, ctx):
                        self._n_maps = 1  # one collective "map": whole
                        self._shuffle_id = sid  # exchange
                        return
                    self._n_maps = child.num_partitions()
                    threads = self._map_task_threads(ctx)
                    # batched multi-partition dispatch: the unit of
                    # scheduling is a partition GROUP (spark.rapids.tpu.
                    # dispatch.partitionBatch); group size 1 is exactly the
                    # PR 2 per-partition behavior
                    group = self._map_group_size(ctx) if self._n_maps > 1 \
                        else 1
                    groups = [list(range(s, min(s + group, self._n_maps)))
                              for s in range(0, self._n_maps, max(1, group))]
                    if threads > 1 and len(groups) > 1:
                        self._materialize_maps_pipelined(sid, ctx, mgr,
                                                         threads, groups)
                    else:
                        for ids in groups:
                            self._run_group_guarded(sid, ids, ctx, mgr)
                    self._shuffle_id = sid
            except BaseException:
                # A cancel/shed/deadline trip (or any map-task error)
                # unwinding MID-materialization leaves blocks already
                # committed under `sid` while self._shuffle_id is still
                # None — cleanup_shuffle keys off _shuffle_id and would
                # never visit them, so each such unwind would strand the
                # finished maps' device blocks in the catalog for the
                # life of the process.
                self._abort_materialization(sid, ctx.conf)
                raise

    def _run_map_guarded(self, sid: int, map_id: int, ctx: TaskContext,
                         mgr, gate_device: bool = False) -> None:
        """One map task under the chaos `pipeline.task` site and the
        transient-device-error retry: a map task is idempotent (block files
        are keyed (map, reduce); the ICI catalog replaces on put), so an
        UNAVAILABLE hiccup re-runs the task instead of failing the query."""
        from ..chaos import inject
        from ..failure import with_device_retry

        def attempt() -> None:
            qlc.checkpoint(f"exchange.map s{sid}m{map_id}")
            inject("pipeline.task", detail=f"s{sid}m{map_id}")
            self._materialize_map(sid, map_id, ctx, mgr, gate_device)

        # bind the owning query on this (possibly pool) thread: the
        # checkpoint above, the per-query retry budget, and any nested
        # checkpoints in the member pull all route to the right query
        with qlc.bind(getattr(self, "_query_ctx", None)):
            with_device_retry(attempt, ctx.conf)

    def _map_group_size(self, ctx: TaskContext) -> int:
        """How many map partitions one scheduled task processes (batched
        multi-partition dispatch). 1 — per-partition tasks — except for the
        TPU exchange in MULTITHREADED mode, which reads
        spark.rapids.tpu.dispatch.partitionBatch."""
        return 1

    def _run_group_guarded(self, sid: int, ids: List[int], ctx: TaskContext,
                           mgr, gate_device: bool = False) -> None:
        """One partition GROUP as a schedulable unit. Idempotent exactly
        like a single map task — a retry rewrites every member's block
        files, keyed (map, reduce) — so the same chaos site and transient
        device-error retry wrap the whole group."""
        if len(ids) == 1:
            self._run_map_guarded(sid, ids[0], ctx, mgr, gate_device)
            return
        from ..chaos import inject
        from ..failure import with_device_retry

        def attempt() -> None:
            qlc.checkpoint(f"exchange.group s{sid}g{ids[0]}-{ids[-1]}")
            inject("pipeline.task", detail=f"s{sid}g{ids[0]}-{ids[-1]}")
            self._materialize_map_group(sid, ids, ctx, mgr)

        with qlc.bind(getattr(self, "_query_ctx", None)):
            with_device_retry(attempt, ctx.conf)

    def _materialize_maps_pipelined(self, sid: int, ctx: TaskContext, mgr,
                                    n_threads: int,
                                    groups: Optional[List[List[int]]] = None
                                    ) -> None:
        """Pipelined map-side materialization (reference
        RapidsShuffleThreadedWriterBase): map tasks run concurrently on a
        bounded pool, device work gated per task by the TPU semaphore, and
        each task's deferred host commit (file serialization I/O, released
        from the semaphore) overlaps sibling maps' device work. Block files
        are keyed (map, reduce) so completion order cannot change results.

        Failure discipline: the first failing map cancels every sibling
        that has not started yet (running ones finish — their semaphore
        permits and in-flight byte reservations release on their own error
        paths), and its error propagates after all submitted work has
        settled, so no map task is still running when the caller sees the
        failure."""
        # Pre-materialize nested exchanges serially first: a concurrent map
        # task must never trigger a recursive materialization while sibling
        # maps hold device permits — the upstream exchange's own map tasks
        # would starve for permits and deadlock.
        for node in self.children[0].collect_nodes():
            if isinstance(node, _ExchangeBase):
                node._ensure_materialized(ctx)
        if groups is None:
            groups = [[m] for m in range(self._n_maps)]
        from concurrent.futures import CancelledError, ThreadPoolExecutor
        pool = ThreadPoolExecutor(
            max_workers=min(n_threads, len(groups)),
            thread_name_prefix="exchange-map")
        try:
            futs = [pool.submit(self._run_group_guarded, sid, ids, ctx, mgr,
                                True)
                    for ids in groups]
            errors = []
            for f in futs:  # wait for ALL non-cancelled maps: no map task
                # may still be running when the error propagates
                try:
                    f.result()
                except CancelledError:
                    continue
                except BaseException as e:  # noqa: BLE001
                    if not errors:
                        # fail fast: not-yet-started siblings are pointless
                        # work (and would delay the error) — cancel them
                        for g in futs:
                            g.cancel()
                    errors.append(e)
            if errors:
                raise errors[0]
        finally:
            pool.shutdown(wait=True)

    def _try_materialize_collective(self, sid: int, ctx: TaskContext) -> bool:
        """Mesh collective data plane; overridden by the device exchange."""
        return False

    def _materialize_map(self, sid: int, map_id: int, ctx: TaskContext,
                         mgr, gate_device: bool = False) -> None:
        from ..profiling import sync_scope
        map_ctx = TaskContext(map_id, ctx.conf)
        # pipelined map tasks run on pool threads with a fresh (empty)
        # sync-scope stack: anchor ledger attribution to this exchange;
        # nested operator pulls re-attribute via their own scopes. The obs
        # map-task span nests under the materialization span cross-thread
        # via the captured parent id.
        with sync_scope(self.node_name()), \
                obs.span(f"map s{sid}m{map_id}", cat="shuffle.map",
                         parent=getattr(self, "_obs_parent", None),
                         shuffle=sid, map=map_id):
            try:
                if gate_device and isinstance(self, TpuExec):
                    # pipelined map tasks take a permit up front so
                    # concurrent device work stays bounded by
                    # concurrentTpuTasks (lazy acquisition would let every
                    # pool thread dispatch at once)
                    from ..memory.semaphore import TpuSemaphore
                    TpuSemaphore.get(ctx.conf).acquire_if_necessary(map_ctx)
                commit = self._run_map_task(sid, map_id, map_ctx, mgr)
            finally:
                map_ctx.complete()  # releases the semaphore, if held
            if commit is not None:
                commit()  # host-side file I/O runs OFF the device semaphore

    def _run_map_task(self, sid: int, map_id: int, map_ctx: TaskContext,
                      mgr):
        """Returns a deferred host-commit callable, or None if the output
        was committed device-side (ICI)."""
        tables = self._partition_map_task(map_id, map_ctx)
        return lambda: mgr.write_map_output(sid, map_id, tables)

    def partition_sizes(self, ctx: TaskContext) -> List[int]:
        """Post-materialization byte size per reduce partition (the map
        output statistics AQE plans against). ICI mode serves DEVICE-SIDE
        counters: the collective keeps the exchange-time per-shard byte
        counts, and the per-map catalog tracks block sizes at put time —
        neither path fetches (or unspills) a block to answer AQE."""
        import os
        self._ensure_materialized(ctx)
        if getattr(self, "_collective", False):
            return list(self._collective_sizes)
        sizes = [0] * self._n_out
        if self._shuffle_mode(ctx) == "ICI":
            from .ici import IciShuffleCatalog
            catalog = IciShuffleCatalog.get()
            mgr2 = TpuShuffleManager.get(ctx.conf)
            # same bounded FetchFailed recovery as the read path (a lost
            # map's sizes are unknowable until its output is re-run), but
            # the sizes themselves come from catalog metadata, not blocks
            return self._ici_recovering_fetch(
                -1, ctx, mgr2,
                lambda: catalog.reduce_sizes(self._shuffle_id, self._n_maps,
                                             self._n_out))
        mgr = TpuShuffleManager.get(ctx.conf)
        for r in range(self._n_out):
            for m in range(self._n_maps):
                p = mgr._path(self._shuffle_id, m, r)
                if os.path.exists(p):
                    sizes[r] += os.path.getsize(p)
        return sizes

    def partition_row_counts(self, ctx: TaskContext) -> Optional[List[int]]:
        """Exact per-reduce ROW counts when the exchange materialized
        collectively (from the device-side sizing counters); None when only
        byte sizes are known (per-map paths)."""
        self._ensure_materialized(ctx)
        if getattr(self, "_collective", False):
            return list(self._collective_rows)
        return None

    def map_block_sizes(self, reduce_id: int, ctx: TaskContext) -> List[int]:
        """Per-map byte sizes of one reduce partition — the granularity AQE
        skew splitting slices on (reference PartialReducerPartitionSpec maps).
        A collective exchange materializes ONE fused block per reduce
        partition, but its row order is (source shard asc, stable), so the
        per-SOURCE row counts from the sizing sync are its map statistics:
        slice m == source shard m, and a contiguous group of sources is a
        contiguous row range of the block (execute_partition_maps serves it
        by slicing — no per-map blocks needed). Returns [] only when the
        exchange truly has nothing to slice on."""
        import os
        self._ensure_materialized(ctx)
        if getattr(self, "_collective", False):
            src = getattr(self, "_collective_src_rows", None)
            if src is None or reduce_id >= len(src):
                return []
            rb = int(getattr(self, "_collective_row_bytes", 0))
            return [int(n) * rb for n in src[reduce_id]]
        if self._shuffle_mode(ctx) == "ICI":
            from .ici import IciShuffleCatalog
            catalog = IciShuffleCatalog.get()
            if self._n_maps <= 1:
                return []
            return catalog.block_sizes(self._shuffle_id, reduce_id,
                                       self._n_maps)
        mgr = TpuShuffleManager.get(ctx.conf)
        out = []
        for m in range(self._n_maps):
            p = mgr._path(self._shuffle_id, m, reduce_id)
            out.append(os.path.getsize(p) if os.path.exists(p) else 0)
        return out

    def _fetch_retry_limit(self, ctx: TaskContext) -> int:
        from ..config import SHUFFLE_FETCH_RETRY_MAX
        return max(1, int(ctx.conf.get(SHUFFLE_FETCH_RETRY_MAX)))

    def _fetch_tables(self, idx: int, ctx: TaskContext, mgr,
                      map_ids=None) -> Iterator:
        """MULTITHREADED-mode reduce fetch with lineage recovery: streams
        one reduce partition's arrow tables in map order; a FetchFailedError
        (corrupt/truncated block detected by the checksum, unreadable file)
        re-materializes the producing map tasks and resumes with the maps
        not yet consumed — already-yielded blocks are never re-yielded. The
        attempt count is conf-bounded (spark.rapids.tpu.shuffle.fetchRetry.
        maxAttempts); the terminal error chains the last FetchFailedError
        as its cause (Spark: FetchFailed → bounded stage retries)."""
        from .ici import FetchFailedError
        limit = self._fetch_retry_limit(ctx)
        pending = list(map_ids) if map_ids is not None \
            else list(range(self._n_maps))
        failures = 0
        while pending:
            # reduce-fetch cancellation boundary: runs on the consumer
            # thread (bound) or a prefetch worker (bound via inheritance)
            qlc.checkpoint(f"exchange.fetch s{self._shuffle_id}r{idx}")
            it = mgr.iter_partition_sources(self._shuffle_id, idx,
                                            self._n_maps,
                                            map_ids=list(pending))
            try:
                for m, t in it:
                    pending.remove(m)
                    if t is not None:
                        yield t
            except FetchFailedError as ff:
                failures += 1
                metrics.counter_inc("shuffle.fetch_retries")
                flight.note("shuffle.fetchRetry", shuffle=self._shuffle_id,
                            reduce=idx, maps=list(ff.map_ids),
                            attempt=failures)
                if obs._ACTIVE:
                    obs.event("shuffle.fetchRetry", cat="shuffle",
                              shuffle=self._shuffle_id, reduce=idx,
                              maps=list(ff.map_ids), attempt=failures)
                if failures > limit:  # maxAttempts counts RECOVERY rounds
                    raise RuntimeError(
                        f"shuffle {self._shuffle_id} reduce {idx}: block "
                        f"fetch failed after {limit} re-materialization "
                        f"attempts (spark.rapids.tpu.shuffle.fetchRetry."
                        f"maxAttempts={limit})") from ff
                with self._mat_lock:
                    for mm in ff.map_ids:
                        self._run_map_guarded(self._shuffle_id, mm, ctx,
                                              mgr)

    def _ici_fetch_blocks(self, idx: int, ctx: TaskContext, mgr, catalog,
                          metric=None) -> List:
        """ICI-mode reduce fetch with conf-bounded lineage recovery:
        transient runtime errors heal via with_device_retry, a
        FetchFailedError (lost peer, invalidated output, corrupted spill
        tier) re-runs the missing map tasks."""
        def fetch():
            if metric is not None:
                with metric.timed():
                    return list(catalog.iter_blocks(
                        self._shuffle_id, idx, self._n_maps))
            return list(catalog.iter_blocks(self._shuffle_id, idx,
                                            self._n_maps))

        return self._ici_recovering_fetch(idx, ctx, mgr, fetch)

    def _ici_recovering_fetch(self, idx: int, ctx: TaskContext, mgr, fetch):
        """Run `fetch` (blocks, sizes, any catalog read) under the shared
        ICI recovery discipline: with_device_retry for transients, bounded
        re-materialization of exactly the maps a FetchFailedError names."""
        from ..failure import with_device_retry
        from .ici import FetchFailedError
        limit = self._fetch_retry_limit(ctx)
        failures = 0
        while True:
            qlc.checkpoint(f"exchange.fetch s{self._shuffle_id}r{idx}")
            try:
                return with_device_retry(fetch, ctx.conf)
            except FetchFailedError as ff:
                failures += 1
                metrics.counter_inc("shuffle.fetch_retries")
                flight.note("shuffle.fetchRetry", shuffle=self._shuffle_id,
                            reduce=idx, maps=list(ff.map_ids),
                            attempt=failures)
                if obs._ACTIVE:
                    obs.event("shuffle.fetchRetry", cat="shuffle",
                              shuffle=self._shuffle_id, reduce=idx,
                              maps=list(ff.map_ids), attempt=failures)
                if failures > limit:  # same accounting as _fetch_tables:
                    # maxAttempts counts recovery rounds, and no map is
                    # re-run whose output could never be fetched again
                    raise RuntimeError(
                        f"shuffle {self._shuffle_id} reduce {idx}: "
                        f"re-materialization failed after {limit} attempts "
                        f"(spark.rapids.tpu.shuffle.fetchRetry.maxAttempts)"
                    ) from ff
                with self._mat_lock:
                    for map_id in ff.map_ids:
                        self._run_map_guarded(self._shuffle_id, map_id,
                                              ctx, mgr)

    def cleanup_shuffle(self, conf) -> None:
        """Release this exchange's shuffle blocks/files and allow
        re-materialization (called at query end by the session)."""
        with self._mat_lock:
            sid = self._shuffle_id
            self._shuffle_id = None
        if sid is None:
            return
        self._abort_materialization(sid, conf)

    def _abort_materialization(self, sid: int, conf) -> None:
        """Release every block/file committed under `sid` regardless of
        whether _shuffle_id was ever set — shared by the normal query-end
        release and the mid-materialization unwind path."""
        from .ici import IciShuffleCatalog
        IciShuffleCatalog.get().cleanup(sid)
        TpuShuffleManager.get(conf).cleanup(sid)
        close_dicts = getattr(self, "_close_dicts", None)
        if close_dicts is not None:  # dictionary-exchange broadcast state
            close_dicts()


class TpuShuffleExchangeExec(_ExchangeBase, TpuExec):
    def __init__(self, child: PhysicalPlan, partitioning: str,
                 keys: Sequence[Expression], num_partitions: int):
        TpuExec.__init__(self, [child])
        self._init_exchange(partitioning, bind_all(list(keys), child.output),
                            num_partitions)

    @property
    def output(self):
        return self.children[0].output

    def node_desc(self) -> str:
        base = f"TpuShuffleExchange[{self.partitioning}, n={self._n_out}"
        # "why not collective" surfaced where the plan is read
        # (explain("metrics"), the bundle's plan tree): a mesh-session
        # exchange that rode the per-map path says why — MULTICHIP_r06's
        # q1 showed `collective_launches: 0` with the reason buried in a
        # code comment (obs/mesh_profile.py)
        reason = getattr(self, "_collective_reason", None)
        if reason and not getattr(self, "_collective", False):
            return f"{base}, per_map={reason}]"
        return base + "]"

    def additional_metrics(self):
        return {"partitionTime": "MODERATE", "serializationTime": "MODERATE",
                "deserializationTime": "MODERATE",
                "dictionaryEncodeTime": "MODERATE"}

    def _collective_mesh(self, ctx: TaskContext):
        """The mesh this exchange's collective would run on, or None.
        Plan-time selection (plan/overrides.py sets `collective_planned`
        when a mesh session is active) covers hash AND single
        partitionings; un-planned exchanges (hand-assembled plans, tests)
        keep the dynamic hash-only eligibility check. Every decline
        records its reason on the node (the plan-time reason from
        overrides.py is kept unless a runtime check finds a different
        cause)."""
        if self._shuffle_mode(ctx) != "ICI":
            return None
        from ..parallel.mesh import (MeshContext, collective_payload,
                                     mesh_session_active)
        # reasons are only meaningful inside a mesh session — a plain ICI
        # session's per-map exchanges are not "fallbacks" from anything
        in_mesh_session = mesh_session_active(ctx.conf) is not None

        def decline(reason: str):
            if in_mesh_session:
                self._collective_reason = reason
            return None

        from ..config import MESH_COLLECTIVE_ENABLED
        if not ctx.conf.get(MESH_COLLECTIVE_ENABLED):
            return decline("collective_conf_off")
        payload = collective_payload(self.output, ctx.conf)
        if payload is None:
            return decline("string_or_nested_payload")
        # "dict": string columns ride the fabric as int32 codes + one
        # broadcast dictionary per exchange (encode pass at materialize,
        # decode-on-read) — spark.rapids.tpu.exchange.dictionaryEncode
        self._dict_payload = payload == "dict"
        if getattr(self, "collective_planned", False):
            mesh = mesh_session_active(ctx.conf)
        elif self.partitioning == "hash":
            mesh = MeshContext.get(ctx.conf, self._n_out)
        else:
            return decline(f"partitioning_{self.partitioning}")
        if mesh is None:
            return decline("mesh_unavailable")
        # hash routing computes murmur3 % n_shards on-device: the reduce
        # partition count must equal the mesh size exactly (the planner's
        # alignPartitions pass guarantees this for mesh sessions)
        if self.partitioning == "hash" \
                and mesh.devices.size != self._n_out:
            return decline("partitions_misaligned")
        return mesh

    def _try_materialize_collective(self, sid: int, ctx: TaskContext) -> bool:
        """ICI-mesh data plane (reference UCX mode, shuffle-plugin/
        UCXShuffleTransport.scala): ONE jitted all_to_all moves every shard's
        hash-bucketed rows to its reduce partition's shard (or funnels every
        shard's rows to shard 0 for single partitioning — the partial→final
        aggregation merge). Used when a mesh session is active (planner
        selection) or the exchange is a hash partitioning onto exactly
        mesh-size partitions, and all columns have fixed-width device
        layouts. Results land in the device-resident catalog keyed as a
        single collective map output, with the exchange-time per-shard
        row/byte counters kept as the partition statistics AQE plans
        against; FetchFailed recovery re-runs the collective."""
        # a re-materialization (next query after cleanup_shuffle) must not
        # inherit the previous query's collective verdict: if this attempt
        # declines or falls back, the per-map path owns the shuffle id
        self._collective = False
        self._close_dicts()
        mesh = self._collective_mesh(ctx)
        if mesh is None:
            reason = getattr(self, "_collective_reason", None)
            if reason:
                # mesh-session exchange routed per-map: count the reason
                # (mesh.per_map_exchange{reason}) for the multichip
                # summary / explain("metrics") — obs/mesh_profile.py
                from ..obs import mesh_profile as _mprof
                _mprof.record_fallback(sid, reason)
            return False
        from ..columnar.batch import concat_batches
        from ..failure import with_device_retry
        from ..memory.hbm import TpuOOM
        from ..memory.spill import SpillableColumnarBatch
        from ..parallel.mesh import mesh_hash_exchange, mesh_single_exchange
        from ..profiling import sync_scope
        from .ici import IciShuffleCatalog
        n_dev = mesh.devices.size
        child = self.children[0]
        # collect per-shard groups as SPILLABLE batches so HBM pressure from
        # later map partitions can evict earlier outputs (the per-map ICI path
        # gets this from the catalog; the collective must provide it itself)
        groups: List[List[SpillableColumnarBatch]] = [[] for _ in range(n_dev)]
        try:
            for m in range(child.num_partitions()):
                mctx = TaskContext(m, ctx.conf)
                try:
                    for b in child.execute_partition(m, mctx):
                        if b.num_rows:
                            groups[m % n_dev].append(SpillableColumnarBatch(b))
                finally:
                    mctx.complete()
            if not any(groups):
                IciShuffleCatalog.get().mark_map_complete(sid, 0)
                self._collective = True
                self._collective_rows = [0] * self._n_out
                self._collective_sizes = [0] * self._n_out
                self._collective_seq = None
                self._collective_src_rows = None
                self._collective_row_bytes = 0
                return True

            def run_collective():
                # idempotent: a transient fault on the fabric (chaos
                # mesh.link) re-stages from the still-open spillables —
                # and a lost-map recovery re-runs the dictionary ENCODE
                # along with everything else (the dictionaries are a pure
                # function of the still-open map outputs)
                with self.metrics["partitionTime"].timed(), \
                        sync_scope(self.node_name()):
                    batches = []
                    for g in groups:
                        if not g:
                            batches.append(None)
                            continue
                        got = [sb.get_batch() for sb in g]
                        batches.append(concat_batches(got) if len(got) > 1
                                       else got[0])
                    names = [a.name for a in self.output]
                    pids = None
                    if self.partitioning == "hash":
                        # partition ids hash the ORIGINAL key values (a
                        # dictionary code is exchange-local; hashing it
                        # would break co-partitioning with sibling
                        # exchanges)
                        pids = [hash_partition_ids(b, self.keys, n_dev,
                                                   ctx,
                                                   metrics=self.metrics)
                                if b is not None else None
                                for b in batches]
                    if getattr(self, "_dict_payload", False):
                        batches = self._encode_dict_payload(batches, ctx)
                    if self.partitioning == "single":
                        return mesh_single_exchange(mesh, batches, names,
                                                    shuffle_id=sid,
                                                    conf=ctx.conf)
                    return mesh_hash_exchange(mesh, batches, pids, names,
                                              shuffle_id=sid,
                                              conf=ctx.conf)

            result = with_device_retry(run_collective, ctx.conf)
        except _DictionaryOverflow:
            # the broadcast dictionary is not worth it (cardinality guard,
            # or >2^31 distinct bytes — beyond int32 offsets): the per-map
            # device-resident path carries raw strings natively
            self._collective_reason = "dictionary_overflow"
            from ..obs import mesh_profile as _mprof
            _mprof.record_fallback(sid, "dictionary_overflow")
            IciShuffleCatalog.get().cleanup(sid)
            self._close_dicts()
            return False
        except TpuOOM:
            # memory pressure while staging the collective: the per-map path
            # has the full incremental-spill discipline; drop any partial
            # state for this shuffle id and let the caller run per-map
            self._collective_reason = "staging_oom"
            from ..obs import mesh_profile as _mprof
            _mprof.record_fallback(sid, "staging_oom")
            IciShuffleCatalog.get().cleanup(sid)
            self._close_dicts()
            return False
        finally:
            for g in groups:
                for sb in g:
                    sb.close()
        catalog = IciShuffleCatalog.get()
        for r in range(self._n_out):
            blk = result.batches[r]
            if result.rows[r]:
                catalog.put_block(sid, 0, r, blk, owner="mesh-collective")
        catalog.mark_map_complete(sid, 0)
        self._collective = True
        # device-side partition statistics: exact per-reduce row/byte counts
        # from the exchange's sizing counters — partition_sizes (AQE) serves
        # these without fetching (or unspilling) a single block
        self._collective_rows = list(result.rows[: self._n_out])
        self._collective_sizes = list(result.bytes[: self._n_out])
        # per-SOURCE row split of each reduce block (the sizing counts'
        # columns): the fused block's row order is (source asc, stable),
        # so AQE skew slicing serves a contiguous source range as a
        # contiguous row slice (map_block_sizes / execute_partition_maps)
        self._collective_src_rows = None if result.src_rows is None \
            else [list(sr) for sr in result.src_rows[: self._n_out]]
        self._collective_row_bytes = int(result.row_bytes)
        # profile seq: the consumer read's flow event references it so the
        # Chrome export ties producer exchange → consumer read
        self._collective_seq = (result.profile or {}).get("seq")
        return True

    def _close_dicts(self) -> None:
        dcols = getattr(self, "_dict_cols", None)
        if dcols:
            for sb in dcols.values():
                sb.close()
        self._dict_cols = None

    def _encode_dict_payload(self, batches, ctx: TaskContext):
        """Map-side dictionary-encode pass of the collective exchange:
        build ONE dictionary per string/binary column across ALL shards'
        map outputs, replace each column with its int32 codes (nulls ride
        the code validity), and park the dictionaries as SPILLABLE device
        batches on the exchange — under HBM pressure they spill and
        restore through the same v2 framing + checksum tier as any
        shuffle block, and `cleanup_shuffle` releases them with the
        blocks. The fabric then moves fixed-width codes instead of raw
        bytes (reference analogue: nvcomp-compressed shuffle batches);
        the reduce side decodes on read (`_decode_dict_block`). Raises
        `_DictionaryOverflow` past the cardinality / 2^31-byte guards."""
        import time

        import pyarrow as pa
        import pyarrow.compute as pc

        from ..columnar.vector import TpuColumnVector
        from ..config import EXCHANGE_DICT_MAX_CARDINALITY
        from ..memory.spill import SpillableColumnarBatch
        from ..parallel import mesh as _mesh
        from ..types import BinaryType, IntegerType, StringType
        t0 = time.perf_counter_ns()
        self._close_dicts()
        str_ords = [i for i, a in enumerate(self.output)
                    if isinstance(a.dtype, (StringType, BinaryType))]
        max_card = int(ctx.conf.get(EXCHANGE_DICT_MAX_CARDINALITY))
        dict_cols: Dict[int, SpillableColumnarBatch] = {}
        codes_by_shard: Dict[int, Dict[int, TpuColumnVector]] = {}
        try:
            with self.metrics["dictionaryEncodeTime"].timed():
                for o in str_ords:
                    per = [b.columns[o].to_arrow() if b is not None
                           else None for b in batches]
                    per = [a.combine_chunks()
                           if isinstance(a, pa.ChunkedArray) else a
                           for a in per]
                    from ..types import to_arrow as _t2a
                    chunks = [a for a in per if a is not None and len(a)]
                    combined = pa.chunked_array(
                        chunks or [], type=_t2a(self.output[o].dtype))
                    uniq = pc.unique(combined).drop_null()
                    nbytes = pc.sum(pc.binary_length(uniq)).as_py() or 0
                    if len(uniq) > max_card or nbytes >= (1 << 31):
                        raise _DictionaryOverflow(
                            f"ordinal {o}: {len(uniq)} distinct values / "
                            f"{nbytes} bytes")
                    dcol = TpuColumnVector.from_arrow(uniq)
                    dict_cols[o] = SpillableColumnarBatch(
                        TpuColumnarBatch([dcol], len(uniq)))
                    for shard, arr in enumerate(per):
                        if arr is None:
                            continue
                        b = batches[shard]
                        codes = pc.index_in(arr, value_set=uniq)
                        vals = np.asarray(
                            codes.fill_null(0).to_numpy(
                                zero_copy_only=False)).astype(np.int32)
                        validity = (np.asarray(codes.is_valid())
                                    if codes.null_count else None)
                        codes_by_shard.setdefault(shard, {})[o] = \
                            TpuColumnVector.from_numpy(
                                IntegerType(), vals, validity,
                                capacity=b.capacity)
        except BaseException:
            for sb in dict_cols.values():
                sb.close()
            raise
        out = []
        for shard, b in enumerate(batches):
            if b is None:
                out.append(None)
                continue
            cols = list(b.columns)
            for o, c in codes_by_shard.get(shard, {}).items():
                cols[o] = c
            out.append(TpuColumnarBatch(cols, b.num_rows, b.names))
        self._dict_cols = dict_cols
        _mesh.record_dict_encode(time.perf_counter_ns() - t0)
        return out

    def _decode_dict_block(self, b: TpuColumnarBatch) -> TpuColumnarBatch:
        """Reduce-side decode-on-read of a dictionary-encoded collective
        block: codes + the exchange's broadcast dictionary → materialized
        string columns via the device ragged gather, with the codes kept
        as each column's `dict_encoding` so a string-keyed downstream
        aggregation consumes them directly."""
        dcols = getattr(self, "_dict_cols", None)
        if not dcols or not getattr(self, "_collective", False):
            return b
        from ..columnar.batch import decode_dictionary_column
        cols = list(b.columns)
        for o, sb in dcols.items():
            dcol = sb.get_batch().columns[0]
            cols[o] = decode_dictionary_column(dcol, cols[o], b.num_rows,
                                               b.capacity)
        return TpuColumnarBatch(cols, b.num_rows, b.names)

    def _materialize_map(self, sid: int, map_id: int, ctx: TaskContext,
                         mgr, gate_device: bool = False) -> None:
        if getattr(self, "_collective", False):
            # collective recovery: re-run the whole exchange (a lost block in
            # mesh mode means the collective result was invalidated). The
            # per-map fallback is NOT sound here — map id 0 covers the whole
            # child, not child partition 0 — so a failed re-run must raise.
            if not self._try_materialize_collective(sid, ctx):
                raise RuntimeError(
                    f"shuffle {sid}: collective re-materialization failed "
                    f"(mesh no longer eligible)")
            return
        super()._materialize_map(sid, map_id, ctx, mgr, gate_device)

    def _chaos_lost_shard(self, idx: int, catalog) -> None:
        """Chaos `mesh.shard`: a shard's HBM lost the collective output
        (peer chip dropped). Converts the injected io_error into catalog
        invalidation so the fetch path raises FetchFailedError and the
        existing lineage recovery re-runs the collective — exactly how a
        real lost peer heals (Spark: lost executor → stage retry)."""
        if not getattr(self, "_collective", False):
            return
        from ..chaos import inject
        try:
            inject("mesh.shard", detail=f"s{self._shuffle_id}r{idx}")
        except OSError:
            catalog.invalidate_map(self._shuffle_id, 0)

    def _device_parts(self, map_id: int, ctx: TaskContext) -> Iterator[List]:
        """Device partition-split of each input batch (shared by both
        shuffle modes; reference prepareBatchShuffleDependency:277)."""
        n = self._n_out
        for batch in self.children[0].execute_partition(map_id, ctx):
            # a deferred-compaction batch skips the empty check rather than
            # force its count: the split plan handles empty inputs (all
            # bounds equal) and its bounds readback IS the chain's one sync
            if not batch.has_pending_rows and batch.num_rows == 0:
                continue
            with self.metrics["partitionTime"].timed():
                if self.partitioning == "hash":
                    # encode+split as ONE cached executable when the keys
                    # trace (opjit.partition_split_plan)
                    parts = hash_split_parts(batch, self.keys, n, ctx,
                                             metrics=self.metrics)
                elif self.partitioning in ("roundrobin", "coalesce"):
                    pids = round_robin_partition_ids(batch, n, map_id)
                    parts = split_by_partition(batch, pids, n)
                elif self.partitioning == "single":
                    parts = [batch] + [None] * (n - 1)
                else:
                    raise NotImplementedError(self.partitioning)
            yield parts

    def _partition_map_task(self, map_id: int, ctx: TaskContext) -> List:
        """MULTITHREADED mode map task: split on device, serialize to host."""
        import pyarrow as pa
        n = self._n_out
        acc: List[List] = [[] for _ in range(n)]
        for parts in self._device_parts(map_id, ctx):
            with self.metrics["serializationTime"].timed():
                for p, sub in enumerate(parts):
                    if sub is not None and sub.num_rows:
                        acc[p].append(sub.to_arrow())
        out = []
        for p in range(n):
            out.append(pa.concat_tables(acc[p]) if acc[p] else None)
        return out

    def _run_map_task(self, sid: int, map_id: int, map_ctx: TaskContext,
                      mgr):
        if self._shuffle_mode(map_ctx) == "ICI":
            # ICI / device-resident mode (reference UCX RapidsCachingWriter):
            # blocks stay on device as spillable batches — no serialization;
            # the device-side commit happens here, under the semaphore (it IS
            # device work), so there is no deferred host commit
            from ..columnar.batch import concat_batches
            from .ici import IciShuffleCatalog, ShuffleHeartbeatManager
            catalog = IciShuffleCatalog.get()
            hb = ShuffleHeartbeatManager.get()
            from ..config import SHUFFLE_HEARTBEAT_TIMEOUT_SECONDS
            hb.timeout_s = float(map_ctx.conf.get(
                SHUFFLE_HEARTBEAT_TIMEOUT_SECONDS))
            hb.register_peer(f"executor-{map_id}")
            acc: List[List[TpuColumnarBatch]] = [[] for _ in range(self._n_out)]
            for parts in self._device_parts(map_id, map_ctx):
                for p, sub in enumerate(parts):
                    if sub is not None and sub.num_rows:
                        acc[p].append(sub)
            for p, batches in enumerate(acc):
                if batches:
                    blk = batches[0] if len(batches) == 1 \
                        else concat_batches(batches)
                    catalog.put_block(sid, map_id, p, blk,
                                      owner=f"executor-{map_id}")
            catalog.mark_map_complete(sid, map_id)
            return None
        tables = self._partition_map_task(map_id, map_ctx)
        return lambda: mgr.write_map_output(sid, map_id, tables)

    # --- batched multi-partition dispatch ---------------------------------
    def _map_group_size(self, ctx: TaskContext) -> int:
        """Both shuffle modes group: MULTITHREADED defers each member's
        host commit off the permit as before, ICI commits device-resident
        blocks to the catalog under the group permit (each member still
        owns its blocks — lineage recovery re-runs SINGLE maps). The ICI
        collective path is tried before grouping and wins when eligible."""
        from ..config import DISPATCH_PARTITION_BATCH
        try:
            return max(1, int(ctx.conf.get(DISPATCH_PARTITION_BATCH)))
        except (TypeError, ValueError):
            return 1

    def _materialize_map_group(self, sid: int, ids: List[int],
                               ctx: TaskContext, mgr) -> None:
        """One map GROUP (spark.rapids.tpu.dispatch.partitionBatch): members
        pull through the child's multi-partition entry point
        (execute_partitions — a fused segment runs same-layout member
        batches as ONE grouped launch) and their hash splits run grouped
        launches with ONE bounds readback per launch. Block identity is
        unchanged: each member's tables commit under its own map id, so
        reduce reads and lineage recovery (which re-runs SINGLE maps via
        _materialize_map) never observe the grouping."""
        from ..memory.semaphore import TpuSemaphore
        from ..profiling import sync_scope
        # Pre-materialize nested exchanges BEFORE taking the group permit:
        # the group holds its one permit across the whole member pull, and a
        # nested exchange materializing inside that window would block on
        # fresh map contexts waiting for the permit this thread already
        # holds — a single-thread self-deadlock the pipelined path avoids
        # the same way. (Grouping can collapse the map side to ONE group,
        # which routes even pipeline-enabled plans through this serial path.)
        for node in self.children[0].collect_nodes():
            if isinstance(node, _ExchangeBase):
                node._ensure_materialized(ctx)
        sem = TpuSemaphore.get(ctx.conf)
        group_ctx = TaskContext(ids[0], ctx.conf)
        member_ctxs: Dict[int, TaskContext] = {}

        def ctx_of(i: int) -> TaskContext:
            mc = member_ctxs.get(i)
            if mc is None:
                mc = member_ctxs[i] = TaskContext(i, ctx.conf)
                # members ride the group's one permit: G members blocking
                # for their own permits from one pool thread would deadlock
                # the pool against concurrentTpuTasks
                sem.adopt(group_ctx, mc)
            return mc

        with sync_scope(self.node_name()), \
                obs.span(f"map s{sid}g{ids[0]}-{ids[-1]}", cat="shuffle.map",
                         parent=getattr(self, "_obs_parent", None),
                         shuffle=sid, maps=list(ids)):
            try:
                # ONE permit for the whole group — the group is one unit of
                # device work (member batches share grouped launches)
                sem.acquire_if_necessary(group_ctx)
                commits = self._run_map_group_task(sid, ids, ctx_of, mgr)
            finally:
                for mc in member_ctxs.values():
                    mc.complete()
                group_ctx.complete()  # releases the permit
            for commit in commits:
                commit()  # host-side file I/O runs OFF the device semaphore

    def _run_map_group_task(self, sid: int, ids: List[int], ctx_of,
                            mgr) -> List:
        import pyarrow as pa
        ici = self._shuffle_mode(ctx_of(ids[0])) == "ICI"
        if ici:
            # device-resident sink (reference UCX RapidsCachingWriter):
            # blocks stay on device and commit to the catalog HERE, under
            # the group permit (the put IS device work) — no host commit
            from ..config import SHUFFLE_HEARTBEAT_TIMEOUT_SECONDS
            from .ici import IciShuffleCatalog, ShuffleHeartbeatManager
            catalog = IciShuffleCatalog.get()
            hb = ShuffleHeartbeatManager.get()
            hb.timeout_s = float(ctx_of(ids[0]).conf.get(
                SHUFFLE_HEARTBEAT_TIMEOUT_SECONDS))
            for i in ids:
                hb.register_peer(f"executor-{i}")
        n = self._n_out
        group = len(ids)
        acc: Dict[int, List[List]] = {i: [[] for _ in range(n)] for i in ids}
        pending: List[Tuple[int, TpuColumnarBatch]] = []

        def sink(i: int, parts) -> None:
            if ici:
                for p, sub in enumerate(parts):
                    if sub is not None and sub.num_rows:
                        acc[i][p].append(sub)
                return
            with self.metrics["serializationTime"].timed():
                for p, sub in enumerate(parts):
                    if sub is not None and sub.num_rows:
                        acc[i][p].append(sub.to_arrow())

        def flush() -> None:
            if not pending:
                return
            lanes, pending[:] = list(pending), []
            with self.metrics["partitionTime"].timed():
                parts_per_lane = None
                if len(lanes) > 1:
                    # N lanes' encode+split in ONE launch, ONE bounds
                    # readback (opjit "exchsplitg")
                    parts_per_lane = hash_split_parts_grouped(
                        [b for _, b in lanes], self.keys, n,
                        ctx_of(lanes[0][0]), metrics=self.metrics)
                if parts_per_lane is None:  # untraceable keys: per-batch
                    parts_per_lane = [
                        hash_split_parts(b, self.keys, n, ctx_of(i),
                                         metrics=self.metrics)
                        for i, b in lanes]
            for (i, _), parts in zip(lanes, parts_per_lane):
                sink(i, parts)

        for i, batch in self.children[0].execute_partitions(list(ids),
                                                            ctx_of):
            if not batch.has_pending_rows and batch.num_rows == 0:
                continue
            if self.partitioning == "hash":
                pending.append((i, batch))
                if len(pending) >= group:
                    flush()
                continue
            with self.metrics["partitionTime"].timed():
                if self.partitioning in ("roundrobin", "coalesce"):
                    pids = round_robin_partition_ids(batch, n, i)
                    parts = split_by_partition(batch, pids, n)
                elif self.partitioning == "single":
                    parts = [batch] + [None] * (n - 1)
                else:
                    raise NotImplementedError(self.partitioning)
            sink(i, parts)
        flush()
        if ici:
            from ..columnar.batch import concat_batches
            for i in ids:
                for p, batches in enumerate(acc[i]):
                    if batches:
                        blk = batches[0] if len(batches) == 1 \
                            else concat_batches(batches)
                        catalog.put_block(sid, i, p, blk,
                                          owner=f"executor-{i}")
                catalog.mark_map_complete(sid, i)
            return []
        commits = []
        for i in ids:
            tables = [pa.concat_tables(a) if a else None for a in acc[i]]
            commits.append(
                lambda t=tables, m=i: mgr.write_map_output(sid, m, t))
        return commits

    def internal_do_execute_columnar(self, idx: int, ctx: TaskContext) -> Iterator:
        self._ensure_materialized(ctx)
        names = [a.name for a in self.output]
        if self._shuffle_mode(ctx) == "ICI":
            # device-resident read (reference RapidsCachingReader): local
            # catalog hit, no host round trip; blocks unspill if evicted.
            # FetchFailed (peer lost, output invalidated, corrupted spill
            # tier) re-runs the missing map tasks — Spark's stage-retry
            # analogue, conf-bounded with the cause chained.
            from .ici import IciShuffleCatalog
            catalog = IciShuffleCatalog.get()
            mgr = TpuShuffleManager.get(ctx.conf)
            self._chaos_lost_shard(idx, catalog)
            if obs._ACTIVE and getattr(self, "_collective", False) \
                    and getattr(self, "_collective_seq", None) is not None:
                # consumer side of the producer→consumer flow: the Chrome
                # export ties this read back to the collective exchange
                # that produced the block (flow id = the profile seq)
                obs.event("mesh.read", cat="shuffle",
                          exchange_seq=self._collective_seq,
                          shuffle=self._shuffle_id, reduce=idx)
            blocks = self._ici_fetch_blocks(
                idx, ctx, mgr, catalog,
                metric=self.metrics["deserializationTime"])
            for b in blocks:
                if b.num_rows:
                    # dictionary-encoded collective blocks decode on read
                    # (codes + broadcast dictionary → device strings)
                    yield self._decode_dict_block(b).rename(names)
            return
        # pipelined read (reference RapidsShuffleThreadedReaderBase): blocks
        # stream from the reader pool in map order while the NEXT block's
        # deserialize+upload is prefetched on a worker thread — downstream
        # device compute overlaps the tunnel upload instead of waiting on it.
        # With coalescing on, fetched map blocks first concatenate HOST-side
        # up to the batch-size targets (reference GpuShuffleCoalesceExec):
        # one upload and one downstream dispatch per target-sized batch
        # instead of one per map block.
        mgr = TpuShuffleManager.get(ctx.conf)
        yield from _pipelined_upload(self, self._fetch_tables(idx, ctx, mgr),
                                     names, ctx)

    def execute_partition_maps(self, idx: int, map_ids: Sequence[int],
                               ctx: TaskContext) -> Iterator:
        """One reduce partition restricted to a subset of map outputs — a
        skew SLICE (reference PartialReducerPartitionSpec read). On the
        collective path "map" means SOURCE SHARD: the fused block's rows
        are ordered (source asc, stable) and the per-source row counts are
        host-known from the sizing sync, so a contiguous source group is
        served as one device slice of the block — the skewed reduce
        partition splits without ever having had per-map blocks."""
        self._ensure_materialized(ctx)
        names = [a.name for a in self.output]
        if getattr(self, "_collective", False) \
                and getattr(self, "_collective_src_rows", None) is not None:
            from ..columnar.batch import slice_batch
            from .ici import IciShuffleCatalog
            src = self._collective_src_rows[idx]
            ms = sorted(int(m) for m in map_ids)
            assert ms == list(range(ms[0], ms[-1] + 1)), \
                f"collective skew slice must be a contiguous source " \
                f"range, got {ms}"  # _slices builds groups in source order
            start = sum(src[s] for s in range(ms[0]))
            length = sum(src[s] for s in ms)
            if not length:
                return
            catalog = IciShuffleCatalog.get()
            mgr = TpuShuffleManager.get(ctx.conf)
            blocks = self._ici_fetch_blocks(
                idx, ctx, mgr, catalog,
                metric=self.metrics["deserializationTime"])
            for b in blocks:  # exactly one fused block per reduce part
                if b.num_rows:
                    full = self._decode_dict_block(b).rename(names)
                    yield slice_batch(full, start, length)
            return
        if self._shuffle_mode(ctx) == "ICI":
            from ..failure import with_device_retry
            from .ici import IciShuffleCatalog
            catalog = IciShuffleCatalog.get()
            blocks = with_device_retry(
                lambda: list(catalog.iter_blocks(self._shuffle_id, idx,
                                                 self._n_maps,
                                                 map_ids=list(map_ids))),
                ctx.conf)
            for b in blocks:
                if b.num_rows:
                    yield self._decode_dict_block(b).rename(names)
            return
        mgr = TpuShuffleManager.get(ctx.conf)
        yield from _pipelined_upload(
            self, self._fetch_tables(idx, ctx, mgr, map_ids=list(map_ids)),
            names, ctx, account_output=True)


class CpuShuffleExchangeExec(_ExchangeBase, CpuExec):
    def __init__(self, child: PhysicalPlan, partitioning: str,
                 keys: Sequence[Expression], num_partitions: int):
        CpuExec.__init__(self, [child])
        self._init_exchange(partitioning, bind_all(list(keys), child.output),
                            num_partitions)

    @property
    def output(self):
        return self.children[0].output

    def node_desc(self) -> str:
        return f"CpuShuffleExchange[{self.partitioning}, n={self._n_out}]"

    def _partition_map_task(self, map_id: int, ctx: TaskContext) -> List:
        import pyarrow as pa
        n = self._n_out
        acc: List[List] = [[] for _ in range(n)]
        for t in self.children[0].execute_partition(map_id, ctx):
            if t.num_rows == 0:
                continue
            if self.partitioning == "hash":
                pids = np_hash_partition_ids(t, self.keys, n, ctx)
            elif self.partitioning in ("roundrobin", "coalesce"):
                pids = (np.arange(t.num_rows) + map_id) % n
            elif self.partitioning == "single":
                acc[0].append(t)
                continue
            else:
                raise NotImplementedError(self.partitioning)
            for p in range(n):
                sel = np.nonzero(pids == p)[0]
                if len(sel):
                    acc[p].append(t.take(pa.array(sel)))
        return [pa.concat_tables(a) if a else None for a in acc]

    def execute_partition(self, idx: int, ctx: TaskContext) -> Iterator:
        self._ensure_materialized(ctx)
        mgr = TpuShuffleManager.get(ctx.conf)
        names = [a.name for a in self.output]
        for t in self._fetch_tables(idx, ctx, mgr):
            if t.num_rows:
                yield t.rename_columns(names)


class TpuShuffleReaderExec(TpuExec):
    """AQE shuffle reader (reference GpuCustomShuffleReaderExec,
    execution/GpuCustomShuffleReaderExec.scala:37): reads the materialized
    exchange with a coalesced partition spec — small reduce partitions are
    grouped up to the advisory size, so downstream tasks see fewer,
    better-filled partitions. (Skew splitting is handled at the join level
    by sub-partitioning, execs/joins.py, where key co-location is not
    required to survive.)"""

    def __init__(self, child, advisory_bytes: int, conf=None):
        super().__init__([child])
        self.advisory_bytes = advisory_bytes
        # planner conf snapshot, threaded in AT CONSTRUCTION: num_partitions
        # materializes the child exchange, and doing that under default_conf
        # would let AQE specs diverge between planning and execution
        # (different shuffle mode / pipeline tunables / partition counts)
        self._conf = conf
        self._specs: Optional[List[List[int]]] = None

    @property
    def output(self):
        return self.children[0].output

    def node_desc(self) -> str:
        n = len(self._specs) if self._specs is not None else "?"
        return f"TpuShuffleReader[coalesced, n={n}]"

    def _ensure_specs(self, ctx: TaskContext) -> List[List[int]]:
        if self._specs is None:
            sizes = self.children[0].partition_sizes(ctx)
            specs: List[List[int]] = []
            cur: List[int] = []
            cur_bytes = 0
            for r, sz in enumerate(sizes):
                if cur and cur_bytes + sz > self.advisory_bytes:
                    specs.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(r)
                cur_bytes += sz
            if cur:
                specs.append(cur)
            self._specs = specs or [[0]]
        return self._specs

    def num_partitions(self) -> int:
        from ..execs.base import TaskContext
        from ..config import default_conf
        # sizes require materialization; the planner threads its conf
        # snapshot through the constructor (default_conf only covers readers
        # built outside the override engine, e.g. hand-assembled test plans)
        ctx = TaskContext(0, self._conf or default_conf())
        try:
            return len(self._ensure_specs(ctx))
        finally:
            ctx.complete()

    def internal_do_execute_columnar(self, idx: int, ctx: TaskContext) -> Iterator:
        specs = self._ensure_specs(ctx)
        yield from _read_reduce_group(self.children[0], specs[idx], ctx,
                                      [a.name for a in self.output])


def _pipelined_upload(exch, tables_it, names, ctx: TaskContext,
                      account_output: bool = False
                      ) -> Iterator[TpuColumnarBatch]:
    """Shared concat+upload tail for the exchange reduce read and the AQE
    grouped read: host-coalesce fetched Arrow tables to the batch targets
    (when enabled, reference GpuShuffleCoalesceExec), then upload on a
    prefetch worker so downstream device compute overlaps the tunnel, with
    waits attributed to the exchange's deserializationTime under a ledger
    scope. `account_output` feeds the exchange's output metrics — only for
    callers that bypass exch.execute_partition (whose wrapper otherwise
    accounts them; double-counting if both ran)."""
    from ..execs.coalesce import (coalesce_arrow_stream, coalesce_enabled,
                                  coalesce_targets)
    from ..profiling import sync_scope
    from ..utils.pipeline import prefetch_iterator
    deser = exch.metrics["deserializationTime"]
    out_rows = exch.metrics["numOutputRows"]
    out_batches = exch.metrics["numOutputBatches"]

    def _upload() -> Iterator[TpuColumnarBatch]:
        # deserializationTime covers producing a device-ready batch: waiting
        # on the pool's read+deserialize AND the upload (the actual decode
        # runs on reader threads, so only its non-overlapped wait is
        # attributable to this task). sync_scope: this generator's frames
        # run on the prefetch worker thread (empty scope stack) — anchor
        # ledger attribution
        it = tables_it
        if coalesce_enabled(ctx.conf):
            it = coalesce_arrow_stream(it, *coalesce_targets(ctx.conf))
        while True:
            with deser.timed(), sync_scope(exch.node_name()):
                t = next(it, None)
                b = (TpuColumnarBatch.from_arrow(t)
                     if t is not None and t.num_rows else None)
            if t is None:
                return
            if b is not None:
                if obs._ACTIVE:
                    # one reduce-side block fetched+uploaded (the row count
                    # stays out of the args: an event must never force a
                    # deferred device count — TL012)
                    obs.event("shuffle.read", cat="shuffle")
                if account_output:
                    out_rows.add(b.num_rows)
                    out_batches.add(1)
                yield b.rename(names)

    yield from prefetch_iterator(_upload(), exch._prefetch_depth(ctx))


def _read_reduce_group(exch, reduce_ids, ctx: TaskContext,
                       names) -> Iterator:
    """Read a group of reduce partitions through an AQE reader. In
    MULTITHREADED mode with coalescing on, the group's fetched Arrow blocks
    concatenate HOST-side across reduce-partition boundaries up to the
    batch-size targets before the upload (reference GpuShuffleCoalesceExec
    under GpuCustomShuffleReaderExec) — grouping small partitions is only a
    win if they also merge into fewer uploads/dispatches."""
    from ..execs.coalesce import coalesce_enabled
    if coalesce_enabled(ctx.conf) \
            and isinstance(exch, TpuShuffleExchangeExec) \
            and exch._shuffle_mode(ctx) == "MULTITHREADED":
        exch._ensure_materialized(ctx)
        mgr = TpuShuffleManager.get(ctx.conf)

        def tables():
            for rid in reduce_ids:
                yield from exch._fetch_tables(rid, ctx, mgr)

        # account_output: this path bypasses exch.execute_partition, whose
        # wrapper would otherwise feed the exchange's output metrics
        yield from _pipelined_upload(exch, tables(), names, ctx,
                                     account_output=True)
        return
    for reduce_id in reduce_ids:
        yield from exch.execute_partition(reduce_id, ctx)


def plan_cpu_exchange(plan, conf):
    from ..plan.planner import plan_physical
    child = plan_physical(plan.children[0], conf)
    part = plan.partitioning
    n = plan.num_partitions
    if part == "coalesce" and n >= child.num_partitions():
        return child  # coalesce to >= current count: no-op
    return CpuShuffleExchangeExec(child, "hash" if plan.keys else part,
                                  plan.keys, n)
