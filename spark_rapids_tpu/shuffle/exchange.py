"""Shuffle exchange — lands with the shuffle milestone."""


def plan_cpu_exchange(plan, conf):
    raise NotImplementedError("exchange lands with the shuffle milestone")
