"""AQE join-input shuffle readers: coordinated coalescing + skew splitting.

Reference: GpuCustomShuffleReaderExec (execution/GpuCustomShuffleReaderExec.
scala:37) handles both CoalescedPartitionSpec and PartialReducerPartitionSpec,
planned by Spark's AQE rules (CoalesceShufflePartitions / OptimizeSkewedJoin).
Here the coordinator stands in for the query-stage planner: it reads both
exchanges' materialized partition statistics ONCE and derives one shared spec
list, so partition i of the left reader always pairs with partition i of the
right reader:

  * coalesce: consecutive small reduce partitions group up to the advisory
    size using the COMBINED (left+right) sizes — both sides group
    identically, preserving co-partitioning.
  * skew split: a reduce partition much larger than the median on one side
    splits into map-range slices near the advisory size; the OTHER side's
    matching partition is replicated per slice (exactly Spark's skew-join
    shape). Splitting side s is sound only when side s's rows appear in
    exactly one slice and the other side is a pure lookup: inner both sides,
    left outer/semi/anti split left only, right outer split right only,
    full outer never splits.
"""

from __future__ import annotations

import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from ..execs.base import TaskContext, TpuExec

# spec entries:
#   ("group", [reduce_ids])                 both sides read the whole group
#   ("slice", side, reduce_id, [map_ids])   `side` reads the map slice, the
#                                           other side replicates reduce_id
Spec = Tuple


_SPLIT_LEFT = {"inner", "cross", "leftouter", "left", "leftsemi", "semi",
               "leftanti", "anti"}
_SPLIT_RIGHT = {"inner", "cross", "rightouter", "right"}


class JoinReaderCoordinator:
    """Shared partition-spec planner for the two sides of a shuffled join."""

    def __init__(self, left_exchange, right_exchange, join_type: str,
                 advisory_bytes: int, skew_threshold: int, skew_factor: float,
                 coalesce: bool = True):
        self.left = left_exchange
        self.right = right_exchange
        self.join_type = join_type
        self.advisory_bytes = advisory_bytes
        self.skew_threshold = skew_threshold
        self.skew_factor = skew_factor
        self.coalesce = coalesce
        self._specs: Optional[List[Spec]] = None
        self._lock = threading.Lock()
        self.skew_splits = 0  # observability

    def specs(self, ctx: TaskContext) -> List[Spec]:
        with self._lock:
            if self._specs is None:
                self._specs = self._plan(ctx)
            return self._specs

    def _median(self, sizes: List[int]) -> float:
        """Median over ALL partitions, zeros included — the single-hot-key
        shape (one huge partition, rest empty) must register as skewed
        (Spark OptimizeSkewedJoin medianSize)."""
        if not sizes:
            return 0.0
        return float(sorted(sizes)[len(sizes) // 2])

    def _skewed(self, size: int, med: float) -> bool:
        return size > max(self.skew_threshold, self.skew_factor * med)

    def _slices(self, exchange, reduce_id: int, ctx) -> List[List[int]]:
        """Partition the reduce partition's maps into near-advisory groups."""
        msizes = exchange.map_block_sizes(reduce_id, ctx)
        if len(msizes) <= 1:
            return []
        groups: List[List[int]] = []
        cur: List[int] = []
        cur_b = 0
        for m, sz in enumerate(msizes):
            if cur and cur_b + sz > self.advisory_bytes:
                groups.append(cur)
                cur, cur_b = [], 0
            cur.append(m)
            cur_b += sz
        if cur:
            groups.append(cur)
        return groups if len(groups) > 1 else []

    def _plan(self, ctx: TaskContext) -> List[Spec]:
        L = self.left.partition_sizes(ctx)
        R = self.right.partition_sizes(ctx)
        med_l, med_r = self._median(L), self._median(R)
        can_l = self.join_type in _SPLIT_LEFT
        can_r = self.join_type in _SPLIT_RIGHT
        specs: List[Spec] = []
        group: List[int] = []
        group_b = 0

        def flush():
            nonlocal group, group_b
            if group:
                specs.append(("group", group))
                group, group_b = [], 0

        for r in range(len(L)):
            combined = L[r] + R[r]
            slices: List[List[int]] = []
            side = 0
            if can_l and self._skewed(L[r], med_l):
                slices = self._slices(self.left, r, ctx)
                side = 0
            if not slices and can_r and self._skewed(R[r], med_r):
                slices = self._slices(self.right, r, ctx)
                side = 1
            if slices:
                flush()
                self.skew_splits += len(slices)
                for maps in slices:
                    specs.append(("slice", side, r, maps))
                continue
            if group and (not self.coalesce
                          or group_b + combined > self.advisory_bytes):
                flush()
            group.append(r)
            group_b += combined
        flush()
        return specs or [("group", [0])]


class TpuCoordinatedShuffleReaderExec(TpuExec):
    """One side of a coordinated join-reader pair (reference
    GpuCustomShuffleReaderExec with coalesced AND partial-reducer specs)."""

    def __init__(self, exchange, coordinator: JoinReaderCoordinator,
                 side: int, conf=None):
        super().__init__([exchange])
        self.coordinator = coordinator
        self.side = side
        # planner conf snapshot (same contract as TpuShuffleReaderExec):
        # num_partitions materializes the exchange, which must see the
        # session conf, not default_conf
        self._conf = conf

    @property
    def output(self):
        return self.children[0].output

    def node_desc(self) -> str:
        n = len(self.coordinator._specs) if self.coordinator._specs is not None \
            else "?"
        s = self.coordinator.skew_splits
        extra = f", skewSplits={s}" if s else ""
        return f"TpuCoordinatedShuffleReader[{'LR'[self.side]}, n={n}{extra}]"

    def num_partitions(self) -> int:
        from ..config import default_conf
        ctx = TaskContext(0, self._conf or default_conf())
        try:
            return len(self.coordinator.specs(ctx))
        finally:
            ctx.complete()

    def internal_do_execute_columnar(self, idx: int, ctx: TaskContext) -> Iterator:
        from .exchange import _read_reduce_group
        spec = self.coordinator.specs(ctx)[idx]
        exch = self.children[0]
        if spec[0] == "group":
            # host-side coalescing across the group's reduce partitions
            # (GpuShuffleCoalesceExec under the coordinated reader)
            yield from _read_reduce_group(exch, spec[1], ctx,
                                          [a.name for a in self.output])
            return
        _, side, reduce_id, maps = spec
        if side == self.side:
            yield from exch.execute_partition_maps(reduce_id, maps, ctx)
        else:
            # the non-split side replicates the full partition per slice
            yield from exch.execute_partition(reduce_id, ctx)
