"""Shuffle batch serialization: Arrow IPC framing + compression codecs +
block integrity checksums.

Reference: GpuColumnarBatchSerializer.scala (JCudfSerialization host-buffer
framing) + the nvcomp LZ4/ZSTD codecs (NvcompLZ4CompressionCodec.scala,
TableCompressionCodec.scala). Arrow IPC replaces JCudfSerialization as the
host wire format; zstd (host) stands in for nvcomp (the TPU has no device
decompression engine — compression trades host CPU for disk/network bytes,
same economics as the reference's MULTITHREADED mode).

Integrity (SPARK-35275 analogue): every v2 block embeds an xxhash64 of its
compressed payload plus the payload length. A flipped byte or truncated
file raises BlockIntegrityError instead of surfacing an arbitrary pyarrow/
zstd error deep in deserialization — the shuffle manager converts that into
FetchFailedError so the exchange re-materializes the producing map task
(lineage recompute) rather than crashing the query.
"""

from __future__ import annotations

import functools
import io
import struct
from typing import List, Optional

_MAGIC = b"TPUS"  # block header magic
_VERSION = 2      # v1 blocks (no checksum) had the codec id (0/1) here

# Spark XXH64 primes (expressions/hashexprs.py holds the device/numpy
# implementations; this is the host-bytes variant tuned for large buffers:
# one struct.unpack of the whole lane region, then plain-int arithmetic,
# which beats per-word numpy scalars by ~an order of magnitude)
_M64 = (1 << 64) - 1
_XP1 = 0x9E3779B185EBCA87
_XP2 = 0xC2B2AE3D27D4EB4F
_XP3 = 0x165667B19E3779F9
_XP4 = 0x85EBCA77C2B2AE63
_XP5 = 0x27D4EB2F165667C5


def _rotl(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


try:  # optional C accelerator (~GB/s); the pure-python path below is the
    # always-available fallback (~10 MB/s — the checksum conf can turn
    # block checksumming off entirely where that matters)
    import xxhash as _xxh_native
except ImportError:
    _xxh_native = None


def xxhash64_bytes(data: bytes, seed: int = 0) -> int:
    """Standard XXH64 over a byte buffer (matches
    expressions.hashexprs.np_xxhash64_bytes, i.e. Spark's XXH64)."""
    if _xxh_native is not None:
        return _xxh_native.xxh64_intdigest(data, seed)
    n = len(data)
    i = 0
    if n >= 32:
        v1 = (seed + _XP1 + _XP2) & _M64
        v2 = (seed + _XP2) & _M64
        v3 = seed & _M64
        v4 = (seed - _XP1) & _M64
        stripes = (n - i) // 32
        lanes = struct.unpack_from(f"<{stripes * 4}Q", data, i)
        # hot loop: rotl/mask inlined — half a million function calls per
        # MiB otherwise dominate the hash time
        for w1, w2, w3, w4 in zip(lanes[0::4], lanes[1::4], lanes[2::4],
                                  lanes[3::4]):
            v1 = (v1 + w1 * _XP2) & _M64
            v1 = (((v1 << 31) | (v1 >> 33)) & _M64) * _XP1 & _M64
            v2 = (v2 + w2 * _XP2) & _M64
            v2 = (((v2 << 31) | (v2 >> 33)) & _M64) * _XP1 & _M64
            v3 = (v3 + w3 * _XP2) & _M64
            v3 = (((v3 << 31) | (v3 >> 33)) & _M64) * _XP1 & _M64
            v4 = (v4 + w4 * _XP2) & _M64
            v4 = (((v4 << 31) | (v4 >> 33)) & _M64) * _XP1 & _M64
        i += stripes * 32
        h = (_rotl(v1, 1) + _rotl(v2, 7) + _rotl(v3, 12)
             + _rotl(v4, 18)) & _M64
        for v in (v1, v2, v3, v4):
            h = ((h ^ ((_rotl((v * _XP2) & _M64, 31) * _XP1) & _M64))
                 * _XP1 + _XP4) & _M64
    else:
        h = (seed + _XP5) & _M64
    h = (h + n) & _M64
    while i <= n - 8:
        (w,) = struct.unpack_from("<Q", data, i)
        h = (h ^ ((_rotl((w * _XP2) & _M64, 31) * _XP1) & _M64)) & _M64
        h = (_rotl(h, 27) * _XP1 + _XP4) & _M64
        i += 8
    if i <= n - 4:
        (w,) = struct.unpack_from("<I", data, i)
        h = (h ^ (w * _XP1)) & _M64
        h = (_rotl(h, 23) * _XP2 + _XP3) & _M64
        i += 4
    while i < n:
        h = (h ^ (data[i] * _XP5)) & _M64
        h = (_rotl(h, 11) * _XP1) & _M64
        i += 1
    h ^= h >> 33
    h = (h * _XP2) & _M64
    h ^= h >> 29
    h = (h * _XP3) & _M64
    h ^= h >> 32
    return h


class BlockIntegrityError(IOError):
    """A shuffle block failed structural/checksum validation: corrupt or
    truncated bytes. The read path maps this (and any other deserialization
    error) to FetchFailedError for lineage recompute."""


class CompressionCodec:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data


class ZstdCodec(CompressionCodec):
    """zstd via the C++ native bridge when built, python zstandard otherwise."""

    name = "zstd"

    def __init__(self, level: int = 1):
        self._level = level
        from .. import native_bridge
        self._native = native_bridge if native_bridge.available() else None
        if self._native is None:
            import zstandard
            self._c = zstandard.ZstdCompressor(level=level)
            self._d = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        if self._native is not None:
            out = self._native.zstd_compress(data, self._level)
            if out is not None:
                return out
        import zstandard
        return zstandard.ZstdCompressor(level=self._level).compress(data)

    def decompress(self, data: bytes) -> bytes:
        import zstandard
        return zstandard.ZstdDecompressor().decompress(data)


def zstd_available() -> bool:
    """True when some zstd engine exists: the C++ native bridge built, or
    the python zstandard module importable."""
    from .. import native_bridge
    if native_bridge.available():
        return True
    import importlib.util
    return importlib.util.find_spec("zstandard") is not None


@functools.lru_cache(maxsize=None)
def _warn_zstd_unavailable() -> None:
    import warnings
    warnings.warn(
        "zstd requested for shuffle compression but neither the native "
        "bridge nor the python zstandard module is available; writing "
        "uncompressed blocks (the frame header records the codec per "
        "block, so readers are unaffected)")


def get_codec(name: str) -> CompressionCodec:
    name = (name or "none").lower()
    if name == "zstd":
        if not zstd_available():
            # degrade, don't fail: environments without any zstd engine
            # (no libzstd headers for the native build, no python module)
            # still shuffle correctly — each block's header names its own
            # codec, so uncompressed blocks interleave freely with zstd
            # ones written by better-equipped processes
            _warn_zstd_unavailable()
            return CompressionCodec()
        return ZstdCodec()
    if name in ("none", "copy"):
        return CompressionCodec()
    raise ValueError(f"unknown shuffle compression codec {name!r}")


def serialize_table(table, codec: CompressionCodec,
                    checksum: bool = True) -> bytes:
    """One shuffle block:
    magic | version u8 | codec u8 | raw_len u64 | payload_len u64 |
    xxhash64(payload) u64 | payload.  checksum=False writes 0 in the
    checksum field, which the reader treats as 'not checksummed'."""
    import pyarrow as pa
    from ..chaos import inject
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    raw = sink.getvalue()
    inject("shuffle.serialize", detail=f"{len(raw)}B")
    payload = codec.compress(raw)
    csum = xxhash64_bytes(payload) if checksum else 0
    header = _MAGIC + struct.pack("<BBQQQ", _VERSION,
                                  1 if codec.name == "zstd" else 0,
                                  len(raw), len(payload), csum)
    return header + payload


def deserialize_table(block: bytes):
    import pyarrow as pa
    if len(block) < 13 or block[:4] != _MAGIC:
        raise BlockIntegrityError(
            f"corrupt shuffle block: bad magic/header ({len(block)} bytes)")
    if block[4] in (0, 1):
        # legacy v1 framing: magic | codec u8 | raw_len u64 | payload —
        # no integrity fields (accepted for mixed-version block stores)
        codec_id, raw_len = struct.unpack("<BQ", block[4:13])
        payload = block[13:]
    else:
        if block[4] != _VERSION or len(block) < 30:
            raise BlockIntegrityError(
                f"corrupt shuffle block: unknown version {block[4]} or "
                f"truncated header ({len(block)} bytes)")
        _, codec_id, raw_len, payload_len, csum = struct.unpack(
            "<BBQQQ", block[4:30])
        payload = block[30:]
        if len(payload) != payload_len:
            raise BlockIntegrityError(
                f"truncated shuffle block: payload {len(payload)} bytes, "
                f"header declares {payload_len}")
        if csum and xxhash64_bytes(payload) != csum:
            raise BlockIntegrityError(
                "shuffle block xxhash64 checksum mismatch "
                f"({payload_len}-byte payload)")
    if codec_id == 1:
        from .. import native_bridge
        out = (native_bridge.zstd_decompress(payload, raw_len)
               if native_bridge.available() else None)
        if out is not None:
            payload = out
        else:
            import zstandard
            payload = zstandard.ZstdDecompressor().decompress(
                payload, max_output_size=raw_len)
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        return r.read_all()
