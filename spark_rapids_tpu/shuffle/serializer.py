"""Shuffle batch serialization: Arrow IPC framing + compression codecs.

Reference: GpuColumnarBatchSerializer.scala (JCudfSerialization host-buffer
framing) + the nvcomp LZ4/ZSTD codecs (NvcompLZ4CompressionCodec.scala,
TableCompressionCodec.scala). Arrow IPC replaces JCudfSerialization as the host
wire format; zstd (host) stands in for nvcomp (the TPU has no device
decompression engine — compression trades host CPU for disk/network bytes,
same economics as the reference's MULTITHREADED mode).
"""

from __future__ import annotations

import io
import struct
from typing import List, Optional

_MAGIC = b"TPUS"  # block header magic


class CompressionCodec:
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes) -> bytes:
        return data


class ZstdCodec(CompressionCodec):
    """zstd via the C++ native bridge when built, python zstandard otherwise."""

    name = "zstd"

    def __init__(self, level: int = 1):
        self._level = level
        from .. import native_bridge
        self._native = native_bridge if native_bridge.available() else None
        if self._native is None:
            import zstandard
            self._c = zstandard.ZstdCompressor(level=level)
            self._d = zstandard.ZstdDecompressor()

    def compress(self, data: bytes) -> bytes:
        if self._native is not None:
            out = self._native.zstd_compress(data, self._level)
            if out is not None:
                return out
        import zstandard
        return zstandard.ZstdCompressor(level=self._level).compress(data)

    def decompress(self, data: bytes) -> bytes:
        import zstandard
        return zstandard.ZstdDecompressor().decompress(data)


def get_codec(name: str) -> CompressionCodec:
    name = (name or "none").lower()
    if name == "zstd":
        return ZstdCodec()
    if name in ("none", "copy"):
        return CompressionCodec()
    raise ValueError(f"unknown shuffle compression codec {name!r}")


def serialize_table(table, codec: CompressionCodec) -> bytes:
    """One shuffle block: magic | codec u8 | raw_len u64 | payload."""
    import pyarrow as pa
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    raw = sink.getvalue()
    payload = codec.compress(raw)
    header = _MAGIC + struct.pack("<BQ", 1 if codec.name == "zstd" else 0,
                                  len(raw))
    return header + payload


def deserialize_table(block: bytes):
    import pyarrow as pa
    assert block[:4] == _MAGIC, "corrupt shuffle block"
    codec_id, raw_len = struct.unpack("<BQ", block[4:13])
    payload = block[13:]
    if codec_id == 1:
        import zstandard
        payload = zstandard.ZstdDecompressor().decompress(payload,
                                                          max_output_size=raw_len)
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        return r.read_all()
