"""ICI shuffle mode: device-resident shuffle catalog + peer heartbeat.

Reference mapping (SURVEY.md §2.7): the UCX mode keeps shuffle blocks
device-resident in a ShuffleBufferCatalog served peer-to-peer over
RDMA/NVLink (RapidsShuffleServer/Client, BufferSendState/BufferReceiveState),
with a driver-coordinated heartbeat discovering peers
(RapidsShuffleHeartbeatManager, Plugin.scala:436-447).

TPU re-design: within one mesh/slice the data plane is XLA's `all_to_all`
over ICI (parallel/distributed.py `ici_all_to_all_exchange` — the compiler
schedules the interconnect transfers, replacing hand-written UCX
transactions). At the exec layer, ICI mode keeps every shuffle block as a
*spillable device batch* in this catalog — no Arrow serialization, no disk
round trip; reduce tasks concat blocks directly on device (≙ the reference's
RapidsCachingWriter/RapidsCachingReader pair). Blocks are spillable, so HBM
pressure pushes them down the usual HBM→host→disk tiers instead of OOMing.
The heartbeat registry tracks peer liveness; a lost peer invalidates its map
outputs so the exchange re-materializes them (Spark would re-run the map
stage)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from ..columnar.batch import TpuColumnarBatch
from ..memory.spill import SpillableColumnarBatch


class ShuffleHeartbeatManager:
    """Driver-side peer registry (reference RapidsShuffleHeartbeatManager):
    executors announce themselves and heartbeat; peers missing beyond the
    timeout are reported lost exactly once."""

    _instance: Optional["ShuffleHeartbeatManager"] = None
    _lock = threading.Lock()

    def __init__(self, timeout_s: float = 30.0):
        self.timeout_s = timeout_s
        self._peers: Dict[str, float] = {}
        self._registered_order: List[str] = []
        self._mu = threading.Lock()

    @classmethod
    def get(cls) -> "ShuffleHeartbeatManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "ShuffleHeartbeatManager":
        with cls._lock:
            cls._instance = cls()
            return cls._instance

    def register_peer(self, executor_id: str,
                      now: Optional[float] = None) -> List[str]:
        """Returns the already-known peers (RapidsExecutorStartupMsg reply)."""
        with self._mu:
            known = list(self._registered_order)
            if executor_id not in self._peers:
                self._registered_order.append(executor_id)
            self._peers[executor_id] = now if now is not None else time.time()
            return known

    def heartbeat(self, executor_id: str,
                  now: Optional[float] = None) -> None:
        with self._mu:
            if executor_id in self._peers:
                self._peers[executor_id] = now if now is not None \
                    else time.time()

    def lost_peers(self, now: Optional[float] = None) -> List[str]:
        t = now if now is not None else time.time()
        with self._mu:
            lost = [e for e, last in self._peers.items()
                    if t - last > self.timeout_s]
            for e in lost:
                del self._peers[e]
                self._registered_order.remove(e)
            return lost

    def peers(self) -> List[str]:
        with self._mu:
            return list(self._registered_order)


class FetchFailedError(RuntimeError):
    """A map output is missing (peer lost / invalidated) — the exchange must
    re-materialize those map tasks (Spark: FetchFailed → stage retry)."""

    def __init__(self, shuffle_id: int, map_ids: List[int]):
        super().__init__(f"shuffle {shuffle_id}: missing map output for "
                         f"maps {map_ids}")
        self.shuffle_id = shuffle_id
        self.map_ids = map_ids


class IciShuffleCatalog:
    """Device-resident shuffle block store (reference ShuffleBufferCatalog +
    ShuffleReceivedBufferCatalog): (shuffle_id, map_id, reduce_id) →
    spillable device batch. Map completion is tracked separately so a
    missing block distinguishes 'legitimately empty partition' from
    'lost/invalidated output' (the latter raises FetchFailedError)."""

    _instance: Optional["IciShuffleCatalog"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._blocks: Dict[Tuple[int, int, int], SpillableColumnarBatch] = {}
        self._owner: Dict[Tuple[int, int], str] = {}  # (sid, map_id) → exec
        self._complete: set = set()  # (sid, map_id) with committed output
        self._mu = threading.Lock()

    @classmethod
    def get(cls) -> "IciShuffleCatalog":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
                import atexit
                atexit.register(cls._shutdown_instance)
            return cls._instance

    @classmethod
    def _shutdown_instance(cls) -> None:
        # close-discipline: catalog-held blocks are owned state, released
        # at shutdown so the MemoryCleaner report only shows real leaks
        inst = cls._instance
        if inst is not None:
            inst.close_all()

    @classmethod
    def reset_for_tests(cls) -> "IciShuffleCatalog":
        with cls._lock:
            if cls._instance is not None:
                cls._instance.close_all()
            cls._instance = cls()
            return cls._instance

    def close_all(self) -> None:
        with self._mu:
            closed = list(self._blocks.values())
            self._blocks.clear()
            self._owner.clear()
            self._complete = set()
        for sb in closed:
            sb.close()

    def put_block(self, shuffle_id: int, map_id: int, reduce_id: int,
                  batch: TpuColumnarBatch,
                  owner: Optional[str] = None) -> None:
        from ..memory.spill import OUTPUT_FOR_SHUFFLE_PRIORITY
        sb = SpillableColumnarBatch(batch,
                                    priority=OUTPUT_FOR_SHUFFLE_PRIORITY)
        with self._mu:
            key = (shuffle_id, map_id, reduce_id)
            old = self._blocks.pop(key, None)
            self._blocks[key] = sb
            if owner is not None:
                self._owner[(shuffle_id, map_id)] = owner
        if old is not None:
            old.close()

    def mark_map_complete(self, shuffle_id: int, map_id: int) -> None:
        with self._mu:
            self._complete.add((shuffle_id, map_id))

    def iter_blocks(self, shuffle_id: int, reduce_id: int,
                    n_maps: int, map_ids=None) -> Iterator[TpuColumnarBatch]:
        """Raises FetchFailedError when any map's output was invalidated —
        including a block whose disk-spilled bytes fail their integrity
        check on unspill (the catalog drops that map's output so the
        exchange re-runs it, instead of surfacing a storage error).
        `map_ids` restricts to a subset of maps (AQE skew slices)."""
        from ..chaos import inject
        from ..memory.spill import SpillCorruptionError
        inject("ici.fetch", detail=f"s{shuffle_id}r{reduce_id}")
        with self._mu:
            missing = [m for m in range(n_maps)
                       if (shuffle_id, m) not in self._complete]
        if missing:
            raise FetchFailedError(shuffle_id, missing)
        for map_id in (range(n_maps) if map_ids is None else map_ids):
            with self._mu:
                sb = self._blocks.get((shuffle_id, map_id, reduce_id))
                if sb is None and (shuffle_id, map_id) not in self._complete:
                    # invalidated since the up-front completeness check (a
                    # concurrent reduce task hit corruption / a peer was
                    # lost): silently skipping would DROP this map's rows
                    raise FetchFailedError(shuffle_id, [map_id])
            try:
                # fetch OUTSIDE the catalog lock: get_batch can unspill
                # (disk read + HBM allocation) and holding _mu across it
                # both stalls every concurrent put and inverts the
                # declared lock order (TL022: _mu is a leaf below the
                # spill catalog's _reg_lock). A concurrent invalidate/
                # cleanup closing the spillable after we released _mu
                # surfaces as ValueError/KeyError — the block is GONE,
                # which is exactly a FetchFailed: lineage recovery re-runs
                # the map.
                batch = sb.get_batch() if sb is not None else None
            except SpillCorruptionError as exc:
                with self._mu:
                    self._invalidate_map_locked(shuffle_id, map_id)
                raise FetchFailedError(shuffle_id, [map_id]) from exc
            except (ValueError, KeyError) as exc:
                raise FetchFailedError(shuffle_id, [map_id]) from exc
            if batch is not None:
                yield batch

    def _invalidate_map_locked(self, shuffle_id: int, map_id: int) -> None:
        """Drop one map's blocks + completion (caller holds self._mu)."""
        victims = [k for k in self._blocks
                   if k[0] == shuffle_id and k[1] == map_id]
        for k in victims:
            self._blocks.pop(k).close()
        self._owner.pop((shuffle_id, map_id), None)
        self._complete.discard((shuffle_id, map_id))

    def reduce_sizes(self, shuffle_id: int, n_maps: int,
                     n_reduces: int) -> List[int]:
        """Per-reduce-partition byte totals from catalog metadata alone
        (sizes are tracked at put time from the spillable's device byte
        count — AQE statistics never unspill or fetch a block). Raises
        FetchFailedError for incomplete maps, exactly like the block fetch,
        so the caller's recovery loop re-runs lost maps first."""
        with self._mu:
            missing = [m for m in range(n_maps)
                       if (shuffle_id, m) not in self._complete]
            if missing:
                raise FetchFailedError(shuffle_id, missing)
            out = [0] * n_reduces
            for (sid, _m, r), sb in self._blocks.items():
                if sid == shuffle_id and r < n_reduces:
                    out[r] += sb.size_bytes
            return out

    def invalidate_map(self, shuffle_id: int, map_id: int) -> None:
        """Drop one map's blocks + completion (a lost peer/shard observed
        by a reader): the next fetch raises FetchFailedError and lineage
        recovery re-runs exactly this map."""
        with self._mu:
            self._invalidate_map_locked(shuffle_id, map_id)

    def block_sizes(self, shuffle_id: int, reduce_id: int,
                    n_maps: int) -> List[int]:
        """Per-map device byte sizes of one reduce partition — one lock pass
        (AQE skew planning granularity)."""
        out = [0] * n_maps
        with self._mu:
            for m in range(n_maps):
                sb = self._blocks.get((shuffle_id, m, reduce_id))
                if sb is not None:
                    out[m] = sb.size_bytes
        return out

    def invalidate_owner(self, executor_id: str) -> List[Tuple[int, int]]:
        """Drop all blocks produced by a lost peer; returns the
        (shuffle_id, map_id) pairs that need re-running."""
        with self._mu:
            lost = [sm for sm, o in self._owner.items() if o == executor_id]
            lost_set = set(lost)
            victims = [k for k in self._blocks if (k[0], k[1]) in lost_set]
            closed = [self._blocks.pop(k) for k in victims]
            for sm in lost:
                del self._owner[sm]
                self._complete.discard(sm)
        for sb in closed:
            sb.close()
        return lost

    def cleanup(self, shuffle_id: int) -> None:
        with self._mu:
            victims = [k for k in self._blocks if k[0] == shuffle_id]
            closed = [self._blocks.pop(k) for k in victims]
            self._owner = {sm: o for sm, o in self._owner.items()
                           if sm[0] != shuffle_id}
            self._complete = {sm for sm in self._complete
                              if sm[0] != shuffle_id}
        for sb in closed:
            sb.close()

    def block_count(self) -> int:
        with self._mu:
            return len(self._blocks)
