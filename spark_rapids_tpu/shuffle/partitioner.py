"""Partitioners: split device batches by hash/round-robin/range/single.

Reference: GpuPartitioning.scala:64-118 (murmur3 on device + contiguousSplit),
GpuHashPartitioningBase.scala (Spark pid = pmod(murmur3(keys, 42), n)),
GpuRangePartitioner.scala. Device strategy: compute pids, stable-sort rows by
pid, sync the n partition boundaries to host, slice — the static-shape analogue
of cuDF's contiguous split.
"""

from __future__ import annotations

import functools as _functools

from typing import List, Optional, Sequence

import jax as _jax
import jax.numpy as jnp
import numpy as np

from ..columnar.batch import TpuColumnarBatch, gather
from ..columnar.vector import TpuColumnVector, bucket_capacity, row_mask
from ..expressions.base import Expression, to_column
from ..expressions.hashexprs import murmur3_batch


def hash_partition_ids(batch: TpuColumnarBatch, key_exprs: Sequence[Expression],
                       n: int, ctx, seed: int = 42,
                       metrics=None) -> jnp.ndarray:
    """Spark HashPartitioning: pmod(murmur3(keys, seed=42), n). Sub-partition
    callers pass a distinct seed so their buckets are independent of the
    upstream exchange's (reference GpuSubPartitionHashJoin.scala hashSeed=100).

    The key-eval + murmur3 + pmod chain runs as ONE cached executable when
    the keys trace (execs/opjit.py); string/host keys stay eager."""
    from ..execs import opjit
    pid = opjit.partition_ids(batch, key_exprs, n, ctx.eval_ctx, seed,
                              metrics)
    if pid is not None:
        return pid
    cols = [to_column(k.eval_tpu(batch, ctx.eval_ctx), batch, k.dtype)
            for k in key_exprs]
    h = murmur3_batch(cols, batch.num_rows, batch.capacity, seed)
    pid = h % n
    return jnp.where(pid < 0, pid + n, pid).astype(jnp.int32)


def round_robin_partition_ids(batch: TpuColumnarBatch, n: int,
                              start: int = 0) -> jnp.ndarray:
    return ((jnp.arange(batch.capacity, dtype=jnp.int32) + start) % n)


@_functools.partial(_jax.jit, static_argnames=("n",))
def _split_plan(pids, num_rows, n: int):
    """Sort-by-pid + partition bounds as one program (the eager version paid
    ~4 dispatches per batch through the tunnel)."""
    cap = pids.shape[0]
    mask = jnp.arange(cap) < num_rows
    key = jnp.where(mask, pids, n)  # padding last
    order = jnp.argsort(key, stable=True)
    sorted_pid = jnp.take(key, order)
    return order, jnp.searchsorted(sorted_pid, jnp.arange(n + 1))


def split_by_partition(batch: TpuColumnarBatch, pids, n: int) -> List[Optional[TpuColumnarBatch]]:
    """Device split: stable sort by pid, one async boundary readback,
    gather slices.

    The n+1 partition bounds decide each output's row count, and the exec
    protocol carries counts as python ints — so ONE small D→H transfer per
    batch is inherent to eager host-driven slicing (the compiled stage in
    execs/compiled.py is the no-sync path). What this avoids is blocking
    the pipeline for the full round trip: the copy starts immediately
    after the searchsorted is enqueued, overlapping the transfer with
    dispatch of the sort/gather work already in flight."""
    # rows_arg: a deferred-compaction batch's pending device count feeds the
    # plan directly — the bounds readback below is then the chain's ONE sync
    order, bounds_dev = _split_plan(pids, batch.rows_arg, n=n)
    return split_with_plan(batch, order, bounds_dev, n)


def split_with_plan(batch: TpuColumnarBatch, order, bounds_dev,
                    n: int) -> List[Optional[TpuColumnarBatch]]:
    """Slice a batch along an already-computed (order, bounds) split plan
    (from _split_plan or the fused opjit.partition_split_plan program)."""
    try:
        bounds_dev.copy_to_host_async()
    except AttributeError:  # older jax arrays: np.asarray below still works
        pass
    from ..columnar.vector import audited_sync
    bounds = audited_sync(bounds_dev, "bounds")
    return _slice_split(batch, order, bounds, n)


def _slice_split(batch: TpuColumnarBatch, order, bounds,
                 n: int) -> List[Optional[TpuColumnarBatch]]:
    """Gather the n partition slices given HOST bounds (the readback already
    happened — per batch in split_with_plan, or ONE transfer for a whole
    partition group in hash_split_parts_grouped)."""
    cap = batch.capacity
    out: List[Optional[TpuColumnarBatch]] = []
    for p in range(n):
        lo, hi = int(bounds[p]), int(bounds[p + 1])
        cnt = hi - lo
        if cnt == 0:
            out.append(None)
            continue
        idx = jnp.take(order, jnp.clip(jnp.arange(bucket_capacity(cnt)) + lo,
                                       0, cap - 1))
        out.append(gather(batch, idx, cnt, bucket_capacity(cnt)))
    return out


def hash_split_parts(batch: TpuColumnarBatch, key_exprs: Sequence[Expression],
                     n: int, ctx, seed: int = 42,
                     metrics=None) -> List[Optional[TpuColumnarBatch]]:
    """Hash-partition a batch into n slices with the ENCODE+SPLIT pair fused
    into one cached executable when the keys trace (opjit.partition_split_plan
    — one dispatch instead of pids + split plan); eager two-program path
    otherwise, bit-identical either way."""
    from ..execs import opjit
    plan = opjit.partition_split_plan(batch, key_exprs, n, ctx.eval_ctx,
                                      seed, metrics)
    if plan is not None:
        return split_with_plan(batch, plan[0], plan[1], n)
    pids = hash_partition_ids(batch, key_exprs, n, ctx, seed=seed,
                              metrics=metrics)
    return split_by_partition(batch, pids, n)


def hash_split_parts_grouped(batches: Sequence[TpuColumnarBatch],
                             key_exprs: Sequence[Expression], n: int, ctx,
                             seed: int = 42, metrics=None
                             ) -> Optional[List[List[Optional[TpuColumnarBatch]]]]:
    """Batched multi-partition dispatch of the hash split: N map partitions'
    batches run their encode+split plans as ONE cached executable
    (opjit.partition_split_plan_grouped) and ALL lanes' partition bounds come
    back in ONE device→host transfer — per-lane slices are bit-identical to
    hash_split_parts. Returns one parts list per input batch, or None when
    the keys don't trace (callers fall back to the per-batch split)."""
    from ..execs import opjit
    plans = opjit.partition_split_plan_grouped(
        batches, [list(key_exprs)] * len(batches), n, ctx.eval_ctx, seed,
        metrics)
    if plans is None:
        return None
    orders, bounds_dev = plans
    for bd in bounds_dev:
        try:
            bd.copy_to_host_async()
        except AttributeError:
            pass
    from ..columnar.vector import audited_device_get
    host_bounds = audited_device_get(bounds_dev, "bounds")
    return [_slice_split(b, o, hb, n)
            for b, o, hb in zip(batches, orders, host_bounds)]


def np_hash_partition_ids(table, key_exprs, n: int, ctx) -> np.ndarray:
    """Host mirror for the CPU exchange path."""
    from ..expressions.hashexprs import _np_hash_col
    import pyarrow as pa
    seeds = np.full(table.num_rows, np.uint32(42), np.uint32)
    for k in key_exprs:
        arr = k.eval_cpu(table, ctx.eval_ctx)
        if not isinstance(arr, (pa.Array, pa.ChunkedArray)):
            arr = pa.array([arr] * table.num_rows)
        seeds = _np_hash_col(k.dtype, arr, seeds)
    h = seeds.view(np.int32).astype(np.int64)
    return ((h % n) + n) % n
