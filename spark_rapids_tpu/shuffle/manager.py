"""Multithreaded shuffle manager: thread-pool parallel write/read of shuffle
blocks on local storage.

Reference: RapidsShuffleInternalManagerBase.scala MULTITHREADED mode
(RapidsShuffleThreadedWriterBase:238, ...ReaderBase:569, BytesInFlightLimiter:529).
The ICI mode (device-resident exchange over the interconnect, UCX analogue)
lives in parallel/distributed.py and is selected via spark.rapids.shuffle.mode.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional

from ..config import (RapidsConf, SHUFFLE_CHECKSUM_ENABLED,
                      SHUFFLE_COMPRESSION_CODEC, SHUFFLE_READER_THREADS,
                      SHUFFLE_WRITER_THREADS, default_conf)
from .serializer import deserialize_table, get_codec, serialize_table


class BytesInFlightLimiter:
    """Caps bytes held by in-flight shuffle IO (reference
    RapidsShuffleInternalManagerBase.scala:529)."""

    def __init__(self, limit_bytes: int = 512 * 1024 * 1024):
        self._limit = limit_bytes
        self._in_flight = 0
        self._cv = threading.Condition()

    def acquire(self, n: int) -> None:
        with self._cv:
            while self._in_flight > 0 and self._in_flight + n > self._limit:
                self._cv.wait()
            self._in_flight += n

    def release(self, n: int) -> None:
        with self._cv:
            self._in_flight -= n
            self._cv.notify_all()


class TpuShuffleManager:
    """Per-process shuffle block store (Spark shuffle-files analogue)."""

    _instance: Optional["TpuShuffleManager"] = None
    _lock = threading.Lock()

    def __init__(self, conf: Optional[RapidsConf] = None):
        conf = conf or default_conf()
        self.root = tempfile.mkdtemp(prefix="tpu_shuffle_")
        self.codec_name = conf.get(SHUFFLE_COMPRESSION_CODEC)
        self.checksum = bool(conf.get(SHUFFLE_CHECKSUM_ENABLED))
        self._writers = ThreadPoolExecutor(
            max_workers=conf.get(SHUFFLE_WRITER_THREADS),
            thread_name_prefix="shuffle-writer")
        self._readers = ThreadPoolExecutor(
            max_workers=conf.get(SHUFFLE_READER_THREADS),
            thread_name_prefix="shuffle-reader")
        self._limiter = BytesInFlightLimiter()
        self._next_shuffle_id = 0
        self._id_lock = threading.Lock()
        # byte counters accumulate from writer/reader POOL threads — an
        # unguarded += loses updates under concurrency
        self._stats_lock = threading.Lock()
        self.bytes_written = 0
        self.bytes_read = 0

    @classmethod
    def get(cls, conf: Optional[RapidsConf] = None) -> "TpuShuffleManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = TpuShuffleManager(conf)
            return cls._instance

    def shutdown(self) -> None:
        """Stop the writer/reader pools and drop the block store. A
        replaced manager instance (tests swap `_instance`) must not keep
        its pool threads and spill directory alive until interpreter
        exit (TL020: the pools are owned resources)."""
        self._writers.shutdown(wait=True)
        self._readers.shutdown(wait=True)
        shutil.rmtree(self.root, ignore_errors=True)

    @classmethod
    def reset_for_tests(cls,
                        conf: Optional[RapidsConf] = None
                        ) -> "TpuShuffleManager":
        with cls._lock:
            old, cls._instance = cls._instance, None
        if old is not None:
            old.shutdown()
        return cls.get(conf)

    def new_shuffle_id(self) -> int:
        with self._id_lock:
            self._next_shuffle_id += 1
            return self._next_shuffle_id

    def _path(self, shuffle_id: int, map_id: int, reduce_id: int) -> str:
        d = os.path.join(self.root, f"shuffle_{shuffle_id}")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"map_{map_id}_reduce_{reduce_id}.block")

    def write_map_output(self, shuffle_id: int, map_id: int,
                         partition_tables: List) -> None:
        """Write one map task's per-reduce-partition tables in parallel.
        Each block lands via write-to-tmp + os.replace, so a crash mid-write
        can never leave a truncated file that `partition_sizes`'s existence
        check would count as a valid block."""
        from ..chaos import corrupt_bytes, inject

        def write_one(reduce_id: int, table) -> None:
            if table is None or table.num_rows == 0:
                return
            # codec per task: zstandard compressor objects are not safe under
            # concurrent use from multiple writer threads
            block = serialize_table(table, get_codec(self.codec_name),
                                    checksum=self.checksum)
            inject("shuffle.write", detail=f"{len(block)}B")
            # chaos corruption AFTER the checksum was embedded: the read
            # side must detect it and heal via lineage recompute
            block = corrupt_bytes("shuffle.write", block)
            self._limiter.acquire(len(block))
            path = self._path(shuffle_id, map_id, reduce_id)
            tmp = path + ".tmp"
            try:
                try:
                    with open(tmp, "wb") as f:
                        f.write(block)
                    os.replace(tmp, path)
                except BaseException:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
                    raise
                with self._stats_lock:
                    self.bytes_written += len(block)
            finally:
                self._limiter.release(len(block))

        futures = [self._writers.submit(write_one, r, t)
                   for r, t in enumerate(partition_tables)]
        for f in futures:
            f.result()

    def iter_partition_sources(self, shuffle_id: int, reduce_id: int,
                               n_maps: int, map_ids=None) -> Iterator:
        """Streaming fetch of one reduce partition's blocks as
        (map_id, table-or-None) pairs in map order: every map's
        read+deserialize is submitted to the reader pool up front — the
        consumer can upload block m while blocks m+1.. are still being read
        (reference RapidsShuffleThreadedReaderBase). `map_ids` restricts to
        a subset of maps (AQE skew slices). None means the map wrote no
        block for this partition (legitimately empty). A corrupted or
        truncated block — or any other deserialization failure — raises
        FetchFailedError naming the producing map so the exchange can
        re-materialize it (SPARK-35275 checksum semantics)."""
        from ..chaos import corrupt_bytes, inject
        from .ici import FetchFailedError

        def read_one(map_id: int):
            p = self._path(shuffle_id, map_id, reduce_id)
            if not os.path.exists(p):
                return None
            try:
                inject("shuffle.read", detail=f"map{map_id}")
                with open(p, "rb") as f:
                    block = f.read()
                block = corrupt_bytes("shuffle.read", block)
                table = deserialize_table(block)
            except Exception as exc:  # noqa: BLE001 — any decode failure is
                # a lost/corrupt block; lineage recompute heals it
                raise FetchFailedError(shuffle_id, [map_id]) from exc
            with self._stats_lock:
                self.bytes_read += len(block)
            return table

        maps = list(range(n_maps)) if map_ids is None else list(map_ids)
        futures = [self._readers.submit(read_one, m) for m in maps]
        for m, f in zip(maps, futures):
            yield m, f.result()

    def iter_partition(self, shuffle_id: int, reduce_id: int,
                       n_maps: int, map_ids=None) -> Iterator:
        """iter_partition_sources without the map ids: yields just the
        non-empty tables in map order."""
        for _, t in self.iter_partition_sources(shuffle_id, reduce_id,
                                                n_maps, map_ids):
            if t is not None:
                yield t

    def read_partition(self, shuffle_id: int, reduce_id: int,
                       n_maps: int, map_ids=None) -> List:
        """Fetch one reduce partition's blocks from all maps in parallel."""
        return list(self.iter_partition(shuffle_id, reduce_id, n_maps,
                                        map_ids))

    def cleanup(self, shuffle_id: int) -> None:
        shutil.rmtree(os.path.join(self.root, f"shuffle_{shuffle_id}"),
                      ignore_errors=True)
