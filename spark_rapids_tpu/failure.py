"""Device-error classification, transient retry, and diagnostic capture.

Reference (SURVEY.md §5 failure detection):
  * RapidsExecutorPlugin.onTaskFailed → containsCudaFatalException →
    logGpuDebugInfoAndExit (Plugin.scala:669-695,635): a fatal device error
    kills the executor so Spark reschedules its tasks elsewhere;
  * GpuCoreDumpHandler (GpuCoreDumpHandler.scala:38-190): capture a device
    core dump to distributed storage before exiting.

TPU analogue: XLA surfaces device failures as XlaRuntimeError (jaxlib ships
subclasses, and jax sometimes re-wraps device-side crashes in plain
RuntimeError carrying the XLA status string). Classification walks the
cause chain matching device-error-shaped exceptions by type name across the
MRO or by an XLA status token in a RuntimeError message, then splits them:

  * **transient** statuses (UNAVAILABLE, RESOURCE_EXHAUSTED, ABORTED,
    CANCELLED) mean the runtime hiccuped but the device is fine — the
    dispatch sites wrap themselves in `with_device_retry` (bounded
    exponential backoff + jitter) so these heal instead of killing the
    query;
  * **fatal** markers (INTERNAL, DATA_LOSS, device halted, ...) mean the
    device/runtime is unusable: `handle_task_failure` writes a diagnostic
    bundle (device topology, memory stats, task metrics, the error) under
    `spark.rapids.tpu.coreDump.dir` and — when `exit_on_fatal` — terminates
    the process so the cluster manager reschedules (tests use
    exit_on_fatal=False). A message carrying both marker classes is fatal.
"""

from __future__ import annotations

import json
import os
import random
import re
import time
import traceback
from typing import Callable, Iterator, Optional, TypeVar

T = TypeVar("T")

_FATAL_MARKERS = (
    "DEADLINE_EXCEEDED", "INTERNAL", "DATA_LOSS", "device halted", "HBM OOM",
    "Device or resource busy", "failed to synchronize",
    "hardware error", "data loss",
)

#: runtime hiccups that heal on re-dispatch (reference: the CUDA driver's
#: retryable launch failures; XLA's UNAVAILABLE family)
_TRANSIENT_MARKERS = (
    "UNAVAILABLE", "RESOURCE_EXHAUSTED", "ABORTED", "CANCELLED",
)

#: an XLA/absl status token at large in a plain RuntimeError message marks
#: the error as device-runtime-shaped even without the XlaRuntimeError type
_XLA_STATUS_RE = re.compile(
    r"\b(UNAVAILABLE|RESOURCE_EXHAUSTED|ABORTED|CANCELLED|DEADLINE_EXCEEDED"
    r"|INTERNAL|DATA_LOSS|FAILED_PRECONDITION|UNIMPLEMENTED|UNKNOWN"
    r"|OUT_OF_RANGE)\b")


def _device_error_messages(exc: BaseException) -> Iterator[str]:
    """Messages of device-error-shaped exceptions across the cause chain:
    any type whose MRO contains an XlaRuntimeError (covers jaxlib
    subclasses), or a plain RuntimeError carrying an XLA status string."""
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        names = {t.__name__ for t in type(cur).__mro__}
        msg = str(cur)
        if "XlaRuntimeError" in names:
            yield msg
        elif isinstance(cur, RuntimeError) and not isinstance(cur, MemoryError) \
                and _XLA_STATUS_RE.search(msg):
            yield msg
        cur = cur.__cause__ or cur.__context__


def is_fatal_device_error(exc: BaseException) -> bool:
    """Classify: does this error mean the device/runtime is unusable
    (reference containsCudaFatalException walking the cause chain)?"""
    return any(any(m in msg for m in _FATAL_MARKERS)
               for msg in _device_error_messages(exc))


def is_transient_device_error(exc: BaseException) -> bool:
    """A device-runtime error expected to heal on re-dispatch. Fatal markers
    win when both appear; the retry OOMs (TpuOOM) have their own framework
    and are never treated as transient."""
    transient = False
    for msg in _device_error_messages(exc):
        if any(m in msg for m in _FATAL_MARKERS):
            return False
        if any(m in msg for m in _TRANSIENT_MARKERS):
            transient = True
    return transient


def with_device_retry(fn: Callable[[], T], conf=None,
                      max_attempts: Optional[int] = None,
                      base_ms: Optional[float] = None,
                      max_ms: Optional[float] = None) -> T:
    """Run `fn`, re-attempting on TRANSIENT device errors with bounded
    exponential backoff + jitter (attempt n sleeps
    min(base * 2^(n-1), max) * U[0.5, 1.0]). Everything else — fatal device
    errors, the retry OOMs, ordinary exceptions — propagates untouched on
    the first raise. `fn` must be idempotent (all wrapped dispatch sites
    are: re-running a cached XLA program, an ICI block fetch, or a keyed
    shuffle map task).

    Retries and blocked time surface as the deviceRetryCount /
    deviceRetryBlockTimeNs task metrics (reference GpuTaskMetrics)."""
    if conf is not None:
        from .config import (DEVICE_RETRY_BACKOFF_BASE_MS,
                             DEVICE_RETRY_BACKOFF_MAX_MS,
                             DEVICE_RETRY_MAX_ATTEMPTS)
        if max_attempts is None:
            max_attempts = conf.get(DEVICE_RETRY_MAX_ATTEMPTS)
        if base_ms is None:
            base_ms = conf.get(DEVICE_RETRY_BACKOFF_BASE_MS)
        if max_ms is None:
            max_ms = conf.get(DEVICE_RETRY_BACKOFF_MAX_MS)
    attempts_left = 4 if max_attempts is None else int(max_attempts)
    base = 10.0 if base_ms is None else float(base_ms)
    cap = 2000.0 if max_ms is None else float(max_ms)
    attempt = 0
    while True:
        try:
            return fn()
        except BaseException as exc:  # noqa: BLE001 — classified below
            transient = is_transient_device_error(exc)
            if attempt >= attempts_left or not transient:
                if transient and attempt >= attempts_left:
                    # exhausted retry: the runtime would not heal — dump a
                    # postmortem bundle (flight ring + registry snapshot +
                    # device state) before the error propagates
                    from .obs import flight as _flight
                    _flight.note("device.retry_exhausted",
                                 attempts=attempt,
                                 error=type(exc).__name__,
                                 message=str(exc)[:120])
                    _flight.postmortem("retry_exhausted", exc, conf)
                raise
            # per-query retry budget (spark.rapids.tpu.query.retryBudget,
            # docs/robustness.md "Query lifecycle"): the per-site attempt
            # bound above caps ONE dispatch's retries; the query-wide
            # budget caps the SUM, so a flapping query fails alone
            # instead of cycling retry/backoff across thousands of tasks
            # while healthy queries wait on the pool
            from .serving.query_context import consume_retry_budget
            if not consume_retry_budget():
                from .obs import flight as _flight
                from .obs import metrics as _metrics
                _metrics.counter_inc("query.retry_budget_exhausted")
                _flight.note("query.retry_budget_exhausted",
                             error=type(exc).__name__,
                             message=str(exc)[:120])
                raise
            attempt += 1
            from .obs import flight as _flight
            from .obs import metrics as _metrics
            from .obs import tracer as _obs
            from .profiling import TaskMetricsRegistry
            if _obs._ACTIVE:
                # the healing retry lands in the SAME span as the failure
                # (and as any chaos injection that caused it) — the query
                # timeline shows fault and recovery correlated in place
                _obs.event("device.retry", cat="retry", attempt=attempt,
                           error=type(exc).__name__, message=str(exc)[:120])
            _metrics.counter_inc("device.retries")
            _flight.note("device.retry", attempt=attempt,
                         error=type(exc).__name__, message=str(exc)[:120])
            reg = TaskMetricsRegistry.get()
            reg.add("deviceRetryCount", 1)
            delay = min(cap, base * (2 ** (attempt - 1))) / 1000.0
            delay *= 0.5 + 0.5 * random.random()
            t0 = time.perf_counter_ns()
            time.sleep(delay)
            reg.add("deviceRetryBlockTimeNs", time.perf_counter_ns() - t0)


def write_diagnostic_bundle(exc: BaseException, dump_dir: str,
                            extra: Optional[dict] = None) -> str:
    """GpuCoreDumpHandler analogue: capture device topology, memory
    accounting, task metrics and the failure into a JSON bundle."""
    os.makedirs(dump_dir, exist_ok=True)
    bundle = {
        "timestamp": time.time(),
        "error_type": type(exc).__name__,
        "error": str(exc),
        "traceback": traceback.format_exception(type(exc), exc,
                                                exc.__traceback__),
    }
    try:
        import jax
        bundle["devices"] = [
            {"id": d.id, "kind": getattr(d, "device_kind", "?"),
             "platform": d.platform} for d in jax.devices()]
    except Exception:  # noqa: BLE001 — a dead runtime must not stop the dump
        bundle["devices"] = "unavailable"
    try:
        from .memory.hbm import HbmBudget
        b = HbmBudget.get()
        bundle["hbm"] = {"budget": b.budget, "used": b.used}
    except Exception:  # noqa: BLE001
        pass
    try:
        from .profiling import TaskMetricsRegistry
        bundle["task_metrics"] = TaskMetricsRegistry.get().snapshot()
    except Exception:  # noqa: BLE001
        pass
    if extra:
        bundle["extra"] = extra
    path = os.path.join(dump_dir,
                        f"tpu-diagnostic-{int(time.time() * 1000)}.json")
    with open(path, "w") as f:
        json.dump(bundle, f, indent=2, default=str)
    return path


def handle_task_failure(exc: BaseException, conf,
                        exit_on_fatal: bool = True) -> Optional[str]:
    """Executor failure hook (reference RapidsExecutorPlugin.onTaskFailed).
    Returns the diagnostic path when a fatal error was captured."""
    from .config import CORE_DUMP_DIR
    # a GENUINE HBM budget exhaustion (marked at the raise site in
    # memory/hbm.py; chaos-injected retry-OOMs lack the marker) that
    # reached the task-failure hook was NOT healed by the retry framework
    # — this, not the raise site, is where the query actually dies, so
    # dump the hbm_oom postmortem here (no false incidents for healed OOMs)
    from .memory.hbm import TpuOOM
    cur: Optional[BaseException] = exc
    seen: set = set()
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, TpuOOM) and getattr(cur, "budget_exhausted",
                                               False):
            from .obs import flight as _flight
            _flight.note("hbm.oom_unhealed", error=str(cur)[:200])
            _flight.postmortem("hbm_oom", exc, conf)
            break
        cur = cur.__cause__ or cur.__context__
    if not is_fatal_device_error(exc):
        return None
    # crash flight recorder (docs/observability.md): the fatal error and
    # its postmortem bundle — last-K flight events, registry snapshot,
    # HBM/semaphore/spill state, active query names — land under
    # spark.rapids.tpu.obs.postmortemDir before any exit
    from .obs import flight as _flight
    from .obs import metrics as _metrics
    _metrics.counter_inc("device.fatal_errors")
    _flight.note("device.fatal", error=type(exc).__name__,
                 message=str(exc)[:200])
    _flight.postmortem("fatal_device_error", exc, conf)
    dump_dir = conf.get(CORE_DUMP_DIR)
    path = None
    if dump_dir:
        try:
            path = write_diagnostic_bundle(exc, str(dump_dir))
        except Exception:  # noqa: BLE001 — never mask the original failure
            pass
    # fault isolation (docs/robustness.md "Query lifecycle"): a fatal
    # error with CONCURRENT queries in flight is quarantined — the
    # postmortem above is already on disk, the failed query unwinds (its
    # scheduler slot and resources release on the raise), and the
    # survivors run to completion. Counted regardless of exit_on_fatal so
    # dashboards keyed on query.quarantined see the incident either way.
    if _metrics.active_query_count() > 1:
        _metrics.counter_inc("query.quarantined")
        _flight.note("query.quarantined",
                     active=_metrics.active_query_count(),
                     error=type(exc).__name__)
        return path
    if exit_on_fatal:
        # single-tenant: the reference exits the executor so Spark
        # reschedules elsewhere (logGpuDebugInfoAndExit); tests pass
        # exit_on_fatal=False
        os._exit(1)
    return path
