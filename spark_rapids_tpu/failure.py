"""Fatal device-error detection and diagnostic capture.

Reference (SURVEY.md §5 failure detection):
  * RapidsExecutorPlugin.onTaskFailed → containsCudaFatalException →
    logGpuDebugInfoAndExit (Plugin.scala:669-695,635): a fatal device error
    kills the executor so Spark reschedules its tasks elsewhere;
  * GpuCoreDumpHandler (GpuCoreDumpHandler.scala:38-190): capture a device
    core dump to distributed storage before exiting.

TPU analogue: XLA surfaces device failures as XlaRuntimeError (and jax
raises RuntimeError for device-side crashes). `handle_task_failure`
classifies the error; for fatal ones it writes a diagnostic bundle (device
topology, memory stats, task metrics, the error) under
`spark.rapids.tpu.coreDump.dir` and — when `exit_on_fatal` — terminates the
process so the cluster manager reschedules (tests use exit_on_fatal=False).
"""

from __future__ import annotations

import json
import os
import time
import traceback
from typing import Optional

_FATAL_MARKERS = (
    "DEADLINE_EXCEEDED", "INTERNAL", "device halted", "HBM OOM",
    "Device or resource busy", "failed to synchronize", "UNAVAILABLE",
    "hardware error", "data loss",
)


def is_fatal_device_error(exc: BaseException) -> bool:
    """Classify: does this error mean the device/runtime is unusable
    (reference containsCudaFatalException walking the cause chain)?"""
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        name = type(cur).__name__
        if name == "XlaRuntimeError":
            msg = str(cur)
            if any(m in msg for m in _FATAL_MARKERS):
                return True
        cur = cur.__cause__ or cur.__context__
    return False


def write_diagnostic_bundle(exc: BaseException, dump_dir: str,
                            extra: Optional[dict] = None) -> str:
    """GpuCoreDumpHandler analogue: capture device topology, memory
    accounting, task metrics and the failure into a JSON bundle."""
    os.makedirs(dump_dir, exist_ok=True)
    bundle = {
        "timestamp": time.time(),
        "error_type": type(exc).__name__,
        "error": str(exc),
        "traceback": traceback.format_exception(type(exc), exc,
                                                exc.__traceback__),
    }
    try:
        import jax
        bundle["devices"] = [
            {"id": d.id, "kind": getattr(d, "device_kind", "?"),
             "platform": d.platform} for d in jax.devices()]
    except Exception:  # noqa: BLE001 — a dead runtime must not stop the dump
        bundle["devices"] = "unavailable"
    try:
        from .memory.hbm import HbmBudget
        b = HbmBudget.get()
        bundle["hbm"] = {"budget": b.budget, "used": b.used}
    except Exception:  # noqa: BLE001
        pass
    try:
        from .profiling import TaskMetricsRegistry
        bundle["task_metrics"] = TaskMetricsRegistry.get().snapshot()
    except Exception:  # noqa: BLE001
        pass
    if extra:
        bundle["extra"] = extra
    path = os.path.join(dump_dir,
                        f"tpu-diagnostic-{int(time.time() * 1000)}.json")
    with open(path, "w") as f:
        json.dump(bundle, f, indent=2, default=str)
    return path


def handle_task_failure(exc: BaseException, conf,
                        exit_on_fatal: bool = True) -> Optional[str]:
    """Executor failure hook (reference RapidsExecutorPlugin.onTaskFailed).
    Returns the diagnostic path when a fatal error was captured."""
    from .config import CORE_DUMP_DIR
    if not is_fatal_device_error(exc):
        return None
    dump_dir = conf.get(CORE_DUMP_DIR)
    path = None
    if dump_dir:
        try:
            path = write_diagnostic_bundle(exc, str(dump_dir))
        except Exception:  # noqa: BLE001 — never mask the original failure
            pass
    if exit_on_fatal:
        # the reference exits the executor so Spark reschedules elsewhere
        # (logGpuDebugInfoAndExit); tests pass exit_on_fatal=False
        os._exit(1)
    return path
