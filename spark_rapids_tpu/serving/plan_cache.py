"""Scheduler-owned plan cache with literal parameter slots.

Production traffic is the same parameterized query shapes arriving over
and over (the reference re-reads session configs per query for the same
reason — GpuOverrides.scala:4565); planning (logical optimize → physical
plan → override/tagging pass) is pure host work that repeats verbatim.
This module caches the finished physical plan under a three-part key:

* **structure** — the normalized LOGICAL plan (node kinds, scalar
  properties, expression shapes with attribute expr_ids canonicalized by
  first-use order, so two independently-built but structurally identical
  plans collide), including the output schema (attribute names/dtypes
  ride in the node signatures) and the active mesh identity;
* **scan identity** — every FileScan's (path, size, mtime) triple, so a
  table swap (same path, new bytes) can never serve the plan chosen for
  the old file statistics;
* **conf** — the PLAN-RELEVANT session confs (every explicitly-set key
  except the observability/scheduler/cache knobs that cannot change a
  plan — the TL032 bug class: a key left out of the fingerprint is a key
  whose change silently reuses a stale artifact).

**Parameter slots**: literals inside Filter conditions and Project
expressions are hole-punched out of the fingerprint (only their dtype is
kept) and collected in walk order. A later submission with different
literal values produces the same key plus its own literal list; the hit
path re-binds the cached template's literal objects (paired positionally,
replaced by identity — ``Expression.transform`` preserves everything
else) into a fresh execution clone. Literals anywhere else (aggregate
expressions, join conditions, limits, sample seeds) stay part of the
fingerprint: their values can change plan shape or semantics that the
re-bind path does not re-derive. Pushed file-scan filters are safe to
re-bind because file/row-group pruning happens at EXECUTION time
(io/parquet.py ``_stats_may_match``/``rg_excluded``), and the clone path
recomputes the derived arrow filter after re-binding.

The cached template NEVER executes — every submission (hit or miss) runs
``template.clone_for_execution``, so cached entries hold no shuffle ids,
no broadcast device buffers, and no per-query metric state; an entry's
only footprint is host planning products (plus a reference to the logical
plan, which keeps identity-fingerprinted in-memory relations alive and
their ``id()`` stable).

Invalidation (each counts ``plan.cache_invalidated`` with a reason):
``invalidate_conf`` drops entries planned under a different value of a
plan-relevant conf (wired to ``session.conf.set/unset``);
``invalidate_relation`` drops entries scanning a cached relation when it
is unpersisted; inserting an entry drops same-structure/same-conf entries
whose scan identity went stale (the file set changed under the paths).
Hits/misses count ``plan.cache_hit``/``plan.cache_miss`` with a per-entry
label for attribution.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..expressions.base import AttributeReference, Expression, Literal
from ..obs import metrics as _metrics
from ..plan import logical as L
from ..types import DataType


class _Uncacheable(Exception):
    """Plan shape this fingerprint does not understand — not an error, the
    query simply plans fresh every time."""


_SCALARS = (str, int, float, bool, bytes, type(None))

#: conf prefixes that can NEVER change a physical plan: observability,
#: scheduling/admission, query-lifecycle budgets, and the plan cache's own
#: knobs. Everything else explicitly set participates in the fingerprint
#: (shuffle partitions, broadcast threshold, optimizer toggles, batch
#: sizes ... all shape plans).
_NONPLAN_PREFIXES = (
    "spark.rapids.tpu.trace.",
    "spark.rapids.tpu.obs.",
    "spark.rapids.tpu.sched.",
    "spark.rapids.tpu.query.",
    "spark.rapids.tpu.plan.cache.",
    "spark.rapids.profile.",
)


def plan_relevant_conf(conf) -> Dict[str, Any]:
    """The explicitly-set conf keys that participate in plan fingerprints
    (and whose changes invalidate cached entries)."""
    return {k: v for k, v in sorted(conf._settings.items())
            if not k.startswith(_NONPLAN_PREFIXES)}


def is_plan_relevant(key: str) -> bool:
    return not str(key).startswith(_NONPLAN_PREFIXES)


def _safe_repr(v) -> str:
    if isinstance(v, _SCALARS):
        return repr(v)
    if isinstance(v, DataType):
        return str(v)
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_safe_repr(x) for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ",".join(f"{_safe_repr(k)}:{_safe_repr(x)}"
                              for k, x in sorted(v.items(),
                                                 key=lambda kv: str(kv[0]))
                              ) + "}"
    raise _Uncacheable(f"unfingerprintable value {type(v).__name__}")


def _expr_sig(e: Expression, id_map: Dict[int, int], punch: bool,
              params: List[Literal]) -> str:
    """Normalized expression signature. ``punch=True`` hole-punches
    Literals into parameter slots (dtype kept, value collected)."""
    if isinstance(e, Literal):
        if punch:
            params.append(e)
            return f"?{e.dtype}"
        return f"lit:{e.dtype}:{_safe_repr(e.value)}"
    if isinstance(e, AttributeReference):
        cid = id_map.setdefault(e.expr_id, len(id_map))
        return f"a{cid}:{e.name}:{e.dtype}:{int(e.nullable)}"
    scalars = []
    for k in sorted(vars(e)):
        if k == "children" or k.startswith("_oj"):
            continue
        v = vars(e)[k]
        if isinstance(v, Expression):
            if not any(v is c for c in e.children):
                raise _Uncacheable(
                    f"{type(e).__name__} holds a non-child expression")
            continue
        scalars.append(f"{k}={_safe_repr(v)}")
    kids = ",".join(_expr_sig(c, id_map, punch, params) for c in e.children)
    return f"{type(e).__name__}({kids})[{';'.join(scalars)}]"


def _order_sig(o: L.SortOrder, id_map, params) -> str:
    return (f"{_expr_sig(o.child, id_map, False, params)}"
            f":{int(o.ascending)}:{int(o.nulls_first)}")


def _attrs_sig(attrs, id_map) -> str:
    parts = []
    for a in attrs:
        cid = id_map.setdefault(a.expr_id, len(id_map))
        parts.append(f"a{cid}:{a.name}:{a.dtype}:{int(a.nullable)}")
    return ",".join(parts)


def _scan_file_sig(paths) -> str:
    """Per-file identity: (path, size, mtime_ns). A rewritten file — same
    path, new bytes — changes this signature, so the old entry can never
    hit again (and is evicted when the fresh plan inserts)."""
    parts = []
    for p in paths:
        try:
            st = os.stat(p)
        except OSError as e:
            raise _Uncacheable(f"unstatable scan path {p}") from e
        parts.append(f"{p}:{st.st_size}:{st.st_mtime_ns}")
    return ";".join(parts)


def _node_sig(plan, id_map: Dict[int, int], params: List[Literal],
              rel_ids: List[int], tokens: List[str],
              scan_paths: List[str]) -> None:
    """Append one preorder token per node; raises _Uncacheable on node
    kinds the fingerprint does not model (windows, generators, ...)."""
    from ..io.cache import CachedRelation, DeviceCachedRelation
    t = type(plan)
    if isinstance(plan, (CachedRelation, DeviceCachedRelation)):
        # identity fingerprint: the entry keeps the logical plan (and so
        # this relation) alive, which both pins the id() and lets
        # unpersist() invalidate by the same id
        rel_ids.append(id(plan))
        tokens.append(f"{t.__name__}:{id(plan)}:"
                      f"{_attrs_sig(plan.output, id_map)}")
        return
    if isinstance(plan, L.LocalRelation):
        rel_ids.append(id(plan))
        tokens.append(f"local:{id(plan)}:{plan.num_partitions}:"
                      f"{_attrs_sig(plan.output, id_map)}")
        return
    if isinstance(plan, L.Range):
        tokens.append(f"range:{plan.start}:{plan.end}:{plan.step}:"
                      f"{plan.num_partitions}")
        return
    if isinstance(plan, L.FileScan):
        # the file SET is key material twice over: the path list rides in
        # the structure token, while each file's (size, mtime) identity
        # lands in the separate scan signature (computed by the caller
        # from scan_paths) — pushed-filter literals stay re-bindable
        # because file/row-group pruning happens at execution time
        scan_paths.extend(plan.paths)
        tokens.append(
            f"scan:{plan.fmt}:{_safe_repr(sorted(plan.paths))}:"
            f"{_safe_repr(plan.options)}:{plan.num_partitions}:"
            f"{_attrs_sig(plan._output, id_map)}")
        return
    if t is L.Project:
        sig = ",".join(_expr_sig(e, id_map, True, params)
                       for e in plan.exprs)
        tokens.append(f"project[{sig}]")
    elif t is L.Filter:
        tokens.append(
            f"filter[{_expr_sig(plan.condition, id_map, True, params)}]")
    elif t is L.Aggregate:
        g = ",".join(_expr_sig(e, id_map, False, params)
                     for e in plan.grouping)
        a = ",".join(_expr_sig(e, id_map, False, params)
                     for e in plan.aggregates)
        tokens.append(f"agg[{g}][{a}][{_attrs_sig(plan._output, id_map)}]")
    elif t is L.Join:
        lk = ",".join(_expr_sig(e, id_map, False, params)
                      for e in plan.left_keys)
        rk = ",".join(_expr_sig(e, id_map, False, params)
                      for e in plan.right_keys)
        c = (_expr_sig(plan.condition, id_map, False, params)
             if plan.condition is not None else "")
        tokens.append(f"join:{plan.join_type}[{lk}][{rk}][{c}]")
    elif t is L.Repartition:
        k = ",".join(_expr_sig(e, id_map, False, params) for e in plan.keys)
        tokens.append(
            f"repart:{plan.partitioning}:{plan.num_partitions}[{k}]")
    elif t is L.Sort:
        o = ",".join(_order_sig(o, id_map, params) for o in plan.order)
        tokens.append(f"sort:{int(plan.global_sort)}[{o}]")
    elif t is L.Limit:
        tokens.append(f"limit:{plan.n}:{plan.offset}")
    elif t is L.Sample:
        tokens.append(f"sample:{plan.fraction}:"
                      f"{int(plan.with_replacement)}:{plan.seed}")
    elif t is L.Union:
        tokens.append(f"union:{len(plan.children)}:"
                      f"{_attrs_sig(plan.output, id_map)}")
    else:
        raise _Uncacheable(f"node {t.__name__}")
    for c in plan.children:
        _node_sig(c, id_map, params, rel_ids, tokens, scan_paths)


class Fingerprint:
    """The three-part cache key plus everything the entry needs to pin and
    invalidate: ``key = (struct_sig, scan_sig, conf_sig)``."""

    __slots__ = ("struct_sig", "scan_sig", "conf_sig", "params", "rel_ids",
                 "pins")

    def __init__(self, struct_sig: str, scan_sig: str, conf_sig: str,
                 params: List[Literal], rel_ids: List[int],
                 pins: List[Any]) -> None:
        self.struct_sig = struct_sig
        self.scan_sig = scan_sig
        self.conf_sig = conf_sig
        self.params = params
        self.rel_ids = rel_ids
        self.pins = pins

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.struct_sig, self.scan_sig, self.conf_sig)


def fingerprint(plan, conf) -> Optional[Fingerprint]:
    """Normalize `plan` under `conf` — or None when the plan is
    uncacheable (the query plans fresh, every time)."""
    from ..parallel.mesh import mesh_session_active
    id_map: Dict[int, int] = {}
    params: List[Literal] = []
    rel_ids: List[int] = []
    tokens: List[str] = []
    scan_paths: List[str] = []
    try:
        _node_sig(plan, id_map, params, rel_ids, tokens, scan_paths)
        scan_sig = _scan_file_sig(scan_paths)
    except (_Uncacheable, AttributeError):
        return None
    # the active mesh shapes the physical plan (collective exchanges,
    # partition alignment). It is itself conf-derived, but test-time mesh
    # resets mint new Mesh objects — fingerprint by identity and pin the
    # object so a recycled id can never alias a dead mesh.
    pins: List[Any] = [plan]
    mesh = mesh_session_active(conf)
    if mesh is not None:
        pins.append(mesh)
        tokens.append(f"mesh:{id(mesh)}:{len(mesh.devices)}")
    conf_items = plan_relevant_conf(conf)
    try:
        conf_sig = ",".join(f"{k}={_safe_repr(str(v))}"
                            for k, v in conf_items.items())
    except _Uncacheable:
        return None
    struct = "|".join(tokens)
    return Fingerprint(
        hashlib.sha256(struct.encode()).hexdigest(),
        hashlib.sha256(scan_sig.encode()).hexdigest() if scan_sig else "",
        hashlib.sha256(conf_sig.encode()).hexdigest(),
        params, rel_ids, pins)


class PlanCacheEntry:
    __slots__ = ("key", "label", "template", "params", "rel_ids",
                 "conf_items", "rules", "pins", "hits")

    def __init__(self, fp: Fingerprint, template,
                 conf_items: Dict[str, Any], rules: List[str]) -> None:
        self.key = fp.key
        self.label = hashlib.sha1(
            "/".join(fp.key).encode()).hexdigest()[:10]
        self.template = template
        self.params = fp.params
        self.rel_ids = fp.rel_ids
        self.conf_items = conf_items
        self.rules = rules
        # pins the logical plan (identity-fingerprinted relations stay
        # alive, their id() stable) and the active mesh object
        self.pins = fp.pins
        self.hits = 0


class PlanCache:
    """Bounded LRU of physical-plan templates, owned by the process-wide
    QueryScheduler so every session frontend shares one cache. All state
    under its own lock (never the scheduler's _mu — planning happens on
    submitter threads while admission keeps running)."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str, str], PlanCacheEntry]" \
            = OrderedDict()
        self.capacity = int(capacity)
        self.hits = 0
        self.misses = 0
        self.invalidations = 0

    def configure(self, capacity: int) -> None:
        with self._lock:
            self.capacity = max(0, int(capacity))
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def lookup(self, key) -> Optional[PlanCacheEntry]:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            e.hits += 1
        _metrics.counter_inc("plan.cache_hit", entry=e.label)
        return e

    def peek(self, key) -> bool:
        """True when `key` is cached; no LRU/counter side effects (explain)."""
        with self._lock:
            return key in self._entries

    def insert(self, entry: PlanCacheEntry) -> None:
        """Insert, evicting same-structure/same-conf entries whose scan
        identity went stale — the file set changed under the paths, so
        those templates can never legitimately hit again."""
        struct, scan, conf = entry.key
        with self._lock:
            doomed = [k for k in self._entries
                      if k[0] == struct and k[2] == conf and k[1] != scan]
            labels = [self._entries.pop(k).label for k in doomed]
            self.invalidations += len(doomed)
            inserted = self.capacity > 0
            if inserted:
                self._entries[entry.key] = entry
                self._entries.move_to_end(entry.key)
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
        for lb in labels:
            _metrics.counter_inc("plan.cache_invalidated", entry=lb,
                                 reason="fileset")

    def count_miss(self, label: str = "") -> None:
        _metrics.counter_inc("plan.cache_miss",
                             **({"entry": label} if label else {}))

    def _evict_where(self, pred, reason: str) -> int:
        with self._lock:
            doomed = [k for k, e in self._entries.items() if pred(e)]
            labels = [self._entries.pop(k).label for k in doomed]
            self.invalidations += len(doomed)
        for lb in labels:
            _metrics.counter_inc("plan.cache_invalidated", entry=lb,
                                 reason=reason)
        return len(labels)

    def invalidate_conf(self, key: str, value) -> int:
        """A plan-relevant conf changed: drop every entry planned under a
        DIFFERENT value of that key (entries that never saw the key set
        were planned under its default — also stale)."""
        if not is_plan_relevant(key):
            return 0
        sval = None if value is None else str(value)
        return self._evict_where(
            lambda e: (None if key not in e.conf_items
                       else str(e.conf_items[key])) != sval,
            reason="conf")

    def invalidate_relation(self, rel_id: int) -> int:
        return self._evict_where(lambda e: rel_id in e.rel_ids,
                                 reason="relation")

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "per_entry_hits": {e.label: e.hits
                                   for e in self._entries.values()},
            }


def build_or_fetch(session, sched, plan, conf):
    """The scheduler's planning step: fingerprint → hit (re-bind literals
    into a fresh clone) or miss (optimize → plan → override → cache the
    never-executed template, run a clone). Returns
    (executable physical plan, "hit"|"miss"|"off"|"uncacheable",
    applied optimizer rule names)."""
    from ..config import PLAN_CACHE_ENABLED
    from ..plan.optimizer import optimize_logical
    from ..plan.overrides import TpuOverrides
    from ..plan.planner import plan_physical

    cache: Optional[PlanCache] = getattr(sched, "plan_cache", None)
    if not conf.get(PLAN_CACHE_ENABLED) or cache is None:
        optimized, rules = optimize_logical(plan, conf)
        final = TpuOverrides.apply(plan_physical(optimized, conf), conf)
        return final, "off", rules

    fp = fingerprint(plan, conf)
    if fp is None:
        optimized, rules = optimize_logical(plan, conf)
        final = TpuOverrides.apply(plan_physical(optimized, conf), conf)
        cache.count_miss()
        return final, "uncacheable", rules

    entry = cache.lookup(fp.key)
    if entry is not None:
        # parameter-slot re-bind: pair this submission's literals with the
        # template's by walk position (same key ⇒ same walk ⇒ same arity)
        rebind = {id(t): n for t, n in zip(entry.params, fp.params)
                  if t is not n and (t.value != n.value
                                     or t.dtype != n.dtype)}
        return (entry.template.clone_for_execution(rebind or None),
                "hit", entry.rules)

    optimized, rules = optimize_logical(plan, conf)
    final = TpuOverrides.apply(plan_physical(optimized, conf), conf)
    entry = PlanCacheEntry(fp, final, plan_relevant_conf(conf), rules)
    cache.insert(entry)
    cache.count_miss(entry.label)
    # the template never executes: run a clone even on the cold path so
    # no shuffle id / broadcast buffer / metric ever lands on the cached
    # object
    return final.clone_for_execution(), "miss", rules
