"""The query scheduler/executor service: ONE device owner, many frontends.

Reference: GpuSemaphore gates how many tasks may hold the device
(GpuSemaphore.scala, SURVEY §2.4) and the plugin's failure hooks isolate a
fatal task (SURVEY §3.1); SURVEY §7 prescribes the "columnar compute
service" shape — many session frontends submitting to one device-owning
scheduler. This module is that service for the TPU engine:

* :class:`QueryScheduler` — process-wide admission control. A submitted
  query enters a bounded FIFO queue (per session, drained round-robin so
  one chatty session cannot starve its neighbors); past the bound the
  submission fails FAST with the typed :class:`QueryQueueFull`
  backpressure error instead of piling more working sets onto an
  already-saturated device (the OOM-everyone failure mode). A queued query
  is admitted only when a concurrency slot is free
  (``spark.rapids.tpu.sched.maxConcurrentQueries``) AND HBM usage is under
  the admission watermark (``spark.rapids.tpu.sched.hbmAdmissionWatermark``
  × budget — waived when nothing is running, so admission always makes
  progress). Execution is caller-runs: the submitting thread executes its
  own query once admitted, so tracer/ledger/lifecycle thread bindings all
  stay on the thread that owns them.
* :func:`execute_plan` — the executor half of the old ``TpuSession._execute``
  (session.py keeps session STATE; the per-partition driving loop,
  failure handling and per-query snapshotting live here). Every query gets
  a :class:`~.query_context.QueryContext` (cancel token + deadline + retry
  budget) bound around its whole execution window.

Lock discipline (TL021/TL022): ``QueryScheduler._mu`` is declared in
``analysis/locks.py``'s ``LOCK_ORDER`` one level above the metrics-registry
structure lock — the queue-depth gauge commits under it (the ``_QL_LOCK``
idiom: an interleaved enqueue/dequeue pair must not publish a stale count)
— and nothing blocking ever runs under it: grant waits happen on per-ticket
events outside the lock, chaos/flight emission happens after release.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from ..execs.base import TaskContext
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from .query_context import (QueryCancelledError, QueryContext,
                            QueryDeadlineExceeded, QueryQueueFull, bind,
                            checkpoint)

#: sessions alive in this process (weak: an abandoned, never-stopped
#: session must not pin itself here forever). TpuSession registers at
#: construction and discards itself in stop(); the LAST session to stop
#: releases the process-wide shuffle manager.
_LIVE_SESSIONS: "weakref.WeakSet" = weakref.WeakSet()


def register_session(session) -> None:
    # a new frontend re-owns the shared state: any pending release from
    # a previous last-session stop() is obsolete (this session's stop()
    # will re-request it)
    global _SHARED_RELEASE_PENDING
    _SHARED_RELEASE_PENDING = False
    _LIVE_SESSIONS.add(session)


def release_session(session) -> None:
    _LIVE_SESSIONS.discard(session)


def other_live_sessions(session) -> bool:
    """Any session frontend OTHER than `session` still alive? Gates the
    shared-resource teardown in TpuSession.stop()."""
    return any(s is not session for s in _LIVE_SESSIONS)


#: set when the last session stopped but shared state could not be
#: released yet (a straggler query outlived stop()'s drain timeout);
#: re-checked when queries end, so the release happens when the
#: straggler finally finishes instead of never
_SHARED_RELEASE_PENDING = False


def request_shared_release() -> bool:
    """Mark the process-wide shuffle manager for release (called by the
    LAST session's stop()) and attempt it now. Returns True if released."""
    global _SHARED_RELEASE_PENDING
    _SHARED_RELEASE_PENDING = True
    return maybe_release_shared()


def maybe_release_shared() -> bool:
    """Release the shuffle manager iff a release is pending AND no live
    session or active query remains. Cheap no-op otherwise (one module
    bool read) — execute_plan calls this after every query so a query
    that outlived its session's stop() drain still triggers the
    teardown when it ends."""
    global _SHARED_RELEASE_PENDING
    if not _SHARED_RELEASE_PENDING:
        return False
    if len(_LIVE_SESSIONS) or _metrics.active_query_count():
        return False
    from ..shuffle.manager import TpuShuffleManager
    with TpuShuffleManager._lock:
        mgr = TpuShuffleManager._instance
        TpuShuffleManager._instance = None
    _SHARED_RELEASE_PENDING = False
    if mgr is not None:
        mgr.shutdown()
    return True


class _Ticket:
    __slots__ = ("qctx", "granted", "enq_ns")

    def __init__(self, qctx: QueryContext):
        self.qctx = qctx
        self.granted = threading.Event()
        self.enq_ns = time.perf_counter_ns()


class QueryScheduler:
    """Process-wide admission-controlled query scheduler (module doc)."""

    _instance: Optional["QueryScheduler"] = None
    _cls_lock = threading.Lock()

    def __init__(self, max_queue: int = 64, max_concurrent: int = 8,
                 hbm_watermark: float = 0.9):
        self.max_queue = int(max_queue)
        self.max_concurrent = int(max_concurrent)
        self.hbm_watermark = float(hbm_watermark)
        self._mu = threading.Lock()
        # session id -> FIFO of queued tickets; _rr holds ids of sessions
        # with a non-empty queue, rotated one grant at a time
        self._queues: Dict[str, deque] = {}
        self._rr: deque = deque()
        self._queued = 0
        self._running: Dict[int, QueryContext] = {}  # id(ticket) -> qctx
        # every live QueryContext (queued or running) by session, for
        # session.cancel()/stop() and the postmortem listing
        self._by_session: Dict[str, List[QueryContext]] = {}
        self._tls = threading.local()

    # --- lifecycle ----------------------------------------------------------
    @classmethod
    def get(cls, conf=None) -> "QueryScheduler":
        with cls._cls_lock:
            if cls._instance is None:
                cls._instance = QueryScheduler()
            inst = cls._instance
        if conf is not None:
            inst._maybe_configure(conf)
        return inst

    @classmethod
    def reset_for_tests(cls) -> "QueryScheduler":
        global _SHARED_RELEASE_PENDING
        _SHARED_RELEASE_PENDING = False
        with cls._cls_lock:
            cls._instance = QueryScheduler()
            return cls._instance

    def _maybe_configure(self, conf) -> None:
        """Only EXPLICITLY SET sched keys overwrite the process state (the
        flight/mesh_profile maybe_configure pattern: a default-conf session
        must not silently resize another session's scheduler)."""
        from ..config import (SCHED_HBM_WATERMARK, SCHED_MAX_CONCURRENT,
                              SCHED_MAX_QUEUE)
        with self._mu:
            if conf.get_raw(SCHED_MAX_QUEUE.key) is not None:
                self.max_queue = int(conf.get(SCHED_MAX_QUEUE))
            if conf.get_raw(SCHED_MAX_CONCURRENT.key) is not None:
                self.max_concurrent = max(
                    1, int(conf.get(SCHED_MAX_CONCURRENT)))
            if conf.get_raw(SCHED_HBM_WATERMARK.key) is not None:
                self.hbm_watermark = float(conf.get(SCHED_HBM_WATERMARK))

    def shutdown(self) -> None:
        """Cancel everything queued or running (the owner-class release for
        the QueryContexts parked on self)."""
        with self._mu:
            pending = [q for qs in self._by_session.values() for q in qs]
        for q in pending:
            q.cancel(reason="scheduler.shutdown")

    # --- admission core (self._mu held) ------------------------------------
    def _hbm_headroom_ok(self) -> bool:
        from ..memory.hbm import HbmBudget
        b = HbmBudget._instance  # no side-effect instantiation
        if b is None or b.budget <= 0:
            return True
        return b.used <= self.hbm_watermark * b.budget

    def _admit_locked(self) -> None:
        """Grant as many queued tickets as the watermarks allow, rotating
        round-robin across sessions. Grants are Event.set — the waiting
        submitter thread runs its own query."""
        while self._rr and len(self._running) < self.max_concurrent:
            # HBM admission watermark, waived when the device is idle so
            # admission can always make progress (a budget left high by
            # parked state must not wedge the queue)
            if self._running and not self._hbm_headroom_ok():
                break
            sid = self._rr[0]
            q = self._queues.get(sid)
            if not q:
                self._rr.popleft()
                continue
            ticket = q.popleft()
            if q:
                self._rr.rotate(-1)
            else:
                self._rr.popleft()
                del self._queues[sid]
            self._queued -= 1
            self._running[id(ticket)] = ticket.qctx
            ticket.granted.set()
        # committed under the lock (the _QL_LOCK idiom): an interleaved
        # enqueue/release pair must never publish a stale depth
        _metrics.gauge_set("sched.queue_depth", self._queued)

    def _release(self, ticket: _Ticket) -> None:
        """Return `ticket`'s slot (running) or queue entry (never admitted)
        and admit successors. Idempotent."""
        with self._mu:
            if self._running.pop(id(ticket), None) is None:
                sid = ticket.qctx.session_id
                q = self._queues.get(sid)
                if q is not None:
                    try:
                        q.remove(ticket)
                        self._queued -= 1
                    except ValueError:
                        pass
                    if not q:
                        del self._queues[sid]
                        try:
                            self._rr.remove(sid)
                        except ValueError:
                            pass
            self._admit_locked()

    def _deregister(self, qctx: QueryContext) -> None:
        """QueryContext.close() hook: drop it from the session index."""
        with self._mu:
            lst = self._by_session.get(qctx.session_id)
            if lst is None:
                return
            lst[:] = [q for q in lst if q is not qctx]
            if not lst:
                del self._by_session[qctx.session_id]

    # --- the submission path ------------------------------------------------
    def submit_and_run(self, qctx: QueryContext, fn):
        """Enqueue `qctx`, wait for admission, then run `fn` on the calling
        thread with the context bound. Raises QueryQueueFull past the queue
        bound; a cancel/deadline while QUEUED raises without running
        anything. Nested execution (a query submitting a query on the same
        thread) bypasses admission — the caller-runs model would deadlock
        a thread against its own held slot."""
        if getattr(self._tls, "admitted", False):
            # nested execution rides the OUTER query's admission slot AND
            # its cancel token: the outer (registered) context stays
            # bound, so session.cancel()/stop()/deadlines interrupt the
            # nested work too — re-binding the nested context would hand
            # checkpoints a token nothing can ever arm (the nested
            # context is registered nowhere; it is part of the outer
            # query's work)
            qctx.mark_running()
            return fn()
        ticket = _Ticket(qctx)
        with self._mu:
            if self._queued >= self.max_queue:
                _metrics.counter_inc("query.rejected_queue_full")
                rejected = True
            else:
                rejected = False
                self._queues.setdefault(qctx.session_id,
                                        deque()).append(ticket)
                if qctx.session_id not in self._rr:
                    self._rr.append(qctx.session_id)
                self._queued += 1
                self._by_session.setdefault(qctx.session_id,
                                            []).append(qctx)
                self._admit_locked()
        if rejected:
            _flight.note("query.rejected", query=qctx.name,
                         session=qctx.session_id, reason="queue_full")
            raise QueryQueueFull(
                f"query {qctx.name} rejected: admission queue full "
                f"(spark.rapids.tpu.sched.maxQueuedQueries="
                f"{self.max_queue})")
        _flight.note("query.queued", query=qctx.name,
                     session=qctx.session_id)
        try:
            # grant wait OFF the lock; short poll so a cancel or deadline
            # arriving while queued is observed promptly, and admission is
            # re-evaluated each tick (HBM headroom can open mid-query,
            # with no completion event to trigger a grant)
            while not ticket.granted.wait(timeout=0.05):
                qctx.check("sched.queue")
                with self._mu:
                    self._admit_locked()
            # chaos `sched.admit` fires BEFORE the admission is recorded:
            # latency extends the measured queue delay (it lands in the
            # sched.admit_wait_ms histogram), io_error fails the query
            # still QUEUED — no query.admitted flight event, no query
            # work started, no resource acquired
            from ..chaos import inject
            inject("sched.admit", detail=qctx.name)
            wait_ms = (time.perf_counter_ns() - ticket.enq_ns) / 1e6
            _metrics.histogram_observe("sched.admit_wait_ms", wait_ms)
            _flight.note("query.admitted", query=qctx.name,
                         session=qctx.session_id,
                         wait_ms=round(wait_ms, 3))
            self._tls.admitted = True
            try:
                with bind(qctx):
                    qctx.mark_running()
                    return fn()
            finally:
                self._tls.admitted = False
        except QueryDeadlineExceeded:
            _metrics.counter_inc("query.deadline_exceeded")
            _flight.note("query.deadline_exceeded", query=qctx.name,
                         session=qctx.session_id)
            raise
        except QueryCancelledError:
            _metrics.counter_inc("query.cancelled")
            _flight.note("query.cancelled", query=qctx.name,
                         session=qctx.session_id,
                         reason=qctx.cancel_reason)
            raise
        finally:
            self._release(ticket)

    # --- session-level control ---------------------------------------------
    def cancel_session(self, session_id: str,
                       reason: str = "session.cancel") -> int:
        """Arm the cancel token of every queued/running query of one
        session frontend; returns how many were flagged."""
        with self._mu:
            targets = list(self._by_session.get(session_id, ()))
        for q in targets:
            q.cancel(reason=reason)
        return len(targets)

    def drain_session(self, session_id: str, timeout_s: float = 30.0
                      ) -> bool:
        """Wait (bounded) until a session has no queued or running query —
        the stop() barrier after cancel_session."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            with self._mu:
                if not self._by_session.get(session_id):
                    return True
            time.sleep(0.01)
        with self._mu:
            return not self._by_session.get(session_id)

    # --- observability ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Queued/running query names + states for the postmortem bundle
        and metrics_snapshot — a crash dump must NAME the queries that
        were queued, running or cancelling when the process died."""
        with self._mu:
            running = [{"query": q.name, "session": q.session_id,
                        "state": q.state}
                       for q in self._running.values()]
            queued = [{"query": t.qctx.name, "session": sid,
                       "state": t.qctx.state}
                      for sid, dq in self._queues.items() for t in dq]
            return {"max_concurrent": self.max_concurrent,
                    "max_queue": self.max_queue,
                    "hbm_watermark": self.hbm_watermark,
                    "queue_depth": self._queued,
                    "running": running, "queued": queued}


# ---------------------------------------------------------------------------
# the executor service: the per-partition driving loop moved out of
# TpuSession._execute (session.py keeps the front door + session state)
# ---------------------------------------------------------------------------


def execute_plan(session, plan, timeout: Optional[float] = None):
    """Plan, admit, and execute one query for `session`, returning the
    pyarrow result table. `timeout` (seconds) overrides the session's
    spark.rapids.tpu.query.timeoutMs deadline for this call."""
    import pyarrow as pa

    from ..config import QUERY_RETRY_BUDGET, QUERY_TIMEOUT_MS, TRACE_TAG
    from ..plan.overrides import TpuOverrides
    from ..plan.planner import plan_physical
    from ..types import to_arrow as t2a
    conf = session._rapids_conf()
    cpu_plan = plan_physical(plan, conf)
    final = TpuOverrides.apply(cpu_plan, conf)
    schema = pa.schema([(a.name, t2a(a.dtype)) for a in final.output])
    session._query_seq = getattr(session, "_query_seq", 0) + 1
    tag = conf.get(TRACE_TAG)
    stem = tag if tag and str(tag) != "None" else "query"
    if stem == "query":
        # untagged sessions fold the session id into the query name:
        # concurrent sessions each minting "query-1" would collide in
        # every name-keyed filter (the STRICT mesh-profile query filter
        # would bleed one tenant's exchanges into another's bundle).
        # Tagged names stay `<tag>-<n>` — the bench artifact contract.
        sid_n = session._session_id.rsplit("-", 1)[-1]
        qname = f"query-s{sid_n}-{session._query_seq}"
    else:
        qname = f"{stem}-{session._query_seq}"
    timeout_ms = float(timeout) * 1000.0 if timeout is not None \
        else float(conf.get(QUERY_TIMEOUT_MS))
    deadline_ns = (time.perf_counter_ns() + int(timeout_ms * 1e6)
                   if timeout_ms and timeout_ms > 0 else None)
    sched = QueryScheduler.get(conf)
    try:
        with QueryContext(qname, session_id=session._session_id,
                          deadline_ns=deadline_ns,
                          retry_budget=conf.get(QUERY_RETRY_BUDGET)
                          ) as qctx:
            tables = sched.submit_and_run(
                qctx, lambda: _run_admitted(session, final, conf, qctx,
                                            stem, qname))
    finally:
        # a query that outlived its session's stop() drain releases the
        # shared state the stop could not (no-op unless pending)
        maybe_release_shared()
    if not tables:
        return schema.empty_table()
    return pa.concat_tables(tables).cast(schema)


def _run_admitted(session, final, conf, qctx: QueryContext, stem: str,
                  qname: str) -> List:
    """One admitted query's execution window: partition loop(s), failure
    handling, and the per-query observability snapshotting. Runs on the
    submitting thread with the QueryContext bound."""
    from .. import obs
    from ..config import (TRACE_BUFFER_EVENTS, TRACE_CATEGORIES,
                          TRACE_ENABLED)
    from ..parallel.mesh import mesh_session_active
    from ..profiling import (SyncLedger, TaskMetricsRegistry,
                             snapshot_plan_metrics)
    task_metrics_before = TaskMetricsRegistry.get().snapshot()
    syncs_before = SyncLedger.get().snapshot()
    # mesh session (docs/distributed.md): the root pull drives ALL
    # partitions through the multi-partition entry point in one group,
    # so the top whole-stage segment (between the last exchange and the
    # result) executes every chip's partition in a single grouped
    # launch — the same batched dispatch the exchange map side uses
    n_parts = final.num_partitions()
    names = [a.name for a in final.output]
    group_pull = n_parts > 1 and mesh_session_active(conf) is not None
    # always-on metrics registry (docs/observability.md): EVERY query
    # (traced or not) registers its lifecycle — the queries.active
    # gauge/list, the latency + rows/s histograms, and the epoch the
    # tracer's exclusivity check reads
    qtok = obs.metrics.query_begin(qname, session=stem)
    qroot = None
    opjit_before = None
    tables: List = []
    # window for this query's collective-exchange profiles (mesh
    # efficiency profiler): profiles are tagged with the traced query
    # name when one is bound; the seq window covers untraced queries
    mesh_seq0 = obs.mesh_profile.current_seq()
    failed = True  # cleared by the last statement of the try body
    try:
        if conf.get(TRACE_ENABLED):
            from ..config import TRACE_MAX_CONCURRENT
            from ..execs import opjit
            # arm FIRST inside the try whose finally guarantees
            # end_query (TL020: an exception can never strand a tracer
            # armed) and query_end. The snapshot BEFORE arming (nothing
            # dispatches in between) is only trusted when the query ran
            # EXCLUSIVELY — a concurrent query's bundle reconciles
            # against the tracer's own per-query counters instead (no
            # cross-query bleed).
            opjit_before = opjit.cache_stats()["calls_by_kind"]
            qroot = obs.begin_query(
                qname,
                buffer_events=conf.get(TRACE_BUFFER_EVENTS),
                categories=conf.get(TRACE_CATEGORIES),
                max_concurrent=conf.get(TRACE_MAX_CONCURRENT))
        if group_pull:
            ids = list(range(n_parts))
            ctxs: Dict[int, TaskContext] = {}

            def ctx_of(i):
                c = ctxs.get(i)
                if c is None:
                    c = ctxs[i] = TaskContext(i, conf)
                return c

            try:
                checkpoint(f"task.group 0-{ids[-1]}")
                with obs.span(f"partition group 0-{ids[-1]}", cat="task",
                              partitions=n_parts):
                    for _p, t in final.execute_partitions(ids, ctx_of):
                        if t.num_rows:
                            tables.append(t.rename_columns(names))
            except BaseException as exc:
                from ..config import FATAL_ERROR_EXIT
                from ..failure import handle_task_failure
                handle_task_failure(
                    exc, conf,
                    exit_on_fatal=conf.get(FATAL_ERROR_EXIT))
                raise
            finally:
                for c in ctxs.values():
                    c.complete()
        else:
            for p in range(n_parts):
                # cooperative cancellation at partition-task start: a
                # cancelled/timed-out query stops scheduling new tasks
                # before any of this partition's resources are acquired
                checkpoint(f"task.start p{p}")
                ctx = TaskContext(p, conf)
                try:
                    with obs.span(f"partition {p}", cat="task",
                                  partition=p):
                        for t in final.execute_partition(p, ctx):
                            if t.num_rows:
                                tables.append(t.rename_columns(names))
                except BaseException as exc:
                    # fatal device errors capture diagnostics and
                    # (outside tests) exit so the cluster manager
                    # reschedules (RapidsExecutorPlugin.onTaskFailed)
                    from ..config import FATAL_ERROR_EXIT
                    from ..failure import handle_task_failure
                    handle_task_failure(
                        exc, conf,
                        exit_on_fatal=conf.get(FATAL_ERROR_EXIT))
                    raise
                finally:
                    ctx.complete()
        failed = False  # reached only when every partition completed
    finally:
        # snapshot metrics into plain dicts so the plan (and any device
        # buffers it references) is not pinned past the query
        session._last_metrics_snapshot = snapshot_plan_metrics(final)
        session._last_plan_tree = _plan_tree_snapshot(final)
        after = TaskMetricsRegistry.get().snapshot()
        session._last_task_metrics = {
            k: after.get(k, 0) - task_metrics_before.get(k, 0)
            for k in after}
        # per-operator blocking-sync deltas for this query alone (the
        # sync ledger is process-wide; docs/configs.md "Dispatch & sync
        # accounting")
        syncs_after = SyncLedger.get().snapshot()
        ledger = {}
        for op, kinds in syncs_after.items():
            prev = syncs_before.get(op, {})
            d = {k: v - prev.get(k, 0) for k, v in kinds.items()
                 if v - prev.get(k, 0)}
            if d:
                ledger[op] = d
        session._last_sync_ledger = ledger
        # this query's per-exchange mesh profiles + per-map fallback
        # reasons (empty outside mesh sessions): the bundle's `mesh`
        # section and the sharded runner both read these
        session._last_mesh_profiles = obs.mesh_profile.profiles_since(
            mesh_seq0, query=qname)
        session._last_mesh_fallbacks = obs.mesh_profile.fallbacks_since(
            mesh_seq0, query=qname)
        # honesty: records evicted from the bounded profiler rings
        # inside this query's window (exchange-heavy / concurrent
        # load) are COUNTED, not silently missing from the bundle
        session._last_mesh_dropped = obs.mesh_profile.window_dropped(
            mesh_seq0)
        if qroot is not None:
            _finish_query_profile(session, qroot, conf, opjit_before)
        else:
            # honor the last_query_profile contract: an untraced query
            # (tracing off, or the process-wide tracer owned by another
            # query) must not leave a previous query's bundle behind
            session._last_query_profile = None
        # release shuffle blocks/files at query end (reference: Spark's
        # ContextCleaner removing shuffle state); exchanges re-materialize
        # if the same DataFrame is collected again
        for node in final.collect_nodes():
            if hasattr(node, "cleanup_shuffle"):
                node.cleanup_shuffle(conf)
        obs.metrics.query_end(
            qtok, rows=sum(t.num_rows for t in tables),
            failed=failed, session=stem)
    return tables


def _finish_query_profile(session, qroot, conf, opjit_before) -> None:
    """Close the tracer, build the diagnostics bundle (metric snapshot +
    sync-ledger delta + dispatch-by-kind delta + the span/event record),
    and write the Chrome trace + bundle artifacts when
    spark.rapids.tpu.trace.dir is set. IMPORTANT: all inputs are the
    deltas this query caused — the bundle's reconciliation asserts the
    tracer saw every dispatch (calls_by_kind) and every blocking sync
    (SyncLedger) the pre-existing counters saw."""
    from .. import obs
    from ..config import TRACE_DIR
    from ..execs import opjit
    profile = obs.end_query(qroot)
    if profile.get("exclusive", True):
        # no other query overlapped: the process-wide counter deltas
        # are attributable to this query — the strongest ground truth
        # (incremented by code paths independent of the tracer)
        disp_after = opjit.cache_stats()["calls_by_kind"]
        disp_delta = {
            k: disp_after.get(k, 0) - (opjit_before or {}).get(k, 0)
            for k in set(disp_after) | set(opjit_before or {})}
    else:
        # concurrent queries: process-wide deltas cross-bleed, so the
        # bundle reconciles against THIS query's own counters — kept
        # by the tracer at exactly the sites where calls_by_kind and
        # the SyncLedger increment, routed by the thread binding
        disp_delta = {k: v for k, v in
                      profile.get("dispatch_counts", {}).items() if v}
        session._last_sync_ledger = {
            op: dict(kinds)
            for op, kinds in profile.get("sync_counts", {}).items()}
    bundle = obs.build_bundle(
        profile,
        plan_tree=session._last_plan_tree,
        metrics=session._last_metrics_snapshot,
        sync_ledger=session._last_sync_ledger,
        dispatch_delta=disp_delta,
        task_metrics=session._last_task_metrics,
        mesh_profiles=getattr(session, "_last_mesh_profiles", None),
        mesh_fallbacks=getattr(session, "_last_mesh_fallbacks", None),
        mesh_dropped=getattr(session, "_last_mesh_dropped", 0))
    out_dir = conf.get(TRACE_DIR)
    if out_dir and str(out_dir) != "None":
        try:
            obs.write_artifacts(bundle, profile, str(out_dir),
                                profile.get("name", "query"))
        except OSError:
            bundle["artifacts"] = {"error": "trace.dir not writable"}
    session._last_query_profile = bundle


def _plan_tree_snapshot(plan) -> List[dict]:
    """Plain-data snapshot of the executed physical plan for
    explain("metrics") and the diagnostics bundle — preorder, so index i
    matches snapshot_plan_metrics's "i:NodeName" keys, and no node (or
    device buffer it pins) survives past the query."""
    out: List[dict] = []

    def walk(node, depth: int) -> None:
        out.append({"i": len(out), "depth": depth,
                    "name": node.node_name(), "desc": node.node_desc(),
                    "tpu": node.is_tpu})
        for c in node.children:
            walk(c, depth + 1)

    walk(plan, 0)
    return out
