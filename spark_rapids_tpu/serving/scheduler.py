"""The query scheduler/executor service: ONE device owner, many frontends.

Reference: GpuSemaphore gates how many tasks may hold the device
(GpuSemaphore.scala, SURVEY §2.4) and the plugin's failure hooks isolate a
fatal task (SURVEY §3.1); SURVEY §7 prescribes the "columnar compute
service" shape — many session frontends submitting to one device-owning
scheduler. This module is that service for the TPU engine:

* :class:`QueryScheduler` — process-wide admission control. A submitted
  query enters a bounded FIFO queue (per session, within its SLO class);
  past the bound the submission fails FAST with the typed
  :class:`QueryQueueFull` backpressure error instead of piling more
  working sets onto an already-saturated device (the OOM-everyone failure
  mode) — unless a strictly lower class is queued, in which case the
  LOWEST class is shed to make room (docs/serving.md). A queued query is
  admitted only when a concurrency slot is free
  (``spark.rapids.tpu.sched.maxConcurrentQueries``) AND HBM usage is under
  the admission watermark (``spark.rapids.tpu.sched.hbmAdmissionWatermark``
  × budget — waived when nothing is running, so admission always makes
  progress) AND the submitting tenant is under its per-tenant HBM quota
  (``spark.rapids.tpu.sched.tenantHbmQuota`` × budget: an over-quota
  tenant queues even when the device has headroom). Admission order is
  SLO-aware: strict class precedence (``interactive`` > ``batch`` >
  ``background``), earliest-deadline-first within a class across session
  queue heads, round-robin across a class's sessions on deadline ties,
  and an anti-starvation aging bound
  (``spark.rapids.tpu.sched.classAgingMs``) that promotes any ticket
  queued past the bound so ``background`` still drains under pressure.
  Sustained overload (a higher-class ticket waiting past
  ``spark.rapids.tpu.sched.shedAfterMs`` with every slot held and a
  lower-class query running) sheds the LOWEST running class through the
  cooperative cancel token — the unwind is the TL020-proven release path,
  and the client gets a typed ``QueryShed`` result with a retry-after
  hint. Execution is caller-runs: the submitting thread executes its
  own query once admitted, so tracer/ledger/lifecycle thread bindings all
  stay on the thread that owns them.
* :func:`execute_plan` — the executor half of the old ``TpuSession._execute``
  (session.py keeps session STATE; the per-partition driving loop,
  failure handling and per-query snapshotting live here). Every query gets
  a :class:`~.query_context.QueryContext` (cancel token + deadline + retry
  budget) bound around its whole execution window.

Lock discipline (TL021/TL022): ``QueryScheduler._mu`` is declared in
``analysis/locks.py``'s ``LOCK_ORDER`` one level above the metrics-registry
structure lock — the queue-depth gauge commits under it (the ``_QL_LOCK``
idiom: an interleaved enqueue/dequeue pair must not publish a stale count)
— and nothing blocking ever runs under it: grant waits happen on per-ticket
events outside the lock, chaos/flight emission happens after release.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Dict, List, Optional

from ..execs.base import TaskContext
from ..obs import flight as _flight
from ..obs import metrics as _metrics
from .query_context import (PRIORITIES, PRIORITY_RANK, QueryCancelledError,
                            QueryContext, QueryDeadlineExceeded,
                            QueryQueueFull, QueryShed, QueryShedError, bind,
                            checkpoint)

#: sessions alive in this process (weak: an abandoned, never-stopped
#: session must not pin itself here forever). TpuSession registers at
#: construction and discards itself in stop(); the LAST session to stop
#: releases the process-wide shuffle manager.
_LIVE_SESSIONS: "weakref.WeakSet" = weakref.WeakSet()


def register_session(session) -> None:
    # a new frontend re-owns the shared state: any pending release from
    # a previous last-session stop() is obsolete (this session's stop()
    # will re-request it)
    global _SHARED_RELEASE_PENDING
    _SHARED_RELEASE_PENDING = False
    _LIVE_SESSIONS.add(session)


def release_session(session) -> None:
    _LIVE_SESSIONS.discard(session)


def other_live_sessions(session) -> bool:
    """Any session frontend OTHER than `session` still alive? Gates the
    shared-resource teardown in TpuSession.stop()."""
    return any(s is not session for s in _LIVE_SESSIONS)


#: set when the last session stopped but shared state could not be
#: released yet (a straggler query outlived stop()'s drain timeout);
#: re-checked when queries end, so the release happens when the
#: straggler finally finishes instead of never
_SHARED_RELEASE_PENDING = False


def request_shared_release() -> bool:
    """Mark the process-wide shuffle manager for release (called by the
    LAST session's stop()) and attempt it now. Returns True if released."""
    global _SHARED_RELEASE_PENDING
    _SHARED_RELEASE_PENDING = True
    return maybe_release_shared()


def maybe_release_shared() -> bool:
    """Release the shuffle manager iff a release is pending AND no live
    session or active query remains. Cheap no-op otherwise (one module
    bool read) — execute_plan calls this after every query so a query
    that outlived its session's stop() drain still triggers the
    teardown when it ends."""
    global _SHARED_RELEASE_PENDING
    if not _SHARED_RELEASE_PENDING:
        return False
    if len(_LIVE_SESSIONS) or _metrics.active_query_count():
        return False
    from ..shuffle.manager import TpuShuffleManager
    with TpuShuffleManager._lock:
        mgr = TpuShuffleManager._instance
        TpuShuffleManager._instance = None
    _SHARED_RELEASE_PENDING = False
    if mgr is not None:
        mgr.shutdown()
    return True


class _Ticket:
    __slots__ = ("qctx", "granted", "enq_ns", "quota_deferred")

    def __init__(self, qctx: QueryContext):
        self.qctx = qctx
        self.granted = threading.Event()
        self.enq_ns = time.perf_counter_ns()
        # sched.quota_defer_total counts DEFERRED TICKETS, not admission
        # passes: set on the first quota skip so the 50ms re-poll loop
        # cannot inflate the counter
        self.quota_deferred = False


class QueryScheduler:
    """Process-wide admission-controlled query scheduler (module doc)."""

    _instance: Optional["QueryScheduler"] = None
    _cls_lock = threading.Lock()

    def __init__(self, max_queue: int = 64, max_concurrent: int = 8,
                 hbm_watermark: float = 0.9, class_aging_ms: float = 10000.0,
                 tenant_hbm_quota: float = 0.0,
                 shed_after_ms: float = 5000.0):
        self.max_queue = int(max_queue)
        self.max_concurrent = int(max_concurrent)
        self.hbm_watermark = float(hbm_watermark)
        #: a ticket queued past this bound is promoted over class
        #: precedence (anti-starvation: background still drains); 0 = off
        self.class_aging_ms = float(class_aging_ms)
        #: per-tenant HBM quota as a fraction of the budget; <=0 = off
        self.tenant_hbm_quota = float(tenant_hbm_quota)
        #: sustained-overload bound: a higher-class ticket waiting past
        #: this with all slots held sheds the lowest running class; 0 = off
        self.shed_after_ms = float(shed_after_ms)
        self._mu = threading.Lock()
        # class -> session id -> FIFO of queued tickets (FIFO per session
        # within a class; EDF across session heads within the class);
        # _rr[cls] holds ids of that class's sessions with a non-empty
        # queue — rotation is PER CLASS, so one class draining cannot
        # perturb another class's fairness position (the PR 14 global
        # rotation would have: a background grant used to advance the
        # same cursor interactive grants read)
        self._queues: Dict[str, Dict[str, deque]] = {}
        self._rr: Dict[str, deque] = {}
        self._queued = 0
        self._running: Dict[int, QueryContext] = {}  # id(ticket) -> qctx
        # every live QueryContext (queued or running) by session, for
        # session.cancel()/stop(), tenant-quota accounting and the
        # postmortem listing
        self._by_session: Dict[str, List[QueryContext]] = {}
        self._tls = threading.local()
        # EMA of completed-query wall seconds — the retry-after hint's
        # scale (GIL attr, monitoring-counter discipline)
        self._lat_ema_s = 0.5
        # process-wide plan cache (serving/plan_cache.py): scheduler-owned
        # so ALL session frontends share one cache; its own lock, never _mu
        from .plan_cache import PlanCache
        self.plan_cache = PlanCache()

    # --- lifecycle ----------------------------------------------------------
    @classmethod
    def get(cls, conf=None) -> "QueryScheduler":
        with cls._cls_lock:
            if cls._instance is None:
                cls._instance = QueryScheduler()
            inst = cls._instance
        if conf is not None:
            inst._maybe_configure(conf)
        return inst

    @classmethod
    def peek(cls) -> Optional["QueryScheduler"]:
        """The live instance WITHOUT creating one (invalidation hooks must
        not boot a scheduler just to find an empty cache)."""
        with cls._cls_lock:
            return cls._instance

    @classmethod
    def reset_for_tests(cls) -> "QueryScheduler":
        global _SHARED_RELEASE_PENDING
        _SHARED_RELEASE_PENDING = False
        with cls._cls_lock:
            cls._instance = QueryScheduler()
            return cls._instance

    def _maybe_configure(self, conf) -> None:
        """Only EXPLICITLY SET sched keys overwrite the process state (the
        flight/mesh_profile maybe_configure pattern: a default-conf session
        must not silently resize another session's scheduler)."""
        from ..config import (PLAN_CACHE_MAX_ENTRIES, SCHED_CLASS_AGING_MS,
                              SCHED_HBM_WATERMARK, SCHED_MAX_CONCURRENT,
                              SCHED_MAX_QUEUE, SCHED_SHED_AFTER_MS,
                              SCHED_TENANT_HBM_QUOTA)
        with self._mu:
            if conf.get_raw(SCHED_MAX_QUEUE.key) is not None:
                self.max_queue = int(conf.get(SCHED_MAX_QUEUE))
            if conf.get_raw(SCHED_MAX_CONCURRENT.key) is not None:
                self.max_concurrent = max(
                    1, int(conf.get(SCHED_MAX_CONCURRENT)))
            if conf.get_raw(SCHED_HBM_WATERMARK.key) is not None:
                self.hbm_watermark = float(conf.get(SCHED_HBM_WATERMARK))
            if conf.get_raw(SCHED_CLASS_AGING_MS.key) is not None:
                self.class_aging_ms = float(conf.get(SCHED_CLASS_AGING_MS))
            if conf.get_raw(SCHED_TENANT_HBM_QUOTA.key) is not None:
                self.tenant_hbm_quota = float(
                    conf.get(SCHED_TENANT_HBM_QUOTA))
            if conf.get_raw(SCHED_SHED_AFTER_MS.key) is not None:
                self.shed_after_ms = float(conf.get(SCHED_SHED_AFTER_MS))
        if conf.get_raw(PLAN_CACHE_MAX_ENTRIES.key) is not None:
            self.plan_cache.configure(conf.get(PLAN_CACHE_MAX_ENTRIES))

    def shutdown(self) -> None:
        """Cancel everything queued or running (the owner-class release for
        the QueryContexts parked on self)."""
        with self._mu:
            pending = [q for qs in self._by_session.values() for q in qs]
        for q in pending:
            q.cancel(reason="scheduler.shutdown")

    # --- admission core (self._mu held) ------------------------------------
    def _hbm_headroom_ok(self) -> bool:
        from ..memory.hbm import HbmBudget
        b = HbmBudget._instance  # no side-effect instantiation
        if b is None or b.budget <= 0:
            return True
        return b.used <= self.hbm_watermark * b.budget

    def _quota_bytes(self) -> Optional[int]:
        """Per-tenant HBM quota in bytes, or None when disabled (quota
        conf <= 0, or no budget to take a fraction of)."""
        if self.tenant_hbm_quota <= 0:
            return None
        from ..memory.hbm import HbmBudget
        b = HbmBudget._instance  # no side-effect instantiation
        if b is None or b.budget <= 0:
            return None
        return int(self.tenant_hbm_quota * b.budget)

    def _over_quota_locked(self, sid: str,
                           quota_bytes: Optional[int]) -> bool:
        """Tenant usage = the net HBM bytes charged to the tenant's LIVE
        QueryContexts (query_context.charge_hbm at HbmBudget.allocate).
        Over quota, the tenant's next ticket queues even when the device
        has headroom — the global watermark still applies on top."""
        if quota_bytes is None:
            return False
        return sum(q.hbm_bytes
                   for q in self._by_session.get(sid, ())) > quota_bytes

    def _skip_quota_locked(self, ticket: _Ticket, sid: str,
                           quota_bytes: Optional[int]) -> bool:
        if not self._over_quota_locked(sid, quota_bytes):
            return False
        if not ticket.quota_deferred:
            ticket.quota_deferred = True
            _metrics.counter_inc("sched.quota_defer_total", session=sid)
            _flight.note("query.quota_deferred", query=ticket.qctx.name,
                         session=sid)
        return True

    def _take_locked(self, cls: str, sid: str, ticket: _Ticket) -> _Ticket:
        """Dequeue a picked ticket and advance the PER-CLASS round-robin:
        the granted session moves to the back of ITS class's rotation
        only — fairness counters are per class, so a background grant can
        never advance the cursor interactive grants are ordered by."""
        dq = self._queues[cls][sid]
        dq.popleft()
        rot = self._rr.get(cls)
        if rot is not None:
            try:
                rot.remove(sid)
            except ValueError:
                pass
            if dq:
                rot.append(sid)
            if not rot:
                del self._rr[cls]
        if not dq:
            del self._queues[cls][sid]
            if not self._queues[cls]:
                del self._queues[cls]
        self._queued -= 1
        return ticket

    def _pick_locked(self, now_ns: int) -> Optional[_Ticket]:
        """SLO-aware pick: (1) anti-starvation aging — the OLDEST ticket
        queued past classAgingMs wins regardless of class, so background
        still drains under a persistent interactive load; (2) strict
        class precedence, earliest-deadline-first across the class's
        session queue heads (per-session order stays FIFO), rotation
        order breaking deadline ties (per-class round-robin). Over-quota
        tenants are skipped in both passes. None = nothing admittable."""
        quota = self._quota_bytes()
        if self.class_aging_ms > 0:
            bound_ns = int(self.class_aging_ms * 1e6)
            aged: Optional[tuple] = None
            for cls in PRIORITIES:
                for sid in self._rr.get(cls, ()):
                    dq = self._queues.get(cls, {}).get(sid)
                    if not dq:
                        continue
                    head = dq[0]
                    if now_ns - head.enq_ns < bound_ns:
                        continue
                    if self._skip_quota_locked(head, sid, quota):
                        continue
                    if aged is None or head.enq_ns < aged[2].enq_ns:
                        aged = (cls, sid, head)
            if aged is not None:
                return self._take_locked(*aged)
        for cls in PRIORITIES:
            best: Optional[tuple] = None
            best_key = float("inf")
            for sid in self._rr.get(cls, ()):
                dq = self._queues.get(cls, {}).get(sid)
                if not dq:
                    continue
                head = dq[0]
                if self._skip_quota_locked(head, sid, quota):
                    continue
                key = (float(head.qctx.deadline_ns)
                       if head.qctx.deadline_ns is not None
                       else float("inf"))
                # strict < keeps the earliest rotation position on ties:
                # deadline-less tickets fall back to pure round-robin
                if best is None or key < best_key:
                    best, best_key = (cls, sid, head), key
            if best is not None:
                return self._take_locked(*best)
        return None

    def _overload_victim_locked(self, now_ns: int
                                ) -> Optional[QueryContext]:
        """Sustained overload: a higher-class ticket has waited past
        shedAfterMs with every slot held while a strictly lower class
        runs → shed the LOWEST running class, one victim per pass (the
        freed slot re-evaluates before anything else is shed)."""
        if (self.shed_after_ms <= 0 or not self._queued
                or len(self._running) < self.max_concurrent):
            return None
        quota = self._quota_bytes()
        bound_ns = int(self.shed_after_ms * 1e6)
        waiter_rank: Optional[int] = None
        for cls, per_sid in self._queues.items():
            r = PRIORITY_RANK[cls]
            for sid, dq in per_sid.items():
                if not dq:
                    continue
                head = dq[0]
                # an over-quota tenant's wait is self-inflicted
                # backpressure, not device overload — never sheds others
                if self._over_quota_locked(sid, quota):
                    continue
                if (now_ns - head.enq_ns >= bound_ns
                        and (waiter_rank is None or r < waiter_rank)):
                    waiter_rank = r
        if waiter_rank is None:
            return None
        victim: Optional[QueryContext] = None
        vrank = waiter_rank
        for q in self._running.values():
            r = PRIORITY_RANK.get(q.priority, 0)
            if r > vrank and not q.cancelled:
                victim, vrank = q, r
        return victim

    def _admit_locked(self) -> Optional[QueryContext]:
        """Grant as many queued tickets as the watermarks allow (SLO
        order — _pick_locked). Grants are Event.set — the waiting
        submitter thread runs its own query. Returns the overload-shed
        victim, if any, for the CALLER to arm outside the lock (the
        cancel token's flight/chaos emission must not run under _mu)."""
        now_ns = time.perf_counter_ns()
        while self._queued and len(self._running) < self.max_concurrent:
            # HBM admission watermark, waived when the device is idle so
            # admission can always make progress (a budget left high by
            # parked state must not wedge the queue)
            if self._running and not self._hbm_headroom_ok():
                break
            ticket = self._pick_locked(now_ns)
            if ticket is None:
                break
            self._running[id(ticket)] = ticket.qctx
            ticket.granted.set()
        victim = self._overload_victim_locked(now_ns)
        # committed under the lock (the _QL_LOCK idiom): an interleaved
        # enqueue/release pair must never publish a stale depth
        _metrics.gauge_set("sched.queue_depth", self._queued)
        return victim

    def _admit_and_shed(self) -> None:
        """The admission entry point off the submit/poll/release paths:
        run one admission pass, then arm any overload victim OUTSIDE the
        lock (chaos + cancel-token flight emission)."""
        with self._mu:
            victim = self._admit_locked()
        if victim is not None:
            self._shed_victim(victim, reason="overload")

    # --- load shedding (docs/serving.md) ------------------------------------
    def _retry_after_s(self) -> float:
        """Client retry hint: roughly how long until a resubmission could
        be admitted — queue depth over concurrency, scaled by the EMA of
        recent query walls. A hint, not a promise (GIL reads)."""
        ema = max(0.05, float(self._lat_ema_s))
        depth = self._queued / max(1, self.max_concurrent)
        return min(30.0, round((depth + 1.0) * ema, 3))

    def _arm_shed(self, qctx: QueryContext, reason: str) -> None:
        if qctx.cancelled:
            return
        hint = self._retry_after_s()
        qctx.shed(retry_after_s=hint, reason=f"shed.{reason}")
        _metrics.counter_inc("sched.shed_total", cls=qctx.priority)
        _flight.note("query.shed", query=qctx.name,
                     session=qctx.session_id, cls=qctx.priority,
                     reason=reason, retry_after_s=hint)

    def _shed_victim(self, qctx: QueryContext, reason: str) -> bool:
        """Shed one RUNNING victim: the chaos `sched.shed` site fires
        BEFORE the token arms (latency delays the shed; io_error fails
        the shed attempt — the victim survives this pass and the next
        admission pass re-decides), then the cooperative cancel token
        arms with the retry-after hint. The victim unwinds through the
        TL020-proven release paths at its next checkpoint."""
        from ..chaos import inject
        try:
            inject("sched.shed", detail=qctx.name)
        except OSError:
            _flight.note("query.shed_aborted", query=qctx.name,
                         session=qctx.session_id, reason=reason)
            return False
        self._arm_shed(qctx, reason)
        return True

    def _try_shed_queued(self, ticket: _Ticket, reason: str) -> bool:
        """Shed one QUEUED victim to make room for a higher-class
        submission. Chaos fires before any state change; io_error fails
        the shed (False → the submission degrades to typed QueryQueueFull
        backpressure). The victim's waiting thread observes its armed
        token at the next 50ms poll tick and unwinds without ever having
        run. True = scheduler state may have changed; retry the enqueue
        (the victim may instead have been granted in the race window —
        that also frees queue space)."""
        from ..chaos import inject
        try:
            inject("sched.shed", detail=ticket.qctx.name)
        except OSError:
            _flight.note("query.shed_aborted", query=ticket.qctx.name,
                         session=ticket.qctx.session_id, reason=reason)
            return False
        with self._mu:
            removed = self._remove_ticket_locked(ticket)
        if removed:
            self._arm_shed(ticket.qctx, reason)
        return True

    def _remove_ticket_locked(self, ticket: _Ticket) -> bool:
        """Drop a still-queued ticket from its class/session queue
        (shed-while-queued, or a never-admitted release). Idempotent."""
        cls = ticket.qctx.priority
        sid = ticket.qctx.session_id
        per_sid = self._queues.get(cls)
        dq = per_sid.get(sid) if per_sid else None
        if dq is None:
            return False
        try:
            dq.remove(ticket)
        except ValueError:
            return False
        self._queued -= 1
        if not dq:
            del per_sid[sid]
            if not per_sid:
                del self._queues[cls]
            rot = self._rr.get(cls)
            if rot is not None:
                try:
                    rot.remove(sid)
                except ValueError:
                    pass
                if not rot:
                    del self._rr[cls]
        return True

    def _release(self, ticket: _Ticket) -> None:
        """Return `ticket`'s slot (running) or queue entry (never admitted)
        and admit successors. Idempotent."""
        with self._mu:
            if self._running.pop(id(ticket), None) is None:
                self._remove_ticket_locked(ticket)
            victim = self._admit_locked()
        if victim is not None:
            self._shed_victim(victim, reason="overload")

    def _deregister(self, qctx: QueryContext) -> None:
        """QueryContext.close() hook: drop it from the session index."""
        with self._mu:
            lst = self._by_session.get(qctx.session_id)
            if lst is None:
                return
            lst[:] = [q for q in lst if q is not qctx]
            if not lst:
                del self._by_session[qctx.session_id]

    # --- the submission path ------------------------------------------------
    def submit_and_run(self, qctx: QueryContext, fn):
        """Enqueue `qctx`, wait for admission, then run `fn` on the calling
        thread with the context bound. Raises QueryQueueFull past the queue
        bound; a cancel/deadline while QUEUED raises without running
        anything. Nested execution (a query submitting a query on the same
        thread) bypasses admission — the caller-runs model would deadlock
        a thread against its own held slot."""
        if getattr(self._tls, "admitted", False):
            # nested execution rides the OUTER query's admission slot AND
            # its cancel token: the outer (registered) context stays
            # bound, so session.cancel()/stop()/deadlines interrupt the
            # nested work too — re-binding the nested context would hand
            # checkpoints a token nothing can ever arm (the nested
            # context is registered nowhere; it is part of the outer
            # query's work)
            qctx.mark_running()
            return fn()
        ticket = _Ticket(qctx)
        my_rank = PRIORITY_RANK[qctx.priority]
        cls = qctx.priority
        enqueued = False
        victim: Optional[QueryContext] = None
        # bounded shed-to-make-room loop: a full queue rejects a
        # submission ONLY when no strictly lower class is queued behind
        # it — otherwise the lowest (youngest-first) class is shed and
        # the enqueue retried. Same-or-higher classes queued means the
        # typed QueryQueueFull backpressure stands, exactly as before.
        for _attempt in range(4):
            queued_victim: Optional[_Ticket] = None
            with self._mu:
                if self._queued < self.max_queue:
                    self._queues.setdefault(cls, {}).setdefault(
                        qctx.session_id, deque()).append(ticket)
                    rot = self._rr.setdefault(cls, deque())
                    if qctx.session_id not in rot:
                        rot.append(qctx.session_id)
                    self._queued += 1
                    self._by_session.setdefault(qctx.session_id,
                                                []).append(qctx)
                    victim = self._admit_locked()
                    enqueued = True
                else:
                    queued_victim = self._find_queued_victim_locked(
                        my_rank)
            if enqueued:
                break
            if queued_victim is None or not self._try_shed_queued(
                    queued_victim, reason="queue_full"):
                break
        if victim is not None:
            self._shed_victim(victim, reason="overload")
        if not enqueued:
            _metrics.counter_inc("query.rejected_queue_full")
            _flight.note("query.rejected", query=qctx.name,
                         session=qctx.session_id, reason="queue_full")
            raise QueryQueueFull(
                f"query {qctx.name} rejected: admission queue full "
                f"(spark.rapids.tpu.sched.maxQueuedQueries="
                f"{self.max_queue})")
        _flight.note("query.queued", query=qctx.name,
                     session=qctx.session_id, cls=qctx.priority)
        try:
            # grant wait OFF the lock; short poll so a cancel or deadline
            # arriving while queued is observed promptly, and admission is
            # re-evaluated each tick (HBM headroom can open mid-query,
            # with no completion event to trigger a grant)
            while not ticket.granted.wait(timeout=0.05):
                qctx.check("sched.queue")
                self._admit_and_shed()
            # chaos `sched.admit` fires BEFORE the admission is recorded:
            # latency extends the measured queue delay (it lands in the
            # sched.admit_wait_ms histogram), io_error fails the query
            # still QUEUED — no query.admitted flight event, no query
            # work started, no resource acquired
            from ..chaos import inject
            inject("sched.admit", detail=qctx.name)
            wait_ms = (time.perf_counter_ns() - ticket.enq_ns) / 1e6
            qctx.admit_wait_ms = wait_ms
            _metrics.histogram_observe("sched.admit_wait_ms", wait_ms)
            _metrics.histogram_observe("sched.class_admit_wait_ms",
                                       wait_ms, cls=qctx.priority)
            _flight.note("query.admitted", query=qctx.name,
                         session=qctx.session_id, cls=qctx.priority,
                         wait_ms=round(wait_ms, 3))
            self._tls.admitted = True
            try:
                with bind(qctx):
                    qctx.mark_running()
                    out = fn()
            finally:
                self._tls.admitted = False
            # completed-query wall EMA — the retry-after hint's scale
            run_s = ((time.perf_counter_ns() - ticket.enq_ns) / 1e9
                     - wait_ms / 1e3)
            self._lat_ema_s = (0.8 * self._lat_ema_s
                               + 0.2 * max(1e-3, run_s))
            return out
        except QueryShedError:
            # counted at arm time (sched.shed_total); deliberately NOT
            # query.cancelled — shedding is a scheduler answer, and the
            # front door converts it into a typed QueryShed result
            _flight.note("query.shed_unwound", query=qctx.name,
                         session=qctx.session_id, cls=qctx.priority)
            raise
        except QueryDeadlineExceeded:
            _metrics.counter_inc("query.deadline_exceeded")
            _flight.note("query.deadline_exceeded", query=qctx.name,
                         session=qctx.session_id)
            raise
        except QueryCancelledError:
            _metrics.counter_inc("query.cancelled")
            _flight.note("query.cancelled", query=qctx.name,
                         session=qctx.session_id,
                         reason=qctx.cancel_reason)
            raise
        finally:
            self._release(ticket)

    def _find_queued_victim_locked(self, my_rank: int
                                   ) -> Optional["_Ticket"]:
        """Lowest-class queued ticket STRICTLY below `my_rank`, youngest
        first (least sunk queue wait), for shed-to-make-room."""
        for cls in reversed(PRIORITIES):
            if PRIORITY_RANK[cls] <= my_rank:
                return None
            per_sid = self._queues.get(cls)
            if not per_sid:
                continue
            youngest: Optional[_Ticket] = None
            for dq in per_sid.values():
                for t in dq:
                    if youngest is None or t.enq_ns > youngest.enq_ns:
                        youngest = t
            if youngest is not None:
                return youngest
        return None

    # --- session-level control ---------------------------------------------
    def cancel_session(self, session_id: str,
                       reason: str = "session.cancel") -> int:
        """Arm the cancel token of every queued/running query of one
        session frontend; returns how many were flagged."""
        with self._mu:
            targets = list(self._by_session.get(session_id, ()))
        for q in targets:
            q.cancel(reason=reason)
        return len(targets)

    def drain_session(self, session_id: str, timeout_s: float = 30.0
                      ) -> bool:
        """Wait (bounded) until a session has no queued or running query —
        the stop() barrier after cancel_session."""
        end = time.monotonic() + timeout_s
        while time.monotonic() < end:
            with self._mu:
                if not self._by_session.get(session_id):
                    return True
            time.sleep(0.01)
        with self._mu:
            return not self._by_session.get(session_id)

    # --- observability ------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Queued/running query names + states for the postmortem bundle
        and metrics_snapshot — a crash dump must NAME the queries that
        were queued, running or cancelling when the process died."""
        with self._mu:
            running = [{"query": q.name, "session": q.session_id,
                        "cls": q.priority, "state": q.state}
                       for q in self._running.values()]
            queued = [{"query": t.qctx.name, "session": sid, "cls": cls,
                       "state": t.qctx.state}
                      for cls, per_sid in self._queues.items()
                      for sid, dq in per_sid.items() for t in dq]
            tenant_hbm = {sid: sum(q.hbm_bytes for q in qs)
                          for sid, qs in self._by_session.items()}
            return {"max_concurrent": self.max_concurrent,
                    "max_queue": self.max_queue,
                    "hbm_watermark": self.hbm_watermark,
                    "class_aging_ms": self.class_aging_ms,
                    "tenant_hbm_quota": self.tenant_hbm_quota,
                    "shed_after_ms": self.shed_after_ms,
                    "queue_depth": self._queued,
                    "tenant_hbm_bytes": tenant_hbm,
                    "running": running, "queued": queued,
                    "plan_cache": self.plan_cache.stats()}


# ---------------------------------------------------------------------------
# the executor service: the per-partition driving loop moved out of
# TpuSession._execute (session.py keeps the front door + session state)
# ---------------------------------------------------------------------------


def execute_plan(session, plan, timeout: Optional[float] = None,
                 priority: Optional[str] = None):
    """Plan, admit, and execute one query for `session`, returning the
    pyarrow result table — or a typed :class:`QueryShed` result when the
    scheduler shed the query under overload (docs/serving.md). `timeout`
    (seconds) overrides the session's spark.rapids.tpu.query.timeoutMs
    deadline for this call; `priority` overrides the session's
    spark.rapids.tpu.query.priority SLO class."""
    import pyarrow as pa

    from ..config import (QUERY_PRIORITY, QUERY_RETRY_BUDGET,
                          QUERY_TIMEOUT_MS, TRACE_TAG)
    from ..types import to_arrow as t2a
    # ONE conf snapshot at submission: every later planning step (logical
    # optimize, physical plan, override pass, plan-cache fingerprint) reads
    # this frozen view, so a concurrent conf.set() can never produce a plan
    # half-built under two conf views (GpuOverrides.scala:4565 analogue)
    conf = session._rapids_conf()
    session._query_seq = getattr(session, "_query_seq", 0) + 1
    tag = conf.get(TRACE_TAG)
    stem = tag if tag and str(tag) != "None" else "query"
    if stem == "query":
        # untagged sessions fold the session id into the query name:
        # concurrent sessions each minting "query-1" would collide in
        # every name-keyed filter (the STRICT mesh-profile query filter
        # would bleed one tenant's exchanges into another's bundle).
        # Tagged names stay `<tag>-<n>` — the bench artifact contract.
        sid_n = session._session_id.rsplit("-", 1)[-1]
        qname = f"query-s{sid_n}-{session._query_seq}"
    else:
        qname = f"{stem}-{session._query_seq}"
    timeout_ms = float(timeout) * 1000.0 if timeout is not None \
        else float(conf.get(QUERY_TIMEOUT_MS))
    deadline_ns = (time.perf_counter_ns() + int(timeout_ms * 1e6)
                   if timeout_ms and timeout_ms > 0 else None)
    cls = str(priority if priority is not None
              else conf.get(QUERY_PRIORITY))
    sched = QueryScheduler.get(conf)
    # planning runs INSIDE the admitted window (see _run_admitted) so the
    # plan.build span lands in the traced bundle and planning wall counts
    # into the query's latency histogram; the closure carries the one conf
    # snapshot into the scheduler-owned plan cache
    holder: Dict[str, Any] = {}

    def plan_fn():
        from .plan_cache import build_or_fetch
        final, cache_status, rules = build_or_fetch(session, sched, plan,
                                                    conf)
        holder["final"] = final
        session._last_plan_cache = cache_status
        session._last_opt_rules = rules
        return final

    try:
        with QueryContext(qname, session_id=session._session_id,
                          deadline_ns=deadline_ns,
                          retry_budget=conf.get(QUERY_RETRY_BUDGET),
                          priority=cls) as qctx:
            try:
                tables = sched.submit_and_run(
                    qctx, lambda: _run_admitted(session, plan_fn, conf,
                                                qctx, stem, qname))
            except QueryShedError as e:
                # typed load-shed RESULT, not an error: the unwind
                # already ran the TL020-proven release paths; the client
                # resubmits after the hint (docs/serving.md). finish(e)
                # records the SHED terminal state HERE — the swallowed
                # exception never reaches __exit__'s finish
                qctx.finish(e)
                return QueryShed(
                    query=qname, session=session._session_id,
                    priority=qctx.priority,
                    reason=qctx.cancel_reason or "shed",
                    retry_after_s=e.retry_after_s)
            finally:
                session._last_admit_wait_ms = qctx.admit_wait_ms
    finally:
        # a query that outlived its session's stop() drain releases the
        # shared state the stop could not (no-op unless pending)
        maybe_release_shared()
    final = holder["final"]
    schema = pa.schema([(a.name, t2a(a.dtype)) for a in final.output])
    if not tables:
        return schema.empty_table()
    return pa.concat_tables(tables).cast(schema)


def _run_admitted(session, plan_fn, conf, qctx: QueryContext, stem: str,
                  qname: str) -> List:
    """One admitted query's execution window: planning (via the scheduler-
    owned plan cache), partition loop(s), failure handling, and the
    per-query observability snapshotting. Runs on the submitting thread
    with the QueryContext bound; planning runs AFTER the tracer arms so
    the plan.build span is part of the query's bundle."""
    from .. import obs
    from ..config import (TRACE_BUFFER_EVENTS, TRACE_CATEGORIES,
                          TRACE_ENABLED)
    from ..parallel.mesh import mesh_session_active
    from ..profiling import (SyncLedger, TaskMetricsRegistry,
                             snapshot_plan_metrics)
    task_metrics_before = TaskMetricsRegistry.get().snapshot()
    syncs_before = SyncLedger.get().snapshot()
    # always-on metrics registry (docs/observability.md): EVERY query
    # (traced or not) registers its lifecycle — the queries.active
    # gauge/list, the latency + rows/s histograms, and the epoch the
    # tracer's exclusivity check reads. Registered BEFORE planning so
    # planning wall counts into the query latency window.
    qtok = obs.metrics.query_begin(qname, session=stem,
                                   cls=qctx.priority)
    qroot = None
    opjit_before = None
    final = None
    tables: List = []
    # window for this query's collective-exchange profiles (mesh
    # efficiency profiler): profiles are tagged with the traced query
    # name when one is bound; the seq window covers untraced queries
    mesh_seq0 = obs.mesh_profile.current_seq()
    failed = True  # cleared by the last statement of the try body
    try:
        if conf.get(TRACE_ENABLED):
            from ..config import TRACE_MAX_CONCURRENT
            from ..execs import opjit
            # arm FIRST inside the try whose finally guarantees
            # end_query (TL020: an exception can never strand a tracer
            # armed) and query_end. The snapshot BEFORE arming (nothing
            # dispatches in between) is only trusted when the query ran
            # EXCLUSIVELY — a concurrent query's bundle reconciles
            # against the tracer's own per-query counters instead (no
            # cross-query bleed).
            opjit_before = opjit.cache_stats()["calls_by_kind"]
            qroot = obs.begin_query(
                qname,
                buffer_events=conf.get(TRACE_BUFFER_EVENTS),
                categories=conf.get(TRACE_CATEGORIES),
                max_concurrent=conf.get(TRACE_MAX_CONCURRENT))
        # planning: plan-cache fetch (literal re-bind) or full logical
        # optimize → physical plan → override pass — one span, one
        # histogram, so planning share is measurable from the bundle
        t_plan0 = time.perf_counter_ns()
        with obs.span("plan.build", cat="plan"):
            final = plan_fn()
        obs.metrics.histogram_observe(
            "plan.build_ms", (time.perf_counter_ns() - t_plan0) / 1e6)
        # mesh session (docs/distributed.md): the root pull drives ALL
        # partitions through the multi-partition entry point in one group,
        # so the top whole-stage segment (between the last exchange and the
        # result) executes every chip's partition in a single grouped
        # launch — the same batched dispatch the exchange map side uses
        n_parts = final.num_partitions()
        names = [a.name for a in final.output]
        group_pull = n_parts > 1 and mesh_session_active(conf) is not None
        if group_pull:
            ids = list(range(n_parts))
            ctxs: Dict[int, TaskContext] = {}

            def ctx_of(i):
                c = ctxs.get(i)
                if c is None:
                    c = ctxs[i] = TaskContext(i, conf)
                return c

            try:
                checkpoint(f"task.group 0-{ids[-1]}")
                with obs.span(f"partition group 0-{ids[-1]}", cat="task",
                              partitions=n_parts):
                    for _p, t in final.execute_partitions(ids, ctx_of):
                        if t.num_rows:
                            tables.append(t.rename_columns(names))
            except BaseException as exc:
                from ..config import FATAL_ERROR_EXIT
                from ..failure import handle_task_failure
                handle_task_failure(
                    exc, conf,
                    exit_on_fatal=conf.get(FATAL_ERROR_EXIT))
                raise
            finally:
                for c in ctxs.values():
                    c.complete()
        else:
            for p in range(n_parts):
                # cooperative cancellation at partition-task start: a
                # cancelled/timed-out query stops scheduling new tasks
                # before any of this partition's resources are acquired
                checkpoint(f"task.start p{p}")
                ctx = TaskContext(p, conf)
                try:
                    with obs.span(f"partition {p}", cat="task",
                                  partition=p):
                        for t in final.execute_partition(p, ctx):
                            if t.num_rows:
                                tables.append(t.rename_columns(names))
                except BaseException as exc:
                    # fatal device errors capture diagnostics and
                    # (outside tests) exit so the cluster manager
                    # reschedules (RapidsExecutorPlugin.onTaskFailed)
                    from ..config import FATAL_ERROR_EXIT
                    from ..failure import handle_task_failure
                    handle_task_failure(
                        exc, conf,
                        exit_on_fatal=conf.get(FATAL_ERROR_EXIT))
                    raise
                finally:
                    ctx.complete()
        failed = False  # reached only when every partition completed
    finally:
        # snapshot metrics into plain dicts so the plan (and any device
        # buffers it references) is not pinned past the query; a planning
        # failure (final is None) leaves no stale previous-query snapshot
        session._last_metrics_snapshot = (
            snapshot_plan_metrics(final) if final is not None else None)
        session._last_plan_tree = (
            _plan_tree_snapshot(final) if final is not None else None)
        after = TaskMetricsRegistry.get().snapshot()
        session._last_task_metrics = {
            k: after.get(k, 0) - task_metrics_before.get(k, 0)
            for k in after}
        # per-operator blocking-sync deltas for this query alone (the
        # sync ledger is process-wide; docs/configs.md "Dispatch & sync
        # accounting")
        syncs_after = SyncLedger.get().snapshot()
        ledger = {}
        for op, kinds in syncs_after.items():
            prev = syncs_before.get(op, {})
            d = {k: v - prev.get(k, 0) for k, v in kinds.items()
                 if v - prev.get(k, 0)}
            if d:
                ledger[op] = d
        session._last_sync_ledger = ledger
        # this query's per-exchange mesh profiles + per-map fallback
        # reasons (empty outside mesh sessions): the bundle's `mesh`
        # section and the sharded runner both read these
        session._last_mesh_profiles = obs.mesh_profile.profiles_since(
            mesh_seq0, query=qname)
        session._last_mesh_fallbacks = obs.mesh_profile.fallbacks_since(
            mesh_seq0, query=qname)
        # honesty: records evicted from the bounded profiler rings
        # inside this query's window (exchange-heavy / concurrent
        # load) are COUNTED, not silently missing from the bundle
        session._last_mesh_dropped = obs.mesh_profile.window_dropped(
            mesh_seq0)
        if qroot is not None:
            _finish_query_profile(session, qroot, conf, opjit_before)
        else:
            # honor the last_query_profile contract: an untraced query
            # (tracing off, or the process-wide tracer owned by another
            # query) must not leave a previous query's bundle behind
            session._last_query_profile = None
        # release shuffle blocks/files at query end (reference: Spark's
        # ContextCleaner removing shuffle state); exchanges re-materialize
        # if the same DataFrame is collected again
        if final is not None:
            for node in final.collect_nodes():
                if hasattr(node, "cleanup_shuffle"):
                    node.cleanup_shuffle(conf)
        obs.metrics.query_end(
            qtok, rows=sum(t.num_rows for t in tables),
            failed=failed, session=stem)
    return tables


def _finish_query_profile(session, qroot, conf, opjit_before) -> None:
    """Close the tracer, build the diagnostics bundle (metric snapshot +
    sync-ledger delta + dispatch-by-kind delta + the span/event record),
    and write the Chrome trace + bundle artifacts when
    spark.rapids.tpu.trace.dir is set. IMPORTANT: all inputs are the
    deltas this query caused — the bundle's reconciliation asserts the
    tracer saw every dispatch (calls_by_kind) and every blocking sync
    (SyncLedger) the pre-existing counters saw."""
    from .. import obs
    from ..config import TRACE_DIR
    from ..execs import opjit
    profile = obs.end_query(qroot)
    if profile.get("exclusive", True):
        # no other query overlapped: the process-wide counter deltas
        # are attributable to this query — the strongest ground truth
        # (incremented by code paths independent of the tracer)
        disp_after = opjit.cache_stats()["calls_by_kind"]
        disp_delta = {
            k: disp_after.get(k, 0) - (opjit_before or {}).get(k, 0)
            for k in set(disp_after) | set(opjit_before or {})}
    else:
        # concurrent queries: process-wide deltas cross-bleed, so the
        # bundle reconciles against THIS query's own counters — kept
        # by the tracer at exactly the sites where calls_by_kind and
        # the SyncLedger increment, routed by the thread binding
        disp_delta = {k: v for k, v in
                      profile.get("dispatch_counts", {}).items() if v}
        session._last_sync_ledger = {
            op: dict(kinds)
            for op, kinds in profile.get("sync_counts", {}).items()}
    bundle = obs.build_bundle(
        profile,
        plan_tree=session._last_plan_tree,
        metrics=session._last_metrics_snapshot,
        sync_ledger=session._last_sync_ledger,
        dispatch_delta=disp_delta,
        task_metrics=session._last_task_metrics,
        mesh_profiles=getattr(session, "_last_mesh_profiles", None),
        mesh_fallbacks=getattr(session, "_last_mesh_fallbacks", None),
        mesh_dropped=getattr(session, "_last_mesh_dropped", 0))
    out_dir = conf.get(TRACE_DIR)
    if out_dir and str(out_dir) != "None":
        try:
            obs.write_artifacts(bundle, profile, str(out_dir),
                                profile.get("name", "query"))
        except OSError:
            bundle["artifacts"] = {"error": "trace.dir not writable"}
    session._last_query_profile = bundle


def _plan_tree_snapshot(plan) -> List[dict]:
    """Plain-data snapshot of the executed physical plan for
    explain("metrics") and the diagnostics bundle — preorder, so index i
    matches snapshot_plan_metrics's "i:NodeName" keys, and no node (or
    device buffer it pins) survives past the query."""
    out: List[dict] = []

    def walk(node, depth: int) -> None:
        out.append({"i": len(out), "depth": depth,
                    "name": node.node_name(), "desc": node.node_desc(),
                    "tpu": node.is_tpu})
        for c in node.children:
            walk(c, depth + 1)

    walk(plan, 0)
    return out
