"""Per-query lifecycle state: cancel token, deadline, retry budget.

Reference analogue: Spark cancels a job group by flagging its TaskContexts
and letting tasks observe the flag at safe points (TaskContext.isInterrupted;
the plugin's retry framework re-checks between attempts). XLA dispatches are
not preemptible, so cancellation here is **cooperative**: the engine checks
:func:`checkpoint` at every pre-existing task boundary — never mid-kernel —
and a tripped check raises :class:`QueryCancelledError` /
:class:`QueryDeadlineExceeded`, unwinding through exactly the release paths
the TL020 static proof covers (finally blocks, ``with`` scopes, completion
listeners). Nothing new is released on cancellation; the point is that the
*existing* unwind discipline runs.

State machine (docs/robustness.md "Query lifecycle")::

    QUEUED ──admit──► RUNNING ──ok──► FINISHED
      │                 │ └─error───► FAILED
      │                 └─cancel/deadline──► CANCELLING ─unwound─► CANCELLED
      └─cancel/deadline/queue-reject while queued ────────────────► CANCELLED
                                                    (deadline → TIMED_OUT)

Thread routing follows the sync-ledger/tracer idiom: :func:`bind` attaches a
context to the calling thread; pool handoffs (exchange map tasks, prefetch
workers) re-bind the captured context on the worker so a cancel lands on
every thread serving the query. An unbound thread's :func:`checkpoint` is a
single thread-local read — the execs/base.py hot loop stays effectively
free when no query lifecycle is in play.

Errors subclass ``BaseException`` on purpose: the shuffle layer converts
*any* ``Exception`` during a block decode into ``FetchFailedError`` and
heals it by re-running map tasks — a cancellation must never be "healed"
into a recompute loop, and ``failure.with_device_retry`` must never retry
it (its transient classifier already says no, and the ``BaseException``
ancestry keeps every generic ``except Exception`` recovery path out of the
way). ``QueryQueueFull`` is an ordinary ``Exception``: backpressure is a
normal, retryable client-facing condition.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

#: lifecycle states (docs/robustness.md "Query lifecycle")
QUEUED = "QUEUED"
RUNNING = "RUNNING"
CANCELLING = "CANCELLING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
TIMED_OUT = "TIMED_OUT"

_TERMINAL = (FINISHED, FAILED, CANCELLED, TIMED_OUT)


class QueryCancelledError(BaseException):
    """The query's cancel token was set (user cancel, session.stop(),
    chaos `query.cancel`). BaseException: see module docstring."""


class QueryDeadlineExceeded(QueryCancelledError):
    """The query ran past its deadline (spark.rapids.tpu.query.timeoutMs
    or df.collect(timeout=...)) and was cancelled at a checkpoint."""


class QueryQueueFull(Exception):
    """Typed backpressure: the scheduler's bounded admission queue is full
    (spark.rapids.tpu.sched.maxQueuedQueries). The submission was rejected
    BEFORE any resource was acquired — resubmit later or shed load."""


class QueryContext:
    """One submitted query's lifecycle handle: cancel token + optional
    deadline + per-query retry budget. Owner discipline (TL020): created
    by the executor front door, used as a ``with`` context so the
    scheduler registration releases on every path."""

    def __init__(self, name: str, session_id: str = "default",
                 deadline_ns: Optional[int] = None,
                 retry_budget: int = 64):
        self.name = name
        self.session_id = session_id
        #: absolute time.perf_counter_ns() deadline, or None
        self.deadline_ns = deadline_ns
        self.state = QUEUED
        self.cancel_reason: Optional[str] = None
        self._cancel = threading.Event()
        self._mu = threading.Lock()
        self._retry_budget = int(retry_budget)
        self._closed = False

    # --- cancellation -------------------------------------------------------
    def cancel(self, reason: str = "user") -> None:
        """Arm the cancel token (idempotent; first reason wins). The query
        keeps running until its next cooperative checkpoint observes the
        token — there is nothing safe to interrupt mid-dispatch."""
        with self._mu:
            if self._cancel.is_set() or self.state in _TERMINAL:
                return
            self.cancel_reason = reason
            if self.state == RUNNING:
                self.state = CANCELLING
        self._cancel.set()
        from ..obs import flight as _flight
        _flight.note("query.cancelling", query=self.name,
                     session=self.session_id, reason=reason)

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def deadline_exceeded(self) -> bool:
        return (self.deadline_ns is not None
                and time.perf_counter_ns() >= self.deadline_ns)

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None when no deadline)."""
        if self.deadline_ns is None:
            return None
        return max(0.0, (self.deadline_ns - time.perf_counter_ns()) / 1e9)

    def check(self, boundary: str = "") -> None:
        """Raise if cancelled or past deadline — the cooperative
        cancellation point. Deadline expiry arms the cancel token too, so
        every other thread serving this query trips at ITS next check."""
        if self._cancel.is_set():
            if self.cancel_reason == "deadline":
                raise QueryDeadlineExceeded(
                    f"query {self.name} exceeded its deadline "
                    f"(observed at {boundary or 'checkpoint'})")
            raise QueryCancelledError(
                f"query {self.name} cancelled "
                f"({self.cancel_reason or 'unknown'}) "
                f"at {boundary or 'checkpoint'}")
        if self.deadline_exceeded():
            self.cancel(reason="deadline")
            raise QueryDeadlineExceeded(
                f"query {self.name} exceeded its deadline at "
                f"{boundary or 'checkpoint'}")

    # --- retry budget -------------------------------------------------------
    def consume_retry(self) -> bool:
        """Take one unit of the per-query transient-retry budget
        (spark.rapids.tpu.query.retryBudget). False = exhausted: the
        caller fails THIS query instead of retrying — one flapping query
        cannot sit in retry loops starving the shared pool."""
        with self._mu:
            if self._retry_budget <= 0:
                return False
            self._retry_budget -= 1
            return True

    # --- state machine ------------------------------------------------------
    def mark_running(self) -> None:
        with self._mu:
            if self.state == QUEUED:
                self.state = RUNNING

    def finish(self, exc: Optional[BaseException] = None) -> str:
        """Record the terminal state from the execution outcome."""
        with self._mu:
            if self.state in _TERMINAL:
                return self.state
            if exc is None:
                self.state = FINISHED
            elif isinstance(exc, QueryDeadlineExceeded):
                self.state = TIMED_OUT
            elif isinstance(exc, QueryCancelledError):
                self.state = CANCELLED
            else:
                self.state = FAILED
            return self.state

    # --- ownership (TL020) --------------------------------------------------
    def close(self) -> None:
        """Deregister from the scheduler's active-query index (idempotent).
        A context that dies unregistered would keep session.cancel() and
        the postmortem's queued/running listing lying forever."""
        if self._closed:
            return
        self._closed = True
        from .scheduler import QueryScheduler
        sched = QueryScheduler._instance
        if sched is not None:
            sched._deregister(self)

    def __enter__(self) -> "QueryContext":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish(exc)
        self.close()


# --- thread binding (the sync-ledger idiom) ---------------------------------

_TL = threading.local()


@contextlib.contextmanager
def bind(qctx: Optional[QueryContext]) -> Iterator[None]:
    """Bind `qctx` to the calling thread for the scope (None = keep the
    current binding — pool handoffs pass whatever they captured)."""
    prev = getattr(_TL, "q", None)
    _TL.q = qctx if qctx is not None else prev
    try:
        yield
    finally:
        _TL.q = prev


def current() -> Optional[QueryContext]:
    return getattr(_TL, "q", None)


def checkpoint(boundary: str = "") -> None:
    """The cooperative cancellation point, called at every pre-existing
    task boundary. Unbound thread: one thread-local read, nothing else.
    Bound: the chaos `query.cancel` site fires first (so a seeded soak can
    race a cancellation against this exact boundary), then the context's
    cancel/deadline check."""
    q = getattr(_TL, "q", None)
    if q is None:
        return
    from ..chaos import inject
    inject("query.cancel", detail=boundary)
    q.check(boundary)


def consume_retry_budget() -> bool:
    """failure.with_device_retry's hook: True when no query is bound (the
    per-site attempt bound still applies) or budget remains."""
    q = getattr(_TL, "q", None)
    return True if q is None else q.consume_retry()
