"""Per-query lifecycle state: cancel token, deadline, retry budget.

Reference analogue: Spark cancels a job group by flagging its TaskContexts
and letting tasks observe the flag at safe points (TaskContext.isInterrupted;
the plugin's retry framework re-checks between attempts). XLA dispatches are
not preemptible, so cancellation here is **cooperative**: the engine checks
:func:`checkpoint` at every pre-existing task boundary — never mid-kernel —
and a tripped check raises :class:`QueryCancelledError` /
:class:`QueryDeadlineExceeded`, unwinding through exactly the release paths
the TL020 static proof covers (finally blocks, ``with`` scopes, completion
listeners). Nothing new is released on cancellation; the point is that the
*existing* unwind discipline runs.

State machine (docs/robustness.md "Query lifecycle")::

    QUEUED ──admit──► RUNNING ──ok──► FINISHED
      │                 │ └─error───► FAILED
      │                 └─cancel/deadline──► CANCELLING ─unwound─► CANCELLED
      └─cancel/deadline/queue-reject while queued ────────────────► CANCELLED
                                                    (deadline → TIMED_OUT)
                                                    (shed     → SHED)

SLO classes (docs/serving.md): every submission carries a *priority class*
— ``interactive`` > ``batch`` > ``background`` — and an optional deadline.
The scheduler admits earliest-deadline-first within a class with strict
precedence across classes (plus an anti-starvation aging bound), and under
sustained overload **sheds** the lowest class through the same cooperative
cancel token: :meth:`QueryContext.shed` arms the token with a retry-after
hint, the next checkpoint raises :class:`QueryShedError` (a
``QueryCancelledError``, so the TL020-proven unwind paths run unchanged),
and the front door converts it into a typed :class:`QueryShed` RESULT —
load shedding is an answer ("come back in ~N seconds"), not an error.

Thread routing follows the sync-ledger/tracer idiom: :func:`bind` attaches a
context to the calling thread; pool handoffs (exchange map tasks, prefetch
workers) re-bind the captured context on the worker so a cancel lands on
every thread serving the query. An unbound thread's :func:`checkpoint` is a
single thread-local read — the execs/base.py hot loop stays effectively
free when no query lifecycle is in play.

Errors subclass ``BaseException`` on purpose: the shuffle layer converts
*any* ``Exception`` during a block decode into ``FetchFailedError`` and
heals it by re-running map tasks — a cancellation must never be "healed"
into a recompute loop, and ``failure.with_device_retry`` must never retry
it (its transient classifier already says no, and the ``BaseException``
ancestry keeps every generic ``except Exception`` recovery path out of the
way). ``QueryQueueFull`` is an ordinary ``Exception``: backpressure is a
normal, retryable client-facing condition.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

#: lifecycle states (docs/robustness.md "Query lifecycle")
QUEUED = "QUEUED"
RUNNING = "RUNNING"
CANCELLING = "CANCELLING"
FINISHED = "FINISHED"
FAILED = "FAILED"
CANCELLED = "CANCELLED"
TIMED_OUT = "TIMED_OUT"
SHED = "SHED"

_TERMINAL = (FINISHED, FAILED, CANCELLED, TIMED_OUT, SHED)

#: SLO priority classes, best first (docs/serving.md): strict precedence
#: across classes at admission, EDF within a class, and under sustained
#: overload the WORST class is shed first. Rank = index (lower is better).
PRIORITIES = ("interactive", "batch", "background")
PRIORITY_RANK = {cls: i for i, cls in enumerate(PRIORITIES)}


def validate_priority(priority: str) -> str:
    p = str(priority).lower()
    if p not in PRIORITY_RANK:
        raise ValueError(
            f"unknown priority class {priority!r} "
            f"(expected one of {', '.join(PRIORITIES)})")
    return p


class QueryCancelledError(BaseException):
    """The query's cancel token was set (user cancel, session.stop(),
    chaos `query.cancel`). BaseException: see module docstring."""


class QueryDeadlineExceeded(QueryCancelledError):
    """The query ran past its deadline (spark.rapids.tpu.query.timeoutMs
    or df.collect(timeout=...)) and was cancelled at a checkpoint."""


class QueryShedError(QueryCancelledError):
    """The scheduler shed this query to protect higher classes under
    sustained overload (docs/serving.md "Load shedding"). Unwinds through
    the same cancel paths as any cancellation; the executor front door
    converts it into a :class:`QueryShed` RESULT carrying the retry-after
    hint — client code never sees this exception from collect()."""

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class QueryShed:
    """Typed load-shed RESULT (not an error): the query was unwound
    leak-free before completion; resubmit after ``retry_after_s``.
    Returned by df.collect()/to_arrow() in place of the row payload."""

    __slots__ = ("query", "session", "priority", "reason", "retry_after_s")

    def __init__(self, query: str, session: str, priority: str,
                 reason: str, retry_after_s: float):
        self.query = query
        self.session = session
        self.priority = priority
        self.reason = reason
        self.retry_after_s = float(retry_after_s)

    def __repr__(self) -> str:
        return (f"QueryShed(query={self.query!r}, session={self.session!r},"
                f" priority={self.priority!r}, reason={self.reason!r},"
                f" retry_after_s={self.retry_after_s:.3f})")


class QueryQueueFull(Exception):
    """Typed backpressure: the scheduler's bounded admission queue is full
    (spark.rapids.tpu.sched.maxQueuedQueries). The submission was rejected
    BEFORE any resource was acquired — resubmit later or shed load."""


class QueryContext:
    """One submitted query's lifecycle handle: cancel token + optional
    deadline + per-query retry budget. Owner discipline (TL020): created
    by the executor front door, used as a ``with`` context so the
    scheduler registration releases on every path."""

    def __init__(self, name: str, session_id: str = "default",
                 deadline_ns: Optional[int] = None,
                 retry_budget: int = 64,
                 priority: str = "interactive"):
        self.name = name
        self.session_id = session_id
        #: absolute time.perf_counter_ns() deadline, or None
        self.deadline_ns = deadline_ns
        #: SLO class (PRIORITIES); drives admission order and shed order
        self.priority = validate_priority(priority)
        self.state = QUEUED
        self.cancel_reason: Optional[str] = None
        #: retry-after hint set by QueryScheduler when this query is shed
        self.shed_retry_after_s: Optional[float] = None
        #: measured admission wait (ms), written at grant time — the
        #: bench serving stage reads it back per query
        self.admit_wait_ms: Optional[float] = None
        #: net HBM bytes charged by this query's bound threads (lock-free
        #: GIL adds, the metrics-cell idiom: a rare lost update is the
        #: standard monitoring tradeoff). The scheduler sums a tenant's
        #: live contexts against its quota at admission time.
        self.hbm_bytes = 0
        self._cancel = threading.Event()
        self._mu = threading.Lock()
        self._retry_budget = int(retry_budget)
        self._closed = False

    # --- cancellation -------------------------------------------------------
    def cancel(self, reason: str = "user") -> None:
        """Arm the cancel token (idempotent; first reason wins). The query
        keeps running until its next cooperative checkpoint observes the
        token — there is nothing safe to interrupt mid-dispatch."""
        with self._mu:
            if self._cancel.is_set() or self.state in _TERMINAL:
                return
            self.cancel_reason = reason
            if self.state == RUNNING:
                self.state = CANCELLING
        self._cancel.set()
        from ..obs import flight as _flight
        _flight.note("query.cancelling", query=self.name,
                     session=self.session_id, reason=reason)

    def shed(self, retry_after_s: float = 1.0,
             reason: str = "shed") -> None:
        """Arm the cancel token for LOAD SHEDDING: same cooperative
        machinery as cancel() (idempotent, observed at the next
        checkpoint, unwinds through the TL020-proven release paths) but
        the check raises QueryShedError so the front door can answer with
        a typed QueryShed result instead of an error."""
        with self._mu:
            if self._cancel.is_set() or self.state in _TERMINAL:
                return
            self.shed_retry_after_s = float(retry_after_s)
        self.cancel(reason=reason)

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def deadline_exceeded(self) -> bool:
        return (self.deadline_ns is not None
                and time.perf_counter_ns() >= self.deadline_ns)

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None when no deadline)."""
        if self.deadline_ns is None:
            return None
        return max(0.0, (self.deadline_ns - time.perf_counter_ns()) / 1e9)

    def check(self, boundary: str = "") -> None:
        """Raise if cancelled or past deadline — the cooperative
        cancellation point. Deadline expiry arms the cancel token too, so
        every other thread serving this query trips at ITS next check."""
        if self._cancel.is_set():
            if self.cancel_reason == "deadline":
                raise QueryDeadlineExceeded(
                    f"query {self.name} exceeded its deadline "
                    f"(observed at {boundary or 'checkpoint'})")
            if self.shed_retry_after_s is not None:
                raise QueryShedError(
                    f"query {self.name} ({self.priority}) shed by the "
                    f"scheduler ({self.cancel_reason}) at "
                    f"{boundary or 'checkpoint'}",
                    retry_after_s=self.shed_retry_after_s)
            raise QueryCancelledError(
                f"query {self.name} cancelled "
                f"({self.cancel_reason or 'unknown'}) "
                f"at {boundary or 'checkpoint'}")
        if self.deadline_exceeded():
            self.cancel(reason="deadline")
            raise QueryDeadlineExceeded(
                f"query {self.name} exceeded its deadline at "
                f"{boundary or 'checkpoint'}")

    # --- retry budget -------------------------------------------------------
    def consume_retry(self) -> bool:
        """Take one unit of the per-query transient-retry budget
        (spark.rapids.tpu.query.retryBudget). False = exhausted: the
        caller fails THIS query instead of retrying — one flapping query
        cannot sit in retry loops starving the shared pool."""
        with self._mu:
            if self._retry_budget <= 0:
                return False
            self._retry_budget -= 1
            return True

    # --- state machine ------------------------------------------------------
    def mark_running(self) -> None:
        with self._mu:
            if self.state == QUEUED:
                self.state = RUNNING

    def finish(self, exc: Optional[BaseException] = None) -> str:
        """Record the terminal state from the execution outcome."""
        with self._mu:
            if self.state in _TERMINAL:
                return self.state
            if exc is None:
                self.state = FINISHED
            elif isinstance(exc, QueryDeadlineExceeded):
                self.state = TIMED_OUT
            elif isinstance(exc, QueryShedError):
                self.state = SHED
            elif isinstance(exc, QueryCancelledError):
                self.state = CANCELLED
            else:
                self.state = FAILED
            return self.state

    # --- ownership (TL020) --------------------------------------------------
    def close(self) -> None:
        """Deregister from the scheduler's active-query index (idempotent).
        A context that dies unregistered would keep session.cancel() and
        the postmortem's queued/running listing lying forever."""
        if self._closed:
            return
        self._closed = True
        from .scheduler import QueryScheduler
        sched = QueryScheduler._instance
        if sched is not None:
            sched._deregister(self)

    def __enter__(self) -> "QueryContext":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.finish(exc)
        self.close()


# --- thread binding (the sync-ledger idiom) ---------------------------------

_TL = threading.local()


@contextlib.contextmanager
def bind(qctx: Optional[QueryContext]) -> Iterator[None]:
    """Bind `qctx` to the calling thread for the scope (None = keep the
    current binding — pool handoffs pass whatever they captured)."""
    prev = getattr(_TL, "q", None)
    _TL.q = qctx if qctx is not None else prev
    try:
        yield
    finally:
        _TL.q = prev


def current() -> Optional[QueryContext]:
    return getattr(_TL, "q", None)


def checkpoint(boundary: str = "") -> None:
    """The cooperative cancellation point, called at every pre-existing
    task boundary. Unbound thread: one thread-local read, nothing else.
    Bound: the chaos `query.cancel` site fires first (so a seeded soak can
    race a cancellation against this exact boundary), then the context's
    cancel/deadline check."""
    q = getattr(_TL, "q", None)
    if q is None:
        return
    from ..chaos import inject
    inject("query.cancel", detail=boundary)
    q.check(boundary)


def consume_retry_budget() -> bool:
    """failure.with_device_retry's hook: True when no query is bound (the
    per-site attempt bound still applies) or budget remains."""
    q = getattr(_TL, "q", None)
    return True if q is None else q.consume_retry()


def charge_hbm(nbytes: int) -> None:
    """HbmBudget.allocate's attribution hook: charge device bytes to the
    query bound on the allocating thread (no-op unbound — pool warm-up,
    session caches). Per-tenant quota admission sums the tenant's live
    contexts' net charges (docs/serving.md "Per-tenant HBM quotas")."""
    q = getattr(_TL, "q", None)
    if q is not None:
        q.hbm_bytes += nbytes


def release_hbm(nbytes: int) -> None:
    """HbmBudget.free's hook: un-charge bytes freed on a bound thread.
    Frees on UNBOUND threads (MemoryCleaner, session teardown) are not
    attributable; the residue disappears when the context closes — quota
    accounting is admission-time and per-live-query by design, so the
    skew is bounded by one query's lifetime."""
    q = getattr(_TL, "q", None)
    if q is not None:
        q.hbm_bytes = max(0, q.hbm_bytes - nbytes)
