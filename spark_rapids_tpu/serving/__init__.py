"""serving: the multi-tenant query lifecycle layer (docs/robustness.md
"Query lifecycle").

Two modules implement the "columnar compute service" shape (SURVEY §7 —
many session frontends, ONE device owner):

* :mod:`.query_context` — per-query lifecycle state: a ``QueryContext``
  carries the cancel token, the optional deadline and the per-query
  transient-retry budget, bound to the executing thread(s) the same way
  the sync ledger and the per-query tracer are. Cooperative cancellation
  ``checkpoint()`` calls sit at every task boundary that already exists
  (partition-task start in the session loop, batch pull in execs/base.py,
  exchange map task and reduce fetch in shuffle/exchange.py, collective
  launch in parallel/mesh.py, the UDF worker round-trip) and unwind
  through the TL020-proven release paths, so a cancelled or timed-out
  query returns ALL permits, HBM, spill files and its tracer to baseline.
* :mod:`.scheduler` — the process-wide ``QueryScheduler`` (HBM admission
  control, bounded FIFO queue with round-robin fairness across sessions,
  typed ``QueryQueueFull`` backpressure) plus the executor service the
  per-partition driving loop moved into from ``session.py``
  (:func:`~.scheduler.execute_plan`).

This package deliberately keeps ``query_context`` import-light (no
engine imports at module scope): hot paths in ``execs/base.py`` import
it at module load, and the scheduler — which does import the exec layer —
is only pulled in lazily by the session front door.
"""

from .query_context import (QueryCancelledError, QueryContext,
                            QueryDeadlineExceeded, QueryQueueFull, bind,
                            checkpoint, consume_retry_budget, current)

__all__ = [
    "QueryCancelledError", "QueryContext", "QueryDeadlineExceeded",
    "QueryQueueFull", "bind", "checkpoint", "consume_retry_budget",
    "current",
]
