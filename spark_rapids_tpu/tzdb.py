"""Timezone transition tables as device arrays.

Reference: GpuTimeZoneDB (spark-rapids-jni) + TimeZoneDB.scala — the reference
loads java.time zone rules into GPU-resident transition tables so timestamp
ops run on device for any timezone. Here the tables come straight from the
system TZif files (/usr/share/zoneinfo, RFC 8536): one sorted vector of
transition instants and one of UTC offsets, and the conversion kernels are a
`searchsorted` plus a gather — pure XLA.

Semantics match java.time resolution (what Spark uses):
  * UTC→local: offset of the interval containing the instant.
  * local→UTC: ambiguous wall times (DST fall-back overlap) take the EARLIER
    offset; skipped wall times (spring-forward gap) resolve with the
    pre-transition offset, which shifts them forward by the gap — both are
    java.time ZonedDateTime.of's documented behavior.

Instants beyond the last explicit transition use the final offset; TZif v2+
files carry transitions far into the future (typically ≥2037), and the POSIX
footer rule beyond that is intentionally not modeled (tagging keeps such
extrapolation on the host oracle's zoneinfo path in tests).
"""

from __future__ import annotations

import os
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

MICROS = 1_000_000
_TZDIRS = ("/usr/share/zoneinfo", "/usr/lib/zoneinfo", "/etc/zoneinfo")

_UTC_NAMES = {"UTC", "GMT", "Etc/UTC", "Etc/GMT", "Z", "+00:00", "UTC+00:00"}


def is_utc(tz: Optional[str]) -> bool:
    return tz is None or tz in _UTC_NAMES


def _parse_tzif(raw: bytes) -> Tuple[np.ndarray, np.ndarray]:
    """TZif bytes → (transition instants [s], offsets [s], len n and n+1)."""

    def parse_block(buf, off, time_size, time_fmt):
        (isutcnt, isstdcnt, leapcnt, timecnt, typecnt,
         charcnt) = struct.unpack_from(">6I", buf, off + 20)
        p = off + 44
        trans = np.frombuffer(buf, dtype=np.dtype(time_fmt).newbyteorder(">"),
                              count=timecnt, offset=p).astype(np.int64)
        p += timecnt * time_size
        idx = np.frombuffer(buf, dtype=np.uint8, count=timecnt, offset=p)
        p += timecnt
        utoffs = np.empty(typecnt, np.int64)
        for i in range(typecnt):
            utoff, _isdst, _abbr = struct.unpack_from(">iBB", buf, p + 6 * i)
            utoffs[i] = utoff
        p += typecnt * 6 + charcnt + leapcnt * (time_size + 4)
        p += (isstdcnt + isutcnt)
        return trans, idx, utoffs, p

    assert raw[:4] == b"TZif", "not a TZif file"
    version = raw[4:5]
    trans, idx, utoffs, end = parse_block(raw, 0, 4, np.int32)
    if version in (b"2", b"3", b"4") and raw[end:end + 4] == b"TZif":
        trans, idx, utoffs, _ = parse_block(raw, end, 8, np.int64)
    if len(trans) == 0:
        base = utoffs[0] if len(utoffs) else 0
        return (np.zeros(0, np.int64), np.array([base], np.int64))
    # offsets[0] = pre-first-transition offset (the first non-DST type per
    # RFC 8536 §3.2 guidance; fall back to type of the first transition)
    first = utoffs[idx[0]] if len(idx) else utoffs[0]
    offsets = np.concatenate([[first], utoffs[idx]])
    return trans, offsets


class TimeZoneDB:
    """Loaded transition table for one zone; arrays are numpy host-side and
    upload lazily as jax constants inside the conversion kernels."""

    _cache: Dict[str, Optional["TimeZoneDB"]] = {}
    _lock = threading.Lock()

    def __init__(self, name: str, trans_s: np.ndarray, offsets_s: np.ndarray):
        self.name = name
        self.trans_micros = trans_s * MICROS
        self.offsets_micros = offsets_s * MICROS
        # wall-clock start of each interval i>=1 (used by local→UTC)
        if len(trans_s):
            self.local_starts_micros = (trans_s + offsets_s[1:]) * MICROS
            self.prev_local_ends_micros = (trans_s + offsets_s[:-1]) * MICROS
        else:
            self.local_starts_micros = np.zeros(0, np.int64)
            self.prev_local_ends_micros = np.zeros(0, np.int64)

    @classmethod
    def get(cls, tz: Optional[str]) -> Optional["TimeZoneDB"]:
        """Load (cached); None when the zone has no TZif file."""
        if tz is None:
            return None
        with cls._lock:
            if tz in cls._cache:
                return cls._cache[tz]
            db = None
            for d in _TZDIRS:
                p = os.path.join(d, tz)
                if os.path.isfile(p):
                    try:
                        with open(p, "rb") as f:
                            trans, offsets = _parse_tzif(f.read())
                        db = cls(tz, trans, offsets)
                    except Exception:  # noqa: BLE001 — unparseable file
                        db = None
                    break
            cls._cache[tz] = db
            return db

    # ---- device kernels --------------------------------------------------
    def utc_to_local(self, micros):
        """UTC micros → wall-clock micros in this zone (jax)."""
        import jax.numpy as jnp
        if len(self.trans_micros) == 0:
            return micros + int(self.offsets_micros[0])
        trans = jnp.asarray(self.trans_micros)
        offs = jnp.asarray(self.offsets_micros)
        k = jnp.searchsorted(trans, micros, side="right")
        return micros + offs[k]

    def local_to_utc(self, local_micros):
        """Wall-clock micros → UTC micros with java.time gap/overlap rules."""
        import jax.numpy as jnp
        if len(self.trans_micros) == 0:
            return local_micros - int(self.offsets_micros[0])
        starts = jnp.asarray(self.local_starts_micros)
        prev_ends = jnp.asarray(self.prev_local_ends_micros)
        offs = jnp.asarray(self.offsets_micros)
        k = jnp.searchsorted(starts, local_micros, side="right")
        # overlap: the wall time also exists in interval k-1 → earlier offset
        ambiguous = (k >= 1) & (local_micros <
                                prev_ends[jnp.clip(k - 1, 0, len(self.trans_micros) - 1)])
        k = jnp.where(ambiguous, k - 1, k)
        return local_micros - offs[k]

    # ---- host mirrors (oracle/parity paths) ------------------------------
    def utc_to_local_np(self, micros: np.ndarray) -> np.ndarray:
        if len(self.trans_micros) == 0:
            return micros + int(self.offsets_micros[0])
        k = np.searchsorted(self.trans_micros, micros, side="right")
        return micros + self.offsets_micros[k]

    def local_to_utc_np(self, local_micros: np.ndarray) -> np.ndarray:
        if len(self.trans_micros) == 0:
            return local_micros - int(self.offsets_micros[0])
        k = np.searchsorted(self.local_starts_micros, local_micros,
                            side="right")
        amb = (k >= 1) & (local_micros <
                          self.prev_local_ends_micros[
                              np.clip(k - 1, 0, len(self.trans_micros) - 1)])
        k = np.where(amb, k - 1, k)
        return local_micros - self.offsets_micros[k]
