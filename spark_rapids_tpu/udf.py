"""User-defined functions: columnar TPU UDFs, Arrow/pandas UDFs, row fallback.

Reference (§2.8): RapidsUDF.evaluateColumnar (sql-plugin-api/.../RapidsUDF.java:22
— user code receives device columns), GpuArrowEvalPythonExec + Pandas UDFs
(Arrow exchange with python workers), and GpuRowBasedScalaUDF (row-at-a-time
CPU lambda over accelerator-resident data, GpuScalaUDF.scala:94).

TPU mapping:
  * tpu_udf      — the RapidsUDF analogue: the user function receives jax
    arrays (data, validity) per argument and returns (data, validity); it runs
    inside the device plan and XLA fuses it with the surrounding projection.
  * pandas_udf   — receives pyarrow arrays on host (the Arrow-exchange path);
    no separate worker process is needed because we're already in python — the
    PythonWorkerSemaphore concern collapses away.
  * udf          — row-at-a-time python fallback (GpuRowBasedScalaUDF analogue).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from .columnar.vector import TpuColumnVector, row_mask
from .expressions.base import (EvalContext, Expression, _DEFAULT_CTX,
                               combine_validity, device_parts, make_column,
                               to_column)
from .types import DataType


class TpuColumnarUDF(Expression):
    """RapidsUDF analogue: fn(*(data, validity) jax arrays) -> (data, validity)."""

    def __init__(self, fn: Callable, return_type: DataType,
                 children: Sequence[Expression], name: str = "tpu_udf"):
        self.children = tuple(children)
        self.fn = fn
        self._dtype = return_type
        self._name = name

    @property
    def dtype(self) -> DataType:
        return self._dtype

    def pretty(self) -> str:
        return f"{self._name}({', '.join(c.pretty() for c in self.children)})"

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        cap = batch.capacity
        args = []
        for c in self.children:
            col = to_column(c.eval_tpu(batch, ctx), batch, c.dtype)
            args.append((col.data, col.validity_or_true()))
        data, validity = self.fn(*args)
        valid = combine_validity(cap, validity, row_mask(batch.num_rows, cap))
        return make_column(self._dtype, data, valid, batch.num_rows)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        """CPU path re-uses the jax fn on host arrays (jax runs on CPU too) —
        the UDF contract is hardware-portable by construction."""
        import jax.numpy as jnp
        import pyarrow as pa
        from .types import to_arrow
        n = table.num_rows if table is not None else 0
        args = []
        for c in self.children:
            arr = c.eval_cpu(table, ctx)
            col = TpuColumnVector.from_arrow(
                arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr)
            args.append((col.data, col.validity_or_true()))
        data, validity = self.fn(*args)
        vals = np.asarray(data)[:n]
        mask = None
        if validity is not None:
            mask = ~np.asarray(validity)[:n]
        return pa.array(vals, type=to_arrow(self._dtype), mask=mask)


class ArrowPandasUDF(Expression):
    """pandas_udf analogue: fn(*pyarrow.Array) -> pyarrow.Array (host)."""

    tpu_supported = True  # runs host-side inside a TPU plan (host-assisted)

    def __init__(self, fn: Callable, return_type: DataType,
                 children: Sequence[Expression], name: str = "pandas_udf"):
        self.children = tuple(children)
        self.fn = fn
        self._dtype = return_type
        self._name = name

    @property
    def dtype(self) -> DataType:
        return self._dtype

    def pretty(self) -> str:
        return f"{self._name}({', '.join(c.pretty() for c in self.children)})"

    def _call(self, arrays):
        import pyarrow as pa
        from .types import to_arrow
        out = self.fn(*arrays)
        if not isinstance(out, (pa.Array, pa.ChunkedArray)):
            out = pa.array(out, type=to_arrow(self._dtype))
        return out.cast(to_arrow(self._dtype))

    def eval_tpu(self, batch, ctx=_DEFAULT_CTX):
        from .columnar.batch import _repad
        args = [to_column(c.eval_tpu(batch, ctx), batch, c.dtype).to_arrow()
                for c in self.children]
        out = self._call_maybe_worker(args, ctx)
        col = TpuColumnVector.from_arrow(out)
        if col.capacity != batch.capacity:
            col = _repad(col, batch.capacity)
        return col

    def _call_maybe_worker(self, args, ctx):
        """Ship to a worker process when the pool is configured and the fn
        pickles; in-process otherwise (reference: worker pool vs row-based
        CPU fallback wrappers)."""
        from .config import (CONCURRENT_PYTHON_WORKERS, PYTHON_UDF_WORKERS,
                             UDF_WORKER_TIMEOUT_SECONDS)
        from .types import to_arrow
        n_workers = ctx.conf.get(PYTHON_UDF_WORKERS)
        if n_workers and n_workers > 0:
            from .udf_workers import get_pool, try_pickle
            blob = try_pickle(self.fn)
            if blob is not None:
                permits = ctx.conf.get(CONCURRENT_PYTHON_WORKERS) or None
                pool = get_pool(n_workers, permits)
                out = pool.run(
                    blob, args,
                    timeout=float(ctx.conf.get(UDF_WORKER_TIMEOUT_SECONDS)))
                return out.cast(to_arrow(self._dtype))
        return self._call(args)

    def eval_cpu(self, table, ctx=_DEFAULT_CTX):
        import pyarrow as pa
        args = []
        for c in self.children:
            a = c.eval_cpu(table, ctx)
            args.append(a.combine_chunks() if isinstance(a, pa.ChunkedArray) else a)
        return self._call(args)


class RowPythonUDF(ArrowPandasUDF):
    """Row-at-a-time python UDF (GpuRowBasedScalaUDF analogue): wraps the row
    lambda into an arrow-batch evaluator."""

    def __init__(self, fn: Callable, return_type: DataType,
                 children: Sequence[Expression], name: str = "udf"):
        self.row_fn = fn  # kept for the UDF compiler (udf_compiler.py)

        def batch_fn(*arrays):
            import pyarrow as pa
            from .types import to_arrow
            cols = [a.to_pylist() for a in arrays]
            out = [fn(*row) for row in zip(*cols)] if cols else []
            return pa.array(out, type=to_arrow(return_type))

        super().__init__(batch_fn, return_type, children, name)


def tpu_udf(return_type, name: str = "tpu_udf"):
    """Decorator: columnar device UDF over (data, validity) jax-array pairs."""
    from .session import Column, _expr, _type_from_string
    rt = _type_from_string(return_type) if isinstance(return_type, str) else return_type

    def wrap(fn: Callable):
        def call(*cols) -> Column:
            return Column(TpuColumnarUDF(fn, rt, [_expr(c) for c in cols],
                                         getattr(fn, "__name__", name)))
        call.__name__ = getattr(fn, "__name__", name)
        return call

    return wrap


def pandas_udf(return_type, name: str = "pandas_udf"):
    from .session import Column, _expr, _type_from_string
    rt = _type_from_string(return_type) if isinstance(return_type, str) else return_type

    def wrap(fn: Callable):
        def call(*cols) -> Column:
            return Column(ArrowPandasUDF(fn, rt, [_expr(c) for c in cols],
                                         getattr(fn, "__name__", name)))
        call.__name__ = getattr(fn, "__name__", name)
        return call

    return wrap


def udf(fn=None, returnType="string"):
    """pyspark.sql.functions.udf-compatible row UDF."""
    from .session import Column, _expr, _type_from_string
    rt = _type_from_string(returnType) if isinstance(returnType, str) else returnType

    def wrap(f: Callable):
        def call(*cols) -> Column:
            return Column(RowPythonUDF(f, rt, [_expr(c) for c in cols],
                                       getattr(f, "__name__", "udf")))
        return call

    if fn is not None:
        return wrap(fn)
    return wrap
