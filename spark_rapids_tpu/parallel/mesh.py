"""Mesh context + the collective (ICI) data plane for the exec-layer shuffle.

This is the framework integration of the UCX-mode shuffle (SURVEY.md §2.7:
shuffle-plugin/ UCXShuffleTransport.scala, RapidsShuffleInternalManagerBase.
scala:238): when a jax.sharding.Mesh is configured, `TpuShuffleExchangeExec`
routes its hash exchange through ONE jitted `shard_map` program whose
`lax.all_to_all` moves every column's rows between shards over the
interconnect — XLA schedules the ICI transfers that the reference hand-codes
as UCX transactions. The exchange is collective: all map inputs are sharded
row-wise over the mesh, re-bucketed by murmur3(key) % n_shards on-device, and
each shard receives exactly its reduce partition.

Static-shape strategy (XLA cannot size buffers data-dependently):
  1. partition ids are computed per shard-group batch with the normal
     expression path (shuffle/partitioner.py);
  2. ONE host sync reads the per-(shard, dest) counts and picks a bucketed
     slot capacity — the analogue of the reference sizing contiguousSplit
     slices before handing them to the transport;
  3. the jitted exchange scatters rows into [n_shards, slot_cap] send
     buffers and `all_to_all`s them; receive-validity rides along.
Compiled programs are cached by (mesh, capacity, slot_cap, column dtypes) so
steady-state queries reuse one executable.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.batch import TpuColumnarBatch, _repad, compact
from ..columnar.vector import TpuColumnVector, bucket_capacity, row_mask
from ..config import MESH_ENABLED, MESH_SIZE

_AXIS = "data"


class MeshContext:
    """Process-wide mesh handle (the TPU analogue of the executor's device
    topology discovered via the shuffle heartbeat, Plugin.scala:436-447)."""

    _lock = threading.Lock()
    _meshes: Dict[int, Mesh] = {}

    @classmethod
    def get(cls, conf, n: Optional[int] = None) -> Optional[Mesh]:
        """Mesh of exactly `n` devices (default: the configured/maximum
        size); None when disabled or the topology is too small."""
        if not conf.get(MESH_ENABLED):
            return None
        limit = conf.get(MESH_SIZE)
        devs = jax.devices()
        avail = min(limit, len(devs)) if limit else len(devs)
        n = n if n is not None else avail
        if n > avail or n < 2:
            return None
        with cls._lock:
            if n not in cls._meshes:
                cls._meshes[n] = Mesh(np.array(devs[:n]), (_AXIS,))
            return cls._meshes[n]

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._meshes = {}


def mesh_eligible_output(output) -> bool:
    """Static (plan-time) eligibility: every column must have a fixed-width
    device layout for the all_to_all to carry it. Strings/nested fall back to
    the in-process catalog path until the ragged device layout lands."""
    from ..columnar.vector import device_layout_ok
    from ..types import is_fixed_width
    return all(is_fixed_width(a.dtype) and device_layout_ok(a.dtype)
               for a in output)


# compiled exchange cache: (mesh, cap, slot_cap, col sig) -> jitted fn
_EXCHANGE_CACHE: Dict[Tuple, "jax.stages.Wrapped"] = {}


def _build_exchange(mesh: Mesh, n_dev: int, slot_cap: int,
                    sig: Tuple[Tuple[str, bool], ...]):
    """One jitted shard_map program moving `len(sig)` columns + validity via
    all_to_all. `sig` is ((dtype_str, has_validity), ...)."""
    key = (mesh, n_dev, slot_cap, sig)
    fn = _EXCHANGE_CACHE.get(key)
    if fn is not None:
        return fn

    n_cols = len(sig)

    def exchange(dest, *flat):
        # per-shard local views: dest [cap], columns/validities [cap]
        cap = dest.shape[0]
        order = jnp.argsort(dest, stable=True)
        sorted_dest = jnp.take(dest, order)
        idx = jnp.arange(cap, dtype=jnp.int32)
        one = jnp.ones((cap,), jnp.int32)
        run_start = jnp.zeros((n_dev + 2,), jnp.int32).at[
            sorted_dest + 1].add(one, mode="drop")
        starts = jnp.cumsum(run_start)[:-1]
        pos_in_bucket = idx - jnp.take(starts, sorted_dest)
        live = sorted_dest < n_dev
        keep = live & (pos_in_bucket < slot_cap)
        send_slot = jnp.where(keep, sorted_dest * slot_cap + pos_in_bucket,
                              n_dev * slot_cap)

        def a2a(x):
            x = x.reshape(n_dev, slot_cap)
            return jax.lax.all_to_all(x, _AXIS, split_axis=0, concat_axis=0,
                                      tiled=False).reshape(-1)

        def scatter_send(x, fill, dt):
            buf = jnp.full((n_dev * slot_cap,), fill, dt).at[send_slot].set(
                jnp.take(x, order), mode="drop")
            return a2a(buf)

        rowok = a2a(jnp.zeros((n_dev * slot_cap,), jnp.bool_).at[
            send_slot].set(keep, mode="drop"))
        outs = [rowok]
        datas = flat[:n_cols]
        valids = flat[n_cols:]
        for (dt, has_v), d, v in zip(sig, datas, valids):
            outs.append(scatter_send(d, 0, d.dtype))
            if has_v:
                outs.append(scatter_send(v, False, jnp.bool_))
        return tuple(outs)

    from .distributed import shard_map
    spec = P(_AXIS)
    n_valid = sum(1 for _, has_v in sig if has_v)
    in_specs = tuple([spec] * (1 + 2 * n_cols))
    out_specs = tuple([spec] * (1 + n_cols + n_valid))
    fn = jax.jit(shard_map(exchange, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, check_rep=False))
    _EXCHANGE_CACHE[key] = fn
    return fn


def mesh_hash_exchange(mesh: Mesh, group_batches: List[Optional[TpuColumnarBatch]],
                       pids_list: List[Optional[jnp.ndarray]],
                       names: Sequence[str]) -> List[TpuColumnarBatch]:
    """Collective hash exchange: `group_batches[d]` is the (possibly empty)
    concatenated map input assigned to shard d, `pids_list[d]` its
    destination-partition ids. Returns one compacted device batch per reduce
    partition (= per shard)."""
    n_dev = mesh.devices.size
    assert len(group_batches) == n_dev
    ref = next(b for b in group_batches if b is not None)
    dtypes = [c.dtype for c in ref.columns]
    cap = bucket_capacity(max([b.capacity for b in group_batches
                               if b is not None] + [1]))

    # per-(shard, dest) counts -> slot capacity (ONE host sync for all
    # shards' pid arrays; a per-shard np.asarray loop would pay one round
    # trip each on high-latency links)
    live = [(b, p) for b, p in zip(group_batches, pids_list)
            if b is not None and b.num_rows]
    fetched = jax.device_get([p for _b, p in live]) if live else []
    max_count = 1
    for (b, _p), pids_np in zip(live, fetched):
        counts = np.bincount(pids_np[: b.num_rows], minlength=n_dev)
        max_count = max(max_count, int(counts.max()))
    slot_cap = bucket_capacity(max_count)

    # stack per-shard arrays into globally sharded [n_dev * cap] inputs
    sharding = NamedSharding(mesh, P(_AXIS))
    sig = []
    col_data: List[List[jnp.ndarray]] = []
    col_valid: List[List[jnp.ndarray]] = []
    has_valid = [any(b is not None and b.columns[i].validity is not None
                     for b in group_batches)
                 for i in range(len(dtypes))]
    for i, dt in enumerate(dtypes):
        carrier = ref.columns[i].data.dtype
        sig.append((str(carrier), has_valid[i]))
        datas, valids = [], []
        for b in group_batches:
            if b is None:
                datas.append(jnp.zeros((cap,), carrier))
                valids.append(jnp.zeros((cap,), jnp.bool_))
            else:
                c = _repad(b.columns[i], cap)
                datas.append(c.data)
                valids.append(c.validity if c.validity is not None
                              else row_mask(b.num_rows, cap))
        col_data.append(datas)
        col_valid.append(valids)
    dests = []
    for b, pids in zip(group_batches, pids_list):
        if b is None or not b.num_rows:
            dests.append(jnp.full((cap,), n_dev, jnp.int32))
        else:
            p = jnp.asarray(pids)[:cap].astype(jnp.int32)
            if p.shape[0] < cap:
                p = jnp.concatenate(
                    [p, jnp.full((cap - p.shape[0],), n_dev, jnp.int32)])
            dests.append(jnp.where(row_mask(b.num_rows, cap), p, n_dev))

    def shard(arrs):
        return jax.device_put(jnp.concatenate(arrs), sharding)

    dest_g = shard(dests)
    flat = [shard(col_data[i]) for i in range(len(dtypes))] + \
           [shard(col_valid[i]) for i in range(len(dtypes))]
    fn = _build_exchange(mesh, n_dev, slot_cap, tuple(sig))
    outs = fn(dest_g, *flat)
    rowok = outs[0]
    pos = 1
    recv_data: List[jnp.ndarray] = []
    recv_valid: List[Optional[jnp.ndarray]] = []
    for i in range(len(dtypes)):
        recv_data.append(outs[pos])
        pos += 1
        if has_valid[i]:
            recv_valid.append(outs[pos])
            pos += 1
        else:
            recv_valid.append(None)

    # slice per shard, compact out the slot gaps
    local = n_dev * slot_cap
    results: List[TpuColumnarBatch] = []
    for r in range(n_dev):
        sl = slice(r * local, (r + 1) * local)
        ok = rowok[sl]
        cols = []
        for i, dt in enumerate(dtypes):
            v = recv_valid[i][sl] if recv_valid[i] is not None else None
            cols.append(TpuColumnVector(dt, recv_data[i][sl], v, local))
        batch = TpuColumnarBatch(cols, local, list(names))
        results.append(compact(batch, ok))
    return results
