"""Mesh context + the collective (ICI) data plane for the exec-layer shuffle.

This is the framework integration of the UCX-mode shuffle (SURVEY.md §2.7:
shuffle-plugin/ UCXShuffleTransport.scala, RapidsShuffleInternalManagerBase.
scala:238): when a jax.sharding.Mesh is configured, `TpuShuffleExchangeExec`
routes its exchange through ONE jitted `shard_map` program whose
`lax.all_to_all` moves every column's rows between shards over the
interconnect — XLA schedules the ICI transfers that the reference hand-codes
as UCX transactions. The exchange is collective: all map inputs are sharded
row-wise over the mesh, re-bucketed by murmur3(key) % n_shards on-device
(hash partitioning) or funneled to shard 0 (single partitioning — the
partial→final aggregation / global-limit merge funnel), and each shard
receives exactly its reduce partition.

Static-shape strategy (XLA cannot size buffers data-dependently):
  1. partition ids are computed per shard-group batch with the normal
     expression path (shuffle/partitioner.py);
  2. ONE audited host sync reads the per-(shard, dest) counts and picks a
     bucketed slot capacity — the analogue of the reference sizing
     contiguousSplit slices before handing them to the transport. The SAME
     counts are the exchange's device-side partition statistics: exact
     per-reduce AND per-source row/byte sizes are known at exchange time,
     so AQE planning (`partition_sizes`, skew `map_block_sizes`) never
     re-fetches blocks;
  3. the jitted exchange scatters rows into [n_shards, slot_cap] send
     buffers, `all_to_all`s them, and — because the per-source counts are
     host-known — FUSES the post-collective compact into the same program:
     received slot (src s, pos p) scatters straight to its final row
     `bases[s] + p` (`bases` = exclusive cumsum of this shard's receive
     counts), reproducing bit-for-bit the (src asc, stable) order the old
     host-side compact produced, with zero host round-trips. The per-reduce
     output blocks leave the program replicated, so downstream consumers
     mix blocks freely.

Staging is donation-friendly: the concatenated global inputs are DONATED
to the exchange program (`donate_argnums`, gated off on the CPU backend
exactly like execs/opjit._donate) so XLA reuses their HBM for the outputs,
and constant pad pieces (empty-shard columns, destination fills) come from
a small process-wide staging pool keyed by (kind, capacity, dtype, fill) —
`mesh.staging_reuse_hits` counts the copies that no longer happen.

Exchange/compute overlap (`spark.rapids.tpu.exchange.overlap.*`, default
OFF — correctness first): the payload splits into K segments along the
slot axis; segment k+1's all_to_all is in flight while the fused compact
consumes segment k into donated accumulators. Every segment scatters to
the SAME final row positions the unsegmented program uses, so results are
bit-identical at any K. Chaos `mesh.link` fires per segment
(`detail="s<id>seg<k>"`); a mid-segment fault abandons the donated
accumulators and the caller's with_device_retry re-stages from the still-
open spillables, so no donated buffer is ever applied twice.

Compiled programs are cached by (mesh, capacity, slot_cap, column dtypes)
so steady-state queries reuse one executable. Every exchange lands in the
process-wide dispatch accounting as ONE kind "mesh_collective" launch
(`opjit.record_external_dispatch`) — O(exchanges) regardless of overlap;
segment launches count separately under "mesh_overlap_segment" — and,
when the query tracer is armed, inside a `mesh.exchange` span carrying the
per-chip send-row breakdown and the stage/launch/wait timing split
(docs/observability.md).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.batch import TpuColumnarBatch, _repad
from ..columnar.vector import (TpuColumnVector, audited_device_get,
                               bucket_capacity, row_mask)
from ..config import (EXCHANGE_OVERLAP_ENABLED, EXCHANGE_OVERLAP_MIN_ROWS,
                      EXCHANGE_OVERLAP_SEGMENTS, MESH_ENABLED, MESH_SIZE,
                      SHUFFLE_MODE)
from ..obs import tracer as obs

_AXIS = "data"


class MeshContext:
    """Process-wide mesh handle (the TPU analogue of the executor's device
    topology discovered via the shuffle heartbeat, Plugin.scala:436-447)."""

    _lock = threading.Lock()
    _meshes: Dict[int, Mesh] = {}

    @classmethod
    def get(cls, conf, n: Optional[int] = None) -> Optional[Mesh]:
        """Mesh of exactly `n` devices (default: the configured/maximum
        size); None when disabled or the topology is too small."""
        if not conf.get(MESH_ENABLED):
            return None
        limit = conf.get(MESH_SIZE)
        devs = jax.devices()
        avail = min(limit, len(devs)) if limit else len(devs)
        n = n if n is not None else avail
        if n > avail or n < 2:
            return None
        with cls._lock:
            if n not in cls._meshes:
                cls._meshes[n] = Mesh(np.array(devs[:n]), (_AXIS,))
            return cls._meshes[n]

    @classmethod
    def reset_for_tests(cls) -> None:
        with cls._lock:
            cls._meshes = {}
        reset_staging_pool()


def mesh_session_active(conf) -> Optional[Mesh]:
    """The mesh this session's PLANNER should target, or None. A mesh
    session is active when the mesh is enabled, the shuffle mode is ICI
    (the collective commits device-resident blocks to the ICI catalog) and
    the topology offers >= 2 devices — the condition under which
    plan/overrides.py selects the collective exchange and aligns hash
    partition counts to the mesh."""
    if str(conf.get(SHUFFLE_MODE)).upper() != "ICI":
        return None
    return MeshContext.get(conf)


def collective_payload(output, conf) -> Optional[str]:
    """Payload classification for the collective data plane (shared by the
    planner's exchange selection and the runtime eligibility check):

    * ``"fixed"`` — every column has a fixed-width device layout; the
      all_to_all carries the raw buffers;
    * ``"dict"`` — the variable-width columns are all strings/binary
      (offsets+bytes device layout): they ride as int32 dictionary codes
      plus one broadcast dictionary per exchange
      (``spark.rapids.tpu.exchange.dictionaryEncode.enabled``), the TPU
      analogue of the reference's compressed shuffle batches;
    * ``None`` — nested or host-only payloads: per-map path.
    """
    from ..columnar.vector import device_layout_ok
    from ..config import EXCHANGE_DICT_ENCODE_ENABLED
    from ..types import BinaryType, StringType, is_fixed_width
    has_var = False
    for a in output:
        if is_fixed_width(a.dtype) and device_layout_ok(a.dtype):
            continue
        if isinstance(a.dtype, (StringType, BinaryType)):
            has_var = True
            continue
        return None
    if not has_var:
        return "fixed"
    return "dict" if conf.get(EXCHANGE_DICT_ENCODE_ENABLED) else None


# compiled exchange cache: (mesh, cap, slot_cap, col sig) -> jitted fn.
# Guarded: collective exchanges can materialize from concurrent query
# threads (TL010 — same discipline as the opjit executable cache).
_CACHE_LOCK = threading.Lock()
_EXCHANGE_CACHE: Dict[Tuple, object] = {}

# staging pool: constant pad pieces (empty-shard columns, destination
# fills) keyed by (kind, capacity, dtype, fill). jax.Arrays are immutable
# and the pieces feed jnp.concatenate (which copies into the donated
# global input), so pooling them is safe even though the concatenated
# staging buffer itself is donated to the exchange program.
_POOL_LOCK = threading.Lock()
_STAGING_POOL: Dict[Tuple, jax.Array] = {}
_STAGING_POOL_MAX = 256


def _pooled_fill(kind: str, cap: int, dtype, fill) -> Tuple[jax.Array, int]:
    """A pooled constant array (cap,) of `fill`; returns (array, hit)."""
    key = (kind, int(cap), str(jnp.dtype(dtype)), fill)
    with _POOL_LOCK:
        arr = _STAGING_POOL.get(key)
    if arr is not None:
        return arr, 1
    arr = jnp.full((cap,), fill, dtype)
    with _POOL_LOCK:
        if len(_STAGING_POOL) < _STAGING_POOL_MAX:
            _STAGING_POOL[key] = arr
    return arr, 0


def reset_staging_pool() -> None:
    with _POOL_LOCK:
        _STAGING_POOL.clear()


def _donate(positions: Iterable[int]) -> Tuple[int, ...]:
    """Buffer-donation argnums for the staged collective inputs: XLA may
    reuse their HBM for the program's outputs instead of allocating fresh
    buffers. The CPU backend does not implement donation (it warns and
    copies) — same gate as execs/opjit._donate. Donated staging is never
    retried in place: a faulted exchange re-stages from the spillables
    (with_device_retry around run_collective), so a donated buffer is
    consumed at most once."""
    return tuple(positions) if jax.default_backend() != "cpu" else ()


# collective-launch statistics (bench MULTICHIP stage + the O(exchanges)
# assertion read these next to opjit calls_by_kind["mesh_collective"]).
_STATS_LOCK = threading.Lock()
_STATS = {"launches": 0, "rows_sent": 0, "stage_ns": 0, "launch_ns": 0,
          "wait_ns": 0, "compact_ns": 0,
          # dictionary-encoded string exchanges (the MULTICHIP summary's
          # multichip_string_collectives / dict_encode_ms keys)
          "dict_exchanges": 0, "dict_encode_ns": 0,
          # staging-pool reuse + segmented-overlap accounting (r07 fused
          # dataplane keys: docs/distributed.md "Fused compact & overlap")
          "staging_reuse_hits": 0, "overlap_segments": 0}


def collective_stats() -> Dict[str, int]:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_collective_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def record_dict_encode(ns: int) -> None:
    """One exchange's map-side dictionary-encode pass completed (every
    value is host-known: a perf_counter wall — zero device syncs)."""
    with _STATS_LOCK:
        _STATS["dict_exchanges"] += 1
        _STATS["dict_encode_ns"] += ns


def _record_launch(rows: int, stage_ns: int, launch_ns: int,
                   wait_ns: int, compact_ns: int,
                   staging_reuse_hits: int = 0,
                   overlap_segments: int = 0) -> None:
    with _STATS_LOCK:
        _STATS["launches"] += 1
        _STATS["rows_sent"] += rows
        _STATS["stage_ns"] += stage_ns
        _STATS["launch_ns"] += launch_ns
        _STATS["wait_ns"] += wait_ns
        _STATS["compact_ns"] += compact_ns
        _STATS["staging_reuse_hits"] += staging_reuse_hits
        _STATS["overlap_segments"] += overlap_segments
    # always-on registry (docs/observability.md): the collective's blocking
    # wait is the fabric's user-visible latency — histogram it per launch
    # (rare: one per exchange) so a serving dashboard sees the tail;
    # the running totals above fold into metrics_snapshot() as-is
    from ..obs import metrics as _metrics
    _metrics.histogram_observe("mesh.collective_wait_ms", wait_ns / 1e6)
    _metrics.counter_inc("mesh.staging_reuse_hits", staging_reuse_hits)


class MeshExchangeResult(NamedTuple):
    """One collective exchange's outputs + its device-side statistics."""
    batches: List[TpuColumnarBatch]  # one compacted batch per reduce part
    rows: List[int]                  # exact received rows per reduce part
    bytes: List[int]                 # device bytes per reduce part
    profile: Optional[Dict] = None   # obs/mesh_profile.py record
    #: per reduce partition: rows contributed by each SOURCE shard (the
    #: sizing counts' column) — the fused block's row order is (source
    #: asc, stable), so a contiguous source range is a contiguous row
    #: range: AQE skew splitting slices on these (map_block_sizes)
    src_rows: Optional[List[List[int]]] = None
    row_bytes: int = 0               # device bytes per row (fixed layout)


def _build_exchange(mesh: Mesh, n_dev: int, slot_cap: int,
                    sig: Tuple[Tuple[str, bool], ...]):
    """ONE jitted program: shard_map all_to_all moving `len(sig)` columns +
    validity AND the fused post-collective compact — received slot (src s,
    pos p) scatters to final row `bases[s] + p` under the host-known
    per-source counts, so the outputs need no host-side compact at all.
    Returns the per-reduce blocks lane-major (`n_lanes * n_dev` outputs,
    each replicated so downstream consumers mix blocks across partitions).
    `sig` is ((dtype_str, has_validity), ...)."""
    key = (mesh, n_dev, slot_cap, sig)
    with _CACHE_LOCK:
        fn = _EXCHANGE_CACHE.get(key)
    if fn is not None:
        return fn

    n_cols = len(sig)
    local = n_dev * slot_cap

    def exchange(dest, counts, *flat):
        # per-shard local views: dest [cap], counts [n_dev] (rows each
        # SOURCE shard sends to this shard), columns/validities [cap]
        cap = dest.shape[0]
        order = jnp.argsort(dest, stable=True)
        sorted_dest = jnp.take(dest, order)
        idx = jnp.arange(cap, dtype=jnp.int32)
        one = jnp.ones((cap,), jnp.int32)
        run_start = jnp.zeros((n_dev + 2,), jnp.int32).at[
            sorted_dest + 1].add(one, mode="drop")
        starts = jnp.cumsum(run_start)[:-1]
        pos_in_bucket = idx - jnp.take(starts, sorted_dest)
        keep = (sorted_dest < n_dev) & (pos_in_bucket < slot_cap)
        send_slot = jnp.where(keep, sorted_dest * slot_cap + pos_in_bucket,
                              local)
        # fused compact: the receive side's slot (s, p) is occupied iff
        # p < counts[s]; its final row is bases[s] + p — identical to the
        # (src asc, stable in-bucket) order the host compact produced
        slot_src = jnp.arange(local, dtype=jnp.int32) // slot_cap
        slot_pos = jnp.arange(local, dtype=jnp.int32) % slot_cap
        bases = jnp.concatenate([
            jnp.zeros((1,), jnp.int32),
            jnp.cumsum(counts)[:-1].astype(jnp.int32)])
        occupied = slot_pos < jnp.take(counts, slot_src)
        out_idx = jnp.where(occupied,
                            jnp.take(bases, slot_src) + slot_pos, local)

        def a2a(x):
            x = x.reshape(n_dev, slot_cap)
            return jax.lax.all_to_all(x, _AXIS, split_axis=0, concat_axis=0,
                                      tiled=False).reshape(-1)

        def move(x, fill, dt):
            buf = jnp.full((local,), fill, dt).at[send_slot].set(
                jnp.take(x, order), mode="drop")
            recv = a2a(buf)
            return jnp.full((local,), fill, dt).at[out_idx].set(
                recv, mode="drop")

        outs = []
        datas = flat[:n_cols]
        valids = flat[n_cols:]
        for (dt, has_v), d, v in zip(sig, datas, valids):
            outs.append(move(d, 0, d.dtype))
            if has_v:
                outs.append(move(v, False, jnp.bool_))
        return tuple(outs)

    from .distributed import shard_map
    spec = P(_AXIS)
    n_valid = sum(1 for _, has_v in sig if has_v)
    n_lanes = n_cols + n_valid
    n_flat = 2 * n_cols
    sm = shard_map(exchange, mesh=mesh,
                   in_specs=tuple([spec] * (2 + n_flat)),
                   out_specs=tuple([spec] * n_lanes), check_rep=False)

    def whole(dest, counts, *flat):
        outs = sm(dest, counts, *flat)
        blocks = []
        for arr in outs:
            for r in range(n_dev):
                blocks.append(arr[r * local:(r + 1) * local])
        return tuple(blocks)

    fn = jax.jit(whole, out_shardings=NamedSharding(mesh, P()),
                 donate_argnums=_donate((0,) + tuple(
                     range(2, 2 + n_flat))))
    with _CACHE_LOCK:
        _EXCHANGE_CACHE[key] = fn
    return fn


def _build_overlap(mesh: Mesh, n_dev: int, slot_cap: int, k_seg: int,
                   sig: Tuple[Tuple[str, bool], ...]):
    """The segmented exchange's cached programs (overlap mode):

    * ``prep``   — ONE dispatch computing every lane's send-layout buffer
                   (slot pitch padded to ``k_seg * seg_cap``);
    * ``a2a``    — per-segment all_to_all of all lanes; the segment index
                   is a TRACED scalar, so all K segments share one
                   executable;
    * ``comp``   — per-segment fused compact scattering the received
                   segment into DONATED accumulators at the same final
                   rows the unsegmented program uses (bit-identical at
                   any K);
    * ``fin``    — replicate-and-slice the accumulators into per-reduce
                   blocks (same output layout as `_build_exchange`).

    Returns (prep, a2a, comp, fin, seg_cap)."""
    key = (mesh, n_dev, slot_cap, k_seg, sig, "overlap")
    with _CACHE_LOCK:
        progs = _EXCHANGE_CACHE.get(key)
    if progs is not None:
        return progs

    n_cols = len(sig)
    seg_cap = -(-slot_cap // k_seg)
    slot_capP = k_seg * seg_cap
    local = n_dev * slot_cap
    localP = n_dev * slot_capP
    n_valid = sum(1 for _, has_v in sig if has_v)
    n_lanes = n_cols + n_valid
    n_flat = 2 * n_cols

    def prepare(dest, *flat):
        cap = dest.shape[0]
        order = jnp.argsort(dest, stable=True)
        sorted_dest = jnp.take(dest, order)
        idx = jnp.arange(cap, dtype=jnp.int32)
        one = jnp.ones((cap,), jnp.int32)
        run_start = jnp.zeros((n_dev + 2,), jnp.int32).at[
            sorted_dest + 1].add(one, mode="drop")
        starts = jnp.cumsum(run_start)[:-1]
        pos_in_bucket = idx - jnp.take(starts, sorted_dest)
        keep = (sorted_dest < n_dev) & (pos_in_bucket < slot_cap)
        send_slot = jnp.where(keep,
                              sorted_dest * slot_capP + pos_in_bucket,
                              localP)
        outs = []
        datas = flat[:n_cols]
        valids = flat[n_cols:]
        for (dt, has_v), d, v in zip(sig, datas, valids):
            outs.append(jnp.full((localP,), 0, d.dtype).at[send_slot].set(
                jnp.take(d, order), mode="drop"))
            if has_v:
                outs.append(jnp.full((localP,), False, jnp.bool_).at[
                    send_slot].set(jnp.take(v, order), mode="drop"))
        return tuple(outs)

    def seg_a2a(k, *sends):
        outs = []
        for s in sends:
            x = s.reshape(n_dev, slot_capP)
            seg = jax.lax.dynamic_slice(
                x, (jnp.int32(0), (k * jnp.int32(seg_cap)).astype(jnp.int32)),
                (n_dev, seg_cap))
            outs.append(jax.lax.all_to_all(
                seg, _AXIS, split_axis=0, concat_axis=0,
                tiled=False).reshape(-1))
        return tuple(outs)

    def seg_compact(k, counts, *accseg):
        accs = accseg[:n_lanes]
        segs = accseg[n_lanes:]
        nloc = n_dev * seg_cap
        seg_src = jnp.arange(nloc, dtype=jnp.int32) // seg_cap
        seg_pos = jnp.arange(nloc, dtype=jnp.int32) % seg_cap
        p = k * seg_cap + seg_pos
        bases = jnp.concatenate([
            jnp.zeros((1,), jnp.int32),
            jnp.cumsum(counts)[:-1].astype(jnp.int32)])
        occupied = p < jnp.take(counts, seg_src)
        out_idx = jnp.where(occupied, jnp.take(bases, seg_src) + p, local)
        return tuple(acc.at[out_idx].set(seg, mode="drop")
                     for acc, seg in zip(accs, segs))

    def finalize(*accs):
        blocks = []
        for acc in accs:
            for r in range(n_dev):
                blocks.append(acc[r * local:(r + 1) * local])
        return tuple(blocks)

    from .distributed import shard_map
    spec = P(_AXIS)
    rep = NamedSharding(mesh, P())
    prep = jax.jit(
        shard_map(prepare, mesh=mesh,
                  in_specs=tuple([spec] * (1 + n_flat)),
                  out_specs=tuple([spec] * n_lanes), check_rep=False),
        donate_argnums=_donate(range(1 + n_flat)))
    a2a = jax.jit(
        shard_map(seg_a2a, mesh=mesh,
                  in_specs=(P(),) + tuple([spec] * n_lanes),
                  out_specs=tuple([spec] * n_lanes), check_rep=False))
    comp = jax.jit(
        shard_map(seg_compact, mesh=mesh,
                  in_specs=(P(), spec) + tuple([spec] * (2 * n_lanes)),
                  out_specs=tuple([spec] * n_lanes), check_rep=False),
        donate_argnums=_donate(range(2, 2 + 2 * n_lanes)))
    fin = jax.jit(finalize, out_shardings=rep,
                  donate_argnums=_donate(range(n_lanes)))
    progs = (prep, a2a, comp, fin, seg_cap)
    with _CACHE_LOCK:
        _EXCHANGE_CACHE[key] = progs
    return progs


def _overlap_segments(conf, slot_cap: int) -> int:
    """Segment count for this exchange, or 0 (unsegmented). Correctness-
    first default: overlap only when explicitly enabled AND the slot
    capacity clears the minimum (below it, per-segment launch overhead
    dominates whatever the fabric could hide)."""
    if conf is None or not conf.get(EXCHANGE_OVERLAP_ENABLED):
        return 0
    k = int(conf.get(EXCHANGE_OVERLAP_SEGMENTS))
    if k <= 1 or slot_cap < max(k, int(conf.get(EXCHANGE_OVERLAP_MIN_ROWS))):
        return 0
    return k


def _fixed_row_bytes(ref: TpuColumnarBatch, has_valid: List[bool]) -> int:
    """Device bytes per row of a fixed-width batch (carrier itemsize +
    1 byte per validity lane) — the row→byte scale for the device-side
    partition statistics."""
    total = 0
    for i, c in enumerate(ref.columns):
        total += int(np.dtype(c.data.dtype).itemsize)
        if has_valid[i]:
            total += 1
    return total


def mesh_hash_exchange(mesh: Mesh,
                       group_batches: List[Optional[TpuColumnarBatch]],
                       pids_list: List[Optional[jnp.ndarray]],
                       names: Sequence[str],
                       shuffle_id: int = -1,
                       partitioning: str = "hash",
                       conf=None) -> MeshExchangeResult:
    """Collective hash exchange: `group_batches[d]` is the (possibly empty)
    concatenated map input assigned to shard d, `pids_list[d]` its
    destination-partition ids. Returns one compacted device batch per reduce
    partition (= per shard) — compaction happens INSIDE the collective
    program (fused compact) under the host-known sizing counts — plus the
    exact per-reduce row/byte counts AND the per-source row split (the
    device-side statistics AQE plans coalescing and skew slicing against —
    no block fetch, no extra sync) and the exchange's efficiency profile
    (obs/mesh_profile.py: phase walls + per-chip skew, all from host
    values this function already holds). `conf` (optional — direct kernel
    callers may omit it) gates the segmented overlap path."""
    from ..chaos import inject
    from ..execs import opjit
    from ..obs import mesh_profile as mprof
    from ..serving.query_context import checkpoint as _cancel_checkpoint
    # collective-launch cancellation boundary: last stop before the
    # staging sync + fabric program — a cancelled/timed-out query never
    # launches the collective (docs/robustness.md "Query lifecycle")
    _cancel_checkpoint(f"mesh.collective s{shuffle_id}")
    n_dev = mesh.devices.size
    assert len(group_batches) == n_dev
    t_stage0 = time.perf_counter_ns()
    ref = next(b for b in group_batches if b is not None)
    dtypes = [c.dtype for c in ref.columns]
    cap = bucket_capacity(max([b.capacity for b in group_batches
                               if b is not None] + [1]))

    # per-(shard, dest) counts -> slot capacity AND the exchange's partition
    # statistics (ONE audited host sync for all shards' pid arrays; a
    # per-shard np.asarray loop would pay one round trip each on
    # high-latency links)
    live = [(d, b, p) for d, (b, p) in enumerate(zip(group_batches,
                                                     pids_list))
            if b is not None and b.num_rows]
    fetched = audited_device_get([p for _d, _b, p in live], "mesh_counts") \
        if live else []
    max_count = 1
    counts_m = np.zeros((n_dev, n_dev), np.int64)
    for (shard, b, _p), pids_np in zip(live, fetched):
        counts = np.bincount(np.asarray(pids_np)[: b.num_rows],
                             minlength=n_dev)
        max_count = max(max_count, int(counts.max()))
        counts_m[shard] += counts
    recv_rows = counts_m.sum(axis=0)
    send_rows = counts_m.sum(axis=1)
    slot_cap = bucket_capacity(max_count)
    overlap_k = _overlap_segments(conf, slot_cap)

    # stack per-shard arrays into globally sharded [n_dev * cap] inputs;
    # constant pad pieces (empty shards, destination fills) come from the
    # staging pool — the copies they replace are the "staging" wall
    sharding = NamedSharding(mesh, P(_AXIS))
    reuse_hits = 0

    def pad(kind: str, dtype, fill):
        nonlocal reuse_hits
        arr, hit = _pooled_fill(kind, cap, dtype, fill)
        reuse_hits += hit
        return arr

    sig = []
    col_data: List[List[jnp.ndarray]] = []
    col_valid: List[List[jnp.ndarray]] = []
    has_valid = [any(b is not None and b.columns[i].validity is not None
                     for b in group_batches)
                 for i in range(len(dtypes))]
    for i, dt in enumerate(dtypes):
        carrier = ref.columns[i].data.dtype
        sig.append((str(carrier), has_valid[i]))
        datas, valids = [], []
        for b in group_batches:
            if b is None:
                datas.append(pad("zeros", carrier, 0))
                valids.append(pad("mask", jnp.bool_, False))
            else:
                c = _repad(b.columns[i], cap)
                datas.append(c.data)
                valids.append(c.validity if c.validity is not None
                              else row_mask(b.num_rows, cap))
        col_data.append(datas)
        col_valid.append(valids)
    dests = []
    for b, pids in zip(group_batches, pids_list):
        if b is None or not b.num_rows:
            dests.append(pad("dest", jnp.int32, n_dev))
        else:
            p = jnp.asarray(pids)[:cap].astype(jnp.int32)
            if p.shape[0] < cap:
                p = jnp.concatenate(
                    [p, jnp.full((cap - p.shape[0],), n_dev, jnp.int32)])
            dests.append(jnp.where(row_mask(b.num_rows, cap), p, n_dev))

    def shard(arrs):
        return jax.device_put(jnp.concatenate(arrs), sharding)

    dest_g = shard(dests)
    counts_g = shard([jnp.asarray(counts_m[:, r].astype(np.int32))
                      for r in range(n_dev)])
    flat = [shard(col_data[i]) for i in range(len(dtypes))] + \
           [shard(col_valid[i]) for i in range(len(dtypes))]
    if overlap_k:
        ovl = _build_overlap(mesh, n_dev, slot_cap, overlap_k, tuple(sig))
    else:
        fn = _build_exchange(mesh, n_dev, slot_cap, tuple(sig))
    t_launch0 = time.perf_counter_ns()
    # pre-allocated profile seq: the span args and the consumer read's
    # flow events reference the profile before it is recorded
    seq = mprof.alloc_seq()
    # the span covers launch → wait → block construction (staging_ms rides
    # as an arg: the per-chip send counts it reports only exist after the
    # sizing sync). The watchdog arms around ONLY the fabric window —
    # inject + launch + wait: chaos `mesh.link` (a slow or flapping ICI
    # link) injects inside it, so a stalled transfer trips the watchdog
    # exactly like a hung chip would. Latency sleeps here; a transient
    # error propagates to the caller's with_device_retry, which re-runs
    # the whole (idempotent) staging — donated buffers are abandoned, not
    # reused.
    with obs.span(f"mesh.exchange s{shuffle_id}",
                  cat="shuffle.collective", shuffle=shuffle_id,
                  n_dev=n_dev, slot_cap=slot_cap, exchange_seq=seq,
                  staging_ms=round((t_launch0 - t_stage0) / 1e6, 3),
                  overlap_segments=overlap_k,
                  per_chip_rows=[int(x) for x in send_rows]):
        with mprof.collective_watchdog(shuffle_id, n_dev) as wd:
            if overlap_k:
                outs = _launch_overlapped(ovl, overlap_k, mesh, n_dev,
                                          slot_cap, tuple(sig), sharding,
                                          dest_g, counts_g, flat,
                                          shuffle_id)
            else:
                inject("mesh.link", detail=f"s{shuffle_id}")
                outs = fn(dest_g, counts_g, *flat)
            t_wait0 = time.perf_counter_ns()
            # the collective is the stage boundary: waiting for it here is
            # the exchange's one blocking device sync (no data moves to
            # host — the ledger records the wait so per-query sync
            # accounting stays exact)
            from ..profiling import record_sync
            record_sync("collective_wait")
            jax.block_until_ready(outs)
            t_end = time.perf_counter_ns()
        opjit.record_external_dispatch("mesh_collective")

        # assemble per-reduce batches from the program's replicated block
        # outputs (lane-major). The compact already happened INSIDE the
        # dispatch: rows [0, recv_rows[r]) are final, the tail is padding
        # (zeros, validity False) — no host compact, no per-partition
        # sync (the counts were host-known from the sizing sync).
        local = n_dev * slot_cap
        row_bytes = _fixed_row_bytes(ref, has_valid)
        lane_of: List[Tuple[int, Optional[int]]] = []
        li = 0
        for i in range(len(dtypes)):
            d_li, li = li, li + 1
            v_li = None
            if has_valid[i]:
                v_li, li = li, li + 1
            lane_of.append((d_li, v_li))
        results: List[TpuColumnarBatch] = []
        sizes: List[int] = []
        for r in range(n_dev):
            cols = []
            for i, dt in enumerate(dtypes):
                d_li, v_li = lane_of[i]
                v = outs[v_li * n_dev + r] if v_li is not None else None
                cols.append(TpuColumnVector(dt, outs[d_li * n_dev + r], v,
                                            int(recv_rows[r])))
            results.append(TpuColumnarBatch(cols, int(recv_rows[r]),
                                            list(names)))
            sizes.append(int(recv_rows[r]) * row_bytes)
        t_compact_end = time.perf_counter_ns()
        profile = mprof.record_exchange(
            seq, shuffle_id, partitioning, n_dev,
            send_rows=[int(x) for x in send_rows],
            recv_rows=[int(x) for x in recv_rows], recv_bytes=sizes,
            stage_ns=t_launch0 - t_stage0, launch_ns=t_wait0 - t_launch0,
            wait_ns=t_end - t_wait0, compact_ns=t_compact_end - t_end,
            watchdog_fired=wd.fired, compact_fused=True,
            staging_reuse_hits=reuse_hits, overlap_segments=overlap_k)
        if profile is not None:
            # the full attribution record as an instant event: the Chrome
            # export derives the per-device tracks + producer→consumer
            # flows from it (all values already host-side)
            obs.event("mesh.profile", cat="mesh", exchange_seq=seq,
                      shuffle=shuffle_id, n_dev=n_dev,
                      phases_ms=dict(profile["phases_ms"]),
                      recv_rows=list(profile["recv_rows"]),
                      skew=dict(profile["skew"]))
    _record_launch(int(send_rows.sum()), t_launch0 - t_stage0,
                   t_wait0 - t_launch0, t_end - t_wait0,
                   t_compact_end - t_end, staging_reuse_hits=reuse_hits,
                   overlap_segments=overlap_k)
    src_rows = [[int(counts_m[s][r]) for s in range(n_dev)]
                for r in range(n_dev)]
    return MeshExchangeResult(results, [int(x) for x in recv_rows], sizes,
                              profile, src_rows, row_bytes)


def _launch_overlapped(progs, k_seg: int, mesh: Mesh, n_dev: int,
                       slot_cap: int, sig: Tuple[Tuple[str, bool], ...],
                       sharding, dest_g, counts_g, flat,
                       shuffle_id: int) -> Tuple:
    """Double-buffered segmented exchange: segment k+1's all_to_all is
    dispatched BEFORE segment k's fused compact, so the fabric moves the
    next segment while the compact consumes the current one. Every segment
    scatters to the same final rows the unsegmented program uses —
    bit-identical at any K. Chaos `mesh.link` fires per segment
    (mid-segment soak): a raised fault abandons the donated accumulators
    mid-flight and the caller re-stages — nothing is applied twice."""
    from ..chaos import inject
    from ..execs import opjit
    prep, a2a, comp, fin, _seg_cap = progs
    sends = prep(dest_g, *flat)
    # fresh (never pooled) accumulators: comp donates them each segment
    accs = []
    for dt, has_v in sig:
        accs.append(jax.device_put(
            jnp.zeros((n_dev * n_dev * slot_cap,), jnp.dtype(dt)),
            sharding))
        if has_v:
            accs.append(jax.device_put(
                jnp.zeros((n_dev * n_dev * slot_cap,), jnp.bool_),
                sharding))
    accs = tuple(accs)
    seg = a2a(jnp.int32(0), *sends)
    for k in range(k_seg):
        # next segment's collective goes on the stream BEFORE this
        # segment's compact — the overlap window
        nxt = a2a(jnp.int32(k + 1), *sends) if k + 1 < k_seg else None
        opjit.record_external_dispatch("mesh_overlap_segment")
        inject("mesh.link", detail=f"s{shuffle_id}seg{k}")
        accs = comp(jnp.int32(k), counts_g, *accs, *seg)
        seg = nxt
    return fin(*accs)


def mesh_single_exchange(mesh: Mesh,
                         group_batches: List[Optional[TpuColumnarBatch]],
                         names: Sequence[str],
                         shuffle_id: int = -1,
                         conf=None) -> MeshExchangeResult:
    """Collective SINGLE-partition funnel: every shard's rows move to shard
    0 in one all_to_all — the fabric path for partial→final aggregation and
    global limit/top-N merges (the reduce-scatter analogue: per-shard
    partial states were already reduced locally by the partial stage; the
    collective carries only the states). Returns mesh-size results where
    only reduce partition 0 is non-empty.

    Cost note: this reuses the hash-exchange program with all-zero
    destinations, so each shard still ships a full [n_dev, slot_cap] send
    buffer — slot groups 1..n-1 are padding the receivers discard,
    ~n_dev× the payload in fabric traffic. Acceptable for the state-merge
    funnels this serves (payloads are per-shard partial STATES, already
    reduced); a ragged gather / all_gather layout is the follow-up if a
    row-heavy single exchange ever rides it (ROADMAP item 2)."""
    pids = [None if b is None
            else jnp.zeros((b.capacity,), jnp.int32)
            for b in group_batches]
    return mesh_hash_exchange(mesh, group_batches, pids, names,
                              shuffle_id=shuffle_id, partitioning="single",
                              conf=conf)
